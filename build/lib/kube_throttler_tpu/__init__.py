"""kube_throttler_tpu — a TPU-native re-design of everpeace/kube-throttler.

The reference (mounted read-only at /root/reference) is a Kubernetes
scheduling-framework plugin, written in Go, that throttles pod scheduling:
pods stay Pending while the aggregate ``resources.requests`` / running-pod
count matched by a ``Throttle`` / ``ClusterThrottle`` CRD would exceed a
threshold (reference README.md:3-15).

This package keeps the reference's *semantics* — the ordered 4-state
admission check, presence-masked per-dimension comparison, temporary
threshold overrides, the reserve-until-observed handshake — but re-expresses
the decision core as batched XLA tensor programs:

- host control plane (``engine/``, ``controllers/``, ``plugin/``): typed CRD
  model, watch-protocol event ingestion, workqueue reconciliation,
  reservation ledger, metrics, status write-back;
- device data plane (``ops/``, ``parallel/``): padded int64 milli-unit
  tensors with presence masks; the (pod × throttle × resource-dim)
  admission check is one vmapped/jitted kernel; scale-out is data-parallel
  sharding of the check matrix over a ``jax.sharding.Mesh``.

Exact decimal semantics of k8s ``resource.Quantity`` are preserved via
integer milli-units (see ``quantity.py``), which requires 64-bit integers:
x64 mode is enabled at import.
"""

import jax

jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"
