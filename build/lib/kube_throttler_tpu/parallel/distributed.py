"""Multi-host runtime: process bring-up and DCN×ICI hybrid meshes.

The reference scales out through the Kubernetes API server's watch protocol
(SURVEY §5 — its only "distributed backend"). The TPU-native equivalent is
jax.distributed over ICI/DCN: every host runs the same host control plane
shard and the device data plane spans all chips of the slice/pod.

Axis → link mapping (scaling-book recipe): the **pods** axis is the
data-parallel axis and is laid over **DCN** (hosts); the **throttles** axis
stays within a host's ICI island. The step's two collectives
(`psum` of [T_loc,R] used-partials over pods, `psum` of [P_loc,4] verdict
counts over throttles — see sharded.py) then put the per-throttle-tile
reduce on the slow links only once per tick while the throttle-axis reduce
rides ICI.
"""

from __future__ import annotations

import logging
import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger(__name__)

_initialized = False


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Bring up jax.distributed for multi-host operation.

    Arguments fall back to ``KT_TPU_COORDINATOR`` / ``KT_TPU_NUM_PROCESSES``
    / ``KT_TPU_PROCESS_ID`` env vars. With no explicit configuration at all,
    ``KT_TPU_AUTO_DISTRIBUTED=1`` opts into JAX's own cluster auto-detection
    (argless ``jax.distributed.initialize()``, e.g. TPU pod metadata); the
    un-opted default is a no-op so single-process callers share the entry
    point without risking a hang waiting for a nonexistent coordinator.
    Returns True iff a multi-process runtime was initialized.
    """
    global _initialized
    if _initialized:
        return True
    coordinator_address = coordinator_address or os.environ.get("KT_TPU_COORDINATOR")
    env_np = os.environ.get("KT_TPU_NUM_PROCESSES")
    env_pid = os.environ.get("KT_TPU_PROCESS_ID")
    if num_processes is None and env_np is not None:
        num_processes = int(env_np)
    if process_id is None and env_pid is not None:
        process_id = int(env_pid)
    if coordinator_address is None and num_processes in (None, 1):
        if os.environ.get("KT_TPU_AUTO_DISTRIBUTED") == "1":
            jax.distributed.initialize()  # cluster auto-detection
            _initialized = True
            return True
        return False  # single-process; nothing to do
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    logger.info(
        "jax.distributed up: process %d/%d, %d local / %d global devices",
        jax.process_index(), jax.process_count(),
        jax.local_device_count(), jax.device_count(),
    )
    return True


def hybrid_mesh(
    ici_shape: Optional[Tuple[int, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """("pods","throttles") mesh spanning all processes.

    Multi-process: pods axis = DCN (one slot per host) × intra-host pods
    factor; throttles axis stays inside each host's ICI island.
    ``ici_shape`` fixes the per-host (pods, throttles) factorization;
    default puts the whole local island on throttles.
    Single-process: degenerates to ``mesh.make_mesh`` over local devices.
    """
    if jax.process_count() == 1:
        from .mesh import make_mesh

        return make_mesh(shape=ici_shape)
    from jax.experimental import mesh_utils

    local = jax.local_device_count()
    if ici_shape is None:
        ici_shape = (1, local)
    assert ici_shape[0] * ici_shape[1] == local, (
        f"ici_shape {ici_shape} must factor the {local} local devices"
    )
    dev_array = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=ici_shape,
        dcn_mesh_shape=(jax.process_count(), 1),
        devices=devices or jax.devices(),
    )
    return Mesh(dev_array, axis_names=("pods", "throttles"))


def shard_global_array(mesh: Mesh, spec: P, local_data: np.ndarray) -> jax.Array:
    """Assemble a global device array from this process's local shard.

    Single-process: a plain device_put with the NamedSharding.
    Multi-process: ``local_data`` is this host's slice of the global array
    (its pod rows / throttle cols), stitched via
    ``jax.make_array_from_process_local_data`` — no host ever materializes
    the global tensor.
    """
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(np.asarray(local_data), sharding)
    return jax.make_array_from_process_local_data(sharding, np.asarray(local_data))
