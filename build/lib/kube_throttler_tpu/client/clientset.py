"""Typed clientset — the hand-written analog of the reference's generated
client-gen output (pkg/generated/clientset/versioned/).

Verb parity with ThrottleInterface (clientset/versioned/typed/schedule/
v1alpha1/throttle.go:39-52): Create, Update, UpdateStatus, Delete,
DeleteCollection, Get, List, Watch, Patch. ClusterThrottles are
cluster-scoped (clusterthrottle.go:39-52); a CoreV1 facade covers the
Pod/Namespace surface the plugin consumes through its second informer
factory (plugin.go:81-88).

``Patch`` is an RFC 7386 JSON merge patch applied to the object's manifest
dict and re-parsed — the moral equivalent of the generated client's
``types.MergePatchType`` path.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from ..api.pod import Namespace, Pod
from ..api.serialization import (
    cluster_throttle_from_dict,
    cluster_throttle_to_dict,
    namespace_from_dict,
    namespace_to_dict,
    normalize_manifest,
    pod_from_dict,
    pod_to_dict,
    throttle_from_dict,
    throttle_to_dict,
)
from ..api.types import ClusterThrottle, Throttle
from ..engine.store import NotFoundError, Store
from .watch import Watch


def json_merge_patch(target: Any, patch: Any) -> Any:
    """RFC 7386: objects merge recursively, ``null`` deletes, everything
    else replaces."""
    if not isinstance(patch, dict):
        return patch
    result: Dict[str, Any] = dict(target) if isinstance(target, dict) else {}
    for k, v in patch.items():
        if v is None:
            result.pop(k, None)
        else:
            result[k] = json_merge_patch(result.get(k), v)
    return result


class ThrottleInterface:
    """Namespaced Throttle client (throttle.go:69-196)."""

    def __init__(self, store: Store, namespace: str) -> None:
        self._store = store
        self._namespace = namespace

    def _scoped(self, thr: Throttle) -> Throttle:
        if thr.namespace != self._namespace:
            from dataclasses import replace

            thr = replace(thr, namespace=self._namespace)
        return thr

    def create(self, thr: Throttle) -> Throttle:
        return self._store.create_throttle(self._scoped(thr))

    def update(self, thr: Throttle) -> Throttle:
        # status-subresource semantics: the store atomically preserves the
        # stored status under its lock (see Store.update_throttle_spec)
        return self._store.update_throttle_spec(self._scoped(thr))

    def update_status(self, thr: Throttle, expected_version: Optional[int] = None) -> Throttle:
        return self._store.update_throttle_status(self._scoped(thr), expected_version)

    def delete(self, name: str) -> Throttle:
        return self._store.delete_throttle(self._namespace, name)

    def delete_collection(
        self, predicate: Optional[Callable[[Throttle], bool]] = None
    ) -> List[Throttle]:
        deleted = []
        for thr in self.list():
            if predicate is None or predicate(thr):
                try:
                    deleted.append(self._store.delete_throttle(self._namespace, thr.name))
                except NotFoundError:
                    pass  # raced with a concurrent delete
        return deleted

    def get(self, name: str) -> Throttle:
        return self._store.get_throttle(self._namespace, name)

    def list(self) -> List[Throttle]:
        return self._store.list_throttles(self._namespace)

    def watch(self, replay: bool = False) -> Watch:
        ns = self._namespace
        return Watch(
            self._store, "Throttle", filter=lambda e: e.obj.namespace == ns, replay=replay
        )

    def patch(self, name: str, patch: Dict[str, Any]) -> Throttle:
        normalized = normalize_manifest(patch)

        def apply(current: Throttle) -> Throttle:
            merged = json_merge_patch(throttle_to_dict(current), normalized)
            return self._scoped(throttle_from_dict(merged))

        # atomic get→merge→update under the store lock (MergePatchType is
        # atomic on a real apiserver; see Store.mutate)
        return self._store.mutate("Throttle", f"{self._namespace}/{name}", apply)


class ClusterThrottleInterface:
    """Cluster-scoped client (clusterthrottle.go:69-186)."""

    def __init__(self, store: Store) -> None:
        self._store = store

    def create(self, thr: ClusterThrottle) -> ClusterThrottle:
        return self._store.create_cluster_throttle(thr)

    def update(self, thr: ClusterThrottle) -> ClusterThrottle:
        return self._store.update_cluster_throttle_spec(thr)

    def update_status(
        self, thr: ClusterThrottle, expected_version: Optional[int] = None
    ) -> ClusterThrottle:
        return self._store.update_cluster_throttle_status(thr, expected_version)

    def delete(self, name: str) -> ClusterThrottle:
        return self._store.delete_cluster_throttle(name)

    def delete_collection(
        self, predicate: Optional[Callable[[ClusterThrottle], bool]] = None
    ) -> List[ClusterThrottle]:
        deleted = []
        for thr in self.list():
            if predicate is None or predicate(thr):
                try:
                    deleted.append(self._store.delete_cluster_throttle(thr.name))
                except NotFoundError:
                    pass  # raced with a concurrent delete
        return deleted

    def get(self, name: str) -> ClusterThrottle:
        return self._store.get_cluster_throttle(name)

    def list(self) -> List[ClusterThrottle]:
        return self._store.list_cluster_throttles()

    def watch(self, replay: bool = False) -> Watch:
        return Watch(self._store, "ClusterThrottle", replay=replay)

    def patch(self, name: str, patch: Dict[str, Any]) -> ClusterThrottle:
        normalized = normalize_manifest(patch)

        def apply(current: ClusterThrottle) -> ClusterThrottle:
            merged = json_merge_patch(cluster_throttle_to_dict(current), normalized)
            return cluster_throttle_from_dict(merged)

        return self._store.mutate("ClusterThrottle", name, apply)


class PodInterface:
    def __init__(self, store: Store, namespace: str) -> None:
        self._store = store
        self._namespace = namespace

    def create(self, pod: Pod) -> Pod:
        return self._store.create_pod(pod)

    def update(self, pod: Pod) -> Pod:
        return self._store.update_pod(pod)

    def delete(self, name: str) -> Pod:
        return self._store.delete_pod(self._namespace, name)

    def get(self, name: str) -> Pod:
        return self._store.get_pod(self._namespace, name)

    def list(self) -> List[Pod]:
        return self._store.list_pods(self._namespace)

    def watch(self, replay: bool = False) -> Watch:
        ns = self._namespace
        return Watch(self._store, "Pod", filter=lambda e: e.obj.namespace == ns, replay=replay)

    def patch(self, name: str, patch: Dict[str, Any]) -> Pod:
        def apply(current: Pod) -> Pod:
            merged = json_merge_patch(pod_to_dict(current), patch)
            return pod_from_dict(merged)

        return self._store.mutate("Pod", f"{self._namespace}/{name}", apply)


class NamespaceInterface:
    def __init__(self, store: Store) -> None:
        self._store = store

    def create(self, ns: Namespace) -> Namespace:
        return self._store.create_namespace(ns)

    def update(self, ns: Namespace) -> Namespace:
        return self._store.update_namespace(ns)

    def get(self, name: str) -> Optional[Namespace]:
        return self._store.get_namespace(name)

    def list(self) -> List[Namespace]:
        return self._store.list_namespaces()

    def watch(self, replay: bool = False) -> Watch:
        return Watch(self._store, "Namespace", replay=replay)

    def patch(self, name: str, patch: Dict[str, Any]) -> Namespace:
        def apply(current: Namespace) -> Namespace:
            merged = json_merge_patch(namespace_to_dict(current), patch)
            return namespace_from_dict(merged)

        return self._store.mutate("Namespace", name, apply)


class ScheduleV1alpha1Client:
    """group schedule.k8s.everpeace.github.com, version v1alpha1
    (schedule_client.go:27-42)."""

    def __init__(self, store: Store) -> None:
        self._store = store

    def throttles(self, namespace: str = "default") -> ThrottleInterface:
        return ThrottleInterface(self._store, namespace)

    def cluster_throttles(self) -> ClusterThrottleInterface:
        return ClusterThrottleInterface(self._store)


class CoreV1Client:
    def __init__(self, store: Store) -> None:
        self._store = store

    def pods(self, namespace: str = "default") -> PodInterface:
        return PodInterface(self._store, namespace)

    def namespaces(self) -> NamespaceInterface:
        return NamespaceInterface(self._store)


class Clientset:
    """The versioned clientset facade (clientset.go:30-41)."""

    def __init__(self, store: Store) -> None:
        self.store = store

    def schedule_v1alpha1(self) -> ScheduleV1alpha1Client:
        return ScheduleV1alpha1Client(self.store)

    def core_v1(self) -> CoreV1Client:
        return CoreV1Client(self.store)


def new_fake_clientset(*objects) -> Clientset:
    """Fake clientset preloaded with objects (fake/clientset.go:38-58):
    a real clientset over a private fresh store — the store *is* the
    deterministic apiserver double, so the fake and the real client share
    one implementation."""
    store = Store()
    # namespaces first so namespaced objects land in existing namespaces
    for obj in objects:
        if isinstance(obj, Namespace):
            store.create_namespace(obj)
    for obj in objects:
        if isinstance(obj, Namespace):
            continue
        if isinstance(obj, Throttle):
            store.create_throttle(obj)
        elif isinstance(obj, ClusterThrottle):
            store.create_cluster_throttle(obj)
        elif isinstance(obj, Pod):
            store.create_pod(obj)
        else:
            raise ValueError(f"unsupported object: {type(obj).__name__}")
    return Clientset(store)
