"""Watch streams over the store (the clientset's Watch verb).

The reference's generated clients expose ``Watch(ctx, opts)`` returning a
``watch.Interface`` whose ``ResultChan()`` yields typed events
(clientset/versioned/typed/schedule/v1alpha1/throttle.go:110-125). Here a
``Watch`` is an iterator over :class:`~..engine.store.Event` objects fed by
the store's synchronous dispatch, decoupled through a queue so consumers run
on their own thread at their own pace.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator, Optional

from ..engine.store import Event, EventType, Store


class Watch:
    """A stoppable stream of events for one kind.

    With ``replay`` the stream begins with synthetic ADDED events for every
    object currently in the store (list-then-watch semantics).
    """

    _SENTINEL = object()

    def __init__(
        self,
        store: Store,
        kind: str,
        filter: Optional[Callable[[Event], bool]] = None,
        replay: bool = False,
    ) -> None:
        self._store = store
        self._kind = kind
        self._filter = filter
        self._queue: "queue.Queue" = queue.Queue()
        self._stopped = threading.Event()
        self._terminal = False  # consumer-side: sentinel observed

        def handler(event: Event) -> None:
            if self._stopped.is_set():
                return
            if self._filter is None or self._filter(event):
                self._queue.put(event)

        self._handler = handler
        store.add_event_handler(kind, handler, replay=replay)

    def stop(self) -> None:
        """Terminate the stream; pending and future ``next()`` calls raise
        StopIteration once drained."""
        if not self._stopped.is_set():
            self._stopped.set()
            self._store.remove_event_handler(self._kind, self._handler)
            self._queue.put(self._SENTINEL)

    def next(self, timeout: Optional[float] = None) -> Event:
        """Block for the next event. Raises ``queue.Empty`` on timeout,
        ``StopIteration`` after :meth:`stop`."""
        # once the sentinel has been observed the stream is terminal — a
        # straggler event that raced in behind the sentinel must never be
        # returned, so the flag (not the queue contents) is authoritative
        if self._terminal:
            raise StopIteration
        item = self._queue.get(timeout=timeout)
        if item is self._SENTINEL:
            self._terminal = True
            raise StopIteration
        return item

    def __iter__(self) -> Iterator[Event]:
        while True:
            try:
                yield self.next()
            except StopIteration:
                return

    def __enter__(self) -> "Watch":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


__all__ = ["Watch", "Event", "EventType"]
