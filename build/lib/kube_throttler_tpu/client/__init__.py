"""Client layer — hand-written analog of the reference's generated API
machinery (pkg/generated/, SURVEY.md §2.2): typed clientset with the full
verb set, watch streams, shared informers with resync + indexers,
indexer-backed listers, a fake clientset for tests, and the wire transport
(list+watch reflectors + remote status writer + mock apiserver) that speaks
the real Kubernetes HTTP protocol (plugin.go:71-130).
"""

from .clientset import (
    Clientset,
    ClusterThrottleInterface,
    CoreV1Client,
    NamespaceInterface,
    PodInterface,
    ScheduleV1alpha1Client,
    ThrottleInterface,
    json_merge_patch,
    new_fake_clientset,
)
from .informers import (
    NAMESPACE_INDEX,
    Indexer,
    InformerBundle,
    SharedIndexInformer,
    SharedInformerFactory,
)
from .listers import (
    ClusterThrottleLister,
    Listers,
    NamespaceLister,
    PodLister,
    ThrottleLister,
)
from .transport import (
    ApiClient,
    ApiError,
    GoneError,
    Reflector,
    RemoteSession,
    RemoteStatusWriter,
    RestConfig,
    parse_kubeconfig,
)
from .watch import Watch

__all__ = [
    "ApiClient",
    "ApiError",
    "Clientset",
    "ClusterThrottleInterface",
    "ClusterThrottleLister",
    "CoreV1Client",
    "GoneError",
    "Indexer",
    "InformerBundle",
    "Listers",
    "NAMESPACE_INDEX",
    "NamespaceInterface",
    "NamespaceLister",
    "PodInterface",
    "PodLister",
    "Reflector",
    "RemoteSession",
    "RemoteStatusWriter",
    "RestConfig",
    "ScheduleV1alpha1Client",
    "SharedIndexInformer",
    "SharedInformerFactory",
    "ThrottleInterface",
    "ThrottleLister",
    "Watch",
    "json_merge_patch",
    "new_fake_clientset",
    "parse_kubeconfig",
]
