"""The state engine's reconcilers (reference pkg/controllers/).

``ThrottleController`` / ``ClusterThrottleController`` recompute
``status.used`` / ``calculatedThreshold`` / ``throttled`` per throttle key,
write status back, un-reserve observed pods, and self-wake at override
boundaries, all driven by store watch events through a rate-limited
workqueue.
"""

from .base import ControllerBase  # noqa: F401
from .throttle import ThrottleController  # noqa: F401
from .clusterthrottle import ClusterThrottleController  # noqa: F401
