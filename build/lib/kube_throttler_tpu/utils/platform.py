"""JAX platform-selection helper.

This environment's sitecustomize registers the tunnel TPU backend and sets
``jax_platforms`` programmatically at interpreter start, which OVERRIDES the
``JAX_PLATFORMS`` env var. Any entrypoint that wants an operator's explicit
``JAX_PLATFORMS=cpu`` (e.g. when the tunnel is down) to actually take effect
must re-assert it through the config API before the first backend init.
"""

import os


def honor_jax_platforms_env() -> None:
    """Re-assert the JAX_PLATFORMS env var through ``jax.config``.

    No-op when the env var is unset (the ambient platform selection stands)
    or when a backend is already initialized (too late to change).
    """
    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
