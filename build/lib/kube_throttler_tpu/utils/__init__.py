"""Small host-side utilities: clocks, keys."""

from .clock import Clock, FakeClock, RealClock  # noqa: F401
