"""Typed API model: Throttle / ClusterThrottle CRDs and pure decision logic.

Layer 2 of the reference (pkg/apis/schedule/v1alpha1): the CRD structs plus
the pure functions the whole system hinges on — ``is_throttled``,
``check_throttled_for``, ``calculate_threshold``, selector matching. These
Python implementations are the *oracle*: every XLA kernel in ``ops/`` is
property-tested against them.
"""

from .pod import Container, Namespace, Pod, PodSpec, PodStatus  # noqa: F401
from .types import (  # noqa: F401
    CalculatedThreshold,
    CheckThrottleStatus,
    ClusterThrottle,
    ClusterThrottleSelector,
    ClusterThrottleSelectorTerm,
    ClusterThrottleSpec,
    IsResourceAmountThrottled,
    LabelSelector,
    ResourceAmount,
    TemporaryThresholdOverride,
    Throttle,
    ThrottleSelector,
    ThrottleSelectorTerm,
    ThrottleSpec,
    ThrottleStatus,
    resource_amount_of_pod,
)
