"""CRD generation — the codegen pipeline, TPU-build edition.

The reference generates ``deploy/crd.yaml`` with controller-gen from Go
struct markers (Makefile:40-42, hack/update-codegen.sh). Here the typed
model lives in :mod:`kube_throttler_tpu.api.types`, so the OpenAPI v3
structural schemas are built programmatically from that model and emitted
by ``tools/gen_crd.py`` (run via ``make gen``).

Also provides :func:`validate` — a minimal structural-schema validator
(the subset controller-gen emits: object/array/string/integer types,
``properties``/``items``/``additionalProperties``/``required``,
``x-kubernetes-int-or-string``) so tests and the in-memory apiserver can
check manifests against the generated schema without a cluster.

Group/version/kind names match the reference exactly
(pkg/apis/schedule/register.go:217-219, v1alpha1/register.go:169-196) so
existing manifests apply unchanged.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

from .serialization import API_GROUP as GROUP
from .serialization import API_VERSION, VERSION


# ---------------------------------------------------------------------------
# Schema builders (composable; mirror the types in api/types.py)
# ---------------------------------------------------------------------------


def _s(t: str, **kw: Any) -> Dict[str, Any]:
    d: Dict[str, Any] = {"type": t}
    d.update(kw)
    return d


def quantity_schema() -> Dict[str, Any]:
    """k8s resource.Quantity: int-or-string with the canonical pattern."""
    return {
        "anyOf": [{"type": "integer"}, {"type": "string"}],
        "pattern": r"^(\+|-)?(([0-9]+(\.[0-9]*)?)|(\.[0-9]+))(([KMGTPE]i)|[numkMGTPE]|([eE](\+|-)?(([0-9]+(\.[0-9]*)?)|(\.[0-9]+))))?$",
        "x-kubernetes-int-or-string": True,
    }


def resource_amount_schema() -> Dict[str, Any]:
    """ResourceAmount {resourceCounts{pod int}, resourceRequests ResourceList}
    (resource_amount.go / api/types.py ResourceAmount)."""
    return _s(
        "object",
        properties={
            "resourceCounts": _s(
                "object",
                description="limits number of resources",
                properties={"pod": _s("integer", description="max running pod count")},
            ),
            "resourceRequests": _s(
                "object",
                description="limits aggregate resources.requests of running pods",
                additionalProperties=quantity_schema(),
            ),
        },
    )


def label_selector_schema() -> Dict[str, Any]:
    """metav1.LabelSelector: matchLabels AND matchExpressions."""
    return {
        "type": "object",
        "properties": {
            "matchLabels": _s("object", additionalProperties=_s("string")),
            "matchExpressions": _s(
                "array",
                items=_s(
                    "object",
                    properties={
                        "key": _s("string"),
                        "operator": _s(
                            "string",
                            description="In, NotIn, Exists or DoesNotExist",
                        ),
                        "values": _s("array", items=_s("string")),
                    },
                    required=["key", "operator"],
                ),
            ),
        },
        "x-kubernetes-map-type": "atomic",
    }


def selector_schema(cluster: bool) -> Dict[str, Any]:
    """selector.selectorTerms[] OR-ed; ClusterThrottle terms add a
    namespaceSelector ANDed with the podSelector (throttle_selector.go:26-54,
    clusterthrottle_selector.go:84-141). The reference's Go field name is the
    typo ``SelecterTerms`` but its JSON tag — the wire format — is
    ``selectorTerms`` (throttle_selector.go:27), so only that spelling is in
    the schema."""
    term_props: Dict[str, Any] = {"podSelector": label_selector_schema()}
    if cluster:
        term_props["namespaceSelector"] = label_selector_schema()
    terms = _s("array", items=_s("object", properties=term_props))
    return _s(
        "object",
        description="OR-ed list of selector terms; each term is an AND of its selectors",
        properties={"selectorTerms": terms},
    )


def override_schema() -> Dict[str, Any]:
    return _s(
        "object",
        description=(
            "time-windowed threshold replacement; begin/end are inclusive "
            "RFC3339 timestamps, either may be empty (open-ended); when "
            "multiple overrides are active the first wins per resource"
        ),
        properties={
            "begin": _s("string"),
            "end": _s("string"),
            "threshold": resource_amount_schema(),
        },
    )


def throttled_flags_schema() -> Dict[str, Any]:
    return _s(
        "object",
        properties={
            "resourceCounts": _s("object", properties={"pod": _s("boolean")}),
            "resourceRequests": _s("object", additionalProperties=_s("boolean")),
        },
    )


def status_schema() -> Dict[str, Any]:
    return _s(
        "object",
        properties={
            "throttled": throttled_flags_schema(),
            "used": resource_amount_schema(),
            "calculatedThreshold": _s(
                "object",
                properties={
                    "threshold": resource_amount_schema(),
                    # Go's zero metav1.Time marshals as JSON null
                    "calculatedAt": _s("string", format="date-time", nullable=True),
                    "messages": _s("array", items=_s("string")),
                },
            ),
        },
    )


def spec_schema(cluster: bool) -> Dict[str, Any]:
    return _s(
        "object",
        properties={
            "throttlerName": _s(
                "string",
                description="the throttler instance (plugin args .name) owning this object",
            ),
            "selector": selector_schema(cluster),
            "threshold": resource_amount_schema(),
            "temporaryThresholdOverrides": _s("array", items=override_schema()),
        },
    )


def _printer_columns() -> List[Dict[str, Any]]:
    return [
        {"name": "throttled", "type": "string", "format": "byte", "jsonPath": ".status.throttled"},
        {
            "name": "calculatedThreshold",
            "type": "string",
            "format": "byte",
            "priority": 1,
            "jsonPath": ".status.calculatedThreshold.threshold",
        },
        {
            "name": "calculatedAt",
            "type": "date",
            "priority": 1,
            "jsonPath": ".status.calculatedThreshold.calculatedAt",
        },
        {"name": "age", "type": "date", "jsonPath": ".metadata.creationTimestamp"},
    ]


def object_schema(cluster: bool) -> Dict[str, Any]:
    return _s(
        "object",
        properties={
            "apiVersion": _s("string"),
            "kind": _s("string"),
            "metadata": _s("object"),
            "spec": spec_schema(cluster),
            "status": status_schema(),
        },
    )


def crd(cluster: bool) -> Dict[str, Any]:
    """One CustomResourceDefinition document (apiextensions.k8s.io/v1)."""
    kind = "ClusterThrottle" if cluster else "Throttle"
    plural = kind.lower() + "s"
    short = ["clthr", "clthrs"] if cluster else ["thr", "thrs"]
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {
            "name": f"{plural}.{GROUP}",
            "annotations": {"kube-throttler-tpu/codegen": "tools/gen_crd.py"},
        },
        "spec": {
            "group": GROUP,
            "scope": "Cluster" if cluster else "Namespaced",
            "names": {
                "kind": kind,
                "listKind": kind + "List",
                "plural": plural,
                "singular": kind.lower(),
                "shortNames": short,
                "categories": ["kube-throttler"],
            },
            "versions": [
                {
                    "name": VERSION,
                    "served": True,
                    "storage": True,
                    "additionalPrinterColumns": _printer_columns(),
                    "subresources": {"status": {}},
                    "schema": {"openAPIV3Schema": object_schema(cluster)},
                }
            ],
        },
    }


def throttle_crd() -> Dict[str, Any]:
    return crd(cluster=False)


def cluster_throttle_crd() -> Dict[str, Any]:
    return crd(cluster=True)


# ---------------------------------------------------------------------------
# Minimal structural-schema validation
# ---------------------------------------------------------------------------


class SchemaError(ValueError):
    def __init__(self, path: str, message: str) -> None:
        self.path = path or "."
        super().__init__(f"{self.path}: {message}")


def _validate(value: Any, schema: Dict[str, Any], path: str, errors: List[SchemaError]) -> None:
    if value is None and schema.get("nullable"):
        return
    if schema.get("x-kubernetes-int-or-string") or "anyOf" in schema:
        if not isinstance(value, (int, str)) or isinstance(value, bool):
            errors.append(SchemaError(path, f"expected integer or string, got {type(value).__name__}"))
        elif isinstance(value, str) and "pattern" in schema and not re.fullmatch(schema["pattern"], value):
            errors.append(SchemaError(path, f"{value!r} does not match pattern {schema['pattern']!r}"))
        return
    t = schema.get("type")
    if t == "object":
        if not isinstance(value, dict):
            errors.append(SchemaError(path, f"expected object, got {type(value).__name__}"))
            return
        props = schema.get("properties", {})
        for req in schema.get("required", []):
            if req not in value:
                errors.append(SchemaError(path, f"missing required field {req!r}"))
        addl = schema.get("additionalProperties")
        for k, v in value.items():
            if k in props:
                _validate(v, props[k], f"{path}.{k}", errors)
            elif isinstance(addl, dict):
                _validate(v, addl, f"{path}.{k}", errors)
            elif props and addl is None:
                # structural schemas prune unknown fields rather than reject;
                # flag them so tests catch typos, mirroring kubectl's
                # server-side "unknown field" warning
                errors.append(SchemaError(path, f"unknown field {k!r}"))
    elif t == "array":
        if not isinstance(value, list):
            errors.append(SchemaError(path, f"expected array, got {type(value).__name__}"))
            return
        item_schema = schema.get("items")
        if isinstance(item_schema, dict):
            for i, item in enumerate(value):
                _validate(item, item_schema, f"{path}[{i}]", errors)
    elif t == "string":
        if not isinstance(value, str):
            errors.append(SchemaError(path, f"expected string, got {type(value).__name__}"))
    elif t == "integer":
        if not isinstance(value, int) or isinstance(value, bool):
            errors.append(SchemaError(path, f"expected integer, got {type(value).__name__}"))
    elif t == "boolean":
        if not isinstance(value, bool):
            errors.append(SchemaError(path, f"expected boolean, got {type(value).__name__}"))


def validate(manifest: Dict[str, Any], schema: Optional[Dict[str, Any]] = None) -> List[SchemaError]:
    """Validate a manifest dict; returns a list of errors (empty == valid).

    With ``schema=None`` the schema is chosen from ``manifest["kind"]``.
    """
    if schema is None:
        kind = manifest.get("kind")
        if kind == "Throttle":
            schema = object_schema(cluster=False)
        elif kind == "ClusterThrottle":
            schema = object_schema(cluster=True)
        else:
            return [SchemaError("kind", f"no schema for kind {kind!r}")]
    errors: List[SchemaError] = []
    _validate(manifest, schema, "", errors)
    return errors
