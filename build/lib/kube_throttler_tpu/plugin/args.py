"""Plugin argument decoding (reference plugin_args.go:29-60).

Same field names (including the ``kubeconfig`` JSON key whose Go field is the
``KubeConifg`` typo — SURVEY §2.3 quirk 5), same defaults and validation:
``name`` and ``targetSchedulerName`` required; interval defaults to 15s;
threadiness defaults to CPU count.

``reconcileTemporaryThresholdInterval`` is decoded-but-unused in the
reference (plugin_args.go:53-55 → plugin.go:93,104 → dropped; override
wakeups are event-driven via NextOverrideHappensIn). Here it IS honored: the
plugin passes it to both controllers as ``resync_interval``, the periodic
enqueue-all backstop (controllers/base.py ``_resync``) that replaces the
reference's 5-minute informer resync. Note the cadence tradeoff: the 15s
default re-enqueues every responsible key 20× more often than the
reference's 5-minute resync — cheap here because the workqueue dedups and
the batched reconcile pays one device aggregate per drain, but deployments
with very large throttle counts that don't need fast staleness repair can
raise it (e.g. ``"5m"``).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from datetime import timedelta
from typing import Any, Mapping

DEFAULT_RECONCILE_TEMPORARY_THRESHOLD_INTERVAL = timedelta(seconds=15)


@dataclass(frozen=True)
class KubeThrottlerPluginArgs:
    name: str
    target_scheduler_name: str
    kubeconfig: str = ""
    reconcile_temporary_threshold_interval: timedelta = (
        DEFAULT_RECONCILE_TEMPORARY_THRESHOLD_INTERVAL
    )
    controller_threadiness: int = 0
    num_key_mutex: int = 0


def decode_plugin_args(config: Mapping[str, Any]) -> KubeThrottlerPluginArgs:
    name = str(config.get("name", "") or "")
    if not name:
        raise ValueError("Name must not be empty")
    target = str(config.get("targetSchedulerName", "") or "")
    if not target:
        raise ValueError("TargetSchedulerName must not be empty")

    interval = config.get("reconcileTemporaryThresholdInterval", 0)
    if isinstance(interval, str) and interval:
        # accept Go duration-ish strings: "15s", "1m30s", "500ms"
        interval = _parse_go_duration(interval)
    elif isinstance(interval, (int, float)) and interval:
        interval = timedelta(seconds=float(interval))
    else:
        interval = timedelta(0)
    if interval == timedelta(0):
        interval = DEFAULT_RECONCILE_TEMPORARY_THRESHOLD_INTERVAL

    threadiness = int(config.get("controllerThrediness", 0) or 0)
    if threadiness == 0:
        threadiness = os.cpu_count() or 1

    return KubeThrottlerPluginArgs(
        name=name,
        target_scheduler_name=target,
        kubeconfig=str(config.get("kubeconfig", "") or ""),
        reconcile_temporary_threshold_interval=interval,
        controller_threadiness=threadiness,
        num_key_mutex=int(config.get("numKeyMutex", 0) or 0) or 128,
    )


def _parse_go_duration(s: str) -> timedelta:
    import re

    total = 0.0
    for value, unit in re.findall(r"([0-9.]+)(ms|s|m|h)", s):
        total += float(value) * {"ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0}[unit]
    if total == 0:
        raise ValueError(f"invalid duration: {s!r}")
    return timedelta(seconds=total)
