"""Scheduler-plugin front-end (reference pkg/scheduler_plugin/).

``KubeThrottler`` implements the scheduling-framework extension points the
reference registers (PreFilter, Reserve/Unreserve, EnqueueExtensions —
plugin.go:54-56) against this framework's own minimal framework surface.
"""

from .framework import ClusterEvent, EventRecorder, RecordingEventRecorder, Status, StatusCode  # noqa: F401
from .args import KubeThrottlerPluginArgs, decode_plugin_args  # noqa: F401
from .plugin import KubeThrottler  # noqa: F401
