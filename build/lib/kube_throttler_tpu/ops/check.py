"""The batched ordered 4-state admission check — the framework's hot kernel.

Reproduces ``check_throttled_for`` (reference throttle_types.go:128-153,
clusterthrottle_types.go:30-55) for every (pod, throttle) pair at once:

    1. pod alone > threshold                  → POD_EXCEEDS (onEqual=False)
    2. persisted status.throttled flags hit   → ACTIVE
    3. used + reserved saturates threshold    → ACTIVE
       (onEqual hardcoded True for Throttle — throttle_types.go:143 —
        caller's flag for ClusterThrottle — clusterthrottle_types.go:45)
    4. used + reserved + pod overflows        → INSUFFICIENT (caller's flag)
    else                                      → NOT_THROTTLED

Presence-mask algebra (absent ≠ zero) follows resource_amount.go:127-159:
a comparison only fires when the dimension is present in BOTH the threshold
and the used side; "blocks this pod" additionally requires the pod to
request that resource non-zero (resource_amount.go:46-65) — except the
pod-count flag, which always blocks.

Shapes: throttle state [T]/[T,R], pods [P]/[P,R], selector mask [P,T].
Everything broadcasts to [P,T,R] inside a single XLA fusion and reduces over
R — no [P,T,R] intermediate is materialized at the default sizes. Two
output forms:

- ``check_pods``          → int8[P,T] full classification (explain path,
  oracle diffing, reason-string formatting for blocked pods);
- ``check_pods_compact``  → int32[P,4] per-pod class counts + bool[P]
  schedulable (the scheduler hot path: 100k×10k never materializes [P,T]).

The two static booleans (kind asymmetry, caller onEqual) select among 4
compiled variants; shapes are padded so object churn never recompiles.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .schema import PodBatch, ThrottleState

CHECK_NOT_AFFECTED = -1
CHECK_NOT_THROTTLED = 0
CHECK_ACTIVE = 1
CHECK_INSUFFICIENT = 2
CHECK_POD_EXCEEDS = 3

STATUS_NAMES = {
    CHECK_NOT_AFFECTED: "not-affected",
    CHECK_NOT_THROTTLED: "not-throttled",
    CHECK_ACTIVE: "active",
    CHECK_INSUFFICIENT: "insufficient",
    CHECK_POD_EXCEEDS: "pod-requests-exceeds-threshold",
}


def _cmp(u, t, on_equal: bool):
    return u >= t if on_equal else u > t


def _classify(state: ThrottleState, pods: PodBatch, mask: jnp.ndarray,
              on_equal: bool, step3_on_equal: bool) -> jnp.ndarray:
    """Core classification → int8[P,T]. Static flags pick the variant."""
    # trace-time guard: DimRegistry capacity may have doubled between the
    # throttle-state and pod-batch encodes; fail with an actionable message
    # instead of an opaque XLA broadcast error
    if state.thr_req.shape[1] != pods.req.shape[1]:
        raise ValueError(
            f"resource-dim mismatch: throttle state has R={state.thr_req.shape[1]} "
            f"but pod batch has R={pods.req.shape[1]}; the dim registry grew — "
            "re-encode both against the same capacity"
        )
    if mask.shape != (pods.req.shape[0], state.thr_req.shape[0]):
        raise ValueError(
            f"mask shape {mask.shape} != (P={pods.req.shape[0]}, T={state.thr_req.shape[0]})"
        )
    # pod-side broadcast views: [P,1,R] vs throttle [1,T,R]
    pod_req = pods.req[:, None, :]
    pod_present = pods.req_present[:, None, :]
    pod_nonzero = pod_present & (pod_req != 0)

    thr_req = state.thr_req[None, :, :]
    thr_req_present = state.thr_req_present[None, :, :]
    thr_cnt = state.thr_cnt[None, :]
    thr_cnt_present = state.thr_cnt_present[None, :]

    # --- step 1: pod alone vs threshold (onEqual=False) -------------------
    # pod count is always 1 and always present
    exceeds_cnt = thr_cnt_present & (1 > thr_cnt)
    exceeds_req = jnp.any(
        thr_req_present & pod_present & (pod_req > thr_req) & (pod_req != 0), axis=-1
    )
    exceeds = exceeds_cnt | exceeds_req

    # --- step 2: persisted throttled flags --------------------------------
    st_active = state.st_cnt_throttled[None, :] | jnp.any(
        state.st_req_flag_present[None, :, :]
        & state.st_req_throttled[None, :, :]
        & pod_nonzero,
        axis=-1,
    )

    # --- step 3: used + reserved saturation -------------------------------
    au_cnt = state.used_cnt + state.res_cnt
    au_cnt_present = state.used_cnt_present | state.res_cnt_present
    au_req = state.used_req + state.res_req
    au_req_present = state.used_req_present | state.res_req_present

    sat_cnt = thr_cnt_present & au_cnt_present[None, :] & _cmp(
        au_cnt[None, :], thr_cnt, step3_on_equal
    )
    sat_req = jnp.any(
        thr_req_present
        & au_req_present[None, :, :]
        & _cmp(au_req[None, :, :], thr_req, step3_on_equal)
        & pod_nonzero,
        axis=-1,
    )
    saturated = sat_cnt | sat_req

    # --- step 4: used + reserved + pod overflow ---------------------------
    # pod contributes count 1 (always present) and its requests
    tot_cnt = au_cnt[None, :] + 1
    tot_req = au_req[None, :, :] + pod_req
    tot_req_present = au_req_present[None, :, :] | pod_present

    over_cnt = thr_cnt_present & _cmp(tot_cnt, thr_cnt, on_equal)
    over_req = jnp.any(
        thr_req_present
        & tot_req_present
        & _cmp(tot_req, thr_req, on_equal)
        & pod_nonzero,
        axis=-1,
    )
    insufficient = over_cnt | over_req

    # --- ordered resolution ----------------------------------------------
    result = jnp.where(
        exceeds,
        jnp.int8(CHECK_POD_EXCEEDS),
        jnp.where(
            st_active | saturated,
            jnp.int8(CHECK_ACTIVE),
            jnp.where(insufficient, jnp.int8(CHECK_INSUFFICIENT), jnp.int8(CHECK_NOT_THROTTLED)),
        ),
    )
    affected = mask & state.valid[None, :] & pods.valid[:, None]
    return jnp.where(affected, result, jnp.int8(CHECK_NOT_AFFECTED))


@partial(jax.jit, static_argnames=("on_equal", "step3_on_equal"))
def check_pods(state: ThrottleState, pods: PodBatch, mask: jnp.ndarray,
               on_equal: bool = False, step3_on_equal: bool = True) -> jnp.ndarray:
    """Full [P,T] classification (int8)."""
    return _classify(state, pods, mask, on_equal, step3_on_equal)


def statuses_to_compact(statuses: jnp.ndarray):
    """[P,T] statuses → (counts int32[P,4], schedulable bool[P]); the
    schedulable gate mirrors PreFilter (plugin.go:177-180). Shared by every
    compact path so the gate can never silently diverge between kernels."""
    counts = jnp.stack(
        [jnp.sum(statuses == c, axis=1, dtype=jnp.int32) for c in range(4)], axis=1
    )
    schedulable = (
        counts[:, CHECK_ACTIVE] + counts[:, CHECK_INSUFFICIENT] + counts[:, CHECK_POD_EXCEEDS]
    ) == 0
    return counts, schedulable


def _compact(state: ThrottleState, pods: PodBatch, mask: jnp.ndarray,
             on_equal: bool, step3_on_equal: bool):
    return statuses_to_compact(_classify(state, pods, mask, on_equal, step3_on_equal))


def check_step(state: ThrottleState, pods: PodBatch, mask: jnp.ndarray):
    """Un-jitted forward step (PreFilter defaults: onEqual=False, Throttle
    kind) for embedding under an outer jit — returns (counts, schedulable)."""
    return _compact(state, pods, mask, False, True)


@partial(jax.jit, static_argnames=("on_equal", "step3_on_equal"))
def check_pods_compact(state: ThrottleState, pods: PodBatch, mask: jnp.ndarray,
                       on_equal: bool = False, step3_on_equal: bool = True):
    """Hot-path form: per-pod class counts, no [P,T] materialization.

    Returns ``(counts int32[P,4], schedulable bool[P])`` where counts[p,c]
    is the number of affected throttles classifying pod p as class c
    (NOT_THROTTLED/ACTIVE/INSUFFICIENT/POD_EXCEEDS), and schedulable[p]
    mirrors PreFilter's gate: no active/insufficient/exceeds throttle
    (plugin.go:177-180).
    """
    return _compact(state, pods, mask, on_equal, step3_on_equal)
