"""Host state engine: the event-driven control plane.

The reference's "distributed communication backend" is the Kubernetes API
server watch protocol (SURVEY §5): list-watch informer caches feed delta
event handlers, reconciles write status back optimistically, and the
scheduler hot path reads caches synchronously. This package reproduces that
protocol against a deterministic in-memory store — the reference's weakest
test dependency was a real kind cluster; a replayable in-process apiserver
lets the 100k-pod configs run anywhere — plus the pieces around it:

- ``store``        — object store with resourceVersion + watch fan-out
- ``workqueue``    — client-go-style rate-limited work queue with AddAfter
- ``reservations`` — the scheduler-cycle reservation ledger
- ``index``        — incremental [P,T] selector-mask maintenance
- ``devicestate``  — host→device tensor mirror serving the check kernels
"""

from .store import Event, EventType, Store  # noqa: F401
from .workqueue import RateLimitingQueue  # noqa: F401
from .reservations import ReservedResourceAmounts  # noqa: F401
