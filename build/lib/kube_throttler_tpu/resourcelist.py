"""ResourceList arithmetic — host-side oracle.

Mirrors the semantics of the reference's ``pkg/resourcelist/resourcelist.go``
(the layer-1 quantity-map arithmetic everything else builds on):

- ``pod_request_resource_list``  — resourcelist.go:27-46: a pod's effective
  request is max(per-init-container max, sum of app containers) + overhead.
- ``add`` / ``sub``              — resourcelist.go:48-62: rhs keys are merged
  into lhs; missing lhs keys start at zero; Sub may go negative.
- ``greater_or_equal``           — resourcelist.go:64-74: lhs ≥ rhs over rhs's
  keys; a key missing from lhs fails the comparison.
- ``set_max`` / ``set_min``      — resourcelist.go:76-98: union-max /
  intersection-min (set_min drops lhs keys absent from rhs).
- ``equal_to``                   — resourcelist.go:100-111: bidirectional
  compare where a missing key reads as the zero quantity.

Here a ResourceList is a plain ``dict[str, Fraction]`` (exact decimals from
``quantity.parse_quantity``). Functions that mutate in Go mutate here too, so
call-site behavior matches the reference.
"""

from __future__ import annotations

from fractions import Fraction
from typing import Dict, TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover
    from .api.pod import Pod

ResourceList = Dict[str, Fraction]

ZERO = Fraction(0)


def pod_request_resource_list(pod: "Pod") -> ResourceList:
    """Effective request of a pod (resourcelist.go:27-46)."""
    ic_res: ResourceList = {}
    for c in pod.spec.init_containers:
        set_max(ic_res, c.requests)

    c_res: ResourceList = {}
    for c in pod.spec.containers:
        add(c_res, c.requests)

    set_max(c_res, ic_res)

    if pod.spec.overhead:
        add(c_res, pod.spec.overhead)

    return c_res


def add(lhs: ResourceList, rhs: ResourceList) -> None:
    for name, q in rhs.items():
        lhs[name] = lhs.get(name, ZERO) + q


def sub(lhs: ResourceList, rhs: ResourceList) -> None:
    for name, q in rhs.items():
        lhs[name] = lhs.get(name, ZERO) - q


def greater_or_equal(lhs: ResourceList, rhs: ResourceList) -> bool:
    for name, q in rhs.items():
        if name not in lhs:
            return False
        if lhs[name] < q:
            return False
    return True


def set_max(lhs: ResourceList, rhs: ResourceList) -> None:
    for name, q in rhs.items():
        if name in lhs:
            lhs[name] = max(lhs[name], q)
        else:
            lhs[name] = q


def set_min(lhs: ResourceList, rhs: ResourceList) -> None:
    for name, q in rhs.items():
        if name in lhs:
            lhs[name] = min(lhs[name], q)
    for name in list(lhs.keys()):
        if name not in rhs:
            del lhs[name]


def equal_to(lhs: ResourceList, rhs: ResourceList) -> bool:
    # missing keys read as zero in either direction (resourcelist.go:100-111)
    for name, q in lhs.items():
        if q != rhs.get(name, ZERO):
            return False
    for name, q in rhs.items():
        if q != lhs.get(name, ZERO):
            return False
    return True
