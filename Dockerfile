# kube-throttler-tpu daemon image (reference Dockerfile:1-20, recast for
# the Python/JAX runtime): a builder stage compiles the C++ selector
# engine and builds the wheel; the runtime stage carries only the
# installed package. Satisfies deploy/deployment.yaml's
# `image: kube-throttler-tpu:latest` — build with `make image` (or
# tools/build_image.sh, which the release workflow calls).
#
# The default CPU jax wheel serves clusters without accelerators; for TPU
# nodes build with  --build-arg JAX_EXTRA="jax[tpu]"  (pulls libtpu).

FROM python:3.12-slim AS builder
ARG JAX_EXTRA=""
WORKDIR /src

RUN apt-get update \
    && apt-get install -y --no-install-recommends g++ \
    && apt-get clean && rm -rf /var/lib/apt/lists/*

COPY pyproject.toml README.md ./
COPY kube_throttler_tpu/ kube_throttler_tpu/
RUN pip install --no-cache-dir build && python -m build --wheel --outdir /dist

# pre-compile the native selector engine for the runtime image so first
# import in a read-only container needs no toolchain
RUN g++ -O3 -std=c++17 -shared -fPIC \
    kube_throttler_tpu/native/ktnative.cpp -o /dist/_ktnative.so

FROM python:3.12-slim AS runtime
ARG JAX_EXTRA=""
COPY --from=builder /dist/ /tmp/wheel/
RUN pip install --no-cache-dir /tmp/wheel/*.whl ${JAX_EXTRA} \
    && cp /tmp/wheel/_ktnative.so \
        "$(python -c 'import kube_throttler_tpu.native as n, pathlib; print(pathlib.Path(n.__file__).parent)')/_ktnative.so" \
    && rm -rf /tmp/wheel

# non-root like the reference deployment expects; the flock lease and the
# native-build cache both live under XDG dirs, which we point at /tmp
RUN useradd --uid 65532 --create-home throttler
USER 65532
ENV XDG_CACHE_HOME=/tmp/.cache

EXPOSE 10259
ENTRYPOINT ["python", "-m", "kube_throttler_tpu.cli"]
CMD ["serve", "--host", "0.0.0.0", "--port", "10259"]
