# Dev workflow targets (reference Makefile parity, minus Go/kind).
PY ?= python

.PHONY: test test-stress race-test crash-test ha-test reshard-test net-chaos shm-chaos upgrade-test scenario-test shard-scenario reshard-scenario preempt-scenario partition-scenario replica-scenario scenario-regression scenario-hunt scenario-hunt-smoke scenario-hunt-long scenario-hunt-nightly lint ci gen bench bench-quick walkthrough smoke serve clean native image dev-cluster dev-run dev-teardown

native:          ## build the C++ selector row-match engine (auto-built on import too)
	$(PY) -c "from kube_throttler_tpu.native import load; import sys; \
		sys.exit(0 if load() is not None else 1)"

test:            ## unit + kernel + integration tiers (8-device virtual CPU mesh)
	$(PY) -m pytest tests/ -q

test-stress:     ## only the stress/concurrency tier
	$(PY) -m pytest tests/test_stress.py -q

race-test:       ## runtime-detector gate: planted races + planted stale verdicts MUST fire (file:line asserts) + detector-armed concurrency smoke + runtime retrace budget; the full suite runs armed anyway (conftest KT_RACE_DETECT=1 KT_EPOCH_ASSERT=1)
	env JAX_PLATFORMS=cpu KT_RACE_DETECT=1 KT_LOCK_ASSERT=1 KT_EPOCH_ASSERT=1 $(PY) -m pytest \
		tests/test_racedetect.py tests/test_retrace.py \
		tests/test_epochassert.py \
		tests/test_lockorder.py tests/test_concurrent_check.py \
		-q -p no:cacheprovider

crash-test:      ## SIGKILL crash-point matrix: every crash.* site x 3 seeds
	$(PY) tools/crashtest.py matrix

ha-test:         ## kill-the-leader failover matrix: every ha.* site x 3 seeds + split-brain fencing
	$(PY) tools/hatest.py matrix

reshard-test:    ## kill-mid-handoff abort matrix: every reshard.* abort path x 3 seeds, zero orphan reservations
	env JAX_PLATFORMS=cpu $(PY) tools/reshardtest.py matrix

scenario-test:   ## trace-driven scenario corpus x 3 seeds, every SLO gate enforced (+ the sharded bad-day variant + the live-resharding chaos scenario + the preemption storm + the TCP-fleet partition bad day + the replica serving tier + hunt-promoted regression repros)
	env JAX_PLATFORMS=cpu $(PY) -m kube_throttler_tpu.scenarios matrix
	env JAX_PLATFORMS=cpu $(PY) -m kube_throttler_tpu.scenarios.sharded --shards 4 --seed 0
	env JAX_PLATFORMS=cpu $(PY) -m kube_throttler_tpu.scenarios.resharding --seed 0
	env JAX_PLATFORMS=cpu $(PY) -m kube_throttler_tpu.scenarios.preemption --seed 0
	env JAX_PLATFORMS=cpu $(PY) -m kube_throttler_tpu.scenarios.partition --seed 0
	env JAX_PLATFORMS=cpu $(PY) -m kube_throttler_tpu.scenarios.replica --seed 0
	env JAX_PLATFORMS=cpu $(PY) -m kube_throttler_tpu.scenarios regressions

preempt-scenario: ## preemption storm alone: gang waves vs low-priority residents, victim-churn SLO gate
	env JAX_PLATFORMS=cpu $(PY) -m kube_throttler_tpu.scenarios.preemption --seed 0

shard-scenario:  ## sharded composed bad-day alone: 4 workers, kill-a-shard episode, knee-lift + zero-wrong-verdict gates
	env JAX_PLATFORMS=cpu $(PY) -m kube_throttler_tpu.scenarios.sharded --shards 4 --seed 0

reshard-scenario: ## live resharding alone: scale 2->4->3 under storm load with one kill-mid-handoff episode
	env JAX_PLATFORMS=cpu $(PY) -m kube_throttler_tpu.scenarios.resharding --seed 0

partition-scenario: ## TCP-fleet partition bad day alone: asymmetric partition + heal mid-storm, zero wrong verdicts / zero lost flips / fencing gates
	env JAX_PLATFORMS=cpu $(PY) -m kube_throttler_tpu.scenarios.partition --seed 0

replica-scenario: ## read-replica serving tier alone: storm + leader flip burst, verdict-oracle + lag-SLO + staleness/forwarding gates
	env JAX_PLATFORMS=cpu $(PY) -m kube_throttler_tpu.scenarios.replica --seed 0

net-chaos:       ## transport-fault matrix: every net.* site x 3 seeds through a live 2-worker TCP fleet + every shm.* site through a live socketpair fleet; verdict-oracle + zero-orphan + zero-lost-flip gates
	env JAX_PLATFORMS=cpu $(PY) tools/netchaostest.py matrix

shm-chaos:       ## shared-memory event-plane faults only: every shm.* site through a live socketpair fleet with the ring asserted ACTIVE pre-fault; restart-delta + verdict-oracle + zero-leaked-segment gates
	env JAX_PLATFORMS=cpu $(PY) tools/netchaostest.py matrix --only shm

upgrade-test:    ## rolling-upgrade chaos matrix: front-first + worker-first rolls with capability skew, mid-roll SIGKILL, and the clean incompatible-major refusal, over a live 3-worker TCP fleet
	env JAX_PLATFORMS=cpu $(PY) tools/upgradetest.py matrix

scenario-regression: ## prove the gates gate: clean vs injected-regression diff report
	env JAX_PLATFORMS=cpu $(PY) -m kube_throttler_tpu.scenarios regression --name smoke

scenario-hunt:   ## nightly budgeted coverage-guided adversarial hunt; findings shrink + promote into scenarios/corpus/regressions/
	env JAX_PLATFORMS=cpu $(PY) -m kube_throttler_tpu.scenarios.hunt run \
		--budget-s 900 --iterations 40 --report hunt-report.json

scenario-hunt-smoke: ## CI acceptance: planted-bug find -> confirm -> shrink -> promote + coverage artifact
	env JAX_PLATFORMS=cpu $(PY) -m kube_throttler_tpu.scenarios.hunt smoke \
		--report hunt-coverage.json

scenario-hunt-long: ## long-horizon tier: multi-virtual-day soaks, durability cycles, 1M-pod arena rung
	env JAX_PLATFORMS=cpu $(PY) -m kube_throttler_tpu.scenarios.hunt long \
		--budget-s 3600 --iterations 20 --report hunt-long-report.json

scenario-hunt-nightly: ## nightly cadence (hack/ci.sh comments): the long tier at the FULL 1M-pod arena rung with durable journal/snapshot cycles, then budget-remainder mutation
	env JAX_PLATFORMS=cpu $(PY) -m kube_throttler_tpu.scenarios.hunt long \
		--budget-s 7200 --iterations 30 --mega-pods 1000000 \
		--report hunt-nightly-report.json

lint:            ## 15-checker static analyzer (locks, purity, registries, blocking, threads, excsafety, protocol, dtype, donation, retrace, envguard, epochs, deadlines, taint) + syntax sanity
	$(PY) -m compileall -q kube_throttler_tpu tools bench.py __graft_entry__.py
	$(PY) -m kube_throttler_tpu.analysis

ci:              ## the CI gate: lint + fast smoke tier (hack/ci.sh) — lint failures fail CI, not review
	hack/ci.sh

gen:             ## regenerate deploy/crd.yaml from the typed API model
	$(PY) tools/gen_crd.py

image:           ## container image for deploy/deployment.yaml (Dockerfile)
	tools/build_image.sh

bench:           ## the five BASELINE.json configs (one JSON line on stdout)
	$(PY) bench.py

bench-quick:
	$(PY) bench.py --quick

walkthrough:     ## reference README walkthrough end-to-end
	$(PY) examples/walkthrough.py

smoke:           ## TPU kernel compatibility smoke on real hardware
	$(PY) tools/tpu_smoke.py

serve:           ## run the daemon against the sample config
	$(PY) -m kube_throttler_tpu.cli serve --name kube-throttler \
		--target-scheduler-name my-scheduler --port 10259

dev-cluster:     ## spin a kind cluster + CRDs/RBAC (needs kind/kubectl)
	hack/dev/up.sh

dev-run:         ## run the daemon in remote mode against the kind cluster
	hack/dev/run.sh

dev-teardown:    ## delete the dev kind cluster
	hack/dev/down.sh

clean:
	find . -name __pycache__ -type d -prune -exec rm -rf {} +
