#!/usr/bin/env bash
# Spin a kind cluster and point the daemon's remote mode at it.
# Requires: kind, kubectl, envsubst (none of which exist in the build
# sandbox — this script is for operator laptops/CI; reference analog:
# Makefile integration-setup, Makefile:130-142).
#
# Usage:
#   hack/dev/up.sh            # create cluster + CRDs + RBAC, write .dev/
#   hack/dev/run.sh           # run the daemon against it (remote mode)
#   hack/dev/down.sh          # tear the cluster down
set -euo pipefail

CLUSTER_NAME=${CLUSTER_NAME:-kube-throttler-tpu-dev}
NODE_IMAGE=${NODE_IMAGE:-kindest/node:v1.29.2}
REPO_ROOT=$(cd "$(dirname "$0")/../.." && pwd)
DEV_DIR="$REPO_ROOT/.dev"
KUBECONFIG_PATH="$DEV_DIR/kubeconfig"

mkdir -p "$DEV_DIR"

if ! kind get clusters 2>/dev/null | grep -qx "$CLUSTER_NAME"; then
  kind create cluster \
    --name="$CLUSTER_NAME" \
    --kubeconfig="$KUBECONFIG_PATH" \
    --config="$REPO_ROOT/hack/dev/kind.conf" \
    --image="$NODE_IMAGE"
else
  kind export kubeconfig --name="$CLUSTER_NAME" --kubeconfig="$KUBECONFIG_PATH"
fi

kubectl --kubeconfig="$KUBECONFIG_PATH" apply -f "$REPO_ROOT/deploy/crd.yaml"
kubectl --kubeconfig="$KUBECONFIG_PATH" apply -f "$REPO_ROOT/deploy/namespace.yaml"
kubectl --kubeconfig="$KUBECONFIG_PATH" apply -f "$REPO_ROOT/deploy/rbac.yaml"

kubectl --kubeconfig="$KUBECONFIG_PATH" wait --timeout=120s \
  --for=condition=Ready "node/${CLUSTER_NAME}-control-plane"

echo "cluster ready; kubeconfig at $KUBECONFIG_PATH"
echo "next: hack/dev/run.sh"
