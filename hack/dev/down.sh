#!/usr/bin/env bash
# Tear down the dev kind cluster (reference analog: integration-teardown).
set -euo pipefail
CLUSTER_NAME=${CLUSTER_NAME:-kube-throttler-tpu-dev}
if kind get clusters 2>/dev/null | grep -qx "$CLUSTER_NAME"; then
  kind delete cluster --name="$CLUSTER_NAME"
fi
