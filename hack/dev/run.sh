#!/usr/bin/env bash
# Run the daemon in REMOTE mode against the kind cluster from up.sh:
# reflectors list+watch the real apiserver, status writes go to the
# Throttle/ClusterThrottle status subresources, Warning events to v1
# Events (reference analog: Makefile dev-run, Makefile:108-118).
set -euo pipefail

REPO_ROOT=$(cd "$(dirname "$0")/../.." && pwd)
DEV_DIR="$REPO_ROOT/.dev"
export KUBECONFIG="${KUBECONFIG:-$DEV_DIR/kubeconfig}"
export SCHEDULER_NAME="${SCHEDULER_NAME:-my-scheduler}"
export THROTTLER_NAME="${THROTTLER_NAME:-kube-throttler}"

[ -f "$KUBECONFIG" ] || { echo "no kubeconfig at $KUBECONFIG — run hack/dev/up.sh first" >&2; exit 1; }

mkdir -p "$DEV_DIR"
envsubst < "$REPO_ROOT/hack/dev/scheduler-config.yaml.template" \
  > "$DEV_DIR/scheduler-config.yaml"

exec python -m kube_throttler_tpu.cli serve \
  --config "$DEV_DIR/scheduler-config.yaml" \
  --kubeconfig "$KUBECONFIG" \
  --port "${PORT:-10259}" \
  "$@"
