#!/usr/bin/env python
"""Remote-mode burst smoke against a REAL kube-apiserver (kind).

The real-cluster twin of the reference's headline integration case
(test/integration/throttle_test.go:167-197): a Throttle capping cpu=1,
21 pods of 100m each pre_filter'd with reservations — exactly 10 must
admit... (cpu=1 / 100m = 10; the reference uses 50m for 20). Here:
cpu=1 vs 21 x 50m pods -> exactly 20 admitted.

Unlike the in-repo mockserver tier, this drives the daemon's remote mode
through a genuine apiserver: CRD schema validation/defaulting, real
resourceVersion semantics, real watch cadence. Run after hack/dev/up.sh:

    python hack/dev/burst_smoke.py [--kubeconfig .dev/kubeconfig]

Exit 0 = 20 admitted, statuses converged on the cluster; nonzero + log
otherwise. (See docs/mockserver-fidelity.md for what this covers that the
mock cannot.)
"""

import argparse
import os
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO_ROOT)

from kube_throttler_tpu.utils.platform import honor_jax_platforms_env  # noqa: E402

honor_jax_platforms_env()

from kube_throttler_tpu.api import ResourceAmount, Throttle, ThrottleSpec  # noqa: E402
from kube_throttler_tpu.api.pod import make_pod  # noqa: E402
from kube_throttler_tpu.api.serialization import object_to_dict  # noqa: E402
from kube_throttler_tpu.api.types import (  # noqa: E402
    LabelSelector,
    ThrottleSelector,
    ThrottleSelectorTerm,
)
from kube_throttler_tpu.client.transport import (  # noqa: E402
    GROUP,
    VERSION,
    RemoteSession,
    parse_kubeconfig,
)
from kube_throttler_tpu.engine.store import Store  # noqa: E402
from kube_throttler_tpu.plugin import KubeThrottler, decode_plugin_args  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--kubeconfig", default=os.path.join(REPO_ROOT, ".dev", "kubeconfig")
    )
    ap.add_argument("--namespace", default="default")
    args = ap.parse_args()

    config = parse_kubeconfig(args.kubeconfig)
    store = Store()
    session = RemoteSession(config, store)
    client = session.client
    ns = args.namespace

    thr = Throttle(
        name="smoke-burst",
        namespace=ns,
        spec=ThrottleSpec(
            throttler_name="kube-throttler",
            threshold=ResourceAmount.of(requests={"cpu": "1"}),
            selector=ThrottleSelector(
                selector_terms=(
                    ThrottleSelectorTerm(
                        LabelSelector(match_labels={"smoke": "burst"})
                    ),
                )
            ),
        ),
    )
    base = f"/apis/{GROUP}/{VERSION}/namespaces/{ns}/throttles"
    doc = object_to_dict(thr)
    try:
        client.post(base, doc)
        print(f"created Throttle {ns}/smoke-burst on the cluster")
    except Exception as e:  # already exists from a previous run
        print(f"throttle create: {e} (continuing)")

    session.start(sync_timeout=60)
    plugin = KubeThrottler(
        decode_plugin_args(
            {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
        ),
        store,
        use_device=True,
        start_workers=True,
        status_writer=session.status_committer,
    )
    try:
        # wait for the throttle to appear through the real watch
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                store.get_throttle(ns, "smoke-burst")
                break
            except Exception:
                time.sleep(0.25)
        else:
            print("FAIL: throttle never arrived through the watch")
            return 1

        admitted = 0
        for i in range(21):
            pod = make_pod(
                f"smoke-b{i}",
                namespace=ns,
                labels={"smoke": "burst"},
                requests={"cpu": "50m"},
            )
            status = plugin.pre_filter(pod)
            if status.is_success():
                plugin.reserve(pod)
                admitted += 1
        print(f"burst: {admitted}/21 admitted (want exactly 20)")
        if admitted != 20:
            return 1

        # the reconcile's status PUT must land on the REAL status
        # subresource and round-trip through the watch
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            got = client.get(f"{base}/smoke-burst")
            status = got.get("status") or {}
            if status.get("throttled") is not None:
                print(f"status on cluster: {status.get('throttled')}")
                return 0
            time.sleep(0.5)
        print("FAIL: status never materialized on the cluster")
        return 1
    finally:
        plugin.stop()
        session.stop()


if __name__ == "__main__":
    sys.exit(main())
