#!/usr/bin/env bash
# CI gate: `make ci`. Static analysis failures fail CI, not review —
# the analyzer (8 checkers + the stale-waiver gate) runs first, then a
# fast smoke tier that proves the analyzer and the runtime lock
# assassin themselves work. The full tier-1 suite stays `make test`;
# this script is the cheap always-on gate (<~1 min).
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== lint: compileall + 8-checker static analysis + stale-waiver gate =="
make lint

echo "== smoke: analyzer fixtures, lock assassin + hold budgets, journal =="
env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_analysis.py tests/test_lockorder.py tests/test_journal.py \
    -q -p no:cacheprovider

echo "== memory: 50k-pod columnar-arena build vs committed per-pod bounds =="
env JAX_PLATFORMS=cpu python tools/memsmoke.py

echo "ci gate: OK"
