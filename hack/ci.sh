#!/usr/bin/env bash
# CI gate: `make ci`. Static analysis failures fail CI, not review —
# the analyzer (15 checkers + the stale-waiver gate) runs first, then a
# fast smoke tier that proves the analyzer, the runtime lock assassin,
# the gen-3 lockset race detector, and the gen-4 verdict-coherence
# assassin themselves work (planted races and planted stale verdicts
# must fire). The full tier-1 suite stays `make test` (race-armed via
# conftest); this script is the cheap always-on gate (<~2 min).
#
# Nightly cadence (NOT part of this gate — the budgeted smoke below is
# the CI hunt tier; these run on the nightly schedule, in this order):
#   make scenario-hunt           budgeted coverage-guided search (~15 min)
#   make scenario-hunt-nightly   long-horizon tier at the FULL 1M-pod
#                                arena rung with durable journal/snapshot
#                                cycles + budget-remainder mutation (~2 h;
#                                memory: ~4 GB RSS — nightly-soak hosts
#                                only, never this gate)
#   make reshard-test            kill-mid-handoff abort matrix (zero
#                                orphan reservations across every
#                                reshard.* abort path x 3 seeds)
# Findings shrink + promote into scenarios/corpus/regressions/ and the
# next `make scenario-test` replays them as permanent tier gates.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== lint: compileall + 15-checker static analysis + stale-waiver gate =="
make lint

echo "== smoke: analyzer fixtures, lock assassin + hold budgets, journal =="
env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_analysis.py tests/test_lockorder.py tests/test_journal.py \
    -q -p no:cacheprovider

echo "== race: lockset detector must-fire gate + armed concurrency smoke =="
make race-test

echo "== memory: 50k-pod columnar-arena build vs committed per-pod bounds =="
env JAX_PLATFORMS=cpu python tools/memsmoke.py

echo "== hunt: planted-bug find -> confirm -> shrink -> promote + coverage artifact =="
# small-budget adversarial-hunt smoke: the planted mock.status.delay
# regression must be found, shrunk to <=2 DSL ops, and promoted. Promotion
# goes to a scratch dir (the committed corpus entry is maintained in-tree;
# CI only proves the lifecycle still works) and the coverage report is the
# archivable artifact.
HUNT_DIR="${KT_CI_ARTIFACTS:-/tmp/kt-ci}/hunt"
rm -rf "$HUNT_DIR" && mkdir -p "$HUNT_DIR"
env JAX_PLATFORMS=cpu python -m kube_throttler_tpu.scenarios.hunt smoke \
    --workdir "$HUNT_DIR" --report "$HUNT_DIR/hunt-coverage.json" \
    --promote-dir "$HUNT_DIR/promoted"
echo "hunt coverage artifact: $HUNT_DIR/hunt-coverage.json"

echo "== upgrade: reduced-scale rolling-upgrade smoke (live TCP fleet roll) =="
# one worker-first roll with a mid-roll SIGKILL plus the clean
# incompatible-major refusal, at smoke scale; the full matrix (both roll
# orders x seeds) stays `make upgrade-test`
env JAX_PLATFORMS=cpu python tools/upgradetest.py smoke

echo "ci gate: OK"
