#!/usr/bin/env bash
# CI gate: `make ci`. Static analysis failures fail CI, not review —
# the analyzer (8 checkers + the stale-waiver gate) runs first, then a
# fast smoke tier that proves the analyzer and the runtime lock
# assassin themselves work. The full tier-1 suite stays `make test`;
# this script is the cheap always-on gate (<~1 min).
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== lint: compileall + 8-checker static analysis + stale-waiver gate =="
make lint

echo "== smoke: analyzer fixtures, lock assassin + hold budgets, journal =="
env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_analysis.py tests/test_lockorder.py tests/test_journal.py \
    -q -p no:cacheprovider

echo "== memory: 50k-pod columnar-arena build vs committed per-pod bounds =="
env JAX_PLATFORMS=cpu python tools/memsmoke.py

echo "== hunt: planted-bug find -> confirm -> shrink -> promote + coverage artifact =="
# small-budget adversarial-hunt smoke: the planted mock.status.delay
# regression must be found, shrunk to <=2 DSL ops, and promoted. Promotion
# goes to a scratch dir (the committed corpus entry is maintained in-tree;
# CI only proves the lifecycle still works) and the coverage report is the
# archivable artifact.
HUNT_DIR="${KT_CI_ARTIFACTS:-/tmp/kt-ci}/hunt"
rm -rf "$HUNT_DIR" && mkdir -p "$HUNT_DIR"
env JAX_PLATFORMS=cpu python -m kube_throttler_tpu.scenarios.hunt smoke \
    --workdir "$HUNT_DIR" --report "$HUNT_DIR/hunt-coverage.json" \
    --promote-dir "$HUNT_DIR/promoted"
echo "hunt coverage artifact: $HUNT_DIR/hunt-coverage.json"

echo "ci gate: OK"
