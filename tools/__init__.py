# tools/ is importable (``from tools import harness``) so the crash, HA,
# and scenario harnesses can share one child-process toolkit instead of
# each growing its own copy.
