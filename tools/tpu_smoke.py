"""Manual TPU compatibility smoke: run every device kernel on real hardware.

Usage: python tools/tpu_smoke.py   (no env overrides — uses ambient platform)

Catches TPU-only lowering gaps (e.g. the X64 rewriter has no s64 dot_general)
that CPU-only unit tests cannot see.
"""

import random
import sys
import time
from datetime import datetime, timedelta, timezone

sys.path.insert(0, ".")

import jax
import numpy as np

from kube_throttler_tpu.api import ResourceAmount, TemporaryThresholdOverride, Throttle, ThrottleSpec
from kube_throttler_tpu.api.pod import make_pod
from kube_throttler_tpu.api.types import ThrottleSpecBase
from kube_throttler_tpu.ops import DimRegistry, check_pods, check_pods_compact, encode_pods, encode_throttle_state
from kube_throttler_tpu.ops.aggregate import aggregate_used, apply_pod_delta, throttled_flags
from kube_throttler_tpu.ops.overrides import calculate_thresholds, encode_override_schedule

NOW = datetime(2024, 1, 15, tzinfo=timezone.utc)


def main():
    print("devices:", jax.devices())
    rng = random.Random(0)
    throttles = [
        Throttle(name=f"t{i}", spec=ThrottleSpec(threshold=ResourceAmount.of(pod=3, requests={"cpu": "1", "memory": "4Gi"})))
        for i in range(64)
    ]
    pods = [make_pod(f"p{i}", requests={"cpu": "100m", "memory": "256Mi"}) for i in range(256)]
    dims = DimRegistry()
    state = encode_throttle_state(throttles, dims)
    batch = encode_pods(pods, dims)
    mask = np.asarray(rng.choices([True, False], k=256 * 64)).reshape(256, 64)

    t0 = time.perf_counter()
    full = check_pods(state, batch, mask)
    full.block_until_ready()
    print(f"check_pods compile+run: {time.perf_counter()-t0:.2f}s, result counts:",
          {int(v): int(c) for v, c in zip(*np.unique(np.asarray(full), return_counts=True))})

    counts, sched_ok = check_pods_compact(state, batch, mask)
    jax.block_until_ready((counts, sched_ok))
    print("compact ok; schedulable:", int(np.asarray(sched_ok).sum()))

    counted = np.ones(256, dtype=bool)
    used_cnt, used_req, contrib = aggregate_used(batch, mask, counted)
    jax.block_until_ready((used_cnt, used_req, contrib))
    print("aggregate ok; max used_req:", int(np.asarray(used_req).max()))

    ids = np.array([0, 1, 64], dtype=np.int32)
    sign = np.array([1, -1, 0], dtype=np.int64)
    out = apply_pod_delta(used_cnt, used_req, contrib, ids, sign,
                          np.asarray(batch.req[0]), np.asarray(batch.req_present[0]))
    jax.block_until_ready(out)
    print("scatter delta ok")

    flags = throttled_flags(state.thr_cnt, state.thr_cnt_present, state.thr_req,
                            state.thr_req_present, used_cnt, used_cnt > 0, used_req, contrib > 0)
    jax.block_until_ready(flags)
    print("throttled_flags ok")

    specs = [
        ThrottleSpecBase(
            threshold=ResourceAmount.of(pod=3, requests={"cpu": "500m"}),
            temporary_threshold_overrides=(
                TemporaryThresholdOverride(
                    begin=(NOW - timedelta(hours=1)).strftime("%Y-%m-%dT%H:%M:%SZ"),
                    end=(NOW + timedelta(hours=1)).strftime("%Y-%m-%dT%H:%M:%SZ"),
                    threshold=ResourceAmount.of(requests={"cpu": "2"}),
                ),
            ),
        )
        for _ in range(64)
    ]
    sched = encode_override_schedule(specs, dims)
    out = calculate_thresholds(sched, np.int64(int(NOW.timestamp() * 1e9)))
    jax.block_until_ready(out)
    print("calculate_thresholds ok")
    print("ALL TPU KERNELS OK")


if __name__ == "__main__":
    main()
