"""Manual TPU compatibility smoke: run every device kernel on real hardware.

Usage: python tools/tpu_smoke.py     — ambient platform (the TPU in CI).
An explicit JAX_PLATFORMS (e.g. =cpu) is honored for off-hardware dry
runs; note that skips the Pallas sweep, which only a TPU backend can
validate. Exit code is nonzero if any kernel fails.

Catches TPU-only lowering gaps (e.g. the X64 rewriter has no s64 dot_general)
that CPU-only unit tests cannot see.
"""

import random
import sys
import time
from datetime import datetime, timedelta, timezone

sys.path.insert(0, ".")

from kube_throttler_tpu.utils.platform import honor_jax_platforms_env

honor_jax_platforms_env()  # an explicit JAX_PLATFORMS wins over ambient pinning

import jax
import numpy as np

from kube_throttler_tpu.api import ResourceAmount, TemporaryThresholdOverride, Throttle, ThrottleSpec
from kube_throttler_tpu.api.pod import make_pod
from kube_throttler_tpu.api.types import ThrottleSpecBase
from kube_throttler_tpu.ops import DimRegistry, check_pods, check_pods_compact, encode_pods, encode_throttle_state
from kube_throttler_tpu.ops.aggregate import aggregate_used, apply_pod_delta, throttled_flags
from kube_throttler_tpu.ops.overrides import calculate_thresholds, encode_override_schedule

NOW = datetime(2024, 1, 15, tzinfo=timezone.utc)


def main():
    print("devices:", jax.devices())
    rng = random.Random(0)
    throttles = [
        Throttle(name=f"t{i}", spec=ThrottleSpec(threshold=ResourceAmount.of(pod=3, requests={"cpu": "1", "memory": "4Gi"})))
        for i in range(64)
    ]
    pods = [make_pod(f"p{i}", requests={"cpu": "100m", "memory": "256Mi"}) for i in range(256)]
    dims = DimRegistry()
    state = encode_throttle_state(throttles, dims)
    batch = encode_pods(pods, dims)
    mask = np.asarray(rng.choices([True, False], k=256 * 64)).reshape(256, 64)

    t0 = time.perf_counter()
    full = check_pods(state, batch, mask)
    full.block_until_ready()
    print(f"check_pods compile+run: {time.perf_counter()-t0:.2f}s, result counts:",
          {int(v): int(c) for v, c in zip(*np.unique(np.asarray(full), return_counts=True))})

    counts, sched_ok = check_pods_compact(state, batch, mask)
    jax.block_until_ready((counts, sched_ok))
    print("compact ok; schedulable:", int(np.asarray(sched_ok).sum()))

    counted = np.ones(256, dtype=bool)
    used_cnt, used_req, contrib = aggregate_used(batch, mask, counted)
    jax.block_until_ready((used_cnt, used_req, contrib))
    print("aggregate ok; max used_req:", int(np.asarray(used_req).max()))

    ids = np.array([0, 1, 64], dtype=np.int32)
    sign = np.array([1, -1, 0], dtype=np.int64)
    out = apply_pod_delta(used_cnt, used_req, contrib, ids, sign,
                          np.asarray(batch.req[0]), np.asarray(batch.req_present[0]))
    jax.block_until_ready(out)
    print("scatter delta ok")

    flags = throttled_flags(state.thr_cnt, state.thr_cnt_present, state.thr_req,
                            state.thr_req_present, used_cnt, used_cnt > 0, used_req, contrib > 0)
    jax.block_until_ready(flags)
    print("throttled_flags ok")

    specs = [
        ThrottleSpecBase(
            threshold=ResourceAmount.of(pod=3, requests={"cpu": "500m"}),
            temporary_threshold_overrides=(
                TemporaryThresholdOverride(
                    begin=(NOW - timedelta(hours=1)).strftime("%Y-%m-%dT%H:%M:%SZ"),
                    end=(NOW + timedelta(hours=1)).strftime("%Y-%m-%dT%H:%M:%SZ"),
                    threshold=ResourceAmount.of(requests={"cpu": "2"}),
                ),
            ),
        )
        for _ in range(64)
    ]
    sched = encode_override_schedule(specs, dims)
    out = calculate_thresholds(sched, np.int64(int(NOW.timestamp() * 1e9)))
    jax.block_until_ready(out)
    print("calculate_thresholds ok")

    # the serving hot path: packed residual-form indexed single-pod check
    from kube_throttler_tpu.ops.fastcheck import (
        fast_check_pod_packed,
        pack_check_state,
        precompute_check_state,
    )

    packed = pack_check_state(precompute_check_state(state))
    idx = np.zeros(8, dtype=np.int32)
    idx[:3] = [0, 5, 63]
    idx_valid = np.zeros(8, dtype=bool)
    idx_valid[:3] = True
    out = fast_check_pod_packed(
        packed, np.asarray(batch.req[0]), np.asarray(batch.req_present[0]),
        idx, idx_valid, False, True,
    )
    jax.block_until_ready(out)
    print("fast_check_pod_packed ok")

    # streaming-batch + rebase kernels (the reconcile data plane)
    from kube_throttler_tpu.ops.aggregate import apply_pod_deltas_batched, rebase_cols

    nb, kmax, R = 32, 4, dims.capacity
    bids = np.full((nb, kmax), 64, dtype=np.int32)
    bids[0, :2] = [1, 2]
    bsign = np.zeros((nb, kmax), dtype=np.int64)
    bsign[0, :2] = 1
    breq = np.zeros((nb, R), dtype=np.int64)
    bpresent = np.zeros((nb, R), dtype=bool)
    out = apply_pod_deltas_batched(used_cnt, used_req, contrib, bids, bsign, breq, bpresent)
    jax.block_until_ready(out)
    print("apply_pod_deltas_batched ok")
    cols_pad = np.full(8, 64, dtype=np.int32)
    cols_pad[:2] = [0, 1]
    out = rebase_cols(used_cnt, used_req, contrib, batch, mask, counted, cols_pad)
    jax.block_until_ready(out)
    print("rebase_cols ok")
    from kube_throttler_tpu.ops.aggregate import aggregate_cols

    out = aggregate_cols(batch, mask, counted, cols_pad)
    jax.block_until_ready(out)
    print("aggregate_cols ok")

    # the sparse [P,K] gather check — the production batch-triage kernel
    from kube_throttler_tpu.ops.check import check_pods_gather

    gcols = np.full((mask.shape[0], 4), -1, dtype=np.int32)
    for i in range(mask.shape[0]):
        nz = np.nonzero(mask[i])[0][:4]
        gcols[i, : nz.size] = nz
    counts_g, ok_g = check_pods_gather(state, batch, gcols)
    jax.block_until_ready((counts_g, ok_g))
    print("check_pods_gather ok")

    # full-scale gather-memory smoke (TPU backends only): dispatch the
    # shapes that OOM'd a 16G v5e in r5 before the R-leading orientation +
    # P-chunking fix — [131072, 64] (the observed failure) and the
    # [131072, 2048] worst rung (exercises the lax.map block decomposition
    # on real hardware, which interpret-mode tests cannot)
    if jax.devices()[0].platform != "cpu":
        import bench as _bench
        from kube_throttler_tpu.ops.schema import PodBatch as _PodBatch

        nprng = np.random.default_rng(0)
        big_state = _bench.synth_state(nprng, 10240, 8)
        # pods built directly — bench.synth_pods also materializes the
        # dense [P,T] mask (~1.3 GB host) the gather path never reads
        big_req = np.zeros((131072, 8), dtype=np.int64)
        big_req[:, 0] = nprng.integers(100, 2000, size=131072)
        big_present = np.zeros((131072, 8), dtype=bool)
        big_present[:, 0] = True
        big_batch = _PodBatch(
            valid=np.ones(131072, dtype=bool), req=big_req, req_present=big_present
        )
        for K in (64, 2048):
            # int32 draws + in-place masking keep the host peak ~2 GB at
            # K=2048 (float64 random + int64 where-intermediates hit ~6 GB)
            big_cols = nprng.integers(0, 10240, (131072, K), dtype=np.int32)
            drop = nprng.random((131072, K), dtype=np.float32) >= 0.3
            big_cols[drop] = -1
            del drop
            t0 = time.perf_counter()
            out = check_pods_gather(big_state, big_batch, big_cols)
            jax.block_until_ready(out)
            print(
                f"full-scale gather K={K} ok "
                f"({time.perf_counter()-t0:.1f}s incl. compile — no HBM OOM)"
            )

    # the Pallas mosaic sweep (TPU backends only): block-padded shapes,
    # precomputed residual form, compared against check_pods on the same
    # padded state — the one kernel only real hardware can validate
    failed = []
    if jax.devices()[0].platform != "cpu":
        try:
            from kube_throttler_tpu.ops.pallas_check import BP, BT, pallas_check_pods

            p_state = encode_throttle_state(throttles, dims, capacity=BT)
            p_batch = encode_pods(pods, dims, capacity=BP)
            p_mask = np.asarray(rng.choices([True, False], k=BP * BT)).reshape(BP, BT)
            want = np.asarray(check_pods(p_state, p_batch, p_mask))
            got = np.asarray(
                pallas_check_pods(precompute_check_state(p_state), p_batch, p_mask)
            )
            np.testing.assert_array_equal(got, want)
            print("pallas sweep ok (matches XLA)")
        except Exception as e:  # noqa: BLE001 — report now, fail at exit
            failed.append(f"pallas: {e.__class__.__name__}: {str(e)[:200]}")
            print(f"pallas sweep FAILED: {failed[-1]}")

    # the full serving-stack prewarm ladder (every bucketed shape compiles)
    from kube_throttler_tpu.api.pod import Namespace
    from kube_throttler_tpu.engine.store import Store
    from kube_throttler_tpu.plugin import KubeThrottler, decode_plugin_args

    store = Store()
    plugin = KubeThrottler(
        decode_plugin_args({"name": "kt", "targetSchedulerName": "s"}),
        store,
        use_device=True,
        start_workers=False,
    )
    store.create_namespace(Namespace("default"))
    t0 = time.perf_counter()
    n = plugin.device_manager.prewarm()
    print(f"prewarm ok: {n} shapes in {time.perf_counter()-t0:.1f}s")
    if failed:
        print("SMOKE FAILED:", "; ".join(failed))
        sys.exit(1)
    print("ALL TPU KERNELS OK")


if __name__ == "__main__":
    main()
