#!/usr/bin/env sh
# Build the daemon container image (reference release.yaml's docker step).
# Usage: tools/build_image.sh [tag] [extra docker build args...]
#   tools/build_image.sh                      # kube-throttler-tpu:latest
#   tools/build_image.sh v0.1.0
#   tools/build_image.sh latest --build-arg JAX_EXTRA="jax[tpu]"
set -eu

TAG="${1:-latest}"
[ "$#" -gt 0 ] && shift

if command -v docker >/dev/null 2>&1; then
    ENGINE=docker
elif command -v podman >/dev/null 2>&1; then
    ENGINE=podman
else
    echo "error: neither docker nor podman found on PATH" >&2
    exit 1
fi

cd "$(dirname "$0")/.."
exec "$ENGINE" build -t "kube-throttler-tpu:${TAG}" "$@" .
