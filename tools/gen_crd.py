"""Emit deploy/crd.yaml from the typed API model (``make gen``).

The TPU build's equivalent of the reference's controller-gen step
(Makefile:40-42): schemas are derived in kube_throttler_tpu/api/crd.py from
the dataclasses in api/types.py, so the CRD can never drift from the code.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import yaml

from kube_throttler_tpu.api import crd


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--out",
        default=str(Path(__file__).resolve().parent.parent / "deploy" / "crd.yaml"),
    )
    out = Path(parser.parse_args(argv).out)
    docs = [crd.cluster_throttle_crd(), crd.throttle_crd()]
    text = "---\n" + "---\n".join(
        yaml.safe_dump(d, sort_keys=True, default_flow_style=False) for d in docs
    )
    out.parent.mkdir(exist_ok=True)
    out.write_text(text)
    print(f"wrote {out} ({len(text.splitlines())} lines, {len(docs)} documents)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
