"""Persistent TPU-availability watcher: capture the bench the moment the tunnel is up.

The tunnel to the real TPU chip has been down for entire working sessions
(rounds 2-4 each ended with a degraded CPU-only BENCH). This watcher runs
from session start: it probes the backend in a throwaway subprocess every
PROBE_INTERVAL seconds and, the FIRST time the probe succeeds, immediately
runs the full benchmark on the live backend and writes the resulting JSON
line to BENCH_TPU_<utcstamp>.json (plus BENCH_TPU_LATEST.json). One
successful capture ends the watch; a deadline (default 11h) bounds it.

While the bench is running it holds a marker file (/tmp/tpu_bench_running)
so interactive measurement work on this 1-core host knows not to trust
concurrent timings.

Usage: python tools/tpu_watch.py [--deadline-s N] [--interval-s N] [--quick]
Exit code: 0 = TPU bench captured, 1 = deadline expired with no backend.
"""

import argparse
import json
import os
import subprocess
import sys
import time
from datetime import datetime, timezone

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MARKER = "/tmp/tpu_bench_running"


def log(msg: str) -> None:
    ts = datetime.now(timezone.utc).strftime("%H:%M:%S")
    print(f"[tpu-watch {ts}] {msg}", flush=True)


try:
    TUNNEL_PORT = int(os.environ.get("KT_TUNNEL_PROBE_PORT", "8103"))
except ValueError:
    TUNNEL_PORT = 8103  # malformed override must not kill an 11h watch
# the tunnel terminal is localhost in every deployment so far, but a
# non-local terminal otherwise forces the FULL_PROBE_EVERY fallback for
# the whole watch — make the host overridable and LOGGED (ADVICE r5)
TUNNEL_HOST = os.environ.get("KT_TUNNEL_PROBE_HOST", "127.0.0.1")

# every Nth attempt runs the full jax probe even when the port pre-probe
# says down — a rotated/wrong port can then cost at most N-1 intervals,
# not the whole watch
FULL_PROBE_EVERY = 10


def _tunnel_port_up(timeout: float = 3.0) -> bool:
    """Zero-CPU pre-probe: the tunnel terminal's local HTTP port refuses
    connections while the backend is down. Gating the heavy jax-import
    subprocess on this keeps an armed watcher from stealing ~5-8s of CPU
    per probe on a 1-core host — measured polluting concurrent bench
    percentile windows (p99 0.16ms → 6.8ms at the full-scale config)."""
    import socket

    try:
        with socket.create_connection((TUNNEL_HOST, TUNNEL_PORT), timeout=timeout):
            return True
    except OSError:
        return False


def probe_once(timeout: float = 60.0, force_full: bool = False) -> bool:
    """True when a throwaway subprocess can init the ambient (TPU) backend
    AND it is not just the CPU fallback platform. The expensive subprocess
    only runs after the zero-CPU port pre-probe succeeds (or on the
    periodic forced full probe — see FULL_PROBE_EVERY)."""
    if not _tunnel_port_up():
        if not force_full:
            return False
        log(
        f"{TUNNEL_HOST}:{TUNNEL_PORT} closed; running the periodic full "
        "probe anyway"
    )
    code = (
        f"import sys; sys.path.insert(0, {REPO!r})\n"
        "from kube_throttler_tpu.utils.platform import honor_jax_platforms_env\n"
        "honor_jax_platforms_env()\n"
        "import jax\n"
        "d = jax.devices()\n"
        "assert d and d[0].platform != 'cpu', f'cpu-only: {d}'\n"
        "print(d[0].platform)\n"
    )
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True, timeout=timeout, env=env)
    except subprocess.TimeoutExpired:
        return False
    if r.returncode == 0:
        log(f"probe OK: platform={r.stdout.decode().strip()}")
        return True
    return False


def run_bench(quick: bool) -> int:
    stamp = datetime.now(timezone.utc).strftime("%Y%m%dT%H%M%SZ")
    out_path = os.path.join(REPO, f"BENCH_TPU_{stamp}.json")
    cmd = [sys.executable, os.path.join(REPO, "bench.py")]
    if quick:
        cmd.append("--quick")
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    # Full-scale on TPU should fit well inside this; the bench's own
    # watchdog emits best-so-far JSON if a config wedges.
    env.setdefault("KT_BENCH_DEADLINE_S", "3600")
    log(f"backend is up — running bench -> {out_path}")
    open(MARKER, "w").write(stamp)
    try:
        with open(out_path + ".log", "w") as logf:
            r = subprocess.run(cmd, stdout=subprocess.PIPE, stderr=logf, timeout=4200, env=env, cwd=REPO)
    except subprocess.TimeoutExpired:
        log("bench subprocess timed out (4200s)")
        return 1
    finally:
        try:
            os.unlink(MARKER)
        except OSError:
            pass
    # bench.py prints exactly one JSON line on stdout (watchdog or main
    # path); validate before declaring the one-shot watch done — a stray
    # warning/traceback line must not end an 11h watch with garbage
    lines = r.stdout.decode(errors="replace").strip().splitlines()
    payload = None
    for cand in reversed(lines):
        try:
            json.loads(cand)
            payload = cand
            break
        except ValueError:
            continue
    if payload is None:
        log(f"bench produced no JSON line (rc={r.returncode}); see {out_path}.log")
        return 1
    with open(out_path, "w") as f:
        f.write(payload + "\n")
    with open(os.path.join(REPO, "BENCH_TPU_LATEST.json"), "w") as f:
        f.write(payload + "\n")
    log(f"captured: {payload[:300]}")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--deadline-s", type=float, default=11 * 3600)
    ap.add_argument("--interval-s", type=float, default=180.0)
    ap.add_argument("--quick", action="store_true", help="run bench --quick instead of full scale")
    args = ap.parse_args()

    # state the configured pre-probe host:port once: a silently wrong
    # endpoint (env typo, rotated tunnel, non-local terminal) otherwise
    # just reads as "backend down" for up to FULL_PROBE_EVERY-1 intervals
    # with nothing in the log to diagnose (ADVICE r5)
    log(
        f"pre-probe {TUNNEL_HOST}:{TUNNEL_PORT} "
        f"(KT_TUNNEL_PROBE_HOST={os.environ.get('KT_TUNNEL_PROBE_HOST', 'unset')}, "
        f"KT_TUNNEL_PROBE_PORT={os.environ.get('KT_TUNNEL_PROBE_PORT', 'unset')}); "
        f"full jax probe every {FULL_PROBE_EVERY} attempts regardless"
    )
    deadline = time.monotonic() + args.deadline_s
    attempt = 0
    while time.monotonic() < deadline:
        attempt += 1
        if probe_once(force_full=attempt % FULL_PROBE_EVERY == 1):
            if run_bench(args.quick) == 0:
                log("TPU bench captured; watcher done")
                return 0
            log("bench failed despite live probe; will re-probe")
        elif attempt % 10 == 1:
            log(f"probe {attempt}: backend down")
        time.sleep(args.interval_s)
    log("deadline expired; backend never came up")
    return 1


if __name__ == "__main__":
    sys.exit(main())
