#!/usr/bin/env python
"""Live rolling-upgrade chaos matrix (`make upgrade-test`).

A real fleet upgrade is a sequence of process bounces under load with
version skew in between: for a window, old and new builds share one
fleet and every wire/durable format crosses the boundary. This harness
drives that window against a LIVE 3-worker ``transport="tcp"`` shard
fleet (real processes over loopback, HMAC-keyed frames) while a
background churner swings group sums across flip thresholds, scatters
``pre_filter`` RPCs, and runs two-phase reserve/unreserve — the
composed bad-day storm the fleet is rolled under.

Cases (x seeds, ``matrix``):

- **worker_first** — the fleet starts ALL-OLD (capabilities masked via
  ``KT_PROTO_CAPS_MASK``, the zero-cap 1.0 baseline). Workers are
  rolled to the new build one at a time behind the resync barrier
  (``ShardSupervisor.rolling_restart``) while the front still speaks the
  old baseline (mixed skew: new workers negotiate DOWN to the pickle
  fallback), then the front upgrades and a second re-handshake roll
  brings every lane to the full capability set. One already-bounced
  shard is SIGKILLed MID-ROLL; the monitor must restore it without
  perturbing the roll's one-at-a-time discipline.
- **front_first** — the mirror order: the front advertises the full set
  first (new front + old workers negotiate the old baseline), then the
  workers roll to the new build.
- **incompatible_major** — a worker is rolled onto ``KT_PROTO_MAJOR=99``:
  the bounce must FAIL CLEANLY — typed ``VersionMismatch`` refusal,
  degraded health naming the mismatch, counted metric, paced retries
  (no crash loop) — and rolling back the override must heal the shard.

Oracle after every case (tools/netchaostest.py helpers): ZERO wrong
verdicts vs a single-process rebuild, ZERO lost flips, ZERO orphan
reservations, and every bounce's wall-clock bounded.

Run: ``python tools/upgradetest.py matrix`` (``make upgrade-test``);
``smoke`` is the reduced-scale CI gate (hack/ci.sh).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tools.netchaostest import audit_all, churn, final_state  # noqa: E402

SEEDS = (0, 1)

OLD_MASK = ""  # zero capabilities: the pre-capability 1.0 baseline


def _new_caps() -> str:
    from kube_throttler_tpu.version import CAPABILITIES

    return ",".join(sorted(CAPABILITIES))


def _set_env(var: str, value) -> None:
    if value is None:
        os.environ.pop(var, None)
    else:
        os.environ[var] = value


def build_fleet(n_shards=3, n_throttles=24, n_pods=160, n_reserved=8,
                rpc_deadline=10.0, worker_env=None):
    """netchaostest.build_fleet with per-side skew control: the front's
    hello reads ``os.environ`` at dial time (mask it BEFORE calling),
    while ``worker_env`` entries land in the supervisor's child env —
    explicit entries there win over the os.environ passthrough, so the
    two sides of the wire can run different advertised versions."""
    import tools.harness as H
    from kube_throttler_tpu.api.pod import Namespace, make_pod
    from kube_throttler_tpu.sharding.front import AdmissionFront
    from kube_throttler_tpu.sharding.supervisor import ShardSupervisor

    env = {**os.environ, "KT_SHARD_QUIET": "1", "KT_LOCK_ASSERT": "0"}
    env.update(worker_env or {})
    front = AdmissionFront(n_shards, rpc_deadline=rpc_deadline)
    supervisor = ShardSupervisor(
        front,
        transport="tcp",
        use_device=False,
        restart_backoff=0.3,
        env=env,
        auth_key=b"upgrade-matrix-psk",
    )
    supervisor.start(ready_timeout=300.0)
    try:
        front.store.create_namespace(Namespace("default"))
        for i in range(n_throttles):
            front.store.create_throttle(H.make_throttle(i))
        pods = []
        for i in range(n_pods):
            pod = make_pod(
                f"p{i}", labels={"grp": f"g{i % n_throttles}"},
                requests={"cpu": "100m"},
            )
            front.store.create_pod(pod)
            pods.append(pod)
        assert front.drain(120.0)
        time.sleep(0.3)
        for pod in pods[:n_reserved]:
            status = front.reserve(pod)
            assert status.is_success(), status.reasons
    except BaseException:
        supervisor.stop()
        front.stop()
        raise
    return front, supervisor, pods


class Churner(threading.Thread):
    """Background bad-day storm: keeps the churn/scatter/two-phase load
    running THROUGH every bounce (storm-time refusals are fail-safe by
    design; only the post-roll equality gates count)."""

    def __init__(self, front, pods):
        super().__init__(name="upgrade-churner", daemon=True)
        self.front = front
        self.pods = pods
        self.halt = threading.Event()

    def run(self) -> None:
        while not self.halt.is_set():
            try:
                churn(self.front, self.pods, rounds=1, per_round=40)
            except Exception:  # noqa: BLE001 — the storm never kills itself
                time.sleep(0.2)

    def stop(self) -> None:
        self.halt.set()
        if self.ident is not None:  # join only once actually started
            self.join(timeout=30.0)


def _caps_of(front, sid) -> frozenset:
    handle = front.shards.get(sid)
    return frozenset(getattr(handle, "negotiated_caps", frozenset()) or frozenset())


def _wait_fleet_ok(front, recovery_s: float) -> None:
    deadline = time.monotonic() + recovery_s
    while time.monotonic() < deadline:
        state, _ = front._shards_health()
        if state == "ok":
            return
        time.sleep(0.1)
    raise AssertionError(f"fleet never recovered: {front._shards_health()}")


def _final_gates(front, result) -> None:
    assert front.drain(120.0)
    time.sleep(0.5)
    wrong, stale = final_state(front)
    assert not wrong, f"wrong verdicts after the roll: {wrong[:3]}"
    assert not stale, f"lost flips after the roll: {stale[:3]}"
    bad = audit_all(front)
    assert not bad, f"orphan audit failed: {bad}"
    result["ok"] = True


def case_worker_first(seed, n_pods=160, bounce_bound_s=90.0,
                      kill_mid_roll=True, recovery_s=60.0):
    """All-old fleet; workers roll to new under the old front (pickle
    fallback skew), one already-bounced shard is SIGKILLed mid-roll,
    then the front upgrades and a second roll re-handshakes every lane
    up to the full capability set."""
    result = {"case": "worker_first", "seed": seed}
    _set_env("KT_PROTO_CAPS_MASK", OLD_MASK)  # the front speaks the baseline
    try:
        front, supervisor, pods = build_fleet(
            n_pods=n_pods, worker_env={"KT_PROTO_CAPS_MASK": OLD_MASK},
        )
    except BaseException:
        _set_env("KT_PROTO_CAPS_MASK", None)
        raise
    churner = Churner(front, pods)
    try:
        for sid in range(front.n_shards):
            assert not _caps_of(front, sid), (
                f"shard {sid} negotiated caps on an all-old fleet"
            )
        churner.start()
        # stage the WORKER upgrade: children spawned from here advertise
        # the full set (explicit env entry wins over the front's mask)
        supervisor.env["KT_PROTO_CAPS_MASK"] = _new_caps()
        bounced, killed = [], {}

        def gate(sid):
            bounced.append(sid)
            if kill_mid_roll and len(bounced) == 2 and not killed:
                # mid-roll SIGKILL of a NON-bouncing shard: the monitor
                # (not the roll) must restore it, with the roll's
                # one-at-a-time discipline undisturbed
                victim = bounced[0]
                proc = supervisor.shard_proc(victim)
                if proc is not None and proc.poll() is None:
                    proc.kill()
                    killed["shard"] = victim
            return None

        report = supervisor.rolling_restart(
            ready_timeout=60.0, settle_timeout=60.0, gate=gate,
        )
        assert report["aborted"] is None, report["aborted"]
        slow = [b for b in report["bounces"] if b["seconds"] > bounce_bound_s]
        assert not slow, f"bounce recovery exceeded {bounce_bound_s}s: {slow}"
        result["kill"] = killed.get("shard")
        _wait_fleet_ok(front, recovery_s)
        # mixed-skew window held: new workers, old front → every lane
        # negotiated DOWN to the zero-cap baseline
        for sid in range(front.n_shards):
            assert not _caps_of(front, sid), (
                f"shard {sid} negotiated caps past the front's mask"
            )
        # upgrade the FRONT: full advertisement + a re-handshake roll
        _set_env("KT_PROTO_CAPS_MASK", None)
        report2 = supervisor.rolling_restart(
            ready_timeout=60.0, settle_timeout=60.0,
        )
        assert report2["aborted"] is None, report2["aborted"]
        churner.stop()
        _wait_fleet_ok(front, recovery_s)
        from kube_throttler_tpu.version import CAPABILITIES

        for sid in range(front.n_shards):
            assert _caps_of(front, sid) == CAPABILITIES, (
                f"shard {sid} did not land on the full capability set: "
                f"{_caps_of(front, sid)}"
            )
        result["bounces"] = len(report["bounces"]) + len(report2["bounces"])
        _final_gates(front, result)
        return result
    finally:
        churner.stop()
        _set_env("KT_PROTO_CAPS_MASK", None)
        supervisor.stop()
        front.stop()


def case_front_first(seed, n_pods=160, bounce_bound_s=90.0, recovery_s=60.0):
    """All-old fleet; the FRONT upgrades first (new front + old workers
    negotiate the baseline), then the workers roll to the new build."""
    result = {"case": "front_first", "seed": seed}
    _set_env("KT_PROTO_CAPS_MASK", OLD_MASK)
    try:
        front, supervisor, pods = build_fleet(
            n_pods=n_pods, worker_env={"KT_PROTO_CAPS_MASK": OLD_MASK},
        )
    except BaseException:
        _set_env("KT_PROTO_CAPS_MASK", None)
        raise
    churner = Churner(front, pods)
    try:
        churner.start()
        # the front upgrades FIRST: full advertisement on every dial from
        # here on; workers stay masked (their env entry is explicit)
        _set_env("KT_PROTO_CAPS_MASK", None)
        report = supervisor.rolling_restart(
            ready_timeout=60.0, settle_timeout=60.0,
        )
        assert report["aborted"] is None, report["aborted"]
        _wait_fleet_ok(front, recovery_s)
        # mixed-skew window: new front, old workers → baseline everywhere
        for sid in range(front.n_shards):
            assert not _caps_of(front, sid), (
                f"old worker {sid} negotiated caps it never advertised"
            )
        # then the workers roll to the new build
        supervisor.env.pop("KT_PROTO_CAPS_MASK", None)
        report2 = supervisor.rolling_restart(
            ready_timeout=60.0, settle_timeout=60.0,
        )
        assert report2["aborted"] is None, report2["aborted"]
        churner.stop()
        _wait_fleet_ok(front, recovery_s)
        from kube_throttler_tpu.version import CAPABILITIES

        for sid in range(front.n_shards):
            assert _caps_of(front, sid) == CAPABILITIES
        slow = [
            b for b in report["bounces"] + report2["bounces"]
            if b["seconds"] > bounce_bound_s
        ]
        assert not slow, f"bounce recovery exceeded {bounce_bound_s}s: {slow}"
        result["bounces"] = len(report["bounces"]) + len(report2["bounces"])
        _final_gates(front, result)
        return result
    finally:
        churner.stop()
        _set_env("KT_PROTO_CAPS_MASK", None)
        supervisor.stop()
        front.stop()


def case_incompatible_major(seed, n_pods=80, recovery_s=60.0):
    """A worker rolled onto an incompatible protocol major must refuse
    CLEANLY: typed VersionMismatch on the handle, degraded fleet health
    naming the mismatch, the counter bumped, no restart hot loop — and
    rolling the override back must heal the shard."""
    result = {"case": "incompatible_major", "seed": seed}
    _set_env("KT_PROTO_MAJOR", None)
    front, supervisor, pods = build_fleet(n_shards=2, n_pods=n_pods)
    try:
        restarts_before = dict(supervisor.restart_counts())
        supervisor.env["KT_PROTO_MAJOR"] = "99"
        report = supervisor.rolling_restart(
            shard_ids=[1], ready_timeout=6.0, settle_timeout=6.0,
        )
        assert report["aborted"] is not None, (
            "an incompatible-major bounce must abort the roll"
        )
        handle = front.shards.get(1)
        refused = getattr(handle, "version_refused", None)
        assert refused and "VersionMismatch" in str(refused), (
            f"no typed refusal on the handle: {refused!r}"
        )
        assert getattr(handle, "version_mismatches", 0) >= 1
        state, detail = front._shards_health()
        assert state != "ok", "fleet health ignored a version refusal"
        assert "version-mismatch" in json.dumps(detail), detail
        # no crash loop: the refusing worker keeps LISTENING (only the
        # lane died); the monitor must not burn restart budget on it
        time.sleep(1.5)
        after = dict(supervisor.restart_counts())
        churn_restarts = after.get(1, 0) - restarts_before.get(1, 0)
        assert churn_restarts <= 1, (
            f"restart hot loop on a version refusal: {churn_restarts} restarts"
        )
        result["refusal"] = str(refused)
        # heal: drop the override, roll the shard back
        supervisor.env.pop("KT_PROTO_MAJOR", None)
        report2 = supervisor.rolling_restart(
            shard_ids=[1], ready_timeout=60.0, settle_timeout=60.0,
        )
        assert report2["aborted"] is None, report2["aborted"]
        _wait_fleet_ok(front, recovery_s)
        _final_gates(front, result)
        return result
    finally:
        _set_env("KT_PROTO_MAJOR", None)
        supervisor.stop()
        front.stop()


CASES = (
    ("worker_first", case_worker_first),
    ("front_first", case_front_first),
    ("incompatible_major", case_incompatible_major),
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="upgradetest")
    sub = parser.add_subparsers(dest="command", required=True)
    m = sub.add_parser("matrix", help="every roll order x seeds")
    m.add_argument("--seeds", default=",".join(str(s) for s in SEEDS))
    m.add_argument("--json", default="", help="write the matrix report here")
    one = sub.add_parser("one", help="a single case")
    one.add_argument("--case", required=True,
                     choices=[name for name, _ in CASES])
    one.add_argument("--seed", type=int, default=0)
    sub.add_parser("smoke", help="reduced-scale CI gate (hack/ci.sh)")
    args = parser.parse_args(argv)

    from kube_throttler_tpu.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()

    if args.command == "one":
        fn = dict(CASES)[args.case]
        result = fn(args.seed)
        print(json.dumps(result, indent=2))
        return 0

    if args.command == "smoke":
        t0 = time.monotonic()
        result = case_worker_first(0, n_pods=60, kill_mid_roll=True)
        print(f"smoke worker_first ok ({time.monotonic() - t0:.1f}s, "
              f"{result['bounces']} bounces, killed shard {result['kill']})")
        t0 = time.monotonic()
        case_incompatible_major(0, n_pods=40)
        print(f"smoke incompatible_major ok ({time.monotonic() - t0:.1f}s)")
        print("upgrade smoke: clean roll, clean refusal")
        return 0

    seeds = [int(s) for s in args.seeds.split(",") if s != ""]
    results, failures = [], 0
    for name, fn in CASES:
        for seed in seeds:
            t0 = time.monotonic()
            try:
                result = fn(seed)
                result["wall_s"] = round(time.monotonic() - t0, 1)
                results.append(result)
                print(f"PASS {name:<20} seed={seed} ({result['wall_s']}s)")
            except Exception as e:  # noqa: BLE001 — matrix reports, then fails
                failures += 1
                results.append({"case": name, "seed": seed, "error": repr(e)})
                print(f"FAIL {name:<20} seed={seed}: {e!r}")
    total = len(CASES) * len(seeds)
    print(f"\n{total - failures}/{total} rolling-upgrade paths clean "
          "(zero wrong verdicts, zero lost flips, zero orphan reservations)")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=2)
            f.write("\n")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
