#!/usr/bin/env python
"""Network-fault chaos matrix for the cross-host TCP shard transport.

Every ``net.*`` framing-layer fault site (faults/plan.py), armed
client-side against one shard of a LIVE 2-worker ``transport="tcp"``
fleet (real processes dialed over loopback — the same stack
``--shard-transport tcp`` serves), x 3 seeds:

    net.connect.refused:error   dials refused during reconnect; backoff
                                must retry through to the heal
    net.send.torn_frame:torn    a frame tears mid-write; the worker sees
                                a short read and drops the lane cleanly
    net.recv.stall:delay        the client's reader stalls mid-frame;
                                RPCs ride the per-op deadline, not hang
    net.partition:error         sends blackhole (asymmetric partition);
                                degraded fail-safe verdicts, then heal ⇒
                                epoch-bumped resync + re-push
    net.reconnect.storm:error   every fresh connection dies at birth;
                                jittered backoff must converge anyway

While the fault is live the driver keeps churning pod events across
flip thresholds, scattering ``pre_filter`` RPCs, and running
reserve/unreserve two-phase transactions. After the heal the matrix
asserts the full recovery contract:

- the armed site actually FIRED (an unfired rule is a vacuous pass);
- every shard reconnected and reports ``ok`` (no supervisor restart —
  transient network loss must not look like process death);
- ZERO wrong verdicts vs a single-process oracle rebuilt from the final
  state (code + normalized reasons);
- ZERO lost flips: every published ``status.throttled`` equals the
  oracle's recompute;
- ZERO orphan reservations: every worker's ``reshard_audit`` is clean —
  a reserve whose prepare outran the deadline must have been aborted on
  every target, not stranded.

An **SHM column** (``--only shm``) runs the zero-copy event-plane fault
sites against a LIVE socketpair fleet with the per-shard shared-memory
ring active (the default spawn path), x the same seeds:

    shm.ring.full:delay         a saturated ring: the writer takes a
                                counted backpressure wait — never a
                                silent drop of a non-sheddable op
    shm.slot.torn_commit:torn   a commit word dies mid-write; the reader
                                detects the torn slot, the worker dies
                                as a unit, and the supervisor's restart
                                + resync brings a FRESH segment
    shm.doorbell.lost:error     lost wakeup bytes; the reader's bounded
                                poll slice turns them into latency only
    shm.reader.stall:delay      a slow consumer (worker-side rule); the
                                lane backpressures, nothing is lost
    shm.segment.unlink:error    the restart-path unlink is lost; the
                                supervisor's sweep backstop must leave
                                /dev/shm clean at stop

SHM cases assert the same zero-wrong-verdict / zero-lost-flip / zero-
orphan gates, plus: the event plane is ACTIVE pre-fault (no silent
pickle fallback masking the matrix), restarts happen exactly when the
case expects them (torn commit: yes; everything else: no), and no
``kt_evt_*`` segment survives the final stop.

Run: ``python tools/netchaostest.py matrix`` (``make net-chaos``); the
tier-1 smoke (tests/test_net_transport.py) runs one case small.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

SEEDS = (0, 1, 2)

# site → (mode, rule kwargs): windowless rules stay finite so the fleet
# always heals inside the case budget (an unbounded blackhole would gate
# the harness's patience, not the code)
CASES = (
    ("net.connect.refused", "error", {"times": 2}),
    ("net.send.torn_frame", "torn", {"times": 2}),
    ("net.recv.stall", "delay", {"times": 3, "delay": 0.5}),
    ("net.partition", "error", {"times": 6}),
    ("net.reconnect.storm", "error", {"times": 2}),
)

# these sites only fire while (re)connecting — pair them with one torn
# frame so the established lane actually drops and the dial path runs
_NEEDS_SEVER = ("net.connect.refused", "net.reconnect.storm")

# shm column: (site, mode, front-side rule kwargs | None, worker
# --fault-site arg | None, expect_restart). Front-side rules arm the
# plan BEFORE spawn (the ring writer captures it at construction);
# worker-side rules ride the worker CLI. shm.segment.unlink needs a
# writer close to fire, so it's paired with one torn commit (the
# restart path closes the old handle) and the sweep backstop carries
# the cleanup contract.
SHM_CASES = (
    ("shm.ring.full", "delay", {"times": 3, "delay": 0.3}, None, False),
    ("shm.slot.torn_commit", "torn", {"times": 1}, None, True),
    ("shm.doorbell.lost", "error", {"times": 5}, None, False),
    ("shm.reader.stall", "delay", None, "shm.reader.stall:delay:2:0.5", False),
    ("shm.segment.unlink", "error", {"times": 1}, None, True),
)


def build_fleet(n_shards=2, n_throttles=24, n_pods=160, n_reserved=8,
                rpc_deadline=10.0, transport="tcp", faults=None,
                worker_args=None):
    import tools.harness as H
    from kube_throttler_tpu.api.pod import Namespace, make_pod
    from kube_throttler_tpu.sharding.front import AdmissionFront
    from kube_throttler_tpu.sharding.supervisor import ShardSupervisor

    front = AdmissionFront(n_shards, rpc_deadline=rpc_deadline, faults=faults)
    supervisor = ShardSupervisor(
        front,
        transport=transport,
        use_device=False,
        restart_backoff=0.3,
        env={**os.environ, "KT_SHARD_QUIET": "1", "KT_LOCK_ASSERT": "0"},
        worker_args=list(worker_args or []),
        # the matrix runs the KEYED framing (HMAC per frame) so every
        # fault path is exercised through the cross-host trust boundary,
        # not the loopback-only keyless shortcut
        auth_key=b"netchaos-matrix-psk" if transport == "tcp" else None,
    )
    supervisor.start(ready_timeout=300.0)
    try:
        front.store.create_namespace(Namespace("default"))
        for i in range(n_throttles):
            front.store.create_throttle(H.make_throttle(i))
        pods = []
        for i in range(n_pods):
            pod = make_pod(
                f"p{i}", labels={"grp": f"g{i % n_throttles}"},
                requests={"cpu": "100m"},
            )
            front.store.create_pod(pod)
            pods.append(pod)
        assert front.drain(120.0)
        time.sleep(0.3)
        # live reservations make the orphan audit meaningful: a two-phase
        # txn stranded by a mid-prepare fault would show up against these
        for pod in pods[:n_reserved]:
            status = front.reserve(pod)
            assert status.is_success(), status.reasons
    except BaseException:
        supervisor.stop()
        front.stop()
        raise
    return front, supervisor, pods


def churn(front, pods, rounds=6, per_round=60):
    """Pod-update churn that swings group sums across flip thresholds
    while the fault is live; interleaves scatter RPCs and two-phase
    reserve/unreserve so every transport path sees the fault. Degraded
    verdicts DURING the storm are fine (fail-safe by design) — only the
    post-heal equality gates count."""
    from kube_throttler_tpu.api.pod import make_pod

    for r in range(rounds):
        cpu = "450m" if r % 2 == 0 else "50m"
        for i in range(min(per_round, len(pods))):
            pod = pods[i]
            front.store.update_pod(
                make_pod(pod.name, labels=dict(pod.labels),
                         requests={"cpu": cpu})
            )
        probe = pods[(r * 7) % len(pods)]
        try:
            front.pre_filter(probe)
        except Exception:  # noqa: BLE001 — storm-time refusal is the point
            pass
        victim = pods[-1 - (r % 8)]
        try:
            st = front.reserve(victim)
            if st.is_success():
                front.unreserve(victim)
        except Exception:  # noqa: BLE001 — storm-time refusal is the point
            pass
        time.sleep(0.25)


def final_state(front):
    """Oracle rebuild: (wrong verdicts, lost flips) vs the final state."""
    import tools.harness as H
    from kube_throttler_tpu.api.pod import Namespace
    from kube_throttler_tpu.engine.store import Store

    store = Store()
    store.create_namespace(Namespace("default"))
    for thr in front.store.list_throttles():
        store.create_throttle(thr)
    for pod in front.store.list_pods():
        store.create_pod(pod)
    oracle = H.build_plugin(store)
    oracle.run_pending_once()
    wrong = []
    for pod in store.list_pods():
        got = front.pre_filter(pod)
        want = oracle.pre_filter(pod)
        if got.code != want.code or H.normalized_reasons(
            got.reasons
        ) != H.normalized_reasons(want.reasons):
            wrong.append(pod.key)
    by_key = {t.key: t for t in store.list_throttles()}
    stale = [
        thr.key
        for thr in front.store.list_throttles()
        if (w := by_key.get(thr.key)) is not None
        and thr.status.throttled != w.status.throttled
    ]
    oracle.stop()
    return wrong, stale


def audit_all(front):
    bad = []
    for sid in range(front.n_shards):
        handle = front.shards.get(sid)
        if handle is None or not handle.alive:
            bad.append(f"shard-{sid}: down")
            continue
        a = handle.request("reshard_audit", None, timeout=30.0)
        if a["orphan_reservations"]:
            bad.append(f"shard-{sid}: orphans {a['orphan_reservations']}")
        if a["pending_handoffs"]:
            bad.append(f"shard-{sid}: pending handoffs")
        if a["fenced_handoffs"]:
            bad.append(f"shard-{sid}: fences {a['fenced_handoffs']}")
    return bad


def run_case(site, mode, seed, rule_kwargs=None, n_pods=160, rounds=6,
             recovery_s=30.0):
    from kube_throttler_tpu.faults.plan import FaultPlan

    rule_kwargs = dict(rule_kwargs or {})
    front, supervisor, pods = build_fleet(n_pods=n_pods)
    result = {"case": f"{site}:{mode}", "seed": seed}
    try:
        target_sid = 1
        handle = front.shards[target_sid]
        plan = FaultPlan(seed=seed).rule(site, mode=mode, **rule_kwargs)
        if site in _NEEDS_SEVER:
            plan.rule("net.send.torn_frame", mode="torn", times=1)
        handle.faults = plan

        churn(front, pods, rounds=rounds)

        # heal: the plan runs dry (finite times), the client reconnects,
        # the supervisor resyncs — every shard must come back ok with NO
        # process restart (network loss is not process death)
        restarts_before = dict(supervisor.restart_counts())
        deadline = time.monotonic() + recovery_s
        recovered = False
        while time.monotonic() < deadline:
            state, _ = front._shards_health()
            if state == "ok":
                recovered = True
                break
            time.sleep(0.1)
        assert recovered, f"fleet never recovered: {front._shards_health()}"
        assert supervisor.restart_counts() == restarts_before, (
            "supervisor restarted a worker over a transient network fault"
        )
        assert front.drain(120.0)
        time.sleep(0.5)

        fired = plan.fired(site)
        assert fired >= 1, f"{site} never fired (vacuous pass)"
        result["fired"] = fired
        result["reconnects"] = getattr(handle, "reconnects", 0)
        result["conn_lost"] = supervisor.connection_losses().get(target_sid, 0)
        result["deadline_exceeded"] = getattr(handle, "deadline_exceeded", 0)

        wrong, stale = final_state(front)
        assert not wrong, f"wrong verdicts after heal: {wrong[:3]}"
        assert not stale, f"lost flips after heal: {stale[:3]}"
        bad = audit_all(front)
        assert not bad, f"orphan audit failed: {bad}"
        result["ok"] = True
        return result
    finally:
        supervisor.stop()
        front.stop()


def _shm_leftovers():
    try:
        return [f for f in os.listdir("/dev/shm") if f.startswith("kt_evt_")]
    except OSError:
        return []


def run_shm_case(site, mode, seed, rule_kwargs=None, worker_fault=None,
                 expect_restart=False, n_pods=160, rounds=6, recovery_s=60.0):
    from kube_throttler_tpu.faults.plan import FaultPlan

    # Front-side plans are passed EMPTY at construction (the ring writer
    # captures front.faults by reference at spawn) and armed only after
    # seeding: a torn commit during build_fleet would kill the worker
    # before the matrix even starts measuring.
    plan = FaultPlan(seed=seed) if rule_kwargs is not None else None
    worker_args = ["--fault-site", worker_fault] if worker_fault else None
    front, supervisor, pods = build_fleet(
        n_pods=n_pods, transport="socketpair", faults=plan,
        worker_args=worker_args,
    )
    result = {"case": f"{site}:{mode}", "seed": seed}
    try:
        # the event plane must be LIVE before the fault window — a fleet
        # that silently fell back to pickle would pass every gate while
        # testing nothing
        for sid in range(front.n_shards):
            handle = front.shards[sid]
            lane = getattr(handle, "shm_lane", None)
            assert lane is not None and not lane.dead, (
                f"shard {sid}: no live shm lane — matrix would be vacuous"
            )
            assert getattr(handle, "_shm_active", False), (
                f"shard {sid}: shm lane never promoted past the barrier"
            )

        restarts_before = sum(supervisor.restart_counts().values())
        if plan is not None:
            plan.rule(site, mode=mode, **dict(rule_kwargs))
            if site == "shm.segment.unlink":
                # the unlink only runs when a writer closes: force one
                # restart so the monitor closes the old handle mid-run
                plan.rule("shm.slot.torn_commit", mode="torn", times=1)
        churn(front, pods, rounds=rounds)

        deadline = time.monotonic() + recovery_s
        recovered = False
        while time.monotonic() < deadline:
            state, _ = front._shards_health()
            if state == "ok":
                recovered = True
                break
            time.sleep(0.1)
        assert recovered, f"fleet never recovered: {front._shards_health()}"
        assert front.drain(120.0)
        time.sleep(0.5)

        restarts_after = sum(supervisor.restart_counts().values())
        if expect_restart:
            assert restarts_after > restarts_before, (
                f"{site}: expected a worker restart (torn ring ⇒ die as a "
                f"unit ⇒ fresh segment), saw none"
            )
        else:
            assert restarts_after == restarts_before, (
                f"{site}: a latency/backpressure fault must not restart "
                f"workers (restarts {restarts_before} -> {restarts_after})"
            )
        result["restarts"] = restarts_after - restarts_before

        if plan is not None:
            fired = plan.fired(site)
            assert fired >= 1, f"{site} never fired (vacuous pass)"
            result["fired"] = fired
        else:
            # worker-side rule: the plan lives in the worker process.
            # Prove the faulted path ran by the pump having decoded
            # frames through the very peek loop the site instruments
            total_frames = 0
            for sid in range(front.n_shards):
                shm = front.shards[sid].request("stats", None, timeout=30.0)["shm"]
                assert shm is not None, f"shard {sid}: pump gone after heal"
                total_frames += shm["frames"]
            assert total_frames > 0, "no frames crossed the ring"
            result["fired"] = None
            result["pump_frames"] = total_frames

        # post-heal the plane must still (or again) be the live path
        for sid in range(front.n_shards):
            handle = front.shards[sid]
            lane = getattr(handle, "shm_lane", None)
            assert lane is not None and not lane.dead, (
                f"shard {sid}: lane dead after heal — fallback is hiding"
            )

        wrong, stale = final_state(front)
        assert not wrong, f"wrong verdicts after heal: {wrong[:3]}"
        assert not stale, f"lost flips after heal: {stale[:3]}"
        bad = audit_all(front)
        assert not bad, f"orphan audit failed: {bad}"
        result["ok"] = True
    finally:
        supervisor.stop()
        front.stop()
    leftovers = _shm_leftovers()
    assert not leftovers, f"leaked shm segments after stop: {leftovers}"
    return result


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="netchaostest")
    sub = parser.add_subparsers(dest="command", required=True)
    m = sub.add_parser("matrix", help="every net.* + shm.* site x 3 seeds")
    m.add_argument("--seeds", default=",".join(str(s) for s in SEEDS))
    m.add_argument("--json", default="", help="write the matrix report here")
    m.add_argument("--only", choices=("all", "net", "shm"), default="all",
                   help="restrict the matrix to one transport column")
    one = sub.add_parser("one", help="a single case")
    one.add_argument("--site", required=True)
    one.add_argument("--mode", default="error")
    one.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)

    from kube_throttler_tpu.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()

    if args.command == "one":
        if args.site.startswith("shm."):
            case = next(
                (c for c in SHM_CASES
                 if c[0] == args.site and c[1] == args.mode),
                None,
            )
            if case is None:
                parser.error(f"unknown shm case {args.site}:{args.mode}")
            _, _, kwargs, worker_fault, expect_restart = case
            result = run_shm_case(
                args.site, args.mode, args.seed, rule_kwargs=kwargs,
                worker_fault=worker_fault, expect_restart=expect_restart,
            )
        else:
            kwargs = next(
                (kw for s, md, kw in CASES
                 if s == args.site and md == args.mode),
                None,
            )
            result = run_case(args.site, args.mode, args.seed,
                              rule_kwargs=kwargs)
        print(json.dumps(result, indent=2))
        return 0

    seeds = [int(s) for s in args.seeds.split(",") if s != ""]
    results, failures = [], 0
    if args.only in ("all", "net"):
        for site, mode, kwargs in CASES:
            for seed in seeds:
                label = f"{site}:{mode}"
                t0 = time.monotonic()
                try:
                    result = run_case(site, mode, seed, rule_kwargs=kwargs)
                    result["wall_s"] = round(time.monotonic() - t0, 1)
                    results.append(result)
                    print(f"PASS {label:<28} seed={seed} "
                          f"fired={result['fired']} "
                          f"reconnects={result['reconnects']} "
                          f"({result['wall_s']}s)")
                except Exception as e:  # noqa: BLE001 — matrix reports, then fails
                    failures += 1
                    results.append(
                        {"case": label, "seed": seed, "error": repr(e)}
                    )
                    print(f"FAIL {label:<28} seed={seed}: {e!r}")
    if args.only in ("all", "shm"):
        for site, mode, kwargs, worker_fault, expect_restart in SHM_CASES:
            for seed in seeds:
                label = f"{site}:{mode}"
                t0 = time.monotonic()
                try:
                    result = run_shm_case(
                        site, mode, seed, rule_kwargs=kwargs,
                        worker_fault=worker_fault,
                        expect_restart=expect_restart,
                    )
                    result["wall_s"] = round(time.monotonic() - t0, 1)
                    results.append(result)
                    print(f"PASS {label:<28} seed={seed} "
                          f"fired={result['fired']} "
                          f"restarts={result['restarts']} "
                          f"({result['wall_s']}s)")
                except Exception as e:  # noqa: BLE001 — matrix reports, then fails
                    failures += 1
                    results.append(
                        {"case": label, "seed": seed, "error": repr(e)}
                    )
                    print(f"FAIL {label:<28} seed={seed}: {e!r}")
    n_net = len(CASES) * len(seeds) if args.only in ("all", "net") else 0
    n_shm = len(SHM_CASES) * len(seeds) if args.only in ("all", "shm") else 0
    total = n_net + n_shm
    print(f"\n{total - failures}/{total} transport-fault paths clean "
          "(zero wrong verdicts, zero lost flips, zero orphan reservations)")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=2)
            f.write("\n")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
