#!/usr/bin/env python
"""Network-fault chaos matrix for the cross-host TCP shard transport.

Every ``net.*`` framing-layer fault site (faults/plan.py), armed
client-side against one shard of a LIVE 2-worker ``transport="tcp"``
fleet (real processes dialed over loopback — the same stack
``--shard-transport tcp`` serves), x 3 seeds:

    net.connect.refused:error   dials refused during reconnect; backoff
                                must retry through to the heal
    net.send.torn_frame:torn    a frame tears mid-write; the worker sees
                                a short read and drops the lane cleanly
    net.recv.stall:delay        the client's reader stalls mid-frame;
                                RPCs ride the per-op deadline, not hang
    net.partition:error         sends blackhole (asymmetric partition);
                                degraded fail-safe verdicts, then heal ⇒
                                epoch-bumped resync + re-push
    net.reconnect.storm:error   every fresh connection dies at birth;
                                jittered backoff must converge anyway

While the fault is live the driver keeps churning pod events across
flip thresholds, scattering ``pre_filter`` RPCs, and running
reserve/unreserve two-phase transactions. After the heal the matrix
asserts the full recovery contract:

- the armed site actually FIRED (an unfired rule is a vacuous pass);
- every shard reconnected and reports ``ok`` (no supervisor restart —
  transient network loss must not look like process death);
- ZERO wrong verdicts vs a single-process oracle rebuilt from the final
  state (code + normalized reasons);
- ZERO lost flips: every published ``status.throttled`` equals the
  oracle's recompute;
- ZERO orphan reservations: every worker's ``reshard_audit`` is clean —
  a reserve whose prepare outran the deadline must have been aborted on
  every target, not stranded.

Run: ``python tools/netchaostest.py matrix`` (``make net-chaos``); the
tier-1 smoke (tests/test_net_transport.py) runs one case small.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

SEEDS = (0, 1, 2)

# site → (mode, rule kwargs): windowless rules stay finite so the fleet
# always heals inside the case budget (an unbounded blackhole would gate
# the harness's patience, not the code)
CASES = (
    ("net.connect.refused", "error", {"times": 2}),
    ("net.send.torn_frame", "torn", {"times": 2}),
    ("net.recv.stall", "delay", {"times": 3, "delay": 0.5}),
    ("net.partition", "error", {"times": 6}),
    ("net.reconnect.storm", "error", {"times": 2}),
)

# these sites only fire while (re)connecting — pair them with one torn
# frame so the established lane actually drops and the dial path runs
_NEEDS_SEVER = ("net.connect.refused", "net.reconnect.storm")


def build_fleet(n_shards=2, n_throttles=24, n_pods=160, n_reserved=8,
                rpc_deadline=10.0):
    import tools.harness as H
    from kube_throttler_tpu.api.pod import Namespace, make_pod
    from kube_throttler_tpu.sharding.front import AdmissionFront
    from kube_throttler_tpu.sharding.supervisor import ShardSupervisor

    front = AdmissionFront(n_shards, rpc_deadline=rpc_deadline)
    supervisor = ShardSupervisor(
        front,
        transport="tcp",
        use_device=False,
        restart_backoff=0.3,
        env={**os.environ, "KT_SHARD_QUIET": "1", "KT_LOCK_ASSERT": "0"},
        # the matrix runs the KEYED framing (HMAC per frame) so every
        # fault path is exercised through the cross-host trust boundary,
        # not the loopback-only keyless shortcut
        auth_key=b"netchaos-matrix-psk",
    )
    supervisor.start(ready_timeout=300.0)
    try:
        front.store.create_namespace(Namespace("default"))
        for i in range(n_throttles):
            front.store.create_throttle(H.make_throttle(i))
        pods = []
        for i in range(n_pods):
            pod = make_pod(
                f"p{i}", labels={"grp": f"g{i % n_throttles}"},
                requests={"cpu": "100m"},
            )
            front.store.create_pod(pod)
            pods.append(pod)
        assert front.drain(120.0)
        time.sleep(0.3)
        # live reservations make the orphan audit meaningful: a two-phase
        # txn stranded by a mid-prepare fault would show up against these
        for pod in pods[:n_reserved]:
            status = front.reserve(pod)
            assert status.is_success(), status.reasons
    except BaseException:
        supervisor.stop()
        front.stop()
        raise
    return front, supervisor, pods


def churn(front, pods, rounds=6, per_round=60):
    """Pod-update churn that swings group sums across flip thresholds
    while the fault is live; interleaves scatter RPCs and two-phase
    reserve/unreserve so every transport path sees the fault. Degraded
    verdicts DURING the storm are fine (fail-safe by design) — only the
    post-heal equality gates count."""
    from kube_throttler_tpu.api.pod import make_pod

    for r in range(rounds):
        cpu = "450m" if r % 2 == 0 else "50m"
        for i in range(min(per_round, len(pods))):
            pod = pods[i]
            front.store.update_pod(
                make_pod(pod.name, labels=dict(pod.labels),
                         requests={"cpu": cpu})
            )
        probe = pods[(r * 7) % len(pods)]
        try:
            front.pre_filter(probe)
        except Exception:  # noqa: BLE001 — storm-time refusal is the point
            pass
        victim = pods[-1 - (r % 8)]
        try:
            st = front.reserve(victim)
            if st.is_success():
                front.unreserve(victim)
        except Exception:  # noqa: BLE001 — storm-time refusal is the point
            pass
        time.sleep(0.25)


def final_state(front):
    """Oracle rebuild: (wrong verdicts, lost flips) vs the final state."""
    import tools.harness as H
    from kube_throttler_tpu.api.pod import Namespace
    from kube_throttler_tpu.engine.store import Store

    store = Store()
    store.create_namespace(Namespace("default"))
    for thr in front.store.list_throttles():
        store.create_throttle(thr)
    for pod in front.store.list_pods():
        store.create_pod(pod)
    oracle = H.build_plugin(store)
    oracle.run_pending_once()
    wrong = []
    for pod in store.list_pods():
        got = front.pre_filter(pod)
        want = oracle.pre_filter(pod)
        if got.code != want.code or H.normalized_reasons(
            got.reasons
        ) != H.normalized_reasons(want.reasons):
            wrong.append(pod.key)
    by_key = {t.key: t for t in store.list_throttles()}
    stale = [
        thr.key
        for thr in front.store.list_throttles()
        if (w := by_key.get(thr.key)) is not None
        and thr.status.throttled != w.status.throttled
    ]
    oracle.stop()
    return wrong, stale


def audit_all(front):
    bad = []
    for sid in range(front.n_shards):
        handle = front.shards.get(sid)
        if handle is None or not handle.alive:
            bad.append(f"shard-{sid}: down")
            continue
        a = handle.request("reshard_audit", None, timeout=30.0)
        if a["orphan_reservations"]:
            bad.append(f"shard-{sid}: orphans {a['orphan_reservations']}")
        if a["pending_handoffs"]:
            bad.append(f"shard-{sid}: pending handoffs")
        if a["fenced_handoffs"]:
            bad.append(f"shard-{sid}: fences {a['fenced_handoffs']}")
    return bad


def run_case(site, mode, seed, rule_kwargs=None, n_pods=160, rounds=6,
             recovery_s=30.0):
    from kube_throttler_tpu.faults.plan import FaultPlan

    rule_kwargs = dict(rule_kwargs or {})
    front, supervisor, pods = build_fleet(n_pods=n_pods)
    result = {"case": f"{site}:{mode}", "seed": seed}
    try:
        target_sid = 1
        handle = front.shards[target_sid]
        plan = FaultPlan(seed=seed).rule(site, mode=mode, **rule_kwargs)
        if site in _NEEDS_SEVER:
            plan.rule("net.send.torn_frame", mode="torn", times=1)
        handle.faults = plan

        churn(front, pods, rounds=rounds)

        # heal: the plan runs dry (finite times), the client reconnects,
        # the supervisor resyncs — every shard must come back ok with NO
        # process restart (network loss is not process death)
        restarts_before = dict(supervisor.restart_counts())
        deadline = time.monotonic() + recovery_s
        recovered = False
        while time.monotonic() < deadline:
            state, _ = front._shards_health()
            if state == "ok":
                recovered = True
                break
            time.sleep(0.1)
        assert recovered, f"fleet never recovered: {front._shards_health()}"
        assert supervisor.restart_counts() == restarts_before, (
            "supervisor restarted a worker over a transient network fault"
        )
        assert front.drain(120.0)
        time.sleep(0.5)

        fired = plan.fired(site)
        assert fired >= 1, f"{site} never fired (vacuous pass)"
        result["fired"] = fired
        result["reconnects"] = getattr(handle, "reconnects", 0)
        result["conn_lost"] = supervisor.connection_losses().get(target_sid, 0)
        result["deadline_exceeded"] = getattr(handle, "deadline_exceeded", 0)

        wrong, stale = final_state(front)
        assert not wrong, f"wrong verdicts after heal: {wrong[:3]}"
        assert not stale, f"lost flips after heal: {stale[:3]}"
        bad = audit_all(front)
        assert not bad, f"orphan audit failed: {bad}"
        result["ok"] = True
        return result
    finally:
        supervisor.stop()
        front.stop()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="netchaostest")
    sub = parser.add_subparsers(dest="command", required=True)
    m = sub.add_parser("matrix", help="every net.* site x 3 seeds")
    m.add_argument("--seeds", default=",".join(str(s) for s in SEEDS))
    m.add_argument("--json", default="", help="write the matrix report here")
    one = sub.add_parser("one", help="a single case")
    one.add_argument("--site", required=True)
    one.add_argument("--mode", default="error")
    one.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)

    from kube_throttler_tpu.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()

    if args.command == "one":
        kwargs = next(
            (kw for s, md, kw in CASES if s == args.site and md == args.mode),
            None,
        )
        result = run_case(args.site, args.mode, args.seed, rule_kwargs=kwargs)
        print(json.dumps(result, indent=2))
        return 0

    seeds = [int(s) for s in args.seeds.split(",") if s != ""]
    results, failures = [], 0
    for site, mode, kwargs in CASES:
        for seed in seeds:
            label = f"{site}:{mode}"
            t0 = time.monotonic()
            try:
                result = run_case(site, mode, seed, rule_kwargs=kwargs)
                result["wall_s"] = round(time.monotonic() - t0, 1)
                results.append(result)
                print(f"PASS {label:<28} seed={seed} fired={result['fired']} "
                      f"reconnects={result['reconnects']} "
                      f"({result['wall_s']}s)")
            except Exception as e:  # noqa: BLE001 — matrix reports, then fails
                failures += 1
                results.append({"case": label, "seed": seed, "error": repr(e)})
                print(f"FAIL {label:<28} seed={seed}: {e!r}")
    total = len(CASES) * len(seeds)
    print(f"\n{total - failures}/{total} network-fault paths clean "
          "(zero wrong verdicts, zero lost flips, zero orphan reservations)")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=2)
            f.write("\n")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
