"""Profile the reconcile drain (cfg5's event→status path). Run:
    python tools/profile_reconcile.py [P] [T] [EVENTS]
Fires pod-churn events with workers stopped, then cProfiles the
synchronous drain — the per-batch cost that sets status-commit lag.
"""
import cProfile
import io
import os
import pstats
import random
import sys
import time
from dataclasses import replace as dc_replace

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from kube_throttler_tpu.utils.platform import honor_jax_platforms_env  # noqa: E402

honor_jax_platforms_env()

import bench  # noqa: E402
from kube_throttler_tpu.api.pod import make_pod  # noqa: E402

P = int(sys.argv[1]) if len(sys.argv) > 1 else 2_000
T = int(sys.argv[2]) if len(sys.argv) > 2 else 200
EVENTS = int(sys.argv[3]) if len(sys.argv) > 3 else 2_000

store, plugin = bench.build_served_stack(P, T, label="prof")

rng = random.Random(1)
pods = store.list_pods()

def fire(n):
    for i in range(n):
        pod = pods[rng.randrange(len(pods))]
        updated = make_pod(
            pod.name, labels=pod.labels,
            requests={"cpu": f"{rng.randrange(1, 8) * 100}m"},
        )
        updated = dc_replace(updated, spec=dc_replace(updated.spec, node_name="node-1"))
        updated.status.phase = "Running"
        store.update_pod(updated)

# warm the drain path
fire(200)
plugin.run_pending_once()

t0 = time.perf_counter()
fire(EVENTS)
t_fire = time.perf_counter() - t0
print(f"fired {EVENTS} events in {t_fire:.2f}s ({EVENTS/t_fire:,.0f}/s ingest)")

pr = cProfile.Profile()
pr.enable()
t0 = time.perf_counter()
n = plugin.run_pending_once()
t_drain = time.perf_counter() - t0
pr.disable()
print(f"drained {n} keys in {t_drain:.2f}s ({n/t_drain:,.0f} keys/s)")
s = io.StringIO()
pstats.Stats(pr, stream=s).sort_stats("cumulative").print_stats(35)
print(s.getvalue())
