#!/usr/bin/env python
"""SIGKILL crash-point harness for the snapshot/recovery subsystem.

Drives the standalone ingest stack (store + journal + snapshots +
TTL'd reservations) in a CHILD process whose fault plan SIGKILLs it at a
seeded ``crash.*`` site (faults/plan.py) — the worst possible instants:
between a store mutation and its journal line, mid-snapshot-tmp-write,
between the snapshot rename and the prune, right after a compaction
rotates the log. The parent then restarts over the same data directory
and asserts the **invariant oracle**:

1. *replay equivalence* — the recovered store (newest valid snapshot +
   journal tail, engine/recovery.py) is byte-identical, object for
   object, to a pure from-genesis replay of the same journal;
2. *admission equivalence* — ``pre_filter`` verdicts (status code +
   reason strings) for every stored pod match between the two;
3. *plane integrity* — the recovery reconcile finds ZERO divergences
   between the rebuilt published ``st_*`` planes and the restored
   statuses (throttled flags included);
4. *reservation safety* — every restored reservation existed unexpired in
   the snapshot, nothing expired is resurrected, and non-TTL entries all
   survive.

Usage:
    python tools/crashtest.py matrix [--seeds 0,1,2] [--events 150]
    python tools/crashtest.py one --site crash.snapshot.pre_rename --seed 0
    python tools/crashtest.py child ...   (internal: the workload driver)

``make crash-test`` runs the full matrix; tests/test_crash_recovery.py
runs one fast smoke cycle in tier-1 and the matrix behind ``-m slow``.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import shutil
import signal
import sys
import tempfile
from dataclasses import replace
from datetime import timedelta

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tools import harness  # noqa: E402 — the shared child-process toolkit

# shared fixtures/oracles (tools/harness.py) under their historical names —
# hatest and the scenario engine import them from harness directly
N_THROTTLES = harness.N_THROTTLES
_throttle = harness.make_throttle
_recompute_status = harness.recompute_status
_dump_store = harness.dump_store
_normalized_reasons = harness.normalized_reasons
_verdicts = harness.verdicts
_build_plugin = harness.build_plugin

# every registered crash.* site (faults/plan.py KNOWN_SITES)
CRASH_SITES = (
    "crash.journal.append",
    "crash.journal.torn",
    "crash.journal.compact",
    "crash.journal.group_commit",
    "crash.gang.partial_reserve",
    "crash.preempt.partial_evict",
    "crash.snapshot.begin",
    "crash.snapshot.tmp_partial",
    "crash.snapshot.pre_rename",
    "crash.snapshot.post_rename",
    "crash.snapshot.prune",
)

# workload knobs the child and the oracle agree on
DEFAULT_EVENTS = 150
SNAPSHOT_EVERY = 25
COMPACT_AFTER = 70
SNAPSHOT_KEEP = 2


def default_hit(site: str, seed: int) -> int:
    """Which 1-based hit of ``site`` to die at: spread kills across the run
    for per-append sites; low-frequency sites (per-snapshot, per-compact)
    use small indices so each seed crashes a different occurrence."""
    if site in ("crash.journal.append", "crash.journal.torn"):
        return 10 + 37 * seed
    if site == "crash.journal.group_commit":
        # hit once per micro-batch group commit (~a third of events flow
        # through batches): die at different batches per seed
        return 2 + 3 * seed
    if site == "crash.gang.partial_reserve":
        # hit once per gang MEMBER-key add (~2-4 per gang reserve): odd
        # indices land mid-group — the exact partial-reserve instant
        return 3 + 8 * seed
    if site == "crash.preempt.partial_evict":
        # hit once per victim delete (~2-4 per preempt cycle): spread so
        # each seed dies mid-eviction of a different cycle — some victims
        # deleted, the commit line never lands
        return 2 + 7 * seed
    return 1 + seed


# --------------------------------------------------------------------------
# child: the workload driver (dies by SIGKILL mid-flight)
# --------------------------------------------------------------------------


def run_child(args) -> int:
    from kube_throttler_tpu.api.pod import Namespace, make_pod
    from kube_throttler_tpu.engine.gang import GangLedger
    from kube_throttler_tpu.engine.recovery import RecoveryManager
    from kube_throttler_tpu.engine.reservations import ReservedResourceAmounts
    from kube_throttler_tpu.engine.snapshot import SnapshotManager
    from kube_throttler_tpu.engine.store import Store
    from kube_throttler_tpu.faults.plan import FaultPlan

    plan = None
    if args.site:
        plan = FaultPlan(seed=args.seed).rule(
            args.site, mode="kill", schedule=[args.hit]
        )
    store = Store()
    recovery = RecoveryManager(
        args.dir, faults=plan, compact_after=args.compact_after
    )
    journal = recovery.recover_store(store)
    reservations = {
        "throttle": ReservedResourceAmounts(8),
        "clusterthrottle": ReservedResourceAmounts(8),
    }
    recovery.restore_reservations(reservations)
    gangs = GangLedger(caches=reservations, journal=journal, faults=plan)
    recovery.restore_gangs(gangs, journal)
    snapshotter = SnapshotManager(
        args.dir,
        store,
        reservations=reservations,
        keep=args.keep,
        faults=plan,
        gang_ledger=gangs,
    )
    snapshotter.bind_journal(journal, every_lines=args.snapshot_every)
    from kube_throttler_tpu.policy.preempt import PreemptionCoordinator
    from kube_throttler_tpu.policy.spec import PolicyEngine

    # journaled eviction driver (no controllers: the child exercises the
    # PREEMPT begin → deletes → commit bracket and its crash artifacts,
    # not victim selection — that has its own seeded equivalence tier)
    preempt = PreemptionCoordinator(
        PolicyEngine(), kind_controllers=(), store=store,
        gang_ledger=gangs, journal=journal, faults=plan,
    )

    rng = random.Random(args.seed)
    if store.get_namespace("default") is None:
        store.create_namespace(Namespace("default"))
    throttles = []
    for i in range(N_THROTTLES):
        try:
            store.create_throttle(_throttle(i))
        except ValueError:
            pass  # recovered from a previous run
        throttles.append(f"t{i}")

    def _mk_pod():
        i = rng.randrange(N_THROTTLES)
        pod = make_pod(
            f"p{rng.randrange(10**9)}",
            labels={"grp": f"g{i}"},
            requests={"cpu": f"{rng.randrange(100, 900)}m"},
        )
        if rng.random() < 0.5:
            pod = replace(pod, spec=replace(pod.spec, node_name="node-1"))
            pod.status.phase = "Running"
        return pod

    for _step in range(args.events):
        op = rng.random()
        if op < 0.35:  # create pod(s) — a third arrive as one MICRO-BATCH
            if rng.random() < 0.35:
                # the batched ingest path: one store.apply_events per burst
                # → the journal GROUP COMMITS it (one buffered write), and
                # site crash.journal.group_commit can die mid-commit
                store.apply_events(
                    [("upsert", "Pod", _mk_pod()) for _ in range(rng.randrange(2, 6))]
                )
            else:
                try:
                    store.create_pod(_mk_pod())
                except ValueError:
                    pass
        elif op < 0.5:  # bind a pending pod
            pods = [
                p for p in store.list_pods("default") if p.status.phase == "Pending"
            ]
            if pods:
                p = rng.choice(pods)
                bound = replace(p, spec=replace(p.spec, node_name="node-1"))
                bound = replace(bound, status=replace(bound.status, phase="Running"))
                store.update_pod(bound)
        elif op < 0.6:  # delete a pod (never a "pv" preempt victim: their
            # presence/absence is the preempt oracle's witness — a random
            # delete of a rolled-back victim would fake a violation)
            pods = [
                p for p in store.list_pods("default") if not p.name.startswith("pv")
            ]
            if pods:
                p = rng.choice(pods)
                store.delete_pod(p.namespace, p.name)
        elif op < 0.7:  # spec churn: bump a threshold
            name = rng.choice(throttles)
            thr = store.get_throttle("default", name)
            spec = thr.spec
            from kube_throttler_tpu.api.types import ResourceAmount

            new_spec = replace(
                spec,
                threshold=ResourceAmount.of(
                    pod=rng.randrange(2, 9),
                    requests={"cpu": str(rng.randrange(1, 6))},
                ),
            )
            store.update_throttle_spec(replace(thr, spec=new_spec))
        elif op < 0.88:  # reconcile stand-in: status write (journaled)
            name = rng.choice(throttles)
            thr = store.get_throttle("default", name)
            store.update_throttle_status(_recompute_status(store, thr))
        elif op < 0.93:  # gang churn: all-or-nothing group reserve/rollback
            if rng.random() < 0.75 or not gangs.pending_groups():
                name = rng.choice(throttles)
                gid = rng.randrange(10**6)
                members = [
                    make_pod(
                        f"gang{gid}-r{i}",
                        labels={"grp": name},
                        requests={"cpu": "250m"},
                        group=f"g{gid}",
                        group_size=rng.randrange(2, 5),
                    )
                    for i in range(rng.randrange(2, 5))
                ]
                member_keys = {
                    p.key: {"throttle": [f"default/{name}"]} for p in members
                }
                ttl = rng.choice([None, 10.0, 60.0])
                # crash.gang.partial_reserve fires INSIDE this loop — the
                # oracle must then find either every member reserved in
                # the recovered state or none of them
                gangs.reserve_group(f"default/g{gid}", members, member_keys, ttl=ttl)
            else:
                # roll an existing group back through the journaled path
                rec = next(iter(gangs._groups.values()), None)  # noqa: SLF001
                if rec is not None:
                    gangs.rollback_group(rec.group_key, "workload churn")
        elif op < 0.96:  # preemption: journaled gang-atomic victim eviction
            # victims are created RUNNING then evicted through the real
            # PREEMPT begin → delete-per-victim → commit bracket;
            # crash.preempt.partial_evict fires inside the delete loop —
            # the oracle must then find either every victim restored
            # (uncommitted ⇒ zero evictions) or every victim gone
            # (committed), never a half-evicted set
            vid = rng.randrange(10**6)
            victims = []
            if rng.random() < 0.5:  # whole-gang victim unit
                size = rng.randrange(2, 5)
                for i in range(size):
                    victims.append(
                        make_pod(
                            f"pv{vid}-r{i}",
                            labels={"grp": rng.choice(throttles)},
                            requests={"cpu": "150m"},
                            group=f"pg{vid}",
                            group_size=size,
                            node_name="node-1",
                            phase="Running",
                        )
                    )
            else:
                for i in range(rng.randrange(1, 3)):
                    victims.append(
                        make_pod(
                            f"pv{vid}-s{i}",
                            labels={"grp": rng.choice(throttles)},
                            requests={"cpu": "150m"},
                            node_name="node-1",
                            phase="Running",
                        )
                    )
            for p in victims:
                try:
                    store.create_pod(p)
                except ValueError:
                    pass
            preempt.execute_eviction(f"default/pre-{vid}", victims)
        else:  # reservation churn with mixed TTLs
            name = rng.choice(throttles)
            cache = reservations["throttle"]
            pod = make_pod(
                f"r{rng.randrange(10**6)}",
                labels={"grp": name},
                requests={"cpu": "250m"},
            )
            if rng.random() < 0.7:
                ttl = rng.choice([None, 5.0, 30.0, timedelta(minutes=2)])
                cache.add_pod(f"default/{name}", pod, ttl=ttl)
            else:
                keys = list(cache.reserved_pod_keys(f"default/{name}"))
                if keys:
                    cache.remove_pod_key(f"default/{name}", rng.choice(keys))

    # survived every event (the seeded hit was never reached): exit through
    # the graceful path — final snapshot + fsynced journal
    snapshotter.write(reason="shutdown")
    journal.close()
    return 0


# --------------------------------------------------------------------------
# parent: restart + invariant oracle
# --------------------------------------------------------------------------


def spawn_child(
    data_dir: str,
    seed: int,
    site: str,
    hit: int,
    events: int,
    timeout: float = 180.0,
):
    argv = [
        "child",
        "--dir", data_dir,
        "--seed", str(seed),
        "--events", str(events),
        "--snapshot-every", str(SNAPSHOT_EVERY),
        "--compact-after", str(COMPACT_AFTER),
        "--keep", str(SNAPSHOT_KEEP),
    ]
    if site:
        argv += ["--site", site, "--hit", str(hit)]
    return harness.run_child(__file__, argv, timeout=timeout)


def run_crash_cycle(
    site: str,
    seed: int,
    workdir: str,
    events: int = DEFAULT_EVENTS,
    hit: int = None,
) -> dict:
    """One full crash/recover/verify cycle; raises AssertionError with a
    diagnosis on any oracle violation, else returns a report dict."""
    from kube_throttler_tpu.engine.journal import attach
    from kube_throttler_tpu.engine.recovery import RecoveryManager
    from kube_throttler_tpu.engine.reservations import ReservedResourceAmounts
    from kube_throttler_tpu.engine.snapshot import find_snapshots, load_snapshot
    from kube_throttler_tpu.engine.store import Store

    hit = default_hit(site, seed) if hit is None else hit
    data_dir = os.path.join(workdir, "data")
    os.makedirs(data_dir, exist_ok=True)
    proc = spawn_child(data_dir, seed, site, hit, events)
    killed = proc.returncode == -signal.SIGKILL
    if not killed and proc.returncode != 0:
        raise AssertionError(
            f"child failed (rc={proc.returncode}) at {site} seed={seed}:\n"
            f"{proc.stdout}\n{proc.stderr}"
        )

    # two pristine copies of the crash artifact: recovery and the pure
    # replay both truncate/compact, so they must not share files
    recovered_dir = os.path.join(workdir, "recovered")
    pure_dir = os.path.join(workdir, "pure")
    for d in (recovered_dir, pure_dir):
        if os.path.exists(d):
            shutil.rmtree(d)
        shutil.copytree(data_dir, d)

    # --- recovered state: snapshot + journal tail ------------------------
    from kube_throttler_tpu.engine.gang import GangLedger

    recovered = Store()
    rec = RecoveryManager(recovered_dir, compact_after=10**9)
    rec_journal = rec.recover_store(recovered)
    caches = {
        "throttle": ReservedResourceAmounts(8),
        "clusterthrottle": ReservedResourceAmounts(8),
    }
    rec.restore_reservations(caches)
    gangs = GangLedger(caches=caches)
    rec.restore_gangs(gangs, rec_journal)
    rec_journal.close()

    # --- pure state: from-genesis journal replay, snapshots ignored ------
    pure = Store()
    attach(pure, os.path.join(pure_dir, "store.journal"), compact_after=10**9).close()

    # oracle 1: replay equivalence (objects, statuses, throttled flags)
    dump_rec, dump_pure = _dump_store(recovered), _dump_store(pure)
    assert dump_rec == dump_pure, (
        f"{site} seed={seed} hit={hit}: recovered state (mode="
        f"{rec.report.journal_mode}) diverges from pure from-genesis replay"
    )

    # oracle 2+3: admission equivalence + zero plane divergence
    plugin_rec = _build_plugin(recovered)
    plugin_pure = _build_plugin(pure)
    try:
        v_rec, v_pure = _verdicts(plugin_rec, recovered), _verdicts(plugin_pure, pure)
        assert v_rec == v_pure, (
            f"{site} seed={seed} hit={hit}: admission verdicts diverge: "
            f"{ {k: (v_rec.get(k), v_pure.get(k)) for k in set(v_rec) | set(v_pure) if v_rec.get(k) != v_pure.get(k)} }"
        )
        divergences = rec.reconcile(
            plugin_rec.informers, device_manager=plugin_rec.device_manager
        )
        assert divergences == 0, (
            f"{site} seed={seed} hit={hit}: {divergences} published-plane "
            f"divergence(s) after recovery: {rec.report.repaired_keys}"
        )
    finally:
        plugin_rec.stop()
        plugin_pure.stop()

    # oracle 4: reservation safety — everything restored was unexpired in
    # the snapshot; nothing with a spent TTL came back; every non-TTL
    # entry survived
    snaps = find_snapshots(recovered_dir)
    if rec.snapshot is not None and snaps:
        snap_res = (rec.snapshot.get("reservations") or {}).get("throttle") or {}
        restored_keys = {
            (tk, pk)
            for tk in caches["throttle"].throttle_keys()
            for pk in caches["throttle"].reserved_pod_keys(tk)
        }
        snap_keys = {
            (tk, pk) for tk, pods in snap_res.items() for pk in pods
        }
        extra = restored_keys - snap_keys
        assert not extra, (
            f"{site} seed={seed}: reservations restored that the snapshot "
            f"never carried: {extra}"
        )
        eternal = {
            (tk, pk)
            for tk, pods in snap_res.items()
            for pk, entry in pods.items()
            if entry.get("ttlRemainingSeconds") is None
        }
        missing = eternal - restored_keys
        assert not missing, (
            f"{site} seed={seed}: non-TTL reservations lost in restore: {missing}"
        )

    # oracle 5: gang all-or-nothing — every restored group is FULLY
    # reserved (each pending member holds a reservation on every recorded
    # throttle key); any group whose journal tail ends in begin (crash
    # mid-reserve) or rollback has NO surviving member reservation; and no
    # gang-member reservation exists outside a restored group record
    reserved_pairs = {
        (tk, pk)
        for cache in caches.values()
        for tk in cache.throttle_keys()
        for pk in cache.reserved_pod_keys(tk)
    }
    with gangs.lock:
        records = {
            gk: (
                {pk: dict(kinds) for pk, kinds in r.members.items()},
                set(r.admitted),
            )
            for gk, r in gangs._groups.items()  # noqa: SLF001 — oracle read
        }
    recorded_members = set()
    for gk, (members, admitted) in records.items():
        for pk, kinds in members.items():
            recorded_members.add(pk)
            if pk in admitted:
                continue
            for _kind, keys in kinds.items():
                for key in keys:
                    assert (key, pk) in reserved_pairs, (
                        f"{site} seed={seed} hit={hit}: gang {gk} member {pk} "
                        f"lost its reservation on {key} — PARTIAL group survived"
                    )
    for gk, entry in rec_journal.gang_ops.items():
        if entry.get("op") == "commit":
            continue
        for pk in entry.get("members") or []:
            holders = {tk for tk, p in reserved_pairs if p == pk}
            assert not holders, (
                f"{site} seed={seed} hit={hit}: gang {gk} ended '{entry['op']}' "
                f"but member {pk} still holds reservations on {holders} — "
                "partial reserve leaked through recovery"
            )
    for tk, pk in reserved_pairs:
        name = pk.partition("/")[2]
        if name.startswith("gang"):
            assert pk in recorded_members, (
                f"{site} seed={seed} hit={hit}: orphan gang-member "
                f"reservation {pk} on {tk} outside any restored group"
            )

    # oracle 6: preemption all-or-nothing — recovery leaves NO open
    # (begin) preemption; a committed one's victims are all gone; an
    # uncommitted (now rollback-stamped) one's victims are ALL present —
    # zero half-evicted victim sets, gang units included (a victim gang's
    # members share one preempt's victim list)
    live_pods = {p.key for p in recovered.list_pods("default")}
    for pid, entry in rec_journal.preempt_ops.items():
        op = entry.get("op")
        assert op != "begin", (
            f"{site} seed={seed} hit={hit}: preemption {pid} still open "
            "(begin without commit) after recovery"
        )
        vkeys = set(entry.get("victims") or [])
        if op == "commit":
            present = vkeys & live_pods
            assert not present, (
                f"{site} seed={seed} hit={hit}: committed preemption {pid} "
                f"left victims alive: {sorted(present)}"
            )
        elif op == "rollback":
            missing = vkeys - live_pods
            assert not missing, (
                f"{site} seed={seed} hit={hit}: rolled-back preemption "
                f"{pid} did not restore victims {sorted(missing)} — "
                "a HALF-EVICTED victim set survived"
            )

    return {
        "site": site,
        "seed": seed,
        "hit": hit,
        "killed": killed,
        "mode": rec.report.journal_mode,
        "snapshot_seq": rec.report.snapshot_seq,
        "snapshots_rejected": rec.report.snapshots_rejected,
        "journal_lines_replayed": rec.report.journal_lines_replayed,
        "torn_tails": rec.report.journal_torn_tails,
        "interior_skipped": rec.report.journal_interior_skipped,
        "reservations_restored": rec.report.reservations_restored,
        "reservations_expired_dropped": rec.report.reservations_expired_dropped,
        "pods": len(pure.list_pods()),
    }


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="crashtest")
    sub = parser.add_subparsers(dest="command", required=True)

    child = sub.add_parser("child", help="internal: the workload driver")
    child.add_argument("--dir", required=True)
    child.add_argument("--seed", type=int, default=0)
    child.add_argument("--site", default="")
    child.add_argument("--hit", type=int, default=1)
    child.add_argument("--events", type=int, default=DEFAULT_EVENTS)
    child.add_argument("--snapshot-every", type=int, default=SNAPSHOT_EVERY)
    child.add_argument("--compact-after", type=int, default=COMPACT_AFTER)
    child.add_argument("--keep", type=int, default=SNAPSHOT_KEEP)

    one = sub.add_parser("one", help="one crash/recover/verify cycle")
    one.add_argument("--site", required=True, choices=CRASH_SITES)
    one.add_argument("--seed", type=int, default=0)
    one.add_argument("--events", type=int, default=DEFAULT_EVENTS)
    one.add_argument("--hit", type=int, default=None)

    matrix = sub.add_parser("matrix", help="full site × seed matrix")
    matrix.add_argument("--seeds", default="0,1,2")
    matrix.add_argument("--events", type=int, default=DEFAULT_EVENTS)

    args = parser.parse_args(argv)

    if args.command == "child":
        return run_child(args)

    if args.command == "one":
        with tempfile.TemporaryDirectory(prefix="crashtest-") as tmp:
            report = run_crash_cycle(
                args.site, args.seed, tmp, events=args.events, hit=args.hit
            )
        print(json.dumps(report, indent=2))
        return 0

    seeds = [int(s) for s in args.seeds.split(",") if s != ""]
    failures = 0
    for site in CRASH_SITES:
        for seed in seeds:
            with tempfile.TemporaryDirectory(prefix="crashtest-") as tmp:
                try:
                    report = run_crash_cycle(site, seed, tmp, events=args.events)
                except AssertionError as e:
                    failures += 1
                    print(f"FAIL {site} seed={seed}: {e}")
                    continue
            print(
                f"PASS {site:<28} seed={seed} hit={report['hit']:<4} "
                f"killed={str(report['killed']):<5} mode={report['mode']:<13} "
                f"replayed={report['journal_lines_replayed']:<4} "
                f"torn={report['torn_tails']} pods={report['pods']}"
            )
    total = len(CRASH_SITES) * len(seeds)
    print(f"\n{total - failures}/{total} crash points recovered cleanly")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
