#!/usr/bin/env python
"""Kill-mid-handoff chaos matrix for live elastic resharding.

Every abort path of the fenced two-phase handoff (sharding/reshard.py),
driven deterministically over in-process shard cores (LocalShard — the
same transport the sharding equivalence tests use; the real-process
SIGKILL variant lives in scenarios/resharding.py), x 3 seeds:

    reshard.handoff.torn:torn   chunk corrupted → sink hash check refuses
    reshard.handoff.torn:error  stream torn outright
    reshard.dest.crash:error    destination fails mid-import
    reshard.fence.race:error    fence superseded after it was taken
    reshard.front.crash:error   coordinator dies between prepare and
                                cutover (TTL reapers clean both sides)
    src-down                    handoff source marked dead mid-stream
    dest-down                   handoff destination marked dead mid-stream

After every episode the matrix asserts the full abort contract:

- the retried (or re-run) rescale completes and adopts the target ring;
- ZERO wrong verdicts vs a single-process oracle rebuilt from the final
  state;
- ZERO orphan reservations: every shard's ``reshard_audit`` is clean —
  no reservation against a throttle the shard no longer holds, no
  pending handoff, no standing fence (TTL reapers forced where the
  abort path leaves orphans by design).

Run: ``python tools/reshardtest.py matrix`` (wired into docs/robustness
as the resharding analog of crashtest/hatest).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

SEEDS = (0, 1, 2)

CASES = (
    ("reshard.handoff.torn", "torn"),
    ("reshard.handoff.torn", "error"),
    ("reshard.dest.crash", "error"),
    ("reshard.fence.race", "error"),
    ("reshard.front.crash", "error"),
    ("src-down", ""),
    ("dest-down", ""),
)


def build_stack(n_shards, core_faults=None, n_throttles=24, n_pods=160,
                n_reserved=12):
    import tools.harness as H
    from kube_throttler_tpu.api.pod import Namespace, make_pod
    from kube_throttler_tpu.sharding.front import AdmissionFront
    from kube_throttler_tpu.sharding.ipc import LocalShard
    from kube_throttler_tpu.sharding.worker import ShardCore

    front = AdmissionFront(n_shards)
    cores = []
    for i in range(n_shards):
        core = ShardCore(i, n_shards, use_device=False, faults=core_faults)
        cores.append(core)
        front.attach_shard(i, LocalShard(i, core, on_push=front.apply_status_push))
    front.store.create_namespace(Namespace("default"))
    for i in range(n_throttles):
        front.store.create_throttle(H.make_throttle(i))
    pods = []
    for i in range(n_pods):
        pod = make_pod(
            f"p{i}", labels={"grp": f"g{i % n_throttles}"},
            requests={"cpu": "100m"},
        )
        front.store.create_pod(pod)
        pods.append(pod)
    assert front.drain(60.0)
    time.sleep(0.3)
    # live reservations make orphan accounting meaningful: a leaked
    # handoff would strand exactly these
    for pod in pods[:n_reserved]:
        status = front.reserve(pod)
        assert status.is_success(), status.reasons
    return front, cores


def attach_new_shard(front, cores, sid, faults=None):
    from kube_throttler_tpu.sharding.ipc import LocalShard
    from kube_throttler_tpu.sharding.worker import ShardCore

    core = ShardCore(sid, sid + 1, use_device=False, faults=faults)
    cores.append(core)
    front.attach_shard(sid, LocalShard(sid, core, on_push=front.apply_status_push))
    front.resync_shard(sid)
    return core


def audit_all(front, cores):
    """Every shard's orphan audit; returns the list of violations."""
    bad = []
    for sid in range(len(cores)):
        handle = front.shards.get(sid)
        if handle is None or not handle.alive:
            continue
        a = handle.request("reshard_audit", None)
        if a["orphan_reservations"]:
            bad.append(f"shard-{sid}: orphans {a['orphan_reservations']}")
        if a["pending_handoffs"]:
            bad.append(f"shard-{sid}: pending handoffs")
        if a["fenced_handoffs"]:
            bad.append(f"shard-{sid}: fences {a['fenced_handoffs']}")
    return bad


def oracle_wrong(front):
    import tools.harness as H
    from kube_throttler_tpu.api.pod import Namespace
    from kube_throttler_tpu.engine.store import Store

    store = Store()
    store.create_namespace(Namespace("default"))
    for thr in front.store.list_throttles():
        store.create_throttle(thr)
    for pod in front.store.list_pods():
        store.create_pod(pod)
    oracle = H.build_plugin(store)
    oracle.run_pending_once()
    wrong = []
    for pod in store.list_pods():
        got = front.pre_filter(pod)
        want = oracle.pre_filter(pod)
        if got.code != want.code or H.normalized_reasons(
            got.reasons
        ) != H.normalized_reasons(want.reasons):
            wrong.append(pod.key)
    oracle.stop()
    return wrong


def run_case(site, mode, seed):
    from kube_throttler_tpu.faults.plan import FaultPlan
    from kube_throttler_tpu.sharding.reshard import (
        CoordinatorCrash,
        ReshardCoordinator,
    )
    from kube_throttler_tpu.sharding.ring import HashRing, plan_reshard

    worker_plan = coord_plan = dest_plan = None
    if site == "reshard.handoff.torn":
        # source-side site: arm the initial cores (only a source hits it)
        worker_plan = FaultPlan(seed=seed).rule(site, mode=mode, times=1)
    elif site == "reshard.dest.crash":
        # destination-side site: arm the NEW shard the rescale streams to
        dest_plan = FaultPlan(seed=seed).rule(site, mode=mode, times=1)
    elif site.startswith("reshard."):
        coord_plan = FaultPlan(seed=seed).rule(site, mode=mode, times=1)

    front, cores = build_stack(2, core_faults=worker_plan)
    result = {"case": f"{site}:{mode}" if mode else site, "seed": seed}
    try:
        attach_new_shard(front, cores, 2, faults=dest_plan)
        front.n_shards = 3
        target = HashRing(3)

        if site in ("src-down", "dest-down"):
            # kill one side mid-stream: fail the first chunk relay by
            # marking the handle dead right before the rescale begins,
            # revive after the first abort, and let the retry land
            plan = plan_reshard(front.ring, target)
            victim_sid = (
                plan.moves[0].src if site == "src-down" else plan.moves[0].dst
            )
            handle = front.shards[victim_sid]
            handle.alive = False

            import threading

            def revive():
                time.sleep(1.0)
                handle.alive = True
                handle.clear_dirty()

            threading.Thread(target=revive, daemon=True).start()
            report = ReshardCoordinator(front).rescale(target, deadline_s=60.0)
            result["aborts"] = report["aborts"]
            assert report["aborts"] >= 1, "down handle never aborted a handoff"
        else:
            coordinator = ReshardCoordinator(front, faults=coord_plan)
            try:
                report = coordinator.rescale(target, deadline_s=60.0)
                result["aborts"] = report["aborts"]
                if site != "reshard.front.crash":
                    armed = worker_plan or dest_plan or coord_plan
                    fired = armed.fired(site)
                    assert fired >= 1, f"{site} never fired"
                    assert report["aborts"] >= 1, f"{site} fired but no abort"
            except CoordinatorCrash:
                assert site == "reshard.front.crash"
                # the orphaned handoff is nobody's problem but the TTL
                # reapers': force them, then prove a fresh coordinator
                # (the restarted front) completes the retarget
                for core in cores:
                    core.prepare_ttl = 0.0
                    core.reap_stale_txns()
                report = ReshardCoordinator(front).rescale(
                    target, deadline_s=60.0
                )
                result["aborts"] = report["aborts"]
                result["reaped"] = sum(c.reaped_handoffs for c in cores)
                assert result["reaped"] >= 1, "reapers never cleaned the orphan"

        assert front.drain(60.0)
        time.sleep(0.4)
        wrong = oracle_wrong(front)
        assert not wrong, f"wrong verdicts after abort+retry: {wrong[:3]}"
        bad = audit_all(front, cores)
        assert not bad, f"orphan audit failed: {bad}"
        result["ok"] = True
        return result
    finally:
        for core in cores:
            core.stop()
        front.stop()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="reshardtest")
    sub = parser.add_subparsers(dest="command", required=True)
    m = sub.add_parser("matrix", help="every abort path x 3 seeds")
    m.add_argument("--seeds", default=",".join(str(s) for s in SEEDS))
    m.add_argument("--json", default="", help="write the matrix report here")
    one = sub.add_parser("one", help="a single case")
    one.add_argument("--site", required=True)
    one.add_argument("--mode", default="error")
    one.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)

    from kube_throttler_tpu.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()

    if args.command == "one":
        result = run_case(args.site, args.mode, args.seed)
        print(json.dumps(result, indent=2))
        return 0

    seeds = [int(s) for s in args.seeds.split(",") if s != ""]
    results, failures = [], 0
    for site, mode in CASES:
        for seed in seeds:
            label = f"{site}:{mode}" if mode else site
            t0 = time.monotonic()
            try:
                result = run_case(site, mode, seed)
                result["wall_s"] = round(time.monotonic() - t0, 1)
                results.append(result)
                print(f"PASS {label:<28} seed={seed} "
                      f"aborts={result.get('aborts')} ({result['wall_s']}s)")
            except Exception as e:  # noqa: BLE001 — matrix reports, then fails
                failures += 1
                results.append({"case": label, "seed": seed, "error": repr(e)})
                print(f"FAIL {label:<28} seed={seed}: {e!r}")
    total = len(CASES) * len(seeds)
    print(f"\n{total - failures}/{total} abort paths clean "
          "(zero wrong verdicts, zero orphan reservations)")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=2)
            f.write("\n")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
