#!/usr/bin/env python
"""Kill-the-leader chaos harness for the active/standby HA subsystem
(engine/replication.py).

Topology per cycle: a LEADER child (store + journal + snapshots + fencing
epoch + a replication HTTP endpoint) drives a crashtest-style workload
while a warm STANDBY child bootstraps from its newest snapshot and streams
the journal tail into its own data directory. A seeded fault plan SIGKILLs
the leader at an ``ha.*`` site (faults/plan.py) — mid-journal-batch,
mid-status-commit, mid-snapshot, mid-replication-send. The OS drops the
leader's flock lease on death; the standby's blocked ``acquire`` returns,
it fast-forwards the remaining tail, bumps the fencing epoch, re-publishes
every throttle status from replicated truth (the flip re-publication
step), answers a full admission sweep, and writes a report.

The parent then asserts the **failover oracle**:

1. *bounded window* — the standby serves (admission verdicts answered)
   within ``--window`` seconds of the leader's death;
2. *replay equivalence* — the standby's post-failover store is identical,
   object for object, to a pure from-genesis replay of the standby's own
   journal (the crashtest oracle, applied to the replicated log);
3. *zero lost flips* — every throttle's post-failover ``throttled`` flags
   equal a deterministic recompute from the replicated pods/specs: a flip
   the dead leader computed but never durably published is re-derived,
   never lost, and nothing phantom appears;
4. *admission equivalence* — ``pre_filter`` verdicts for every pod match
   between the promoted standby and a plugin built over the pure replay;
5. *epoch monotonicity* — the standby's term is strictly greater than the
   dead leader's, and its journal records it (a restart re-learns it);
6. *clean stream* — zero replication lines skipped (nothing torn leaked
   past the chunk protocol).

A separate **split-brain scenario** (in-process, per seed) proves the
fencing half: a paused-then-resumed old leader's status/lease writes are
rejected by the mockserver's epoch gate (reason ``FencedEpoch``), counted,
and leave state untouched, while the async committer demotes itself on the
first rejection.

Usage:
    python tools/hatest.py matrix [--seeds 0,1,2] [--events 120]
    python tools/hatest.py one --site ha.status.commit --seed 0
    python tools/hatest.py splitbrain [--seed 0]
    python tools/hatest.py leader|standby ...   (internal: the children)

``make ha-test`` runs the full matrix; tests/test_ha.py runs one smoke
cycle in tier-1 and the matrix behind ``-m slow``.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import shutil
import signal
import sys
import tempfile
import time
from dataclasses import replace

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tools import harness  # noqa: E402 — the shared child-process toolkit

HA_SITES = (
    "ha.journal.batch",
    "ha.status.commit",
    "ha.snapshot.write",
    "ha.replication.send",
)

DEFAULT_EVENTS = 120
DEFAULT_WINDOW_S = 10.0  # 2 x the pair's nominal 5s lease duration
SNAPSHOT_EVERY = 25
COMPACT_AFTER = 10**9  # never compact under the stream in the harness
LEASE_RETRY = 0.05
EVENT_PACE_S = 0.002  # keep the stream flowing while the workload runs


def default_hit(site: str, seed: int) -> int:
    """1-based hit of ``site`` to die at, spread so each seed kills a
    different occurrence (site hit frequencies differ wildly)."""
    if site == "ha.status.commit":
        return 4 + 7 * seed  # ~30% of events are status writes
    if site == "ha.journal.batch":
        return 2 + 2 * seed  # one hit per micro-batch
    if site == "ha.replication.send":
        return 3 + 2 * seed  # one hit per standby poll with data
    # ha.snapshot.write: hit 1 is the pre-replication bootstrap snapshot —
    # die at a later cut, while the standby is streaming
    return 2 + seed


# --------------------------------------------------------------------------
# leader child
# --------------------------------------------------------------------------


def run_leader(args) -> int:
    from kube_throttler_tpu.api.pod import Namespace, make_pod
    from kube_throttler_tpu.engine.recovery import RecoveryManager
    from kube_throttler_tpu.engine.replication import (
        FencingEpoch,
        HaCoordinator,
        ReplicationServer,
        ReplicationSource,
    )
    from kube_throttler_tpu.engine.snapshot import SnapshotManager
    from kube_throttler_tpu.engine.store import Store
    from kube_throttler_tpu.faults.plan import FaultPlan
    from kube_throttler_tpu.utils.leaderelect import FileLeaseElector

    plan = None
    if args.site:
        plan = FaultPlan(seed=args.seed).rule(
            args.site, mode="kill", schedule=[args.hit]
        )

    elector = FileLeaseElector(args.lock, retry_period=LEASE_RETRY)
    assert elector.try_acquire(), "leader child must win the fresh lease"

    store = Store()
    recovery = RecoveryManager(args.dir, faults=plan, compact_after=COMPACT_AFTER)
    journal = recovery.recover_store(store)
    epoch = FencingEpoch(args.dir)
    epoch.observe(recovery.report.epoch)
    journal.fencing = epoch
    snapshotter = SnapshotManager(args.dir, store, keep=2, faults=plan)
    snapshotter.fencing = epoch
    ha = HaCoordinator(epoch, role="leader", journal=journal, snapshotter=snapshotter)
    ha.become_leader()
    snapshotter.bind_journal(journal, every_lines=args.snapshot_every)

    rng = random.Random(args.seed)
    if store.get_namespace("default") is None:
        store.create_namespace(Namespace("default"))
    throttles = []
    for i in range(harness.N_THROTTLES):
        try:
            store.create_throttle(harness.make_throttle(i))
        except ValueError:
            pass
        throttles.append(f"t{i}")
    # one snapshot up front so the standby bootstraps from a snapshot, not
    # from a genesis stream — the "warm standby from newest snapshot" path
    snapshotter.write(reason="bootstrap")

    source = ReplicationSource(args.dir, journal, epoch, faults=plan)
    server = ReplicationServer(source)
    server.start()
    print(f"HATEST leader port={server.port} epoch={epoch.current()}", flush=True)

    # let the standby attach before churning, so the kill interrupts a LIVE
    # replication stream (deterministic coverage of the streaming path)
    deadline = time.time() + 30
    while source.chunks_served == 0 and time.time() < deadline:
        time.sleep(0.01)

    def _mk_pod():
        i = rng.randrange(harness.N_THROTTLES)
        pod = make_pod(
            f"p{rng.randrange(10**9)}",
            labels={"grp": f"g{i}"},
            requests={"cpu": f"{rng.randrange(100, 900)}m"},
        )
        if rng.random() < 0.5:
            pod = replace(pod, spec=replace(pod.spec, node_name="node-1"))
            pod.status.phase = "Running"
        return pod

    for _step in range(args.events):
        op = rng.random()
        if op < 0.35:  # create pod(s); some arrive as one micro-batch
            if rng.random() < 0.35:
                store.apply_events(
                    [("upsert", "Pod", _mk_pod()) for _ in range(rng.randrange(2, 6))]
                )
            else:
                try:
                    store.create_pod(_mk_pod())
                except ValueError:
                    pass
        elif op < 0.5:  # bind a pending pod
            pods = [
                p for p in store.list_pods("default") if p.status.phase == "Pending"
            ]
            if pods:
                p = rng.choice(pods)
                bound = replace(p, spec=replace(p.spec, node_name="node-1"))
                bound = replace(bound, status=replace(bound.status, phase="Running"))
                store.update_pod(bound)
        elif op < 0.6:  # delete a pod
            pods = store.list_pods("default")
            if pods:
                p = rng.choice(pods)
                store.delete_pod(p.namespace, p.name)
        elif op < 0.7:  # spec churn: bump a threshold
            from kube_throttler_tpu.api.types import ResourceAmount

            name = rng.choice(throttles)
            thr = store.get_throttle("default", name)
            store.update_throttle_spec(
                replace(
                    thr,
                    spec=replace(
                        thr.spec,
                        threshold=ResourceAmount.of(
                            pod=rng.randrange(2, 9),
                            requests={"cpu": str(rng.randrange(1, 6))},
                        ),
                    ),
                )
            )
        else:  # reconcile stand-in: status write (possibly a FLIP)
            name = rng.choice(throttles)
            thr = store.get_throttle("default", name)
            store.update_throttle_status(harness.recompute_status(store, thr))
        time.sleep(EVENT_PACE_S)

    # the seeded site never fired: report and idle — the parent SIGKILLs
    # us so a failover still happens at a known instant
    print("HATEST leader done", flush=True)
    while True:
        time.sleep(0.5)


# --------------------------------------------------------------------------
# standby child
# --------------------------------------------------------------------------


def run_standby(args) -> int:
    import jax  # warm the backend BEFORE promotion: the window measures HA,

    jax.devices()  # not JAX cold-start

    from kube_throttler_tpu.engine.recovery import RecoveryManager
    from kube_throttler_tpu.engine.replication import (
        FencingEpoch,
        HaCoordinator,
        StandbyReplicator,
    )
    from kube_throttler_tpu.engine.store import Store
    from kube_throttler_tpu.utils.leaderelect import FileLeaseElector

    store = Store()
    recovery = RecoveryManager(args.dir, compact_after=COMPACT_AFTER)
    journal = recovery.recover_store(store)
    epoch = FencingEpoch(args.dir)
    epoch.observe(recovery.report.epoch)
    journal.fencing = epoch
    replicator = StandbyReplicator(
        store, journal, args.leader_url, epoch=epoch, poll_interval=0.02
    )
    ha = HaCoordinator(epoch, role="standby", replicator=replicator, journal=journal)
    if not replicator.bootstrap(deadline_s=30.0):
        print("HATEST standby bootstrap FAILED", flush=True)
        return 1
    replicator.start()
    print(f"HATEST standby synced offset={replicator.consumed_offset()}", flush=True)

    elector = FileLeaseElector(args.lock, retry_period=LEASE_RETRY)
    elector.acquire()  # blocks until the leader dies (flock freed by the OS)
    t_acquired = time.time()
    new_epoch = ha.promote()

    # flip re-publication: recompute EVERY throttle status from replicated
    # truth — anything the dead leader flipped but never journaled is
    # re-derived here (the daemon path drives the same sweep through the
    # controllers' two-lane pipeline via HaCoordinator.promote_reconcile)
    for thr in store.list_throttles():
        store.update_throttle_status(harness.recompute_status(store, thr))

    plugin = harness.build_plugin(store)
    try:
        verdicts = harness.verdicts(plugin, store)
    finally:
        plugin.stop()
    t_serving = time.time()

    report = {
        "t_acquired": t_acquired,
        "t_serving": t_serving,
        "epoch": new_epoch,
        "failover_s": ha.failover_duration_s,
        "dump": harness.dump_store(store),
        "verdicts": verdicts,
        "replication": {
            "events_applied": replicator.events_applied,
            "bytes_applied": replicator.bytes_applied,
            "lines_skipped": replicator.lines_skipped,
            "apply_errors": replicator.apply_errors,
            "polls": replicator.polls,
            "diverged": replicator.diverged,
        },
    }
    journal.close()
    path = os.path.join(args.dir, "hatest-report.json")
    with open(path, "w") as f:
        json.dump(report, f)
    elector.release()
    print(f"HATEST standby report={path}", flush=True)
    return 0


# --------------------------------------------------------------------------
# parent: one failover cycle + the oracle
# --------------------------------------------------------------------------


def _spawn(role: str, extra):
    return harness.spawn_child(__file__, [role] + extra)


# the shared line-waiter (tools/harness.py) under its historical name
_wait_line = harness.wait_line


def run_ha_cycle(
    site: str,
    seed: int,
    workdir: str,
    events: int = DEFAULT_EVENTS,
    hit: int = None,
    window_s: float = DEFAULT_WINDOW_S,
) -> dict:
    """One leader-kill/standby-promote/verify cycle; raises AssertionError
    with a diagnosis on any oracle violation, else returns a report."""
    from kube_throttler_tpu.engine.journal import attach
    from kube_throttler_tpu.engine.store import Store

    hit = default_hit(site, seed) if hit is None else hit
    lock = os.path.join(workdir, "lease.lock")
    leader_dir = os.path.join(workdir, "leader")
    standby_dir = os.path.join(workdir, "standby")
    os.makedirs(leader_dir, exist_ok=True)
    os.makedirs(standby_dir, exist_ok=True)

    leader = standby = None
    try:
        leader = _spawn(
            "leader",
            [
                "--dir", leader_dir, "--lock", lock,
                "--seed", str(seed), "--events", str(events),
                "--snapshot-every", str(SNAPSHOT_EVERY),
            ]
            + (["--site", site, "--hit", str(hit)] if site else []),
        )
        line = _wait_line(leader, "HATEST leader port=", 60)
        port = int(line.split("port=")[1].split()[0])

        standby = _spawn(
            "standby",
            [
                "--dir", standby_dir, "--lock", lock,
                "--leader-url", f"http://127.0.0.1:{port}",
            ],
        )
        _wait_line(standby, "HATEST standby synced", 120)

        # wait for the seeded SIGKILL (or the workload's end, then kill)
        killed_by_site = True
        deadline = time.time() + 120
        while leader.poll() is None and time.time() < deadline:
            try:
                if _wait_line(leader, "HATEST leader done", 0.2):
                    killed_by_site = False
                    break
            except AssertionError:
                continue
        if leader.poll() is None:
            leader.kill()
        leader.wait(timeout=30)
        t_kill = time.time()
        killed = killed_by_site and leader.returncode == -signal.SIGKILL

        # the standby must promote and report within the window
        line = _wait_line(standby, "HATEST standby report=", window_s + 60)
        report_path = line.split("report=")[1].strip()
        assert standby.wait(timeout=30) == 0, "standby child failed"
        with open(report_path) as f:
            report = json.load(f)
    finally:
        harness.kill_children((leader, standby))

    # oracle 1: bounded failover window (kill → admission answered). The
    # parent's death-detection can lag the actual SIGKILL by a poll tick;
    # the standby's lease acquisition is never earlier than the death
    # (flock is held until the process dies), so anchor on whichever of
    # the two timestamps is earlier — both are same-host wall clock.
    window = report["t_serving"] - min(t_kill, report["t_acquired"])
    assert window <= window_s, (
        f"{site} seed={seed}: standby served {window:.2f}s after the kill "
        f"(bound {window_s:.1f}s)"
    )

    # oracle 2: standby state ≡ pure from-genesis replay of ITS journal
    pure_dir = os.path.join(workdir, "pure")
    if os.path.exists(pure_dir):
        shutil.rmtree(pure_dir)
    shutil.copytree(standby_dir, pure_dir)
    pure = Store()
    pure_journal = attach(
        pure, os.path.join(pure_dir, "store.journal"), compact_after=10**9
    )
    pure_journal.close()
    dump_pure = json.loads(json.dumps(harness.dump_store(pure)))
    assert dump_pure == report["dump"], (
        f"{site} seed={seed} hit={hit}: promoted standby state diverges "
        "from a pure from-genesis replay of its own journal"
    )

    # oracle 3: zero lost flips — post-failover throttled flags equal a
    # deterministic recompute from the replicated pods/specs
    from kube_throttler_tpu.api.serialization import object_to_dict

    for thr in pure.list_throttles():
        expected = harness.recompute_status(pure, thr)
        got = report["dump"]["Throttle"][thr.key]["status"]["throttled"]
        want = json.loads(
            json.dumps(object_to_dict(expected)["status"]["throttled"])
        )
        assert got == want, (
            f"{site} seed={seed} hit={hit}: flip lost on {thr.key}: "
            f"published {got} != recomputed {want}"
        )

    # oracle 4: admission equivalence against the pure replay
    plugin_pure = harness.build_plugin(pure)
    try:
        v_pure = json.loads(json.dumps(harness.verdicts(plugin_pure, pure)))
    finally:
        plugin_pure.stop()
    v_standby = json.loads(json.dumps(report["verdicts"]))
    assert v_pure == v_standby, (
        f"{site} seed={seed} hit={hit}: admission verdicts diverge: "
        f"{ {k: (v_standby.get(k), v_pure.get(k)) for k in set(v_standby) | set(v_pure) if v_standby.get(k) != v_pure.get(k)} }"
    )

    # oracle 5: epoch monotonicity, recorded in the standby's journal
    assert report["epoch"] >= 2, "promotion must bump past the leader's term"
    assert pure_journal.last_epoch == report["epoch"], (
        f"{site} seed={seed}: standby journal records epoch "
        f"{pure_journal.last_epoch}, report says {report['epoch']}"
    )

    # oracle 6: the stream never leaked torn bytes
    rep = report["replication"]
    assert rep["lines_skipped"] == 0, (
        f"{site} seed={seed}: {rep['lines_skipped']} replication line(s) "
        "skipped — the chunk protocol leaked a torn artifact"
    )
    assert not rep["diverged"], f"{site} seed={seed}: replication diverged"

    return {
        "site": site,
        "seed": seed,
        "hit": hit,
        "killed": killed,
        "window_s": round(window, 3),
        "failover_s": round(report["failover_s"], 4),
        "epoch": report["epoch"],
        "events_replicated": rep["events_applied"],
        "pods": len(pure.list_pods()),
    }


# --------------------------------------------------------------------------
# split-brain fencing scenario (in-process)
# --------------------------------------------------------------------------


def run_splitbrain(seed: int = 0) -> dict:
    """A paused-then-resumed old leader keeps writing with its stale
    epoch: every status/lease write must bounce off the mockserver's
    fencing gate, the async committer must demote itself on the first
    rejection, and the state the new leader wrote must stay untouched."""
    import threading

    from kube_throttler_tpu.api.pod import Namespace
    from kube_throttler_tpu.api.serialization import object_to_dict
    from kube_throttler_tpu.client.mockserver import MockApiServer
    from kube_throttler_tpu.client.transport import (
        ApiClient,
        AsyncStatusCommitter,
        FencedError,
        RemoteStatusWriter,
        RemoteVersions,
        RestConfig,
    )
    from kube_throttler_tpu.engine.replication import FencingEpoch

    server = MockApiServer()
    server.store.create_namespace(Namespace("default"))
    thr = harness.make_throttle(seed % harness.N_THROTTLES)
    server.store.create_throttle(thr)
    server.start()
    try:
        url = f"http://127.0.0.1:{server.port}"
        epoch_a, epoch_b = FencingEpoch(), FencingEpoch()
        epoch_a.bump()  # term 1: the original leader
        client_a = ApiClient(
            RestConfig(server=url), qps=None, epoch_provider=epoch_a.current
        )

        def status_put(client, obj):
            key = f"{obj.namespace}/{obj.name}"
            rv = server.store.resource_version("Throttle", key)
            body = object_to_dict(obj)
            body.setdefault("metadata", {})["resourceVersion"] = str(rv)
            return client.put(
                f"/apis/schedule.k8s.everpeace.github.com/v1alpha1/"
                f"namespaces/{obj.namespace}/throttles/{obj.name}/status",
                body,
            )

        status_put(client_a, harness.recompute_status(server.store, thr))
        assert server.fencing_epoch == 1 and server.stale_rejections() == 0

        # failover: the standby bumps past term 1 and writes
        epoch_b.observe(1)
        epoch_b.bump()  # term 2
        client_b = ApiClient(
            RestConfig(server=url), qps=None, epoch_provider=epoch_b.current
        )
        thr_live = server.store.get_throttle("default", thr.name)
        status_put(client_b, harness.recompute_status(server.store, thr_live))
        assert server.fencing_epoch == 2

        # the zombie resumes: direct PUT bounces with FencedError...
        state_before = object_to_dict(server.store.get_throttle("default", thr.name))
        rejected = False
        try:
            status_put(client_a, harness.recompute_status(server.store, thr_live))
        except FencedError:
            rejected = True
        assert rejected, "stale-epoch status PUT was accepted (split brain!)"
        assert server.stale_rejections() == 1
        assert (
            object_to_dict(server.store.get_throttle("default", thr.name))
            == state_before
        ), "a rejected write still mutated state"

        # ...its lease renewal bounces the same way...
        lease_rejected = False
        try:
            client_a.put(
                "/apis/coordination.k8s.io/v1/namespaces/kube-system/leases/kt",
                {"metadata": {"name": "kt"}, "spec": {"holderIdentity": "zombie"}},
            )
        except FencedError:
            lease_rejected = True
        except Exception:
            pass
        assert lease_rejected, "stale-epoch lease write was accepted"

        # ...and the async committer demotes itself on the first rejection
        fenced = threading.Event()
        versions = RemoteVersions()
        key = f"{thr.namespace}/{thr.name}"
        versions.set(
            "Throttle", key, str(server.store.resource_version("Throttle", key))
        )
        committer = AsyncStatusCommitter(
            RemoteStatusWriter(client_a, versions),
            workers=1,
            on_fenced=fenced.set,
        )
        committer.start()
        committer.update_throttle_status(
            harness.recompute_status(server.store, thr_live)
        )
        assert fenced.wait(5.0), "committer never fired on_fenced"
        committer.stop()
        total_rejected = server.stale_rejections()
        assert total_rejected >= 2
        return {
            "seed": seed,
            "stale_rejected": total_rejected,
            "fencing_epoch": server.fencing_epoch,
        }
    finally:
        server.stop()


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="hatest")
    sub = parser.add_subparsers(dest="command", required=True)

    leader = sub.add_parser("leader", help="internal: the leader child")
    leader.add_argument("--dir", required=True)
    leader.add_argument("--lock", required=True)
    leader.add_argument("--seed", type=int, default=0)
    leader.add_argument("--site", default="")
    leader.add_argument("--hit", type=int, default=1)
    leader.add_argument("--events", type=int, default=DEFAULT_EVENTS)
    leader.add_argument("--snapshot-every", type=int, default=SNAPSHOT_EVERY)

    standby = sub.add_parser("standby", help="internal: the standby child")
    standby.add_argument("--dir", required=True)
    standby.add_argument("--lock", required=True)
    standby.add_argument("--leader-url", required=True)

    one = sub.add_parser("one", help="one failover cycle")
    one.add_argument("--site", required=True, choices=HA_SITES)
    one.add_argument("--seed", type=int, default=0)
    one.add_argument("--events", type=int, default=DEFAULT_EVENTS)
    one.add_argument("--hit", type=int, default=None)
    one.add_argument("--window", type=float, default=DEFAULT_WINDOW_S)

    split = sub.add_parser("splitbrain", help="stale-epoch fencing scenario")
    split.add_argument("--seed", type=int, default=0)

    matrix = sub.add_parser("matrix", help="full ha.* site × seed matrix")
    matrix.add_argument("--seeds", default="0,1,2")
    matrix.add_argument("--events", type=int, default=DEFAULT_EVENTS)
    matrix.add_argument("--window", type=float, default=DEFAULT_WINDOW_S)

    args = parser.parse_args(argv)

    if args.command == "leader":
        return run_leader(args)
    if args.command == "standby":
        return run_standby(args)

    if args.command == "one":
        with tempfile.TemporaryDirectory(prefix="hatest-") as tmp:
            report = run_ha_cycle(
                args.site, args.seed, tmp,
                events=args.events, hit=args.hit, window_s=args.window,
            )
        print(json.dumps(report, indent=2))
        return 0

    if args.command == "splitbrain":
        print(json.dumps(run_splitbrain(args.seed), indent=2))
        return 0

    seeds = [int(s) for s in args.seeds.split(",") if s != ""]
    failures = 0
    for site in HA_SITES:
        for seed in seeds:
            with tempfile.TemporaryDirectory(prefix="hatest-") as tmp:
                try:
                    report = run_ha_cycle(
                        site, seed, tmp, events=args.events, window_s=args.window
                    )
                except AssertionError as e:
                    failures += 1
                    print(f"FAIL {site} seed={seed}: {e}")
                    continue
            print(
                f"PASS {site:<22} seed={seed} hit={report['hit']:<4} "
                f"killed={str(report['killed']):<5} "
                f"window={report['window_s']:<6} epoch={report['epoch']} "
                f"replicated={report['events_replicated']:<4} pods={report['pods']}"
            )
    for seed in seeds:
        try:
            report = run_splitbrain(seed)
        except AssertionError as e:
            failures += 1
            print(f"FAIL splitbrain seed={seed}: {e}")
            continue
        print(
            f"PASS {'splitbrain':<22} seed={seed} "
            f"stale_rejected={report['stale_rejected']} "
            f"epoch={report['fencing_epoch']}"
        )
    total = len(HA_SITES) * len(seeds) + len(seeds)
    print(f"\n{total - failures}/{total} HA scenarios green")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
