"""Shared child-process harness for the destructive test tools.

Three harnesses drive workloads in CHILD processes and assert invariant
oracles over what the parent finds afterwards: the SIGKILL crash matrix
(tools/crashtest.py), the kill-the-leader HA matrix (tools/hatest.py), and
the scenario engine's process-level scenarios (apiserver restart, leader
kill — kube_throttler_tpu/scenarios/). This module is the single copy of
what they share:

- **process management**: the child environment (PYTHONPATH to the repo
  checkout, JAX pinned to CPU), run-to-completion and streaming spawns,
  the line-waiter that reads a child's stdout until a marker appears (the
  transcript rides any assertion), and best-effort cleanup;
- **workload fixtures**: the deterministic throttle factory and the
  reconcile stand-in that derives status.used/throttled through the real
  status-subresource write path (which the journal records);
- **oracle helpers**: full store dumps, plugin construction, and
  normalized ``pre_filter`` verdict sweeps — the vocabulary every
  "recovered state ≡ replayed state" assertion is written in.

Keeping these here means a new process-level scenario is a workload loop
plus an oracle, not a third copy of spawn/wait/kill plumbing.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from queue import Empty, Queue
from typing import List, Optional, Sequence

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

# workload knobs the drivers and the oracles agree on
N_THROTTLES = 4


# --------------------------------------------------------------------------
# process management
# --------------------------------------------------------------------------


def child_env() -> dict:
    """Environment for a harness child: the repo importable, JAX on CPU
    (children must never fight over an accelerator mid-matrix)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    return env


def run_child(script: str, argv: Sequence[str], timeout: float = 180.0):
    """Run ``python <script> <argv...>`` to completion (the crash-matrix
    shape: the child either finishes its workload or dies by SIGKILL at
    the seeded site). Returns the CompletedProcess."""
    cmd = [sys.executable, os.path.abspath(script), *argv]
    return subprocess.run(
        cmd,
        capture_output=True,
        text=True,
        timeout=timeout,
        env=child_env(),
        cwd=REPO_ROOT,
    )


def spawn_child(script: str, argv: Sequence[str]) -> subprocess.Popen:
    """Start ``python <script> <argv...>`` streaming (the HA/scenario
    shape: the parent watches stdout markers while the child runs)."""
    cmd = [sys.executable, os.path.abspath(script), *argv]
    return subprocess.Popen(
        cmd,
        cwd=REPO_ROOT,
        env=child_env(),
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )


def wait_line(proc: subprocess.Popen, prefix: str, timeout_s: float) -> str:
    """Read ``proc``'s stdout lines until one starts with ``prefix``; the
    transcript so far rides any assertion. A single drain thread per
    process survives repeated calls (lines already seen are re-scanned
    first, so two waits for the same marker both succeed)."""
    lines: "Queue[str]" = getattr(proc, "_kt_lines", None)
    if lines is None:

        def drain():
            for line in proc.stdout:
                proc._kt_lines.put(line)

        proc._kt_lines = lines = Queue()
        proc._kt_seen = []
        t = threading.Thread(target=drain, daemon=True)
        proc._kt_drain = t
        t.start()
    deadline = time.time() + timeout_s
    for line in proc._kt_seen:
        if line.startswith(prefix):
            return line
    while time.time() < deadline:
        try:
            line = lines.get(timeout=0.2)
        except Empty:
            if proc.poll() is not None and lines.empty():
                break
            continue
        proc._kt_seen.append(line)
        if line.startswith(prefix):
            return line
    raise AssertionError(
        f"never saw {prefix!r} from {proc.args[2] if len(proc.args) > 2 else proc.args}"
        f" (rc={proc.poll()}):\n{''.join(proc._kt_seen)}"
    )


def was_sigkilled(proc) -> bool:
    """True when the (finished) process died by SIGKILL — the seeded
    crash-site death, as opposed to a workload error."""
    rc = proc.returncode if not isinstance(proc, int) else proc
    return rc == -signal.SIGKILL


def kill_children(procs: Sequence[Optional[subprocess.Popen]]) -> None:
    """Best-effort cleanup: SIGKILL whatever is still alive and reap it
    (every harness' ``finally`` block)."""
    for p in procs:
        if p is not None and p.poll() is None:
            p.kill()
            try:
                p.wait(timeout=10)
            except Exception:
                pass


# --------------------------------------------------------------------------
# workload fixtures (deterministic; shared by every child driver)
# --------------------------------------------------------------------------


def make_throttle(i: int):
    """Throttle ``t<i>`` selecting pod group ``g<i>`` with a small
    pod-count + cpu threshold — the crash/HA workloads' fixed topology."""
    from kube_throttler_tpu.api.types import (
        LabelSelector,
        ResourceAmount,
        Throttle,
        ThrottleSelector,
        ThrottleSelectorTerm,
        ThrottleSpec,
    )

    return Throttle(
        name=f"t{i}",
        namespace="default",
        spec=ThrottleSpec(
            throttler_name="kube-throttler",
            threshold=ResourceAmount.of(
                pod=3 + i, requests={"cpu": str(1 + i)}
            ),
            selector=ThrottleSelector(
                selector_terms=(
                    ThrottleSelectorTerm(
                        LabelSelector(match_labels={"grp": f"g{i}"})
                    ),
                )
            ),
        ),
    )


def recompute_status(store, thr):
    """A deterministic reconcile stand-in: count/sum the Running pods the
    throttle's matchLabels selector matches and derive throttled flags —
    enough to populate status.used/throttled/calculatedThreshold through
    the real status-subresource write path (which the journal records)."""
    from kube_throttler_tpu.api.types import (
        CalculatedThreshold,
        IsResourceAmountThrottled,
        ResourceAmount,
        ThrottleStatus,
    )
    from kube_throttler_tpu.resourcelist import pod_request_resource_list

    grp = thr.spec.selector.selector_terms[0].pod_selector.match_labels.get("grp")
    running = [
        p
        for p in store.list_pods("default")
        if p.labels.get("grp") == grp and p.status.phase == "Running"
    ]
    cpu = sum(
        (pod_request_resource_list(p).get("cpu", 0) for p in running), 0
    )
    # exact-Fraction quantities go straight into the dataclass (of() parses
    # strings; these are already canonical)
    used = ResourceAmount(
        resource_counts=len(running), resource_requests={"cpu": cpu}
    )
    threshold = thr.spec.threshold
    flags = IsResourceAmountThrottled(
        resource_counts_pod=(
            threshold.resource_counts is not None
            and len(running) >= threshold.resource_counts
        ),
        resource_requests={
            "cpu": cpu >= (threshold.resource_requests or {}).get("cpu", 0)
        },
    )
    return thr.with_status(
        ThrottleStatus(
            calculated_threshold=CalculatedThreshold(threshold=threshold),
            throttled=flags,
            used=used,
        )
    )


# --------------------------------------------------------------------------
# oracle helpers
# --------------------------------------------------------------------------


def dump_store(store) -> dict:
    """Object-for-object dump of every kind — the replay-equivalence
    oracle's comparison form."""
    from kube_throttler_tpu.api.serialization import object_to_dict

    return {
        "Namespace": {n.name: object_to_dict(n) for n in store.list_namespaces()},
        "Throttle": {t.key: object_to_dict(t) for t in store.list_throttles()},
        "ClusterThrottle": {
            t.name: object_to_dict(t) for t in store.list_cluster_throttles()
        },
        "Pod": {p.key: object_to_dict(p) for p in store.list_pods()},
    }


def normalized_reasons(reasons) -> list:
    """Reason strings with their name lists sorted — verdict comparisons
    must not depend on iteration order."""
    out = []
    for r in reasons:
        head, _, names = r.partition("=")
        out.append(f"{head}={','.join(sorted(names.split(',')))}")
    return sorted(out)


def verdicts(plugin, store) -> dict:
    """``pre_filter`` status (code + normalized reasons) for every stored
    pod — the admission-equivalence oracle's comparison form."""
    out = {}
    for pod in sorted(store.list_pods(), key=lambda p: p.key):
        status = plugin.pre_filter(pod)
        out[pod.key] = (status.code.value, normalized_reasons(status.reasons))
    return out


def build_plugin(store):
    """A KubeThrottler over ``store`` with workers parked — the oracle's
    admission surface."""
    from kube_throttler_tpu.plugin import KubeThrottler, decode_plugin_args

    return KubeThrottler(
        decode_plugin_args(
            {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
        ),
        store,
        use_device=True,
        start_workers=False,
    )
