"""Memory-regression smoke for the columnar arena store (`make ci`).

Builds the REAL serving stack (store → device mirror → informers →
controllers, workers parked) at 50k pods × 1k throttles and gates two
per-pod marginals against committed bounds:

- **heap objects per pod** — the columnar arena's whole point: a stored
  pod must cost ~zero retained Python objects (measured 0.003/pod; the
  frozen-dict model cost ~10/pod). The bound is deliberately loose (0.5)
  so only a real regression — some layer quietly retaining per-pod
  objects again — trips it, not allocator noise.
- **RSS per pod** — arrays + interned strings + key maps (measured
  ~2.5 KB/pod at 50k; bound 6 KB). A blown bound means a dense per-pod
  structure crept back in (the dense [P,T] mask alone would be ~20 KB/pod
  at this shape).

Exit 0 on pass, 1 with a diff-style report on breach. Runs in ~15 s on
one core; wired into hack/ci.sh after lint.
"""

from __future__ import annotations

import gc
import os
import resource
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

PODS = 50_000
THROTTLES = 1_000
GROUPS = 250

# committed bounds (see module docstring for the measured baselines)
MAX_HEAP_OBJECTS_PER_POD = 0.5
MAX_RSS_BYTES_PER_POD = 6_144


def _rss_kb() -> int:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("KT_LOCK_ASSERT", "0")
    import random
    from dataclasses import replace as _replace

    from kube_throttler_tpu.api.pod import Namespace, make_pod
    from kube_throttler_tpu.engine.store import Store
    from tools.harness import build_plugin, make_throttle

    rng = random.Random(0)
    store = Store()
    if store.pod_arena is None:
        print("memsmoke: store is in frozen-dict reference mode; skipping")
        return 0
    plugin = build_plugin(store)
    store.create_namespace(Namespace("default"))
    for i in range(THROTTLES):
        store.create_throttle(_replace(make_throttle(i % 500), name=f"t{i}"))

    gc.collect()
    objs0, rss0 = len(gc.get_objects()), _rss_kb()
    t0 = time.perf_counter()
    for i in range(PODS):
        pod = make_pod(
            f"p{i}",
            labels={"grp": f"g{rng.randrange(GROUPS)}"},
            requests={"cpu": f"{rng.randrange(1, 8) * 100}m"},
        )
        pod = _replace(pod, spec=_replace(pod.spec, node_name="node-1"))
        pod.status.phase = "Running"
        store.create_pod(pod)
    build_s = time.perf_counter() - t0
    gc.collect()
    objs_per_pod = (len(gc.get_objects()) - objs0) / PODS
    rss_per_pod = (_rss_kb() - rss0) * 1024 / PODS

    stats = store.pod_arena.stats()
    print(
        f"memsmoke: {PODS} pods x {THROTTLES} throttles in {build_s:.1f}s — "
        f"{objs_per_pod:.3f} heap objects/pod (bound {MAX_HEAP_OBJECTS_PER_POD}), "
        f"{rss_per_pod:.0f} B RSS/pod (bound {MAX_RSS_BYTES_PER_POD}); "
        f"arena: {stats['slots_live']} slots, {stats['intern_pool_size']} interned, "
        f"{stats['request_shapes']} request shapes"
    )
    ok = True
    if objs_per_pod > MAX_HEAP_OBJECTS_PER_POD:
        print(
            f"memsmoke: FAIL heap objects/pod {objs_per_pod:.3f} > "
            f"{MAX_HEAP_OBJECTS_PER_POD} — a layer is retaining per-pod "
            "objects again (index/informer/devicestate retention?)"
        )
        ok = False
    if rss_per_pod > MAX_RSS_BYTES_PER_POD:
        print(
            f"memsmoke: FAIL RSS/pod {rss_per_pod:.0f} B > {MAX_RSS_BYTES_PER_POD} B "
            "— a dense per-pod structure crept back in"
        )
        ok = False
    plugin.stop()
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
