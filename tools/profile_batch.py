"""Profile pre_filter_batch phases (VERDICT r3 task 3). Run:
    python tools/profile_batch.py [P] [T]
"""
import cProfile
import io
import os
import pstats
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from kube_throttler_tpu.utils.platform import honor_jax_platforms_env  # noqa: E402

honor_jax_platforms_env()

import bench  # noqa: E402

P = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000
T = int(sys.argv[2]) if len(sys.argv) > 2 else 1_000

store, plugin = bench.build_served_stack(P, T, label="prof")

plugin.pre_filter_batch()  # warm/compile

t0 = time.perf_counter()
out = plugin.pre_filter_batch()
print(f"warm pre_filter_batch: {(time.perf_counter()-t0)*1e3:.1f}ms "
      f"for {len(out['schedulable'])} pods")

pr = cProfile.Profile()
pr.enable()
plugin.pre_filter_batch()
pr.disable()
s = io.StringIO()
pstats.Stats(pr, stream=s).sort_stats("cumulative").print_stats(30)
print(s.getvalue())
