"""Regression guard for the padded realistic-shape parallel path: the
driver's dryrun exercises P=8192×T=512; this in-tree version runs the same
assertions (no mid-run recompile, 2D == ring == 1-device dense oracle,
non-degenerate verdicts) at a CI-sized shape so a regression is caught by
`pytest` and not only at round end."""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def test_realistic_shape_parallel_agreement():
    import __graft_entry__ as ge

    # no-op under pytest (conftest already forces the 8-device CPU mesh),
    # but keeps the test runnable standalone on hosts with fewer devices
    ge._force_device_count(8)
    ge._dryrun_realistic(8, P=1024, T=128)
