"""Runtime retrace budget (utils/retrace.py): registered jit entries'
compile-cache sizes are snapshotted at warmup; a later tick whose
counts grew past KT_JIT_RETRACE_BUDGET fails, naming the entries."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import pytest

from kube_throttler_tpu.utils import retrace


@pytest.fixture(autouse=True)
def _isolate(monkeypatch):
    saved = dict(retrace._registry)
    retrace.reset()
    monkeypatch.setattr(retrace, "_registry", {})
    yield
    retrace._registry.update(saved)
    retrace.reset()


def _entry():
    @jax.jit
    def f(x):
        return x + 1

    return f


class TestRegistry:
    def test_register_all_picks_up_jit_entries(self):
        f = _entry()
        ns = {"f": f, "_private": _entry(), "plain": lambda x: x}
        n = retrace.register_all(ns, "kube_throttler_tpu.ops.fake")
        assert n == 1
        assert retrace.registered() == ("ops.fake.f",)

    def test_cache_sizes_count_compiles(self):
        f = _entry()
        retrace.register("e", f)
        assert retrace.cache_sizes()["e"] == 0
        f(jnp.ones(3))
        assert retrace.cache_sizes()["e"] == 1
        f(jnp.ones(3))  # same shape: cached
        assert retrace.cache_sizes()["e"] == 1
        f(jnp.ones(4))  # new shape: recompile
        assert retrace.cache_sizes()["e"] == 2


class TestBudget:
    def test_disarmed_without_env(self, monkeypatch):
        monkeypatch.delenv("KT_JIT_RETRACE_BUDGET", raising=False)
        assert retrace.budget() is None
        retrace.on_tick()  # no-op, no baseline taken
        assert retrace._baseline is None

    def test_malformed_env_disarms_not_crashes(self, monkeypatch):
        monkeypatch.setenv("KT_JIT_RETRACE_BUDGET", "banana")
        assert retrace.budget() is None
        retrace.on_tick()

    def test_fires_on_post_warmup_recompile(self, monkeypatch):
        monkeypatch.setenv("KT_JIT_RETRACE_BUDGET", "0")
        monkeypatch.setenv("KT_JIT_RETRACE_WARMUP", "1")
        f = _entry()
        retrace.register("e", f)
        f(jnp.ones(3))
        retrace.on_tick()  # warmup tick: baseline pinned at 1 compile
        f(jnp.ones(3))
        retrace.on_tick()  # steady state: same shape, no growth
        f(jnp.ones(7))  # shape leak
        with pytest.raises(retrace.RetraceBudgetExceeded) as ei:
            retrace.on_tick()
        assert "e: +1" in str(ei.value)

    def test_budget_allows_n_recompiles(self, monkeypatch):
        monkeypatch.setenv("KT_JIT_RETRACE_BUDGET", "2")
        monkeypatch.setenv("KT_JIT_RETRACE_WARMUP", "1")
        f = _entry()
        retrace.register("e", f)
        f(jnp.ones(3))
        retrace.on_tick()
        f(jnp.ones(4))
        f(jnp.ones(5))
        retrace.on_tick()  # +2 == budget: still inside
        f(jnp.ones(6))
        with pytest.raises(retrace.RetraceBudgetExceeded):
            retrace.on_tick()

    def test_tick_wired_into_aggregate_drain(self):
        # the devicestate tick path calls on_tick() — prove the wiring
        # exists by source, not by spinning a full manager here (the
        # integration tiers drive that with the budget armed)
        import inspect

        from kube_throttler_tpu.engine import devicestate

        src = inspect.getsource(devicestate.DeviceStateManager.aggregate_used_for)
        assert "_retrace_on_tick()" in src
