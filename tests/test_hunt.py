"""Adversarial scenario hunt (PR 12): mutator purity and bounds,
canonical fault-plan dedupe, coverage-map novelty accounting, shrinker
soundness, the search loop (stub-evaluated: the synthetic model makes
hundreds of iterations affordable), the planted-bug fixture through the
REAL engine (found → confirmed → shrunk → promoted → replays red), the
committed regression corpus, and the hunt metric families."""

from __future__ import annotations

import json
import os

import pytest

from kube_throttler_tpu.faults.plan import KNOWN_SITES, FaultPlan, FaultRule
from kube_throttler_tpu.scenarios.corpus import (
    REGRESSIONS_DIR,
    load_regressions,
)
from kube_throttler_tpu.scenarios.dsl import (
    FaultSpec,
    Scenario,
    scenario_from_dict,
    scenario_to_dict,
)
from kube_throttler_tpu.scenarios.hunt.coverage import (
    CoverageMap,
    fingerprint_keys,
    hit_bucket,
)
from kube_throttler_tpu.scenarios.hunt.loop import (
    HuntConfig,
    InProcessEvaluator,
    base_programs,
    hunt,
    planted_bug_program,
)
from kube_throttler_tpu.scenarios.hunt.mutate import (
    BOUNDS,
    MUTABLE_FAULT_SITES,
    mutate,
    normalize,
    program_sha,
    program_size,
)
from kube_throttler_tpu.scenarios.hunt.shrink import failed_gates_of, shrink
from kube_throttler_tpu.scenarios.trace import (
    build_trace,
    canonical_fault_plan,
    serialize_trace,
)


# ---------------------------------------------------------------- mutators


class TestMutatePurity:
    def test_same_seed_identical_child_and_trace_bytes(self):
        base = base_programs()[0]
        for seed in (0, 3, 11):
            a = mutate(base, seed)
            b = mutate(base, seed)
            assert a == b
            # the PR 8 property holds for every child: same (child, trace
            # seed) ⇒ identical committed trace bytes
            assert serialize_trace(*build_trace(a, 0)) == serialize_trace(
                *build_trace(b, 0)
            )

    def test_seeds_explore(self):
        base = base_programs()[0]
        children = {program_sha(mutate(base, s)) for s in range(16)}
        assert len(children) >= 4, "mutation space collapsed"

    def test_children_stay_in_bounds(self):
        program = base_programs()[0]
        for seed in range(40):
            program = mutate(program, seed)
            topo = program.topology
            assert BOUNDS["pods"][0] <= topo.pods <= BOUNDS["pods"][1]
            assert BOUNDS["throttles"][0] <= topo.throttles <= BOUNDS["throttles"][1]
            assert BOUNDS["duration_s"][0] <= program.duration_s <= BOUNDS["duration_s"][1]
            assert len(program.faults) <= BOUNDS["max_faults"]
            assert program.name == f"hunt-{program_sha(program)[:12]}"

    def test_mutable_sites_are_registered(self):
        assert set(MUTABLE_FAULT_SITES) <= KNOWN_SITES

    def test_order_permuted_schedules_dedupe(self):
        base = base_programs()[0]
        f1 = FaultSpec(site="mock.watch.cut", mode="close", window=(0.5, 1.5))
        f2 = FaultSpec(site="mock.status.conflict", mode="conflict", window=(1.0, 2.0))
        from dataclasses import replace

        a = normalize(replace(base, faults=(f1, f2)))
        b = normalize(replace(base, faults=(f2, f1)))
        assert program_sha(a) == program_sha(b)
        assert a == b  # the sorted normal form IS the program

    def test_serialization_round_trip(self):
        child = mutate(base_programs()[1], 5)
        assert scenario_from_dict(scenario_to_dict(child)) == child


class TestCanonicalFaultPlan:
    def test_rule_canonical_drops_defaults(self):
        rule = FaultRule(site="mock.list")
        assert rule.canonical() == {"site": "mock.list"}
        rule = FaultRule(
            site="mock.list", mode="delay", delay=0.1, window=(1.0, 2.0),
            probability=0.5, times=2, at_times=[3.0, 1.0],
        )
        assert rule.canonical() == {
            "site": "mock.list", "mode": "delay", "delay": 0.1,
            "window": [1.0, 2.0], "probability": 0.5, "times": 2,
            "at_times": [1.0, 3.0],
        }

    def test_plan_order_preserved(self):
        plan = FaultPlan(seed=0)
        plan.rule("mock.*", mode="error")
        plan.rule("mock.status.*", mode="delay", delay=0.1)
        rules = plan.canonical_rules()
        assert [r["site"] for r in rules] == ["mock.*", "mock.status.*"]

    def test_trace_header_commits_plan(self):
        scn = planted_bug_program()
        header, _ = build_trace(scn, 0)
        rules, sha = canonical_fault_plan(scn)
        assert header["fault_plan"] == rules
        assert header["fault_plan_sha256"] == sha
        assert rules[0]["site"] == "mock.status.delay"


# ------------------------------------------------------ FaultPlan hygiene


class TestPlanResetRearm:
    def test_reset_rearms_overlapping_windows_and_at_times(self):
        """The mutated-schedule hygiene regression: a plan whose rules
        carry OVERLAPPING windows plus an at_times instant must replay the
        exact same firing sequence after reset() — the shrinker re-replays
        schedules in fresh plans, but a soak reusing one plan relies on
        reset() re-arming every virtual-time rule."""
        plan = FaultPlan(seed=0)
        now = [0.0]
        plan.set_time_source(lambda: now[0])
        plan.rule("mock.list", mode="error", window=(1.0, 3.0), times=1)
        plan.rule("mock.list", mode="delay", window=(2.0, 4.0), times=1)
        plan.rule("mock.list", mode="gone", at_times=[2.5])

        def sequence():
            fired = []
            for t in (0.5, 1.5, 2.2, 2.6, 2.7, 3.5, 4.5):
                now[0] = t
                f = plan.check("mock.list")
                fired.append((t, None if f is None else f.mode))
            return fired

        first = sequence()
        # in the overlap, rule priority decides; each times=1 rule fires
        # once, the at_times rule once at the first hit ≥ 2.5, and the
        # second window keeps serving until it closes at 4.0
        assert first == [
            (0.5, None),
            (1.5, "error"),   # rule 0's window, first firing consumes times=1
            (2.2, "delay"),   # overlap: rule 0 exhausted → rule 1 fires
            (2.6, "gone"),    # at_times 2.5 due (window rules exhausted)
            (2.7, None),      # everything spent
            (3.5, None),
            (4.5, None),
        ]
        plan.reset()
        assert sequence() == first  # every virtual-time rule re-armed
        assert plan.fired("mock.list") == 3


# ---------------------------------------------------------------- coverage


class TestCoverage:
    def test_hit_bucket(self):
        assert [hit_bucket(n) for n in (0, 1, 2, 3, 4, 7, 8, 100)] == [
            0, 1, 2, 2, 4, 4, 8, 64,
        ]

    def test_fingerprint_keys(self):
        report = {
            "fingerprint": {
                "fault_sites": {"mock.list": 3},
                "metric_families": {"kube_throttler_status_lag_seconds": {}},
                "health_transitions": [["reflector/Pod", "ok", "degraded"]],
            },
            "gates": {"flip_p99": {"pass": False}, "verdicts": {"pass": True}},
        }
        keys = fingerprint_keys(report)
        assert keys == {
            "fault:mock.list:2",
            "metric:kube_throttler_status_lag_seconds",
            "health:reflector/Pod:ok->degraded",
            "gate:flip_p99:fail",
            "gate:verdicts:pass",
        }

    def test_novelty_accounting(self):
        cm = CoverageMap()
        assert cm.observe({"a", "b"}) == 2
        assert cm.observe({"a"}) == 0
        assert cm.observe({"a", "c"}) == 1
        assert len(cm) == 3
        rep = cm.report()
        assert rep["coverage_keys"] == 3
        assert rep["keys"] == ["a", "b", "c"]


# --------------------------------------------- stub-evaluated loop + shrink

# The synthetic stack model: a program is "buggy" iff its schedule stalls
# status PUTs hard enough (the planted class). Everything else passes.
# Fingerprints derive from the schedule so coverage-guided search has a
# real gradient to climb — all deterministic, thousands of evals/second.


def _stub_evaluate(scn: Scenario, seed: int):
    buggy = any(
        f.site == "mock.status.delay" and f.delay >= 0.2 for f in scn.faults
    )
    sites = {}
    for f in scn.faults:
        sites[f.site] = sites.get(f.site, 0) + (3 if f.window is not None else 1)
    fams = {"kube_throttler_status_lag_seconds": {"series": 2, "delta": 1.0}}
    if scn.pattern != "churn":
        fams["kube_throttler_ingest_events_total"] = {"series": 1, "delta": 9.0}
    transitions = (
        [["committer", "ok", "degraded"]] if buggy else []
    )
    gates = {
        "flip_p99": {"pass": not buggy, "value": 2000 if buggy else 20, "bound": 250},
        "verdicts": {"pass": True, "value": {"wrong": 0}, "bound": 0},
    }
    return {
        "scenario": scn.name,
        "all_pass": not buggy,
        "gates": gates,
        "trace_sha256": program_sha(scn),
        "fingerprint": {
            "fault_sites": sites,
            "metric_families": fams,
            "health_transitions": transitions,
        },
    }


class TestShrinker:
    def _camouflaged(self) -> Scenario:
        from dataclasses import replace

        base = base_programs()[1]  # diurnal arrival (shrinkable structure)
        return normalize(
            replace(
                base,
                pattern="drain",
                faults=(
                    FaultSpec(site="mock.status.delay", mode="delay",
                              delay=0.3, window=(0.2, 2.0)),
                    FaultSpec(site="mock.watch.cut", mode="close",
                              window=(0.5, 1.5), probability=0.1),
                    FaultSpec(site="scenario.apiserver.restart",
                              mode="restart", t=1.0),
                ),
            )
        )

    def test_shrinks_to_minimal_and_stays_red(self):
        program = self._camouflaged()
        assert program_size(program) > 3
        res = shrink(
            program, 0, _stub_evaluate,
            target_gates=["flip_p99"], max_attempts=40,
        )
        minimal = res["program"]
        assert res["size"] == program_size(minimal) == 1
        assert len(minimal.faults) == 1
        assert minimal.faults[0].site == "mock.status.delay"
        assert minimal.pattern == "churn"
        assert minimal.arrival.kind == "constant"
        assert res["steps"] >= 4
        assert "flip_p99" in res["failed_gates"]
        # soundness: the minimal program still fails under a FRESH eval
        assert failed_gates_of(_stub_evaluate(minimal, 0)) == ["flip_p99"]

    def test_never_accepts_a_green_candidate(self):
        """Every accepted step's recorded failed_gates must intersect the
        target set — a candidate whose re-replay went green is rejected
        even when it would reduce size."""
        res = shrink(
            self._camouflaged(), 0, _stub_evaluate,
            target_gates=["flip_p99"], max_attempts=40,
        )
        for step in res["history"]:
            assert "flip_p99" in step["failed_gates"]

    def test_requires_target_gates(self):
        with pytest.raises(ValueError):
            shrink(base_programs()[0], 0, _stub_evaluate, target_gates=[])


class TestHuntLoopStub:
    def test_search_finds_plants_and_promotes(self, tmp_path):
        """Open-ended search (nothing seeded): the fault-insert mutators
        must DISCOVER the buggy schedule class, the loop must confirm +
        shrink it, and the promotion must land in the corpus dir."""
        cfg = HuntConfig(
            workdir=str(tmp_path / "hunt"),
            budget_s=60.0,
            max_iterations=300,
            hunt_seed=1,
            promote_dir=str(tmp_path / "regressions"),
            shrink_stages=("faults", "flags", "arrival"),
            shrink_max_attempts=20,
            max_findings=1,
            stop_on_finding=True,
        )
        from kube_throttler_tpu.metrics import METRIC_NAMES, Registry

        registry = Registry()
        report = hunt(cfg, evaluate=_stub_evaluate, registry=registry)
        assert report["findings"], "search never found the planted bug class"
        finding = report["findings"][0]
        assert "flip_p99" in finding["failed_gates"]
        assert finding["minimal_size"] <= 2
        assert report["promoted"]
        # the promoted entry round-trips through the corpus loader
        entries = [
            e for e in _load_dir(str(tmp_path / "regressions"))
        ]
        assert len(entries) == 1
        entry = entries[0]
        assert entry["expect"] == "fail:flip_p99"
        assert any(
            f.site == "mock.status.delay" for f in entry["scenario"].faults
        )
        # coverage artifact shape
        cov = report["coverage"]
        assert cov["coverage_keys"] > 0
        assert "mock.status.delay" in cov["fault_sites_reached"]
        assert cov["metric_families_touched"]
        assert "committer:ok->degraded" in cov["health_transitions_seen"]
        assert os.path.exists(report["report_path"])
        # hunt metric families moved and are all registered names
        fams = registry.family_totals()
        for name in (
            "kube_throttler_hunt_iterations_total",
            "kube_throttler_hunt_coverage_size",
            "kube_throttler_hunt_findings_total",
        ):
            assert name in fams and name in METRIC_NAMES
        assert fams["kube_throttler_hunt_iterations_total"][1] == report["iterations"]

    def test_deterministic_given_seed(self, tmp_path):
        reports = []
        for run in ("a", "b"):
            cfg = HuntConfig(
                workdir=str(tmp_path / run),
                budget_s=60.0,
                max_iterations=60,
                hunt_seed=7,
                do_promote=False,
                max_findings=1,
                stop_on_finding=True,
            )
            reports.append(hunt(cfg, evaluate=_stub_evaluate))
        trail_a = [(l["program"], l["novelty"]) for l in reports[0]["log"]]
        trail_b = [(l["program"], l["novelty"]) for l in reports[1]["log"]]
        assert trail_a == trail_b
        assert reports[0]["coverage"]["keys"] == reports[1]["coverage"]["keys"]

    def test_novelty_gates_corpus_admission(self, tmp_path):
        """A child whose fingerprint adds nothing new never joins the
        corpus queue (iteration log novelty 0 and corpus size stays at
        what novel programs earned)."""
        cfg = HuntConfig(
            workdir=str(tmp_path),
            budget_s=30.0,
            max_iterations=40,
            hunt_seed=3,
            do_promote=False,
            max_findings=0,
        )
        report = hunt(cfg, evaluate=_stub_evaluate)
        novel = [l for l in report["log"] if l.get("novelty", 0) > 0]
        assert report["corpus_size"] == len(novel)
        assert any(l.get("novelty", 1) == 0 for l in report["log"])


def _load_dir(path):
    """load_regressions against an arbitrary directory (the loader reads
    the committed dir; tests point it elsewhere via monkey-free reuse)."""
    import importlib

    # (attribute access via the package resolves the corpus FUNCTION the
    # scenarios __init__ re-exports, not the module — go through importlib)
    corpus_mod = importlib.import_module("kube_throttler_tpu.scenarios.corpus")

    old = corpus_mod.REGRESSIONS_DIR
    corpus_mod.REGRESSIONS_DIR = path
    try:
        return corpus_mod.load_regressions()
    finally:
        corpus_mod.REGRESSIONS_DIR = old


# --------------------------------------- the planted bug through the REAL engine


class TestPlantedBugRealEngine:
    def test_find_shrink_promote_and_replay_red(self, tmp_path):
        """Tier-1 end-to-end on the real stack: the planted
        mock.status.delay program (seeded into the corpus — `make
        scenario-hunt-smoke` proves the same lifecycle in fresh
        interpreters) fails flip_p99 through the REAL mockserver fault
        verb, is confirmed, shrunk to ≤2 DSL ops, promoted — and the
        promoted repro replays RED (pre-fix) via the corpus loader."""
        from dataclasses import replace as _replace

        evaluator = InProcessEvaluator(str(tmp_path / "evals"))
        # loosen the flip bound to the tier-1 in-process allowance (the
        # smoke scenario's 400 ms): this test shares a busy interpreter,
        # and the planted stall fails at ~3000 ms either way — the loose
        # bound only protects the shrinker's CLEAN candidates from
        # co-tenant noise
        plant = planted_bug_program()
        plant = normalize(
            _replace(plant, slo=_replace(plant.slo, flip_p99_ms=400.0))
        )
        cfg = HuntConfig(
            workdir=str(tmp_path / "hunt"),
            budget_s=600.0,
            max_iterations=2,
            bases=[],  # in-process runs are pricey: evaluate only the plant
            extra_programs=[plant],
            promote_dir=str(tmp_path / "regressions"),
            shrink_stages=("faults",),
            shrink_max_attempts=3,
            max_findings=1,
            stop_on_finding=True,
        )
        report = hunt(cfg, evaluate=evaluator)
        assert report["findings"], report["log"]
        finding = report["findings"][0]
        assert "flip_p99" in finding["failed_gates"]
        assert finding["minimal_size"] <= 2
        assert report["promoted"]

        entries = _load_dir(str(tmp_path / "regressions"))
        assert len(entries) == 1
        entry = entries[0]
        assert entry["expect"].startswith("fail:")
        replay = evaluator(entry["scenario"], entry["seed"])
        assert replay is not None
        gate = entry["expect"].split(":", 1)[1]
        assert gate in failed_gates_of(replay), (
            "promoted repro no longer replays red — the regression gate "
            "stopped gating"
        )


# ------------------------------------------------- the committed corpus


class TestCommittedRegressionCorpus:
    def test_committed_entries_load_and_are_valid(self):
        entries = load_regressions()
        assert entries, (
            f"no committed regression repros under {REGRESSIONS_DIR} — "
            "the hunt's promotion acceptance artifact is missing"
        )
        for entry in entries:
            scn = entry["scenario"]
            assert isinstance(scn, Scenario)
            for f in scn.faults:
                assert f.site in KNOWN_SITES
            assert entry["expect"] == "pass" or entry["expect"].startswith("fail:")
            assert entry["provenance"].get("found_by") == "scenario-hunt"
            # determinism: the committed program still builds byte-stable
            # traces (two builds, identical bytes)
            a = serialize_trace(*build_trace(scn, entry["seed"]))
            b = serialize_trace(*build_trace(scn, entry["seed"]))
            assert a == b
