"""Oracle decision-logic tests, golden-cased from the reference unit suites
(resource_amount_test.go, throttle_types_test.go,
temporary_threshold_override_test.go, *selector_test.go)."""

from datetime import datetime, timedelta, timezone

import pytest

from kube_throttler_tpu.api import (
    CheckThrottleStatus,
    ClusterThrottle,
    ClusterThrottleSelector,
    ClusterThrottleSelectorTerm,
    ClusterThrottleSpec,
    IsResourceAmountThrottled,
    LabelSelector,
    Namespace,
    ResourceAmount,
    TemporaryThresholdOverride,
    Throttle,
    ThrottleSelector,
    ThrottleSelectorTerm,
    ThrottleSpec,
    resource_amount_of_pod,
)
from kube_throttler_tpu.api.types import (
    CalculatedThreshold,
    LabelSelectorRequirement,
    ThrottleSpecBase,
    ThrottleStatus,
)
from kube_throttler_tpu.api.pod import make_pod

NOW = datetime(2024, 1, 15, 12, 0, 0, tzinfo=timezone.utc)


def rfc(dt: datetime) -> str:
    return dt.strftime("%Y-%m-%dT%H:%M:%SZ")


class TestIsThrottled:
    def test_empty_threshold_never_throttles(self):
        # resource_amount_test.go:31-58
        empty = ResourceAmount()
        for on_equal in (False, True):
            got = empty.is_throttled(ResourceAmount.of(pod=3), on_equal)
            assert got == IsResourceAmountThrottled()
            got = empty.is_throttled(ResourceAmount.of(requests={"r1": "1000"}), on_equal)
            assert got == IsResourceAmountThrottled()

    def test_count_equality_boundary(self):
        # resource_amount_test.go:74-117: 3 vs 3 throttles only onEqual
        thr = ResourceAmount.of(pod=3, requests={"r1": "10", "r2": "20"})
        flags_false = {"r1": False, "r2": False}
        for on_equal in (False, True):
            got = thr.is_throttled(ResourceAmount.of(pod=2), on_equal)
            assert got == IsResourceAmountThrottled(False, flags_false)
        assert thr.is_throttled(ResourceAmount.of(pod=3), False) == IsResourceAmountThrottled(False, flags_false)
        assert thr.is_throttled(ResourceAmount.of(pod=3), True) == IsResourceAmountThrottled(True, flags_false)
        for on_equal in (False, True):
            assert thr.is_throttled(ResourceAmount.of(pod=4), on_equal) == IsResourceAmountThrottled(True, flags_false)

    def test_request_dims_evaluated_independently(self):
        thr = ResourceAmount.of(pod=3, requests={"r1": "10", "r2": "20"})
        got = thr.is_throttled(ResourceAmount.of(requests={"r1": "10", "r2": "19"}), True)
        assert got.resource_requests == {"r1": True, "r2": False}
        got = thr.is_throttled(ResourceAmount.of(requests={"r1": "10", "r2": "19"}), False)
        assert got.resource_requests == {"r1": False, "r2": False}
        got = thr.is_throttled(ResourceAmount.of(requests={"r1": "11", "r2": "21"}), False)
        assert got.resource_requests == {"r1": True, "r2": True}

    def test_used_dim_absent_from_threshold_unchecked(self):
        thr = ResourceAmount.of(requests={"r1": "10"})
        got = thr.is_throttled(ResourceAmount.of(requests={"r9": "99999"}), True)
        assert got.resource_requests == {"r1": False}

    def test_threshold_dim_absent_from_used_not_throttled(self):
        thr = ResourceAmount.of(requests={"r1": "10", "r2": "5"})
        got = thr.is_throttled(ResourceAmount.of(requests={"r1": "10"}), True)
        assert got.resource_requests == {"r1": True, "r2": False}


class TestIsThrottledFor:
    def test_pod_count_flag_always_blocks(self):
        flags = IsResourceAmountThrottled(resource_counts_pod=True)
        pod = make_pod("p")  # no requests at all
        assert flags.is_throttled_for(pod)

    def test_request_flag_blocks_only_nonzero_requesters(self):
        flags = IsResourceAmountThrottled(False, {"cpu": True})
        assert flags.is_throttled_for(make_pod("p", requests={"cpu": "100m"}))
        assert not flags.is_throttled_for(make_pod("p", requests={"memory": "1Gi"}))
        assert not flags.is_throttled_for(make_pod("p", requests={"cpu": "0"}))
        assert not flags.is_throttled_for(make_pod("p"))


class TestAddSub:
    def test_add_nil_counts(self):
        a = ResourceAmount().add(ResourceAmount.of(pod=2, requests={"cpu": "1"}))
        assert a.resource_counts == 2
        b = ResourceAmount.of(pod=1).add(ResourceAmount.of(requests={"cpu": "1"}))
        assert b.resource_counts == 1

    def test_sub_clamps_pod_count_but_not_requests(self):
        a = ResourceAmount.of(pod=1, requests={"cpu": "1"})
        got = a.sub(ResourceAmount.of(pod=5, requests={"cpu": "3"}))
        assert got.resource_counts == 0
        assert got.resource_requests["cpu"] < 0


class TestOverrides:
    def test_is_active_inclusive_boundaries(self):
        # temporary_threshold_override_test.go:40-101
        o = TemporaryThresholdOverride(begin=rfc(NOW), end=rfc(NOW + timedelta(hours=1)))
        assert o.is_active(NOW)
        assert o.is_active(NOW + timedelta(hours=1))
        assert not o.is_active(NOW - timedelta(seconds=1))
        assert not o.is_active(NOW + timedelta(hours=1, seconds=1))

    def test_open_ended(self):
        assert TemporaryThresholdOverride().is_active(NOW)
        assert TemporaryThresholdOverride(begin=rfc(NOW - timedelta(days=1))).is_active(NOW)
        assert TemporaryThresholdOverride(end=rfc(NOW + timedelta(days=1))).is_active(NOW)

    def test_bad_rfc3339_raises(self):
        with pytest.raises(ValueError):
            TemporaryThresholdOverride(begin="error").is_active(NOW)
        # date-only / missing offset are invalid under Go's RFC3339 layout
        with pytest.raises(ValueError):
            TemporaryThresholdOverride(begin="2024-01-15").is_active(NOW)
        with pytest.raises(ValueError):
            TemporaryThresholdOverride(begin="2024-01-15T12:00:00").is_active(NOW)


class TestCalculateThreshold:
    threshold = ResourceAmount.of(pod=5, requests={"cpu": "5", "memory": "5"})
    override1 = TemporaryThresholdOverride(
        begin=rfc(NOW - timedelta(hours=1)),
        end=rfc(NOW + timedelta(hours=1)),
        threshold=ResourceAmount.of(pod=2, requests={"cpu": "2"}),
    )
    override2 = TemporaryThresholdOverride(
        begin=rfc(NOW - timedelta(hours=2)),
        end=rfc(NOW + timedelta(hours=2)),
        threshold=ResourceAmount.of(pod=3, requests={"cpu": "3", "memory": "3"}),
    )

    def test_no_active_overrides(self):
        spec = ThrottleSpecBase(threshold=self.threshold)
        got = spec.calculate_threshold(NOW)
        assert got.threshold == self.threshold
        assert got.calculated_at == NOW
        assert got.messages == ()

    def test_single_active_override_replaces_whole_threshold(self):
        spec = ThrottleSpecBase(
            threshold=self.threshold, temporary_threshold_overrides=(self.override1,)
        )
        got = spec.calculate_threshold(NOW)
        # memory dim from spec does NOT leak through (throttle_types.go:96-98)
        assert got.threshold == self.override1.threshold

    def test_merge_first_wins_per_dimension(self):
        # throttle_types_test.go:110-133
        spec = ThrottleSpecBase(
            threshold=self.threshold,
            temporary_threshold_overrides=(self.override1, self.override2),
        )
        got = spec.calculate_threshold(NOW)
        assert got.threshold == ResourceAmount.of(pod=2, requests={"cpu": "2", "memory": "3"})

    def test_parse_error_skipped_with_message(self):
        # throttle_types_test.go:135-151
        errored = TemporaryThresholdOverride(begin="error", threshold=ResourceAmount.of(pod=9))
        spec = ThrottleSpecBase(
            threshold=self.threshold,
            temporary_threshold_overrides=(self.override1, errored),
        )
        got = spec.calculate_threshold(NOW)
        assert got.threshold == self.override1.threshold
        assert len(got.messages) == 1
        assert got.messages[0].startswith("index 1: Failed to parse Begin")

    def test_inactive_overrides_keep_spec_threshold(self):
        old = TemporaryThresholdOverride(
            begin=rfc(NOW - timedelta(days=2)),
            end=rfc(NOW - timedelta(days=1)),
            threshold=ResourceAmount.of(pod=1),
        )
        spec = ThrottleSpecBase(threshold=self.threshold, temporary_threshold_overrides=(old,))
        assert spec.calculate_threshold(NOW).threshold == self.threshold


class TestNextOverrideHappensIn:
    def test_soonest_future_boundary(self):
        o1 = TemporaryThresholdOverride(
            begin=rfc(NOW + timedelta(hours=2)), end=rfc(NOW + timedelta(hours=3))
        )
        o2 = TemporaryThresholdOverride(
            begin=rfc(NOW - timedelta(hours=1)), end=rfc(NOW + timedelta(minutes=30))
        )
        spec = ThrottleSpecBase(temporary_threshold_overrides=(o1, o2))
        assert spec.next_override_happens_in(NOW) == timedelta(minutes=30)

    def test_no_future_boundaries(self):
        o = TemporaryThresholdOverride(
            begin=rfc(NOW - timedelta(hours=2)), end=rfc(NOW - timedelta(hours=1))
        )
        spec = ThrottleSpecBase(temporary_threshold_overrides=(o,))
        assert spec.next_override_happens_in(NOW) is None

    def test_parse_error_skips_override(self):
        bad = TemporaryThresholdOverride(begin="nope", end=rfc(NOW + timedelta(hours=1)))
        spec = ThrottleSpecBase(temporary_threshold_overrides=(bad,))
        assert spec.next_override_happens_in(NOW) is None


class TestSelectors:
    def test_empty_selector_matches_nothing(self):
        # throttle_selector_test.go: empty selector matches nothing
        sel = ThrottleSelector()
        assert not sel.matches_to_pod(make_pod("p", labels={"a": "b"}))

    def test_empty_term_matches_everything(self):
        sel = ThrottleSelector(selector_terms=(ThrottleSelectorTerm(),))
        assert sel.matches_to_pod(make_pod("p"))
        assert sel.matches_to_pod(make_pod("p", labels={"x": "y"}))

    def test_terms_are_ored(self):
        sel = ThrottleSelector(
            selector_terms=(
                ThrottleSelectorTerm(LabelSelector(match_labels={"team": "a"})),
                ThrottleSelectorTerm(LabelSelector(match_labels={"team": "b"})),
            )
        )
        assert sel.matches_to_pod(make_pod("p", labels={"team": "a"}))
        assert sel.matches_to_pod(make_pod("p", labels={"team": "b"}))
        assert not sel.matches_to_pod(make_pod("p", labels={"team": "c"}))

    def test_match_expressions(self):
        sel = LabelSelector(
            match_expressions=(
                LabelSelectorRequirement("env", "In", ("prod", "staging")),
                LabelSelectorRequirement("canary", "DoesNotExist"),
            )
        )
        assert sel.matches({"env": "prod"})
        assert not sel.matches({"env": "dev"})
        assert not sel.matches({"env": "prod", "canary": "1"})
        assert not sel.matches({})

    def test_cluster_term_requires_namespace_and_pod_match(self):
        term = ClusterThrottleSelectorTerm(
            pod_selector=LabelSelector(match_labels={"throttle": "t1"}),
            namespace_selector=LabelSelector(match_labels={"throttle": "true"}),
        )
        sel = ClusterThrottleSelector(selector_terms=(term,))
        ns_match = Namespace("ns1", labels={"throttle": "true"})
        ns_other = Namespace("ns2")
        pod = make_pod("p", labels={"throttle": "t1"})
        assert sel.matches_to_pod(pod, ns_match)
        assert not sel.matches_to_pod(pod, ns_other)
        assert not sel.matches_to_pod(make_pod("p"), ns_match)
        assert sel.matches_to_namespace(ns_match)
        assert not sel.matches_to_namespace(ns_other)


class TestCheckThrottledFor:
    """The ordered 4-state check incl. the Throttle/ClusterThrottle
    onEqual asymmetry (throttle_types.go:143 vs clusterthrottle_types.go:45)."""

    def _throttle(self, threshold, used=None, throttled=None, calculated=None):
        status = ThrottleStatus(
            calculated_threshold=calculated or CalculatedThreshold(),
            throttled=throttled or IsResourceAmountThrottled(),
            used=used or ResourceAmount(),
        )
        return Throttle(name="t1", spec=ThrottleSpec(threshold=threshold), status=status)

    def test_pod_requests_exceeds_threshold(self):
        thr = self._throttle(ResourceAmount.of(requests={"cpu": "100m"}))
        pod = make_pod("p", requests={"cpu": "200m"})
        got = thr.check_throttled_for(pod, ResourceAmount(), False)
        assert got == CheckThrottleStatus.POD_REQUESTS_EXCEEDS_THRESHOLD

    def test_active_via_status_flags(self):
        thr = self._throttle(
            ResourceAmount.of(requests={"cpu": "1"}),
            throttled=IsResourceAmountThrottled(False, {"cpu": True}),
        )
        pod = make_pod("p", requests={"cpu": "100m"})
        assert thr.check_throttled_for(pod, ResourceAmount(), False) == CheckThrottleStatus.ACTIVE

    def test_active_via_used_plus_reserved_saturation(self):
        # Throttle step 3 hardcodes onEqual=True: used == threshold → active
        thr = self._throttle(
            ResourceAmount.of(requests={"cpu": "1"}),
            used=ResourceAmount.of(pod=2, requests={"cpu": "1"}),
        )
        pod = make_pod("p", requests={"cpu": "100m"})
        assert thr.check_throttled_for(pod, ResourceAmount(), False) == CheckThrottleStatus.ACTIVE

    def test_clusterthrottle_step3_uses_caller_flag(self):
        # same state on a ClusterThrottle with onEqual=False → falls through
        # to step 4: used+pod > threshold → insufficient
        clthr = ClusterThrottle(
            name="c1",
            spec=ClusterThrottleSpec(threshold=ResourceAmount.of(requests={"cpu": "1"})),
            status=ThrottleStatus(used=ResourceAmount.of(pod=2, requests={"cpu": "1"})),
        )
        pod = make_pod("p", requests={"cpu": "100m"})
        assert clthr.check_throttled_for(pod, ResourceAmount(), False) == CheckThrottleStatus.INSUFFICIENT
        # and with onEqual=True it matches the Throttle behavior
        assert clthr.check_throttled_for(pod, ResourceAmount(), True) == CheckThrottleStatus.ACTIVE

    def test_insufficient(self):
        thr = self._throttle(
            ResourceAmount.of(requests={"cpu": "1"}),
            used=ResourceAmount.of(pod=1, requests={"cpu": "900m"}),
        )
        pod = make_pod("p", requests={"cpu": "200m"})
        assert thr.check_throttled_for(pod, ResourceAmount(), False) == CheckThrottleStatus.INSUFFICIENT

    def test_not_throttled(self):
        thr = self._throttle(
            ResourceAmount.of(requests={"cpu": "1"}),
            used=ResourceAmount.of(pod=1, requests={"cpu": "500m"}),
        )
        pod = make_pod("p", requests={"cpu": "200m"})
        assert thr.check_throttled_for(pod, ResourceAmount(), False) == CheckThrottleStatus.NOT_THROTTLED

    def test_reserved_counts_toward_saturation(self):
        thr = self._throttle(
            ResourceAmount.of(requests={"cpu": "1"}),
            used=ResourceAmount.of(pod=1, requests={"cpu": "500m"}),
        )
        pod = make_pod("p", requests={"cpu": "200m"})
        reserved = ResourceAmount.of(pod=1, requests={"cpu": "500m"})
        assert thr.check_throttled_for(pod, reserved, False) == CheckThrottleStatus.ACTIVE

    def test_calculated_threshold_takes_precedence(self):
        thr = self._throttle(
            ResourceAmount.of(requests={"cpu": "10"}),
            calculated=CalculatedThreshold(
                threshold=ResourceAmount.of(requests={"cpu": "100m"}), calculated_at=NOW
            ),
        )
        pod = make_pod("p", requests={"cpu": "200m"})
        assert (
            thr.check_throttled_for(pod, ResourceAmount(), False)
            == CheckThrottleStatus.POD_REQUESTS_EXCEEDS_THRESHOLD
        )

    def test_pod_count_threshold_zero_blocks_any_pod(self):
        # pod-count 0 threshold: pod alone (count 1 > 0) → exceeds
        thr = self._throttle(ResourceAmount.of(pod=0))
        pod = make_pod("p")
        assert (
            thr.check_throttled_for(pod, ResourceAmount(), False)
            == CheckThrottleStatus.POD_REQUESTS_EXCEEDS_THRESHOLD
        )

    def test_unrelated_resource_not_blocked(self):
        # throttle saturated on cpu, pod requests only memory → not throttled
        thr = self._throttle(
            ResourceAmount.of(requests={"cpu": "200m"}),
            used=ResourceAmount.of(pod=1, requests={"cpu": "200m"}),
        )
        pod = make_pod("p", requests={"memory": "512Mi"})
        assert thr.check_throttled_for(pod, ResourceAmount(), False) == CheckThrottleStatus.NOT_THROTTLED


class TestReviewRegressions:
    """Regressions from the round-1 code review findings."""

    def test_huge_utc_offset_is_parse_error_not_crash(self):
        # offsets ≥24h must surface as override parse messages, not crash
        bad = TemporaryThresholdOverride(begin="2026-01-01T00:00:00+25:00")
        spec = ThrottleSpecBase(temporary_threshold_overrides=(bad,))
        got = spec.calculate_threshold(NOW)
        assert len(got.messages) == 1 and "index 0" in got.messages[0]
        assert spec.next_override_happens_in(NOW) is None

    def test_fractional_seconds_exact(self):
        from kube_throttler_tpu.api.types import parse_rfc3339

        assert parse_rfc3339("2026-01-01T00:00:00.000249Z").microsecond == 249
        assert parse_rfc3339("2026-01-01T00:00:00.5Z").microsecond == 500000

    def test_empty_resource_counts_object_is_zero_threshold(self):
        from kube_throttler_tpu.api.serialization import resource_amount_from_dict

        # Go unmarshals resourceCounts:{} to Pod:0 — present, not absent
        ra = resource_amount_from_dict({"resourceCounts": {}})
        assert ra.resource_counts == 0
        assert resource_amount_from_dict({}).resource_counts is None

    def test_invalid_selector_errors_before_label_compare(self):
        from kube_throttler_tpu.api.types import SelectorError

        sel = LabelSelector(
            match_labels={"app": "web"},
            match_expressions=(LabelSelectorRequirement("k", "BadOp"),),
        )
        with pytest.raises(SelectorError):
            sel.matches({"app": "api"})  # matchLabels alone would fail → still error
