"""Runtime lock-order assassin (utils/lockorder.py) under KT_LOCK_ASSERT=1.

conftest turns the env flag on for the whole suite, so make_lock here
returns instrumented primitives. Each test resets the process-global
order graph — the graph is deliberately cumulative (two threads never
need to collide in time), which also means tests must not leak edges
into each other.
"""

from __future__ import annotations

import threading

import pytest

from kube_throttler_tpu.utils import lockorder
from kube_throttler_tpu.utils.lockorder import (
    LockAssertionError,
    LockOrderViolation,
    assert_held,
    guard_attrs,
    make_condition,
    make_lock,
    make_rlock,
    reset_graph,
)


@pytest.fixture(autouse=True)
def _fresh_graph():
    reset_graph()
    yield
    reset_graph()


def test_enabled_in_suite():
    assert lockorder.enabled(), "conftest must arm KT_LOCK_ASSERT for the suite"


def test_inversion_detected_without_a_timed_collision():
    a, b = make_lock("t.a"), make_lock("t.b")
    with a:
        with b:
            pass
    # same thread, opposite order, long after the first pair released:
    # the cumulative edge graph still catches it
    with pytest.raises(LockOrderViolation) as ei:
        with b:
            with a:
                pass
    msg = str(ei.value)
    assert "t.a" in msg and "t.b" in msg
    assert "first sighting" in msg  # diagnostic carries the prior stack


def test_inversion_detected_across_threads():
    a, b = make_lock("x.a"), make_lock("x.b")

    def t1():
        with a:
            with b:
                pass

    th = threading.Thread(target=t1)
    th.start()
    th.join()

    with pytest.raises(LockOrderViolation):
        with b:
            with a:
                pass


def test_transitive_cycle_detected():
    a, b, c = make_lock("tr.a"), make_lock("tr.b"), make_lock("tr.c")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with pytest.raises(LockOrderViolation):
        with c:
            with a:
                pass


def test_consistent_order_never_raises():
    a, b = make_lock("ok.a"), make_lock("ok.b")
    for _ in range(3):
        with a:
            with b:
                pass


def test_nonreentrant_self_reacquire_raises():
    a = make_lock("self.a")
    with pytest.raises(LockOrderViolation, match="re-acquired"):
        with a:
            with a:
                pass


def test_rlock_reenters_fine():
    r = make_rlock("self.r")
    with r:
        with r:
            assert r._is_owned()


def test_release_by_non_owner_raises():
    a = make_lock("rel.a")
    a.acquire()
    err = []

    def t():
        try:
            a.release()
        except LockAssertionError as e:
            err.append(e)

    th = threading.Thread(target=t)
    th.start()
    th.join()
    a.release()
    assert err, "foreign-thread release must raise"


def test_assert_held():
    a = make_lock("ah.a")
    with pytest.raises(LockAssertionError, match="requires lock"):
        assert_held(a, "helper")
    with a:
        assert_held(a, "helper")  # no raise


def test_condition_wait_rebalances_held_stack():
    lock = make_lock("cv.lock")
    cv = make_condition(lock)
    other = make_lock("cv.other")
    done = threading.Event()

    def waiter():
        with cv:
            cv.wait(timeout=5)
            done.set()

    th = threading.Thread(target=waiter)
    th.start()
    # while the waiter sleeps inside wait() it must NOT count as holding
    # cv.lock — acquiring other->lock here would otherwise record a bogus
    # inversion against the waiter's lock->(wait)->... stack
    with other:
        with cv:
            cv.notify_all()
    th.join()
    assert done.is_set()


def test_guard_attrs_rebind_without_lock_raises():
    @guard_attrs
    class Box:
        GUARDED_BY = {"items": "self._lock"}

        def __init__(self):
            self._lock = make_lock("ga.box")
            self.items = []  # construction writes are exempt

        def good(self):
            with self._lock:
                self.items = [1]

        def bad(self):
            self.items = [2]

    box = Box()
    box.good()
    with pytest.raises(LockAssertionError, match="rebound without holding"):
        box.bad()
    # unguarded attributes stay writable
    box.note = "ok"


def test_guard_attrs_inert_without_table():
    @guard_attrs
    class Plain:
        def __init__(self):
            self.x = 1

    p = Plain()
    p.x = 2
    assert p.x == 2


def test_hold_budget_raises_on_over_hold_and_lock_survives():
    """PR 10 runtime half of the blocking checker: a lock held past its
    budget raises AFTER release (the raise reports the over-hold, never
    extends it), and the lock stays usable afterwards."""
    import time

    lockorder.clear_hold_budgets()
    a = make_lock("budget.test.a")
    other = make_lock("other.unbudgeted")
    lockorder.set_hold_budget("budget.test.*", 0.02)
    try:
        with a:
            pass  # fast hold: under budget, no raise
        with pytest.raises(lockorder.LockHoldBudgetExceeded, match="hold budget"):
            with a:
                time.sleep(0.05)
        # unmatched locks fall through to the (unset) env default: no raise
        with other:
            time.sleep(0.05)
    finally:
        lockorder.clear_hold_budgets()
    # the over-hold released the lock before raising: still acquirable
    with a:
        assert a._is_owned()


def test_hold_budget_rearm_and_clear():
    import time

    lockorder.clear_hold_budgets()
    a = make_lock("budget.rearm")
    lockorder.set_hold_budget("budget.rearm", 0.01)
    lockorder.set_hold_budget("budget.rearm", 5.0)  # re-arm replaces
    try:
        with a:
            time.sleep(0.02)  # over the old budget, under the new: fine
    finally:
        lockorder.clear_hold_budgets()
    with a:
        time.sleep(0.02)  # budgets cleared: no raise
