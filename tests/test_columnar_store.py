"""Columnar arena store (PR 11): intern/arena semantics, lazy-edge
materialization round trips, zero per-pod retention, the sparse selector
index vs its dense readout, snapshot v2 + the committed pre-bump (v1)
fixture's migration read path, and the seeded columnar ≡ frozen-dict ≡
batched ≡ sequential equivalence sweep over store dumps, published
``st_*`` planes, and ``pre_filter`` verdicts.
"""

from __future__ import annotations

import gc
import json
import os
import random
import weakref
from dataclasses import replace as _replace

import numpy as np
import pytest

from kube_throttler_tpu.api.pod import Container, Namespace, Pod, PodSpec, PodStatus, make_pod
from kube_throttler_tpu.api.serialization import object_to_dict
from kube_throttler_tpu.engine.columnar import (
    ColumnarEventFrame,
    InternPool,
    PodArena,
    pods_from_columns,
)
from kube_throttler_tpu.engine.index import SelectorIndex
from kube_throttler_tpu.engine.store import Store
from kube_throttler_tpu.quantity import parse_quantity

from tools.harness import (
    build_plugin,
    dump_store,
    make_throttle,
    recompute_status,
    verdicts,
)

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures")


def _pod(i: int, grp: str = "a", cpu: str = "100m", phase: str = "Running") -> Pod:
    pod = make_pod(
        f"p{i}", labels={"grp": grp}, requests={"cpu": cpu},
        annotations={"note": "x"} if i % 2 else None,
    )
    pod = _replace(pod, spec=_replace(pod.spec, node_name="node-1"))
    pod.status.phase = phase
    return pod


class TestInternPool:
    def test_ids_dense_and_reversible(self):
        pool = InternPool()
        ids = [pool.id_of(s) for s in ("a", "b", "a", "c")]
        assert ids == [0, 1, 0, 2]
        assert [pool.name_of(i) for i in (0, 1, 2)] == ["a", "b", "c"]
        assert len(pool) == 3


class TestPodArena:
    def test_materialize_round_trips_every_wire_field(self):
        arena = PodArena()
        pod = Pod(
            name="rt", namespace="ns1", labels={"a": "1", "b": "2"},
            annotations={"k": "v"}, uid="uid-rt",
            spec=PodSpec(
                scheduler_name="sched", node_name="n1",
                containers=[Container.of({"cpu": "250m", "memory": "1Gi"}, name="app")],
                init_containers=[Container.of({"cpu": "1"}, name="init")],
                overhead={"cpu": parse_quantity("10m")},
            ),
            status=PodStatus(phase="Pending"),
        )
        slot = arena.absorb("ns1/rt", pod)
        out = arena.materialize(slot)
        assert object_to_dict(out) == object_to_dict(pod)
        assert out == pod

    def test_shapes_shared_across_pods(self):
        arena = PodArena()
        a, b = _pod(1, grp="g"), _pod(2, grp="g")
        arena.absorb(a.key, a)
        arena.absorb(b.key, b)
        # canonicalization: both live pods share ONE labels dict and one
        # request shape
        assert a.labels is b.labels
        assert a.__dict__["_kt_req_sid"] == b.__dict__["_kt_req_sid"]
        st = arena.stats()
        assert st["label_shapes"] == 1 and st["request_shapes"] == 1

    def test_slot_recycling_and_generations(self):
        arena = PodArena()
        a = _pod(1)
        slot = arena.absorb(a.key, a)
        gen0 = int(arena.gen[slot])
        assert arena.free(a.key) == slot
        assert arena.stats()["slots_recycled_total"] == 1
        b = _pod(99)
        slot2 = arena.absorb(b.key, b)
        assert slot2 == slot  # recycled
        assert int(arena.gen[slot]) > gen0  # generation moved

    def test_entries_for_cached_per_shape(self):
        from kube_throttler_tpu.ops.schema import DimRegistry

        arena = PodArena()
        dims = DimRegistry()
        a, b = _pod(1, cpu="300m"), _pod(2, cpu="300m")
        arena.absorb(a.key, a)
        arena.absorb(b.key, b)
        e1 = arena.entries_for(a.__dict__["_kt_req_sid"], dims)
        e2 = arena.entries_for(b.__dict__["_kt_req_sid"], dims)
        assert e1 is e2  # same shape → the cached list, not a recompute
        assert e1 == [(dims.index_of("cpu"), 300)]


class TestColumnarStore:
    def test_get_list_materialize_lazily(self):
        store = Store(columnar=True)
        pod = _pod(3)
        store.create_pod(pod)
        before = store.pod_arena.materializations_total
        got = store.get_pod("default", "p3")
        assert got == pod and got is not pod
        assert store.pod_arena.materializations_total == before + 1

    def test_update_event_carries_materialized_old(self):
        store = Store(columnar=True)
        seen = []
        store.create_pod(_pod(4, cpu="100m"))
        store.add_event_handler("Pod", lambda e: seen.append(e), replay=False)
        store.update_pod(_pod(4, cpu="700m"))
        (ev,) = seen
        assert ev.old_obj is not None
        assert ev.old_obj.spec.containers[0].requests["cpu"] == parse_quantity("100m")
        assert ev.obj.spec.containers[0].requests["cpu"] == parse_quantity("700m")

    def test_no_per_pod_object_retention(self):
        # the tentpole invariant: once the event dispatch ends, the full
        # serving stack (store + informers + device mirror + controllers)
        # retains NO per-pod Python objects
        store = Store(columnar=True)
        plugin = build_plugin(store)
        store.create_namespace(Namespace("default"))
        store.create_throttle(make_throttle(0))
        pods = [_pod(i, grp="g0") for i in range(8)]
        refs = [weakref.ref(p) for p in pods]
        for p in pods:
            store.create_pod(p)
        # one more event so devicestate's last-event affected-keys cache
        # (strong ref by design) moves off the batch's final pod
        store.create_pod(_pod(99, grp="zzz"))
        del pods, p
        gc.collect()
        alive = [r for r in refs if r() is not None]
        assert not alive, f"{len(alive)} pod objects still retained"
        # the stack still answers for them (materialized on demand)
        assert len(store.list_pods()) == 9
        assert plugin.device_manager.matched_pods("throttle", "default/t0")

    def test_mutate_and_status_subresource_semantics_unchanged(self):
        store = Store(columnar=True)
        store.create_pod(_pod(5))
        out = store.mutate(
            "Pod", "default/p5", lambda cur: _replace(cur, labels={"grp": "moved"})
        )
        assert out.labels == {"grp": "moved"}
        assert store.get_pod("default", "p5").labels == {"grp": "moved"}

    def test_frame_built_for_on_frame_listeners(self):
        store = Store(columnar=True)
        frames = []

        class L:
            def on_frame(self, frame, events):
                frames.append((frame, events))

            def on_batch(self, events):  # pragma: no cover — on_frame wins
                raise AssertionError("on_frame listeners must get the frame")

        store.add_batch_listener(L())
        store.apply_events(
            [("create", "Pod", _pod(6)), ("create", "Namespace", Namespace("n2"))]
        )
        (frame, events) = frames[0]
        assert isinstance(frame, ColumnarEventFrame)
        assert len(frame) == 2
        assert frame.keys == ["default/p6", "n2"]
        assert frame.kinds.tolist() == [
            ColumnarEventFrame.KINDS["Pod"], ColumnarEventFrame.KINDS["Namespace"]
        ]
        assert frame.slots[0] == store.pod_arena.slot_of("default/p6")
        assert frame.slots[1] == -1
        assert frame.rvs.tolist() == [e.rv for e in events]


class TestSparseIndex:
    def _seeded(self, n_pods=40, n_thr=12, seed=3):
        rng = random.Random(seed)
        idx = SelectorIndex("throttle")
        pods = []
        for i in range(n_pods):
            pod = _pod(i, grp=f"g{rng.randrange(5)}")
            pods.append(pod)
            idx.upsert_pod(pod)
        thrs = []
        for i in range(n_thr):
            t = _replace(make_throttle(i % 5), name=f"t{i}")
            thrs.append(t)
            idx.upsert_throttle(t)
        return idx, pods, thrs, rng

    def test_dense_property_matches_sparse_accessors(self):
        idx, pods, thrs, rng = self._seeded()
        dense = idx.mask
        for pod in pods:
            row = idx.pod_row(pod.key)
            np.testing.assert_array_equal(
                np.flatnonzero(dense[row]), idx.row_cols(row)
            )
        for t in thrs:
            col = idx.throttle_col(t.key)
            np.testing.assert_array_equal(
                np.flatnonzero(dense[:, col]), idx.rows_of_col(col)
            )

    def test_churn_keeps_sparse_consistent(self):
        idx, pods, thrs, rng = self._seeded()
        for _ in range(120):
            verb = rng.choice(["pod", "thr", "rm_pod", "rm_thr"])
            if verb == "pod":
                i = rng.randrange(len(pods))
                pods[i] = _pod(i, grp=f"g{rng.randrange(5)}")
                idx.upsert_pod(pods[i])
            elif verb == "thr":
                i = rng.randrange(len(thrs))
                thrs[i] = _replace(make_throttle(rng.randrange(5)), name=f"t{i}")
                idx.upsert_throttle(thrs[i])
            elif verb == "rm_pod":
                idx.remove_pod(pods[rng.randrange(len(pods))].key)
            else:
                idx.remove_throttle(thrs[rng.randrange(len(thrs))].key)
        dense = idx.mask
        # row/col readouts agree with the dense materialization after churn
        for key, row in list(idx._pod_rows.items()):
            np.testing.assert_array_equal(np.flatnonzero(dense[row]), idx.row_cols(row))
        nnz = dense.sum(axis=1).max() if dense.size else 0
        assert idx.nnz_max() == nnz
        sub_rows = np.array(sorted(idx._pod_rows.values()))[:7]
        if sub_rows.size:
            np.testing.assert_array_equal(dense[sub_rows], idx.mask_rows(sub_rows))

    def test_kcap_growth_on_wide_rows(self):
        idx = SelectorIndex("throttle")
        pod = _pod(0, grp="g0")
        idx.upsert_pod(pod)
        # 20 throttles all matching the one pod: the row outgrows the
        # initial kcap (8) and the sparse plane doubles
        for i in range(20):
            idx.upsert_throttle(_replace(make_throttle(0), name=f"w{i}"))
        row = idx.pod_row(pod.key)
        assert idx.row_cols(row).size == 20
        assert idx.nnz_max() == 20


class TestSnapshotV2Migration:
    def test_prebump_v1_fixture_recovers_bit_identically(self, tmp_path):
        """The committed pre-bump snapshot (pods as manifest dicts,
        header version 1) must recover through engine/recovery.py into
        exactly the store a v2 writer → v2 reader round trip produces."""
        import shutil

        from kube_throttler_tpu.engine.recovery import RecoveryManager
        from kube_throttler_tpu.engine.snapshot import SnapshotManager, load_snapshot

        fixture = os.path.join(FIXTURES, "snapshot-v1-prebump.ktsnap")
        payload = load_snapshot(fixture)  # version-1 header parses
        assert payload["rv"] == 42

        v1_dir = tmp_path / "v1"
        v1_dir.mkdir()
        shutil.copy(fixture, v1_dir / "snapshot-000000000001.ktsnap")
        store_v1 = Store(columnar=True)
        rec = RecoveryManager(str(v1_dir))
        journal = rec.recover_store(store_v1)
        journal.close()
        assert rec.report.snapshot_objects == 9
        assert store_v1.latest_resource_version >= 42

        # the SAME state written by the v2 writer and recovered again
        v2_dir = tmp_path / "v2"
        v2_dir.mkdir()
        mgr = SnapshotManager(str(v2_dir), store_v1)
        path = mgr.write(reason="migrate")
        payload2 = load_snapshot(path)
        assert "podColumns" in payload2  # columnar block, v2 shape
        assert not any(d["kind"] == "Pod" for d in payload2["objects"])
        store_v2 = Store(columnar=True)
        rec2 = RecoveryManager(str(v2_dir))
        rec2.recover_store(store_v2).close()
        assert dump_store(store_v2) == dump_store(store_v1)

    def test_v2_round_trip_frozen_dict_reader(self, tmp_path):
        # a frozen-dict (reference-mode) store recovers a v2 columnar
        # snapshot identically — the migration path runs both directions
        from kube_throttler_tpu.engine.recovery import RecoveryManager
        from kube_throttler_tpu.engine.snapshot import SnapshotManager

        src = Store(columnar=True)
        src.create_namespace(Namespace("default"))
        for i in range(4):
            src.create_pod(_pod(i, grp=f"g{i % 2}", cpu=f"{(i + 1) * 100}m"))
        SnapshotManager(str(tmp_path), src).write()
        dst = Store(columnar=False)
        RecoveryManager(str(tmp_path)).recover_store(dst).close()
        assert dump_store(dst)["Pod"] == dump_store(src)["Pod"]

    def test_unsupported_version_still_rejected(self, tmp_path):
        import hashlib

        from kube_throttler_tpu.engine.snapshot import SnapshotError, parse_snapshot_bytes

        data = json.dumps({"objects": []}).encode()
        header = json.dumps(
            {
                "format": "kube-throttler-snapshot",
                "version": 3,
                "sha256": hashlib.sha256(data).hexdigest(),
                "length": len(data),
            }
        ).encode()
        with pytest.raises(SnapshotError):
            parse_snapshot_bytes(header + b"\n" + data + b"\n")

    def test_pods_from_columns_shares_shapes(self):
        arena = PodArena()
        pods = [_pod(i, grp="same") for i in range(3)]
        for p in pods:
            arena.absorb(p.key, p)
        block = arena.export_columns([p.key for p in pods])
        out = list(pods_from_columns(block))
        assert [object_to_dict(o) for o in out] == [object_to_dict(p) for p in pods]
        assert out[0].labels is out[1].labels  # shared shape on the read side


class TestEquivalenceSweep:
    """Columnar ≡ frozen-dict ≡ batched ≡ sequential, pinned on store
    dumps, published st_* planes, and pre_filter verdicts (the bench
    --mega sweep's committed twin)."""

    def _stream(self, seed, n_pods, n_thr):
        rng = random.Random(seed)
        ops = []
        for i in range(n_thr):
            ops.append(("create", "Throttle", _replace(make_throttle(i % 6), name=f"t{i}")))
        for i in range(n_pods):
            ops.append(("create", "Pod", _pod(i, grp=f"g{rng.randrange(6)}",
                                              cpu=f"{rng.randrange(1, 8) * 100}m")))
        for _ in range(n_pods):
            i = rng.randrange(n_pods)
            verb = rng.choice(["relabel", "resize", "delete", "revive", "finish"])
            if verb == "delete":
                ops.append(("delete", "Pod", f"default/p{i}"))
            elif verb == "finish":
                ops.append(("upsert", "Pod", _pod(i, grp=f"g{rng.randrange(6)}",
                                                  phase="Succeeded")))
            else:
                ops.append(("upsert", "Pod", _pod(i, grp=f"g{rng.randrange(6)}",
                                                  cpu=f"{rng.randrange(1, 8) * 100}m")))
        return ops

    @pytest.mark.parametrize("seed", [0, 7])
    def test_sweep(self, seed):
        ops = self._stream(seed, n_pods=60, n_thr=18)
        ns = Namespace("default")

        def run(columnar, chunk):
            store = Store(columnar=columnar)
            plugin = build_plugin(store)
            store.create_namespace(ns)
            for s in range(0, len(ops), chunk):
                store.apply_events(ops[s : s + chunk])
            for thr in store.list_throttles():
                store.update_throttle_status(recompute_status(store, thr))
            return (
                dump_store(store),
                plugin.device_manager.published_flags(),
                verdicts(plugin, store),
            )

        col_batched = run(True, 32)
        col_seq = run(True, 1)
        ref = run(False, 1)
        assert col_batched[0] == col_seq[0] == ref[0]  # dumps
        assert col_batched[1] == col_seq[1] == ref[1]  # st_* planes
        assert col_batched[2] == col_seq[2] == ref[2]  # verdicts


class TestCrossArenaProbe:
    def test_foreign_pod_never_resolves_against_local_shape_table(self):
        """Regression (found by the sharded bad-day oracle): a pod
        materialized from one store and probed against another stack —
        the front→shard IPC shape, and every oracle sweep — carries its
        OWN arena's request-shape id. Resolving that id against the
        serving arena's table silently encodes the wrong request row;
        the arena token must gate the fast path."""
        # store A interns shapes in the order [7cpu]; store B in the
        # order [100m] — the same sid means different shapes
        store_a = Store(columnar=True)
        store_a.create_pod(_pod(0, grp="g0", cpu="7"))
        probe = store_a.get_pod("default", "p0")  # sid 0 in A = 7 cpu

        store_b = Store(columnar=True)
        plugin_b = build_plugin(store_b)
        store_b.create_namespace(Namespace("default"))
        thr = make_throttle(0)  # grp g0, cpu threshold 1
        store_b.create_throttle(thr)
        store_b.create_pod(_pod(1, grp="g0", cpu="100m"))  # sid 0 in B = 100m

        assert probe.__dict__["_kt_req_sid"] == 0  # ids collide across arenas
        status = plugin_b.pre_filter(probe)
        # 7 cpu > the 1-cpu threshold: pod-requests-exceeds-threshold —
        # with the bug the probe read B's sid-0 shape (100m) and passed
        assert status.code.value != "Success", status
        assert any("exceeds" in r for r in status.reasons), status.reasons

    def test_unpickled_pod_token_never_matches(self):
        import pickle

        store = Store(columnar=True)
        store.create_pod(_pod(0))
        pod = store.get_pod("default", "p0")
        clone = pickle.loads(pickle.dumps(pod))
        assert clone.__dict__.get("_kt_arena") is not store.pod_arena.token


class TestStoreMetrics:
    def test_families_registered_and_sampled(self):
        from kube_throttler_tpu.metrics import METRIC_NAMES, Registry, register_store_metrics

        for fam in (
            "kube_throttler_store_arena_slots_live",
            "kube_throttler_store_arena_slots_recycled_total",
            "kube_throttler_store_intern_pool_size",
            "kube_throttler_store_materializations_total",
        ):
            assert fam in METRIC_NAMES
        store = Store(columnar=True)
        reg = Registry()
        register_store_metrics(reg, store)
        store.create_pod(_pod(1))
        store.get_pod("default", "p1")
        store.delete_pod("default", "p1")
        text = reg.exposition()
        assert "kube_throttler_store_arena_slots_live 0" in text
        assert "kube_throttler_store_arena_slots_recycled_total 1" in text
        assert "kube_throttler_store_materializations_total" in text

    def test_reference_store_is_a_noop(self):
        from kube_throttler_tpu.metrics import Registry, register_store_metrics

        reg = Registry()
        register_store_metrics(reg, Store(columnar=False))
        assert "kube_throttler_store_arena_slots_live" not in reg.exposition()
