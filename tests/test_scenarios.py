"""Scenario engine (PR 8): trace determinism, virtual-time fault rules,
mockserver restart semantics, the verdict-safe ingest overload posture,
chunked batched relists, the tier-1 determinism smoke, and the
injected-regression gate demonstration. The full corpus matrix runs
behind ``-m slow`` (``make scenario-test`` drives 3 seeds)."""

from __future__ import annotations

import threading
import time

import pytest

from kube_throttler_tpu.faults.plan import FaultPlan
from kube_throttler_tpu.scenarios.corpus import SCENARIOS, corpus, get_scenario
from kube_throttler_tpu.scenarios.dsl import Arrival, arrival_rate
from kube_throttler_tpu.scenarios.trace import (
    build_trace,
    serialize_trace,
    trace_sha256,
)


# ---------------------------------------------------------------- traces


class TestTraceDeterminism:
    def test_same_seed_byte_identical(self):
        scn = get_scenario("smoke")
        a = serialize_trace(*build_trace(scn, 3))
        b = serialize_trace(*build_trace(scn, 3))
        assert a == b
        assert trace_sha256(a) == trace_sha256(b)

    def test_different_seed_differs(self):
        scn = get_scenario("smoke")
        assert serialize_trace(*build_trace(scn, 0)) != serialize_trace(
            *build_trace(scn, 1)
        )

    def test_ops_time_ordered_and_bounded(self):
        scn = get_scenario("smoke")
        header, ops = build_trace(scn, 0)
        ts = [op["t_us"] for op in ops]
        assert ts == sorted(ts)
        assert header["ops"] == len(ops) > 0

    def test_patterns_emit_their_shapes(self):
        drain = get_scenario("rolling_drain")
        _, ops = build_trace(drain, 0)
        verbs = {op["verb"] for op in ops}
        assert "delete_pod" in verbs and "create_pod" in verbs
        herd = get_scenario("thundering_herd")
        _, hops = build_trace(herd, 0)
        herd_creates = [
            op for op in hops if op["verb"] == "create_pod" and op["name"].startswith("h")
        ]
        assert len(herd_creates) == herd.herd_size

    def test_prev_chain_exact(self):
        """Each pod's prev_m must equal its last emitted cpu_m — the
        crossing bookkeeping the replayer trusts."""
        scn = get_scenario("rolling_drain")
        _, ops = build_trace(scn, 1)
        last: dict = {}
        for op in ops:
            if op["verb"] == "update_throttle":
                continue
            name = op["name"]
            if name in last:
                assert op["prev_m"] == last[name], op
            if op["verb"] == "delete_pod":
                last[name] = 0
            else:
                last[name] = op["cpu_m"]

    def test_corpus_has_six_scenarios(self):
        assert len(corpus()) >= 6
        assert "smoke" in SCENARIOS


class TestArrival:
    def test_shapes(self):
        assert arrival_rate(Arrival(kind="constant", rate_hz=100), 3, 10) == 100
        ramp = Arrival(kind="ramp", rate_hz=100, start_frac=0.1)
        assert arrival_rate(ramp, 0, 10) == pytest.approx(10)
        assert arrival_rate(ramp, 10, 10) == pytest.approx(100)
        di = Arrival(kind="diurnal", rate_hz=100, trough_frac=0.2, cycles=1)
        assert arrival_rate(di, 0, 10) == pytest.approx(20)
        assert arrival_rate(di, 5, 10) == pytest.approx(100)
        bu = Arrival(kind="bursts", rate_hz=100, trough_frac=0.1, burst_s=1, idle_s=1)
        assert arrival_rate(bu, 0.5, 10) == 100
        assert arrival_rate(bu, 1.5, 10) == pytest.approx(10)


# ------------------------------------------------- virtual-time fault rules


class TestVirtualTimeRules:
    def test_at_times_fires_once_per_instant(self):
        plan = FaultPlan(seed=0)
        now = [0.0]
        plan.set_time_source(lambda: now[0])
        plan.rule("scenario.churn.stall", mode="delay", at_times=[1.0, 2.0])
        assert plan.check("scenario.churn.stall") is None  # t=0: not due
        now[0] = 1.2
        f = plan.check("scenario.churn.stall")
        assert f is not None and f.mode == "delay"
        assert plan.check("scenario.churn.stall") is None  # 1.0 consumed
        now[0] = 5.0
        assert plan.check("scenario.churn.stall") is not None  # 2.0 due
        assert plan.check("scenario.churn.stall") is None  # schedule spent

    def test_window_gates_probability_rule(self):
        plan = FaultPlan(seed=0)
        now = [0.0]
        plan.set_time_source(lambda: now[0])
        plan.rule("mock.status.conflict", window=(1.0, 2.0), probability=1.0)
        assert plan.check("mock.status.conflict") is None
        now[0] = 1.5
        assert plan.check("mock.status.conflict") is not None
        now[0] = 2.0
        assert plan.check("mock.status.conflict") is None  # half-open interval

    def test_virtual_rule_inert_without_time_source(self):
        plan = FaultPlan(seed=0)
        plan.rule("scenario.churn.stall", at_times=[0.0])
        plan.rule("mock.list", window=(0.0, 10.0))
        assert plan.check("scenario.churn.stall") is None
        assert plan.check("mock.list") is None

    def test_reset_rearms_at_times(self):
        plan = FaultPlan(seed=0)
        now = [5.0]
        plan.set_time_source(lambda: now[0])
        plan.rule("scenario.churn.stall", at_times=[1.0])
        assert plan.check("scenario.churn.stall") is not None
        plan.reset()
        assert plan.check("scenario.churn.stall") is not None


# ---------------------------------------------------- mockserver restart


class TestMockserverRestart:
    def _server(self):
        from kube_throttler_tpu.api.pod import Namespace, make_pod
        from kube_throttler_tpu.client.mockserver import MockApiServer

        server = MockApiServer(bookmark_interval=0.1)
        server.store.create_namespace(Namespace("default"))
        for i in range(6):
            server.store.create_pod(make_pod(f"p{i}"))
        server.start()
        return server

    def test_restart_same_port_and_rv_reset_410(self):
        from kube_throttler_tpu.client.transport import (
            ApiClient,
            GoneError,
            RestConfig,
        )

        server = self._server()
        try:
            port = server.port
            client = ApiClient(RestConfig(server=server.url), qps=None)
            items, rv = client.list("Pod")
            assert len(items) == 6
            server.restart(reset_rv_window=True)
            assert server.port == port  # same address across the restart
            # a pre-restart resume point is below the fresh RV horizon
            with pytest.raises(GoneError):
                for _ in client.watch("Pod", "1", read_timeout=5.0):
                    break
            # LIST works and a from-now watch resumes cleanly
            items2, rv2 = client.list("Pod")
            assert len(items2) == 6
        finally:
            server.stop()

    def test_continue_token_expires_on_restart(self):
        from kube_throttler_tpu.client.transport import (
            ApiClient,
            GoneError,
            RestConfig,
        )

        server = self._server()
        try:
            client = ApiClient(RestConfig(server=server.url), qps=None)
            pages = client.list_pages("Pod", page_size=2)
            first, _ = next(pages)
            assert len(first) == 2
            server.reset_rv_window()  # outstanding continue tokens expire
            with pytest.raises(GoneError):
                next(pages)
        finally:
            server.stop()

    def test_reflector_recovers_through_restart(self):
        from kube_throttler_tpu.api.pod import make_pod
        from kube_throttler_tpu.client.transport import (
            ApiClient,
            Reflector,
            RestConfig,
        )
        from kube_throttler_tpu.engine.store import Store

        server = self._server()
        local = Store()
        refl = Reflector(
            ApiClient(RestConfig(server=server.url), qps=None),
            "Pod",
            local,
            backoff=0.05,
            backoff_cap=0.2,
        )
        try:
            refl.start()
            assert refl.wait_for_sync(10)
            server.restart(reset_rv_window=True)
            server.store.create_pod(make_pod("after-restart"))
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                if any(p.name == "after-restart" for p in local.list_pods()):
                    break
                time.sleep(0.05)
            else:
                raise AssertionError(
                    "reflector never recovered the post-restart pod "
                    "(410 → relist path broken)"
                )
        finally:
            refl.stop()
            server.stop()

    def test_status_delay_verb_stalls_put(self):
        from kube_throttler_tpu.client.transport import ApiClient, RestConfig
        from kube_throttler_tpu.api.serialization import object_to_dict
        from kube_throttler_tpu.api.types import (
            LabelSelector,
            ResourceAmount,
            Throttle,
            ThrottleSelector,
            ThrottleSelectorTerm,
            ThrottleSpec,
        )

        server = self._server()
        try:
            thr = Throttle(
                name="t1",
                spec=ThrottleSpec(
                    throttler_name="kube-throttler",
                    threshold=ResourceAmount.of(pod=3),
                    selector=ThrottleSelector(
                        selector_terms=(
                            ThrottleSelectorTerm(LabelSelector(match_labels={"a": "b"})),
                        )
                    ),
                ),
            )
            server.store.create_throttle(thr)
            server.faults = FaultPlan(seed=0).rule(
                "mock.status.delay", mode="delay", delay=0.3
            )
            client = ApiClient(RestConfig(server=server.url), qps=None)
            body = object_to_dict(thr)
            body["metadata"]["resourceVersion"] = str(
                server.store.resource_version("Throttle", "default/t1")
            )
            t0 = time.monotonic()
            client.put(
                "/apis/schedule.k8s.everpeace.github.com/v1alpha1/"
                "namespaces/default/throttles/t1/status",
                body,
            )
            assert time.monotonic() - t0 >= 0.3  # the stall landed
        finally:
            server.stop()


# ------------------------------------------- ingest overload shed posture


class TestIngestShedPolicy:
    def _blocked_pipeline(self, maxsize=4):
        from kube_throttler_tpu.engine.ingest import MicroBatchIngest
        from kube_throttler_tpu.engine.store import Store

        pipeline = MicroBatchIngest(Store(), maxsize=maxsize)
        gate = threading.Event()
        entered = threading.Event()
        orig = pipeline._apply_ops

        def blocking(ops):
            entered.set()
            gate.wait(10)
            return orig(ops)

        pipeline._apply_ops = blocking
        # park the dispatcher inside an apply so the queue backs up
        pipeline.submit("upsert", "Pod", object())
        assert entered.wait(5)
        return pipeline, gate

    def test_sheds_oldest_pod_upsert_only(self):
        pipeline, gate = self._blocked_pipeline(maxsize=3)
        try:
            pipeline.submit("upsert", "Pod", "p1")
            pipeline.submit("delete", "Pod", "p2")       # critical: a delete
            pipeline.submit("upsert", "Throttle", "t1")  # critical: a throttle
            # queue is now full (3); this pod upsert sheds the OLDEST pod
            # upsert (p1), never the delete or the throttle op
            pipeline.submit("upsert", "Pod", "p3")
            with pipeline._cond:
                queued = list(pipeline._queue)
            assert ("upsert", "Pod", "p1") not in queued
            assert ("delete", "Pod", "p2") in queued
            assert ("upsert", "Throttle", "t1") in queued
            assert ("upsert", "Pod", "p3") in queued
            assert pipeline.dropped == 1 and pipeline.overflowed
        finally:
            gate.set()
            pipeline.stop()

    def test_critical_ops_exceed_bound_rather_than_shed(self):
        pipeline, gate = self._blocked_pipeline(maxsize=2)
        try:
            pipeline.submit("delete", "Pod", "d1")
            pipeline.submit("upsert", "Throttle", "t1")
            # full of critical ops: an incoming POD upsert is dropped...
            pipeline.submit("upsert", "Pod", "px")
            with pipeline._cond:
                assert ("upsert", "Pod", "px") not in list(pipeline._queue)
            # ...but an incoming CRITICAL op exceeds the bound instead
            pipeline.submit("delete", "Throttle", "t2")
            with pipeline._cond:
                queued = list(pipeline._queue)
            assert ("delete", "Throttle", "t2") in queued
            assert len(queued) == 3  # bound exceeded by the critical op
            assert pipeline.dropped == 1
        finally:
            gate.set()
            pipeline.stop()

    def test_take_overflow_consumes_per_kind(self):
        pipeline, gate = self._blocked_pipeline(maxsize=2)
        try:
            for i in range(5):
                pipeline.submit("upsert", "Pod", f"p{i}")
            assert pipeline.take_overflow("Pod") is True
            assert pipeline.take_overflow("Pod") is False  # consumed
            assert pipeline.take_overflow("Throttle") is False
            assert pipeline.overflowed  # sticky stat survives consumption
        finally:
            gate.set()
            pipeline.stop()

    def test_overflow_forces_relist_and_repairs_gap(self):
        """E2E: a pod storm through a TINY ingest queue sheds events; the
        reflector consumes the overflow marker, forces a relist, and the
        local cache converges to apiserver truth anyway."""
        from kube_throttler_tpu.api.pod import Namespace, make_pod
        from kube_throttler_tpu.client.mockserver import MockApiServer
        from kube_throttler_tpu.client.transport import (
            ApiClient,
            Reflector,
            RestConfig,
        )
        from kube_throttler_tpu.engine.ingest import MicroBatchIngest
        from kube_throttler_tpu.engine.store import Store

        server = MockApiServer(bookmark_interval=0.1)
        server.store.create_namespace(Namespace("default"))
        server.start()
        local = Store()
        pipeline = MicroBatchIngest(local, maxsize=8, max_batch=4)
        # slow the dispatcher so the storm outruns it and sheds
        orig = pipeline._apply_ops

        def slow(ops):
            time.sleep(0.002 * len(ops))
            return orig(ops)

        pipeline._apply_ops = slow
        refl = Reflector(
            ApiClient(RestConfig(server=server.url), qps=None),
            "Pod",
            local,
            backoff=0.05,
            backoff_cap=0.2,
            ingest_batcher=pipeline,
        )
        try:
            refl.start()
            assert refl.wait_for_sync(10)
            for i in range(300):
                server.store.create_pod(make_pod(f"storm{i}"))
            deadline = time.monotonic() + 30
            want = {p.key for p in server.store.list_pods()}
            while time.monotonic() < deadline:
                pipeline.flush(1.0)
                if {p.key for p in local.list_pods()} == want:
                    break
                time.sleep(0.1)
            else:
                raise AssertionError(
                    f"local cache never converged: {len(local.list_pods())}"
                    f"/{len(want)} pods (dropped={pipeline.dropped})"
                )
            assert pipeline.dropped > 0, "storm never overflowed the tiny queue"
        finally:
            refl.stop()
            pipeline.stop()
            server.stop()


# ----------------------------------------------- chunked batched relists


class TestChunkedRelist:
    def test_batched_relist_equivalent_to_direct(self):
        from kube_throttler_tpu.api.pod import Namespace, make_pod
        from kube_throttler_tpu.api.serialization import object_to_dict
        from kube_throttler_tpu.client.transport import Reflector
        from kube_throttler_tpu.engine.ingest import MicroBatchIngest
        from kube_throttler_tpu.engine.store import Store

        def page_of(pods, rv="9"):
            items = []
            for p in pods:
                d = object_to_dict(p)
                d.setdefault("metadata", {})["resourceVersion"] = "5"
                items.append(d)
            return iter([(items, rv)])

        pods = [make_pod(f"p{i}", labels={"x": str(i)}) for i in range(300)]
        results = {}
        for batched in (False, True):
            store = Store()
            store.create_namespace(Namespace("default"))
            store.create_pod(make_pod("stale"))  # must be relist-deleted
            store.create_pod(pods[0])  # unchanged-content upsert path
            pipeline = MicroBatchIngest(store) if batched else None
            refl = Reflector(None, "Pod", store, ingest_batcher=pipeline)
            rv = refl._sync_pages(page_of(pods))
            assert rv == "9"
            results[batched] = sorted(p.key for p in store.list_pods())
            if pipeline is not None:
                pipeline.stop()
        assert results[False] == results[True]
        assert "default/stale" not in results[True]
        assert len(results[True]) == 300


# --------------------------------------------- the engine: tier-1 smokes


def _run_smoke(seed, workdir, regression=None):
    from kube_throttler_tpu.scenarios.engine import run_scenario

    return run_scenario(
        get_scenario("smoke"), seed, str(workdir), regression=regression
    )


class TestScenarioEngineSmoke:
    def test_determinism_same_seed_twice(self, tmp_path):
        """Same scenario + seed twice: byte-identical committed traces and
        identical SLO gate verdicts (the tier-1 determinism smoke)."""
        r1 = _run_smoke(11, tmp_path / "a")
        r2 = _run_smoke(11, tmp_path / "b")
        assert r1["trace_sha256"] == r2["trace_sha256"]
        with open(r1["trace_path"], "rb") as f1, open(r2["trace_path"], "rb") as f2:
            assert f1.read() == f2.read()
        v1 = {k: g["pass"] for k, g in r1["gates"].items()}
        v2 = {k: g["pass"] for k, g in r2["gates"].items()}
        assert v1 == v2
        assert r1["all_pass"] and r2["all_pass"], (r1["gates"], r2["gates"])
        # the gates the smoke must exercise
        assert {"flip_p99", "ingest_sustain", "recovery", "verdicts"} <= set(v1)
        assert r1["measurements"]["restarts"] == 1
        assert r1["measurements"]["wrong_verdicts"] == 0

    def test_injected_regression_fails_its_gate(self, tmp_path, monkeypatch):
        """The gate-actually-gates check: a deliberate per-PUT stall must
        demonstrably fail the flip-p99 gate the clean run passes, and the
        diff report must name it. Enforcement is forced so the check is
        deterministic on hosts below the latency core floor (where the
        flip gates otherwise degrade to advisory — see slo.py)."""
        from kube_throttler_tpu.scenarios.slo import diff_reports

        monkeypatch.setenv("KT_SCENARIO_ENFORCE_LATENCY", "1")
        clean = _run_smoke(0, tmp_path / "clean")
        regressed = _run_smoke(0, tmp_path / "reg", regression="flip_stall")
        assert clean["gates"]["flip_p99"]["pass"], clean["gates"]
        assert not regressed["gates"]["flip_p99"]["pass"], regressed["gates"]
        assert clean["all_pass"] and not regressed["all_pass"]
        diff = diff_reports(clean, regressed)
        assert "flip_p99" in diff and "flip_stall" in diff


# -------------------------------------------- host-speed gate calibration


class TestLatencyGateCalibration:
    """Flip-lag gates degrade to advisory below the host core floor
    (slo._latency_gates_enforced) — correctness gates never do."""

    def _measurements(self, p99):
        return {
            "flip_lag_p99_ms": p99,
            "flip_lag_p50_ms": p99 / 2,
            "flip_samples": 50,
            "flip_crossings": 10,
            "pace_frac": 1.0,
            "applied_frac": 1.0,
            "converged": True,
            "events_per_sec": 100.0,
            "wrong_verdicts": 0,
            "verdicts_checked": 10,
        }

    def test_slow_host_overshoot_is_advisory_not_enforced(self, monkeypatch):
        from kube_throttler_tpu.scenarios.slo import evaluate_gates

        monkeypatch.delenv("KT_SCENARIO_ENFORCE_LATENCY", raising=False)
        monkeypatch.setenv("KT_SCENARIO_LATENCY_CORE_FLOOR", str(10**6))
        scn = get_scenario("smoke")
        gates = evaluate_gates(scn, self._measurements(scn.slo.flip_p99_ms * 5))
        assert gates["flip_p99"]["pass"]  # advisory, not enforced
        assert "ADVISORY" in gates["flip_p99"]["note"]
        assert "would FAIL" in gates["flip_p99"]["note"]
        # the measured value is still reported for calibration
        assert gates["flip_p99"]["value"] == scn.slo.flip_p99_ms * 5
        # correctness gates stay enforced on any host
        assert gates["verdicts"]["pass"] and gates["ingest_sustain"]["pass"]

    def test_enforce_env_overrides_core_floor(self, monkeypatch):
        from kube_throttler_tpu.scenarios.slo import evaluate_gates

        monkeypatch.setenv("KT_SCENARIO_ENFORCE_LATENCY", "1")
        monkeypatch.setenv("KT_SCENARIO_LATENCY_CORE_FLOOR", str(10**6))
        scn = get_scenario("smoke")
        gates = evaluate_gates(scn, self._measurements(scn.slo.flip_p99_ms * 5))
        assert not gates["flip_p99"]["pass"]

    def test_fast_host_in_bound_has_no_advisory_marker(self, monkeypatch):
        from kube_throttler_tpu.scenarios.slo import evaluate_gates

        monkeypatch.delenv("KT_SCENARIO_ENFORCE_LATENCY", raising=False)
        monkeypatch.setenv("KT_SCENARIO_LATENCY_CORE_FLOOR", "1")
        scn = get_scenario("smoke")
        gates = evaluate_gates(scn, self._measurements(scn.slo.flip_p99_ms / 2))
        assert gates["flip_p99"]["pass"]
        assert "ADVISORY" not in gates["flip_p99"].get("note", "")

    def test_unmeasurable_still_fails_below_floor(self, monkeypatch):
        """Too few flip samples is a trace-content defect, not host
        speed — the unmeasurable branch never degrades to advisory."""
        from kube_throttler_tpu.scenarios.slo import evaluate_gates

        monkeypatch.delenv("KT_SCENARIO_ENFORCE_LATENCY", raising=False)
        monkeypatch.setenv("KT_SCENARIO_LATENCY_CORE_FLOOR", str(10**6))
        scn = get_scenario("smoke")
        m = self._measurements(1.0)
        m["flip_samples"] = 0
        assert not evaluate_gates(scn, m)["flip_p99"]["pass"]

    def test_malformed_floor_env_falls_back(self, monkeypatch):
        from kube_throttler_tpu.scenarios.slo import _latency_gates_enforced

        monkeypatch.delenv("KT_SCENARIO_ENFORCE_LATENCY", raising=False)
        monkeypatch.setenv("KT_SCENARIO_LATENCY_CORE_FLOOR", "many")
        assert _latency_gates_enforced() in (True, False)  # no raise
        monkeypatch.setenv("KT_SCENARIO_LATENCY_CORE_FLOOR", "1")
        assert _latency_gates_enforced()  # every host has ≥1 core

    def test_hunt_inprocess_evaluator_forces_enforcement(
        self, tmp_path, monkeypatch
    ):
        """The hunt DETECTS regressions by latency gates failing —
        advisory mode would hide every planted stall, so the evaluator
        enforces for the duration of the eval (and restores after)."""
        import os as _os

        from kube_throttler_tpu.scenarios.hunt.loop import (
            InProcessEvaluator,
            base_programs,
        )

        monkeypatch.delenv("KT_SCENARIO_ENFORCE_LATENCY", raising=False)
        seen = {}

        def fake_run(scn, seed, wd):
            seen["enforce"] = _os.environ.get("KT_SCENARIO_ENFORCE_LATENCY")
            return {"gates": {}}

        monkeypatch.setattr(
            "kube_throttler_tpu.scenarios.engine.run_scenario", fake_run
        )
        out = InProcessEvaluator(str(tmp_path))(base_programs()[0], 0)
        assert out == {"gates": {}}
        assert seen["enforce"] == "1"
        assert "KT_SCENARIO_ENFORCE_LATENCY" not in _os.environ  # restored

    def test_hunt_subprocess_evaluator_forces_enforcement(
        self, tmp_path, monkeypatch
    ):
        from kube_throttler_tpu.scenarios.hunt import loop as hunt_loop

        monkeypatch.delenv("KT_SCENARIO_ENFORCE_LATENCY", raising=False)
        captured = {}

        def fake_run(cmd, **kw):
            captured["env"] = kw["env"]

            class P:
                returncode = 0
                stdout = ""
                stderr = ""

            return P()

        monkeypatch.setattr(hunt_loop.subprocess, "run", fake_run)
        ev = hunt_loop.SubprocessEvaluator(str(tmp_path))
        assert ev(hunt_loop.base_programs()[0], 0) is None  # no report file
        assert captured["env"]["KT_SCENARIO_ENFORCE_LATENCY"] == "1"


# ------------------------------------------------------- slow: the corpus


@pytest.mark.slow
class TestScenarioCorpus:
    @pytest.mark.parametrize("name", [s.name for s in corpus()])
    def test_corpus_gates_green(self, name, tmp_path):
        """Each corpus scenario in a FRESH interpreter (sequential
        in-process runs contaminate each other's heaps — see
        scenarios/__main__._run_isolated). ``make scenario-test`` runs
        the full 3-seed matrix; this slow-tier pass pins seed 0."""
        import json
        import os
        import subprocess
        import sys

        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        proc = subprocess.run(
            [
                sys.executable, "-m", "kube_throttler_tpu.scenarios", "run",
                "--name", name, "--seed", "0", "--workdir", str(tmp_path),
            ],
            capture_output=True, text=True, timeout=1200, env=env,
        )
        report_path = tmp_path / f"report-{name}-s0.json"
        assert report_path.exists(), proc.stdout[-3000:]
        with open(report_path) as f:
            report = json.load(f)
        assert report["all_pass"], {
            k: g for k, g in report["gates"].items() if not g["pass"]
        }
