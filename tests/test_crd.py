"""CRD generation + structural validation (deploy/crd.yaml pipeline).

The reference ships a controller-gen'd deploy/crd.yaml; here the schema is
derived from the typed model (api/crd.py) and deploy/crd.yaml is emitted by
tools/gen_crd.py. These tests pin: the generated file is in sync with the
code, example manifests validate, typos are rejected, and manifests
round-trip through the typed objects.
"""

import subprocess
import sys
from pathlib import Path

import yaml

from kube_throttler_tpu.api import crd, serialization

REPO = Path(__file__).resolve().parent.parent


def _load_all(path):
    with open(path) as f:
        return [d for d in yaml.safe_load_all(f) if d]


def test_generated_crd_file_in_sync():
    docs = _load_all(REPO / "deploy" / "crd.yaml")
    assert docs == [crd.cluster_throttle_crd(), crd.throttle_crd()]


def test_gen_tool_runs(tmp_path):
    # write to a temp path: regenerating deploy/crd.yaml in place would
    # silently repair the drift test_generated_crd_file_in_sync exists to catch
    dest = tmp_path / "crd.yaml"
    out = subprocess.run(
        [sys.executable, str(REPO / "tools" / "gen_crd.py"), "--out", str(dest)],
        capture_output=True,
        text=True,
        cwd=REPO,
    )
    assert out.returncode == 0, out.stderr
    assert "2 documents" in out.stdout
    assert dest.read_text() == (REPO / "deploy" / "crd.yaml").read_text()


def test_crd_names_and_scope():
    t = crd.throttle_crd()
    ct = crd.cluster_throttle_crd()
    assert t["metadata"]["name"] == "throttles.schedule.k8s.everpeace.github.com"
    assert t["spec"]["scope"] == "Namespaced"
    assert ct["spec"]["scope"] == "Cluster"
    assert ct["spec"]["names"]["shortNames"] == ["clthr", "clthrs"]
    v = t["spec"]["versions"][0]
    assert v["name"] == "v1alpha1" and v["subresources"] == {"status": {}}


def test_example_manifests_validate_and_roundtrip():
    for name in ["throttle.yaml", "clusterthrottle.yaml", "throttle-with-overrides.yaml"]:
        for raw in _load_all(REPO / "examples" / name):
            # kubectl-style YAML→JSON normalization (RFC3339 strings, typo keys)
            doc = serialization.normalize_manifest(raw)
            assert crd.validate(doc) == [], (name, crd.validate(doc))
            obj = serialization.object_from_dict(doc)
            back = serialization.object_to_dict(obj)
            assert crd.validate(back) == [], (name, crd.validate(back))
            # spec survives the round trip
            assert serialization.object_from_dict(back).spec == obj.spec


def test_example_pods_parse():
    pods = [serialization.pod_from_dict(d) for d in _load_all(REPO / "examples" / "pods.yaml")]
    assert [p.name for p in pods] == ["pod1", "pod2", "pod1m", "pod3"]
    assert all(p.spec.scheduler_name == "my-scheduler" for p in pods)


def test_validation_rejects_typos_and_wrong_types():
    bad_field = {
        "apiVersion": crd.API_VERSION,
        "kind": "Throttle",
        "metadata": {"name": "x"},
        "spec": {"throttlerName": "t", "thresold": {}},  # typo
    }
    errs = crd.validate(bad_field)
    assert any("thresold" in str(e) for e in errs)

    bad_type = {
        "kind": "ClusterThrottle",
        "spec": {"threshold": {"resourceCounts": {"pod": "five"}}},
    }
    errs = crd.validate(bad_type)
    assert any("resourceCounts.pod" in str(e) for e in errs)

    bad_expr = {
        "kind": "Throttle",
        "spec": {
            "selector": {
                "selectorTerms": [
                    {"podSelector": {"matchExpressions": [{"key": "k"}]}}  # missing operator
                ]
            }
        },
    }
    errs = crd.validate(bad_expr)
    assert any("operator" in str(e) for e in errs)


def test_quantity_schema_accepts_int_or_string():
    ok = {
        "kind": "Throttle",
        "spec": {"threshold": {"resourceRequests": {"cpu": 1, "memory": "1Gi"}}},
    }
    assert crd.validate(ok) == []
    bad = {
        "kind": "Throttle",
        "spec": {"threshold": {"resourceRequests": {"cpu": 0.5}}},
    }
    assert crd.validate(bad) != []


def test_deploy_manifests_are_well_formed_yaml():
    deploy = REPO / "deploy"
    names = {p.name for p in deploy.glob("*.yaml")}
    assert {
        "crd.yaml",
        "config.yaml",
        "deployment.yaml",
        "rbac.yaml",
        "namespace.yaml",
        "kustomization.yaml",
    } <= names
    for p in sorted(deploy.glob("*.yaml")) + [REPO / "prometheus" / "servicemonitor.yaml"]:
        docs = _load_all(p)
        assert docs, p
        for d in docs:
            assert "kind" in d, p
    # the daemon config embedded in the ConfigMap decodes as plugin args
    cfg = yaml.safe_load((deploy / "config.yaml").read_text())
    sched = yaml.safe_load(cfg["data"]["config.yaml"])
    args = sched["profiles"][0]["pluginConfig"][0]["args"]
    from kube_throttler_tpu.plugin import decode_plugin_args

    decoded = decode_plugin_args(args)
    assert decoded.name == "kube-throttler"
    assert decoded.target_scheduler_name == "my-scheduler"


def test_quantity_pattern_rejects_garbage_strings():
    bad = {
        "kind": "Throttle",
        "spec": {"threshold": {"resourceRequests": {"cpu": "lots"}}},
    }
    errs = crd.validate(bad)
    assert any("pattern" in str(e) for e in errs)
    # suffixed forms still pass
    ok = {
        "kind": "Throttle",
        "spec": {"threshold": {"resourceRequests": {"cpu": "1500m", "memory": "2Gi", "x": "1e3"}}},
    }
    assert crd.validate(ok) == []


def test_date_only_override_boundary_normalizes():
    import datetime as dt

    raw = {
        "kind": "Throttle",
        "metadata": {"name": "d"},
        "spec": {
            "throttlerName": "t",
            "temporaryThresholdOverrides": [
                {"begin": dt.date(2024, 1, 1), "end": dt.date(2024, 1, 7), "threshold": {}}
            ],
        },
    }
    norm = serialization.normalize_manifest(raw)
    assert norm["spec"]["temporaryThresholdOverrides"][0]["begin"] == "2024-01-01"
    assert crd.validate(norm) == []
    obj = serialization.object_from_dict(norm)
    assert obj.spec.temporary_threshold_overrides[0].begin == "2024-01-01"
