"""Client layer: typed clientset verbs, watch streams, informers, listers
(parity with pkg/generated/ — SURVEY.md §2.2)."""

import queue
import threading

import pytest

from kube_throttler_tpu.api import (
    ClusterThrottle,
    ClusterThrottleSelector,
    ClusterThrottleSelectorTerm,
    ClusterThrottleSpec,
    LabelSelector,
    ResourceAmount,
    Throttle,
    ThrottleSelector,
    ThrottleSelectorTerm,
    ThrottleSpec,
)
from kube_throttler_tpu.api.pod import Namespace, make_pod
from kube_throttler_tpu.api.serialization import (
    cluster_throttle_from_dict,
    cluster_throttle_to_dict,
    pod_from_dict,
    pod_to_dict,
    throttle_from_dict,
    throttle_to_dict,
)
from kube_throttler_tpu.client import (
    Clientset,
    SharedInformerFactory,
    ThrottleLister,
    json_merge_patch,
    new_fake_clientset,
)
from kube_throttler_tpu.client.listers import ClusterThrottleLister, PodLister
from kube_throttler_tpu.engine.store import EventType, Store


def _throttle(name, ns="default", cpu="1", pod=5):
    return Throttle(
        name=name,
        namespace=ns,
        spec=ThrottleSpec(
            throttler_name="kube-throttler",
            threshold=ResourceAmount.of(pod=pod, requests={"cpu": cpu}),
            selector=ThrottleSelector(
                selector_terms=(
                    ThrottleSelectorTerm(LabelSelector(match_labels={"throttle": name})),
                )
            ),
        ),
    )


def _cluster_throttle(name, cpu="1"):
    return ClusterThrottle(
        name=name,
        spec=ClusterThrottleSpec(
            throttler_name="kube-throttler",
            threshold=ResourceAmount.of(requests={"cpu": cpu}),
            selector=ClusterThrottleSelector(
                selector_terms=(
                    ClusterThrottleSelectorTerm(
                        pod_selector=LabelSelector(match_labels={"ct": name})
                    ),
                )
            ),
        ),
    )


class TestClientsetVerbs:
    def test_create_get_list_delete(self):
        cs = new_fake_clientset(Namespace("default"))
        api = cs.schedule_v1alpha1().throttles("default")
        api.create(_throttle("t1"))
        api.create(_throttle("t2"))
        assert api.get("t1").name == "t1"
        assert sorted(t.name for t in api.list()) == ["t1", "t2"]
        api.delete("t1")
        assert [t.name for t in api.list()] == ["t2"]

    def test_namespace_scoping(self):
        cs = new_fake_clientset(Namespace("default"), Namespace("other"))
        cs.schedule_v1alpha1().throttles("default").create(_throttle("t1"))
        cs.schedule_v1alpha1().throttles("other").create(_throttle("t1", ns="other"))
        assert len(cs.schedule_v1alpha1().throttles("default").list()) == 1
        assert len(cs.store.list_throttles()) == 2
        # create through a namespace-scoped interface forces that namespace
        cs.schedule_v1alpha1().throttles("other").create(_throttle("t2", ns="default"))
        assert cs.store.get_throttle("other", "t2").namespace == "other"

    def test_update_and_update_status(self):
        from dataclasses import replace

        from kube_throttler_tpu.api.types import ThrottleStatus

        cs = new_fake_clientset(_throttle("t1"))
        api = cs.schedule_v1alpha1().throttles("default")
        t = api.get("t1")
        api.update(replace(t, spec=replace(t.spec, threshold=ResourceAmount.of(pod=9))))
        assert api.get("t1").spec.threshold.resource_counts == 9
        api.update_status(t.with_status(ThrottleStatus(used=ResourceAmount.of(pod=2))))
        got = api.get("t1")
        assert got.status.used.resource_counts == 2
        assert got.spec.threshold.resource_counts == 9  # status write keeps spec

    def test_delete_collection_with_predicate(self):
        cs = new_fake_clientset(_throttle("t1"), _throttle("t2"), _throttle("keep"))
        api = cs.schedule_v1alpha1().throttles("default")
        deleted = api.delete_collection(lambda t: t.name.startswith("t"))
        assert sorted(t.name for t in deleted) == ["t1", "t2"]
        assert [t.name for t in api.list()] == ["keep"]

    def test_patch_merge_semantics(self):
        cs = new_fake_clientset(_throttle("t1", cpu="1", pod=5))
        api = cs.schedule_v1alpha1().throttles("default")
        api.patch("t1", {"spec": {"threshold": {"resourceRequests": {"cpu": "200m"}}}})
        got = api.get("t1")
        # patched dimension replaced, sibling dimensions survive the merge
        assert got.spec.threshold.resource_requests["cpu"] == pytest.approx(0.2)
        assert got.spec.threshold.resource_counts == 5
        assert got.spec.selector.selector_terms  # untouched subtree preserved

    def test_cluster_throttle_interface(self):
        cs = new_fake_clientset(_cluster_throttle("ct1"))
        api = cs.schedule_v1alpha1().cluster_throttles()
        assert api.get("ct1").name == "ct1"
        api.patch("ct1", {"spec": {"threshold": {"resourceCounts": {"pod": 3}}}})
        assert api.get("ct1").spec.threshold.resource_counts == 3
        api.delete_collection()
        assert api.list() == []

    def test_pod_interface(self):
        cs = new_fake_clientset(Namespace("default"))
        pods = cs.core_v1().pods("default")
        pods.create(make_pod("p1", requests={"cpu": "100m"}))
        pods.patch("p1", {"spec": {"nodeName": "node-1"}})
        assert pods.get("p1").spec.node_name == "node-1"


class TestReviewRegressions:
    def test_patch_preserves_microsecond_calculated_at(self):
        from datetime import datetime, timezone

        from kube_throttler_tpu.api.types import CalculatedThreshold, ThrottleStatus

        cs = new_fake_clientset(_throttle("t1"))
        api = cs.schedule_v1alpha1().throttles("default")
        stamped = ThrottleStatus(
            calculated_threshold=CalculatedThreshold(
                threshold=ResourceAmount.of(pod=5),
                calculated_at=datetime(2024, 3, 1, 1, 2, 3, 456789, tzinfo=timezone.utc),
            )
        )
        api.update_status(api.get("t1").with_status(stamped))
        api.patch("t1", {"spec": {"threshold": {"resourceCounts": {"pod": 7}}}})
        got = api.get("t1")
        assert got.status.calculated_threshold.calculated_at == stamped.calculated_threshold.calculated_at
        # and the serializer itself round-trips fractional seconds
        assert throttle_from_dict(throttle_to_dict(got)).status == got.status

    def test_patch_accepts_reference_typo_spelling(self):
        cs = new_fake_clientset(_throttle("t1"))
        api = cs.schedule_v1alpha1().throttles("default")
        api.patch(
            "t1",
            {
                "spec": {
                    "selector": {
                        "selecterTerms": [{"podSelector": {"matchLabels": {"a": "new"}}}]
                    }
                }
            },
        )
        terms = api.get("t1").spec.selector.selector_terms
        assert len(terms) == 1
        assert terms[0].pod_selector.match_labels == {"a": "new"}

    def test_update_cannot_clobber_controller_status(self):
        from dataclasses import replace

        from kube_throttler_tpu.api.types import ThrottleStatus

        cs = new_fake_clientset(_throttle("t1"))
        api = cs.schedule_v1alpha1().throttles("default")
        stale = api.get("t1")  # read BEFORE the controller writes status
        api.update_status(stale.with_status(ThrottleStatus(used=ResourceAmount.of(pod=3))))
        # spec update from the stale read must not wipe status (subresource
        # semantics) — neither via update nor via patch
        api.update(replace(stale, spec=replace(stale.spec, threshold=ResourceAmount.of(pod=8))))
        got = api.get("t1")
        assert got.spec.threshold.resource_counts == 8
        assert got.status.used.resource_counts == 3
        api.patch("t1", {"spec": {"threshold": {"resourceCounts": {"pod": 9}}}})
        assert api.get("t1").status.used.resource_counts == 3

    def test_pod_patch_preserves_uid(self):
        cs = new_fake_clientset(Namespace("default"))
        pods = cs.core_v1().pods("default")
        created = pods.create(make_pod("p1", requests={"cpu": "100m"}))
        patched = pods.patch("p1", {"spec": {"nodeName": "n1"}})
        assert patched.uid == created.uid
        assert pod_from_dict(pod_to_dict(created)).uid == created.uid

    def test_resync_never_resurrects_deleted_object(self):
        store = Store()
        factory = SharedInformerFactory(store, resync_period=0.01)
        inf = factory.pods()
        alive = {}
        errors = []

        def handler(e):
            key = f"{e.obj.namespace}/{e.obj.name}"
            if e.type == EventType.DELETED:
                alive.pop(key, None)
            else:
                if e.type == EventType.MODIFIED and e.old_obj is e.obj and key not in alive:
                    errors.append(f"sync event for deleted {key}")
                alive[key] = e.obj

        inf.add_event_handler(handler)
        factory.start()
        import time

        for i in range(60):
            store.create_pod(make_pod(f"p{i}"))
            time.sleep(0.002)
            store.delete_pod("default", f"p{i}")
        time.sleep(0.05)
        factory.shutdown()
        assert errors == []
        assert alive == {}


class TestJsonMergePatch:
    def test_rfc7386_cases(self):
        # from RFC 7386 appendix A
        assert json_merge_patch({"a": "b"}, {"a": "c"}) == {"a": "c"}
        assert json_merge_patch({"a": "b"}, {"b": "c"}) == {"a": "b", "b": "c"}
        assert json_merge_patch({"a": "b"}, {"a": None}) == {}
        assert json_merge_patch({"a": "b", "b": "c"}, {"a": None}) == {"b": "c"}
        assert json_merge_patch({"a": ["b"]}, {"a": "c"}) == {"a": "c"}
        assert json_merge_patch({"a": {"b": "c"}}, {"a": {"b": "d", "c": None}}) == {
            "a": {"b": "d"}
        }
        assert json_merge_patch({"a": [{"b": "c"}]}, {"a": [1]}) == {"a": [1]}


class TestRoundTrip:
    def test_throttle_roundtrip(self):
        from datetime import datetime, timezone

        from kube_throttler_tpu.api.types import (
            CalculatedThreshold,
            IsResourceAmountThrottled,
            TemporaryThresholdOverride,
            ThrottleStatus,
        )

        t = _throttle("t1")
        t = Throttle(
            name=t.name,
            namespace=t.namespace,
            spec=ThrottleSpec(
                throttler_name=t.spec.throttler_name,
                threshold=t.spec.threshold,
                temporary_threshold_overrides=(
                    TemporaryThresholdOverride(
                        begin="2024-01-01T00:00:00Z",
                        end="2024-01-02T00:00:00Z",
                        threshold=ResourceAmount.of(requests={"cpu": "2"}),
                    ),
                ),
                selector=t.spec.selector,
            ),
            status=ThrottleStatus(
                calculated_threshold=CalculatedThreshold(
                    threshold=ResourceAmount.of(pod=5, requests={"cpu": "1"}),
                    calculated_at=datetime(2024, 1, 1, 12, tzinfo=timezone.utc),
                    messages=("ok",),
                ),
                throttled=IsResourceAmountThrottled(
                    resource_counts_pod=True, resource_requests={"cpu": False}
                ),
                used=ResourceAmount.of(pod=5, requests={"cpu": "900m"}),
            ),
        )
        assert throttle_from_dict(throttle_to_dict(t)) == t

    def test_cluster_throttle_roundtrip(self):
        ct = _cluster_throttle("ct1")
        assert cluster_throttle_from_dict(cluster_throttle_to_dict(ct)) == ct

    def test_pod_roundtrip_effective_request(self):
        from kube_throttler_tpu.resourcelist import pod_request_resource_list

        p = make_pod(
            "p1",
            requests={"cpu": "100m", "memory": "1Gi"},
            init_requests=[{"cpu": "500m"}],
            overhead={"cpu": "10m"},
            node_name="n1",
            phase="Running",
        )
        p2 = pod_from_dict(pod_to_dict(p))
        assert pod_request_resource_list(p2) == pod_request_resource_list(p)
        assert p2.spec.node_name == "n1" and p2.status.phase == "Running"


class TestWatch:
    def test_watch_stream_and_stop(self):
        cs = new_fake_clientset(_throttle("t0"))
        api = cs.schedule_v1alpha1().throttles("default")
        w = api.watch(replay=True)
        e = w.next(timeout=1)
        assert e.type == EventType.ADDED and e.obj.name == "t0"
        api.create(_throttle("t1"))
        api.delete("t1")
        assert [(w.next(timeout=1).type) for _ in range(2)] == [
            EventType.ADDED,
            EventType.DELETED,
        ]
        w.stop()
        with pytest.raises(StopIteration):
            w.next(timeout=1)
        # after stop, further mutations do not reach the stream
        api.create(_throttle("t2"))
        with pytest.raises(StopIteration):
            w.next(timeout=1)

    def test_watch_namespace_filter(self):
        cs = new_fake_clientset(Namespace("default"), Namespace("other"))
        w = cs.schedule_v1alpha1().throttles("other").watch()
        cs.schedule_v1alpha1().throttles("default").create(_throttle("t1"))
        cs.schedule_v1alpha1().throttles("other").create(_throttle("t2", ns="other"))
        assert w.next(timeout=1).obj.name == "t2"
        with pytest.raises(queue.Empty):
            w.next(timeout=0.05)
        w.stop()

    def test_watch_from_consumer_thread(self):
        cs = new_fake_clientset()
        w = cs.schedule_v1alpha1().cluster_throttles().watch()
        seen = []

        def consume():
            for e in w:
                seen.append(e.obj.name)

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        for i in range(10):
            cs.schedule_v1alpha1().cluster_throttles().create(_cluster_throttle(f"c{i}"))
        w.stop()
        t.join(timeout=2)
        assert not t.is_alive()
        assert seen == [f"c{i}" for i in range(10)]


class TestInformersAndListers:
    def test_indexer_namespace_index_and_listers(self):
        store = Store()
        factory = SharedInformerFactory(store, resync_period=0)
        inf = factory.throttles()
        store.create_throttle(_throttle("t1"))
        store.create_throttle(_throttle("t2", ns="other"))
        lister = ThrottleLister(inf.indexer)
        assert sorted(t.name for t in lister.list()) == ["t1", "t2"]
        assert [t.name for t in lister.throttles("other").list()] == ["t2"]
        assert lister.throttles("default").get("t1").namespace == "default"
        with pytest.raises(KeyError):
            lister.throttles("default").get("t2")
        store.delete_throttle("other", "t2")
        assert lister.throttles("other").list() == []
        factory.shutdown()

    def test_informer_replays_preexisting_objects(self):
        store = Store()
        store.create_cluster_throttle(_cluster_throttle("ct1"))
        factory = SharedInformerFactory(store, resync_period=0)
        inf = factory.cluster_throttles()  # created after the object existed
        assert ClusterThrottleLister(inf.indexer).get("ct1").name == "ct1"
        seen = []
        inf.add_event_handler(lambda e: seen.append((e.type, e.obj.name)))
        assert seen == [(EventType.ADDED, "ct1")]
        assert factory.wait_for_cache_sync()
        factory.shutdown()

    def test_resync_redelivers_sync_events(self):
        store = Store()
        store.create_pod(make_pod("p1"))
        factory = SharedInformerFactory(store, resync_period=0.05)
        inf = factory.pods()
        synced = threading.Event()

        def handler(e):
            if e.type == EventType.MODIFIED and e.old_obj is e.obj:
                synced.set()

        inf.add_event_handler(handler, replay=False)
        factory.start()
        assert synced.wait(timeout=2), "resync never fired"
        factory.shutdown()

    def test_pod_lister_namespace_view(self):
        store = Store()
        factory = SharedInformerFactory(store, resync_period=0)
        lister = PodLister(factory.pods().indexer)
        store.create_pod(make_pod("a", namespace="ns1"))
        store.create_pod(make_pod("b", namespace="ns2"))
        store.create_pod(make_pod("c", namespace="ns1"))
        assert sorted(p.name for p in lister.pods("ns1").list()) == ["a", "c"]
        assert lister.pods("ns2").get("b").name == "b"
        # predicate filter (the labels.Selector analog)
        assert [p.name for p in lister.list(lambda p: p.name == "b")] == ["b"]
        factory.shutdown()


class TestFakeClientset:
    def test_preloaded_objects_visible_through_all_surfaces(self):
        cs = new_fake_clientset(
            Namespace("ns1", labels={"team": "a"}),
            _throttle("t1", ns="ns1"),
            _cluster_throttle("ct1"),
            make_pod("p1", namespace="ns1"),
        )
        assert cs.schedule_v1alpha1().throttles("ns1").get("t1").name == "t1"
        assert cs.schedule_v1alpha1().cluster_throttles().get("ct1").name == "ct1"
        assert cs.core_v1().pods("ns1").get("p1").name == "p1"
        assert cs.core_v1().namespaces().get("ns1").labels == {"team": "a"}
