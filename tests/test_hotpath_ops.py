"""Property tests for the two hot-path kernels added for the bench push:

- ``fast_check_pod_indexed`` must agree with the dense residual-form check
  restricted to the gathered rows, for every (on_equal, step3_on_equal)
  variant and under index padding;
- ``apply_pod_deltas_batched`` must equal sequential ``apply_pod_delta``
  application (scatter-adds commute exactly in int64).
"""

import random

import jax
import numpy as np
import pytest

from kube_throttler_tpu.ops.aggregate import apply_pod_delta, apply_pod_deltas_batched
from kube_throttler_tpu.ops.check import CHECK_NOT_AFFECTED
from kube_throttler_tpu.ops.fastcheck import (
    fast_check_pod_indexed,
    fast_check_pod_packed,
    fast_check_pods,
    pack_check_state,
    precompute_check_state,
)
from kube_throttler_tpu.ops.schema import PodBatch, ThrottleState


def _rand_state(npr, T, R):
    """Random padded ThrottleState with adversarial presence masks."""
    thr_req = npr.integers(0, 2000, (T, R)).astype(np.int64)
    used_req = npr.integers(0, 2000, (T, R)).astype(np.int64)
    res_req = npr.integers(0, 500, (T, R)).astype(np.int64)
    return ThrottleState(
        valid=npr.random(T) < 0.9,
        thr_cnt=npr.integers(0, 10, T).astype(np.int64),
        thr_cnt_present=npr.random(T) < 0.8,
        thr_req=thr_req,
        thr_req_present=npr.random((T, R)) < 0.8,
        used_cnt=npr.integers(0, 12, T).astype(np.int64),
        used_cnt_present=npr.random(T) < 0.8,
        used_req=used_req,
        used_req_present=npr.random((T, R)) < 0.8,
        res_cnt=npr.integers(0, 3, T).astype(np.int64),
        res_cnt_present=npr.random(T) < 0.4,
        res_req=res_req,
        res_req_present=npr.random((T, R)) < 0.4,
        st_cnt_throttled=npr.random(T) < 0.3,
        st_req_throttled=npr.random((T, R)) < 0.3,
        st_req_flag_present=npr.random((T, R)) < 0.6,
    )


def _rand_pod(rng, R):
    req = np.zeros(R, dtype=np.int64)
    present = np.zeros(R, dtype=bool)
    for r in range(R):
        if rng.random() < 0.7:
            req[r] = rng.randrange(0, 2000)
            present[r] = True
    return req, present


@pytest.mark.parametrize("on_equal,s3", [(False, True), (True, True), (False, False)])
def test_indexed_matches_dense(on_equal, s3):
    rng = random.Random(42)
    npr = np.random.default_rng(42)
    for trial in range(20):
        T, R, K = 37, 5, 8
        state = _rand_state(npr, T, R)
        pre = precompute_check_state(state)
        pod_req, pod_present = _rand_pod(rng, R)

        # K slots: some live rows, some padded (idx_valid=False, idx clamped 0)
        n_live = rng.randrange(0, K + 1)
        idx = np.zeros(K, dtype=np.int32)
        valid = np.zeros(K, dtype=bool)
        idx[:n_live] = npr.integers(0, T, n_live)
        valid[:n_live] = True

        got = np.asarray(
            fast_check_pod_indexed(pre, pod_req, pod_present, idx, valid, on_equal, s3)
        )
        packed = np.asarray(
            fast_check_pod_packed(
                pack_check_state(pre), pod_req, pod_present, idx, valid, on_equal, s3
            )
        )
        np.testing.assert_array_equal(packed, got)

        batch = PodBatch(
            valid=np.ones(1, dtype=bool), req=pod_req[None], req_present=pod_present[None]
        )
        mask = np.zeros((1, T), dtype=bool)
        mask[0, idx[:n_live]] = True
        dense = np.asarray(fast_check_pods(pre, batch, mask, on_equal, s3))[0]

        for slot in range(K):
            if valid[slot]:
                assert got[slot] == dense[idx[slot]], (trial, slot)
            else:
                assert got[slot] == CHECK_NOT_AFFECTED


def test_batched_deltas_match_sequential():
    npr = np.random.default_rng(7)
    T, R, N, K = 23, 4, 50, 3
    used_cnt = npr.integers(0, 100, T).astype(np.int64)
    used_req = npr.integers(0, 10_000, (T, R)).astype(np.int64)
    contrib = npr.integers(0, 20, (T, R)).astype(np.int32)

    # pad ~20% of slots out-of-range (row T) — scatter must drop them
    ids = npr.integers(0, T + 1, (N, K)).astype(np.int32)
    signs = npr.choice(np.array([-1, 0, 1], dtype=np.int64), (N, K))
    pod_req = npr.integers(0, 500, (N, R)).astype(np.int64)
    pod_present = npr.random((N, R)) < 0.8

    seq = (used_cnt.copy(), used_req.copy(), contrib.copy())
    for i in range(N):
        seq = apply_pod_delta(*seq, ids[i], signs[i], pod_req[i], pod_present[i])
    seq = [np.asarray(a) for a in seq]

    bat = apply_pod_deltas_batched(
        used_cnt, used_req, contrib, ids, signs, pod_req, pod_present
    )
    for got, want in zip(bat, seq):
        np.testing.assert_array_equal(np.asarray(got), want)
