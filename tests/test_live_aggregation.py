"""The live reconcile data plane: device used-aggregates + index-backed
collections (VERDICT r1 item 2 — no store scans in reconcile).

Each scenario drives the REAL daemon path (store events → DeviceStateManager
deltas/rebases → controller reconcile_batch → status write) and asserts the
written ``status.used`` equals an independent oracle recompute, across the
sequences where incremental bookkeeping is easiest to get wrong:

- pod delta followed by a selector edit on the same throttle before any
  flush (the delta must be dropped, not double-applied, when the column is
  rebased);
- pod label move between throttles;
- bind/terminate phase flips (counted-set membership);
- namespace (re)definition (full-rebase path for clusterthrottles);
- delta-burst overflow (pending-list cap forces a full rebase);
- new resource dimension appearing mid-stream (R growth).
"""

from __future__ import annotations

from dataclasses import replace
from datetime import datetime, timezone

from kube_throttler_tpu.api.pod import Namespace, make_pod
from kube_throttler_tpu.api.types import (
    ClusterThrottle,
    ClusterThrottleSelector,
    ClusterThrottleSelectorTerm,
    LabelSelector,
    ResourceAmount,
    ClusterThrottleSpec,
    Throttle,
    ThrottleSelector,
    ThrottleSelectorTerm,
    ThrottleSpec,
    resource_amount_of_pod,
)
from kube_throttler_tpu.engine.store import Store
from kube_throttler_tpu.plugin import KubeThrottler, decode_plugin_args
from kube_throttler_tpu.utils.clock import FakeClock

NOW = datetime(2024, 3, 1, 12, 0, 0, tzinfo=timezone.utc)


def _stack():
    store = Store()
    clock = FakeClock(NOW)
    plugin = KubeThrottler(
        decode_plugin_args(
            {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
        ),
        store,
        clock=clock,
        use_device=True,
    )
    store.create_namespace(Namespace("default"))
    return store, plugin, clock


def _throttle(name, labels, **threshold):
    return Throttle(
        name=name,
        spec=ThrottleSpec(
            throttler_name="kube-throttler",
            threshold=ResourceAmount.of(**threshold),
            selector=ThrottleSelector(
                selector_terms=(
                    ThrottleSelectorTerm(LabelSelector(match_labels=labels)),
                )
            ),
        ),
    )


def _ct_team_x(name):
    """ClusterThrottle selecting pods {grp: a} in namespaces {team: x}."""
    return ClusterThrottle(
        name=name,
        spec=ClusterThrottleSpec(
            throttler_name="kube-throttler",
            threshold=ResourceAmount.of(pod=10),
            selector=ClusterThrottleSelector(
                selector_terms=(
                    ClusterThrottleSelectorTerm(
                        pod_selector=LabelSelector(match_labels={"grp": "a"}),
                        namespace_selector=LabelSelector(match_labels={"team": "x"}),
                    ),
                )
            ),
        ),
    )


def _bound(pod):
    bound = replace(pod, spec=replace(pod.spec, node_name="node-1"))
    bound.status.phase = "Running"
    return bound


def _oracle_used(store, thr):
    """Independent recompute of status.used from raw store contents."""
    used = ResourceAmount()
    for pod in store.list_pods():
        if pod.spec.scheduler_name != "my-scheduler" or not pod.is_scheduled():
            continue
        if not pod.is_not_finished():
            continue
        if isinstance(thr, Throttle):
            if pod.namespace != thr.namespace:
                continue
            if not thr.spec.selector.matches_to_pod(pod):
                continue
        else:
            ns = store.get_namespace(pod.namespace)
            if ns is None or not thr.spec.selector.matches_to_pod(pod, ns):
                continue
        used = used.add(resource_amount_of_pod(pod))
    return used


def _assert_status_matches_oracle(store, plugin):
    plugin.run_pending_once()
    for thr in store.list_throttles():
        assert thr.status.used == _oracle_used(store, thr), thr.key
    for thr in store.list_cluster_throttles():
        assert thr.status.used == _oracle_used(store, thr), thr.key


class TestDeltaThenRebase:
    def test_pod_delta_then_selector_edit_does_not_double_count(self):
        store, plugin, _ = _stack()
        store.create_throttle(_throttle("t1", {"grp": "a"}, pod=10, requests={"cpu": "1"}))
        plugin.run_pending_once()

        # pod event (pending delta) and a selector edit on the same throttle
        # land in the SAME flush window: the rebase reads current state, so
        # the pod's delta must be dropped or it applies twice
        pod = _bound(make_pod("p1", labels={"grp": "a"}, requests={"cpu": "300m"}))
        store.create_pod(pod)
        thr = store.get_throttle("default", "t1")
        store.update_throttle(thr)  # no-op spec touch still marks the column
        _assert_status_matches_oracle(store, plugin)
        thr = store.get_throttle("default", "t1")
        assert thr.status.used.resource_counts == 1

    def test_label_move_between_throttles(self):
        store, plugin, _ = _stack()
        store.create_throttle(_throttle("ta", {"grp": "a"}, pod=10, requests={"cpu": "1"}))
        store.create_throttle(_throttle("tb", {"grp": "b"}, pod=10, requests={"cpu": "1"}))
        pod = _bound(make_pod("p1", labels={"grp": "a"}, requests={"cpu": "200m"}))
        store.create_pod(pod)
        _assert_status_matches_oracle(store, plugin)

        moved = replace(pod, labels={"grp": "b"})
        store.update_pod(moved)
        _assert_status_matches_oracle(store, plugin)
        assert store.get_throttle("default", "ta").status.used == ResourceAmount()
        assert store.get_throttle("default", "tb").status.used.resource_counts == 1

    def test_phase_flip_leaves_then_rejoins_counted_set(self):
        store, plugin, _ = _stack()
        store.create_throttle(_throttle("t1", {"grp": "a"}, pod=10))
        pod = _bound(make_pod("p1", labels={"grp": "a"}, requests={"cpu": "100m"}))
        store.create_pod(pod)
        _assert_status_matches_oracle(store, plugin)

        finished = replace(pod)
        finished.status.phase = "Succeeded"
        store.update_pod(finished)
        _assert_status_matches_oracle(store, plugin)
        assert store.get_throttle("default", "t1").status.used == ResourceAmount()


class TestFullRebasePaths:
    def test_namespace_definition_triggers_clusterthrottle_rebase(self):
        store, plugin, _ = _stack()
        store.create_cluster_throttle(_ct_team_x("ct1"))
        store.create_namespace(Namespace("team-ns", labels={"team": "x"}))
        pod = _bound(
            make_pod("p1", namespace="team-ns", labels={"grp": "a"}, requests={"cpu": "1"})
        )
        store.create_pod(pod)
        _assert_status_matches_oracle(store, plugin)
        ct = store.get_cluster_throttle("ct1")
        assert ct.status.used.resource_counts == 1

        # relabel the namespace so the selector no longer matches: many mask
        # rows flip at once → full-rebase path
        store.update_namespace(Namespace("team-ns", labels={"team": "y"}))
        store.update_pod(replace(pod))  # poke a reconcile
        _assert_status_matches_oracle(store, plugin)

    def test_namespace_relabel_converges_without_pod_poke(self):
        """The namespace event alone must enqueue the affected
        clusterthrottle (controllers/clusterthrottle._on_namespace_event) —
        no pod activity required for status.used to converge."""
        store, plugin, _ = _stack()
        store.create_cluster_throttle(_ct_team_x("ct1"))
        store.create_namespace(Namespace("team-ns", labels={"team": "x"}))
        store.create_pod(
            _bound(
                make_pod(
                    "p1", namespace="team-ns", labels={"grp": "a"}, requests={"cpu": "1"}
                )
            )
        )
        _assert_status_matches_oracle(store, plugin)
        assert store.get_cluster_throttle("ct1").status.used.resource_counts == 1

        store.update_namespace(Namespace("team-ns", labels={"team": "y"}))
        _assert_status_matches_oracle(store, plugin)
        assert store.get_cluster_throttle("ct1").status.used == ResourceAmount()

        # and back: the namespace re-matching must also converge unpoked
        store.update_namespace(Namespace("team-ns", labels={"team": "x"}))
        _assert_status_matches_oracle(store, plugin)
        assert store.get_cluster_throttle("ct1").status.used.resource_counts == 1

    def test_namespace_delete_clears_clusterthrottle_used(self):
        """Deleting a Namespace object must un-match its pods from every
        clusterthrottle (the oracle requires the Namespace,
        clusterthrottle_controller.go:273-276) — a DELETED event must not
        be treated as an upsert that re-marks the namespace as existing."""
        store, plugin, _ = _stack()
        store.create_cluster_throttle(_ct_team_x("ct1"))
        store.create_namespace(Namespace("team-ns", labels={"team": "x"}))
        store.create_pod(
            _bound(
                make_pod(
                    "p1", namespace="team-ns", labels={"grp": "a"}, requests={"cpu": "1"}
                )
            )
        )
        plugin.run_pending_once()
        assert store.get_cluster_throttle("ct1").status.used.resource_counts == 1

        store.delete_namespace("team-ns")
        plugin.run_pending_once()
        assert store.get_cluster_throttle("ct1").status.used == ResourceAmount()

        # re-creating the namespace restores the match (existence flips back)
        store.create_namespace(Namespace("team-ns", labels={"team": "x"}))
        plugin.run_pending_once()
        assert store.get_cluster_throttle("ct1").status.used.resource_counts == 1

    def test_namespace_move_between_selector_terms_converges(self):
        """A relabel that moves the namespace from one selector term to
        another keeps the OR-aggregate namespace match True on both sides
        while the counted pod set changes completely — the flip detection
        must be per term."""
        store, plugin, _ = _stack()
        store.create_cluster_throttle(
            ClusterThrottle(
                name="ct2",
                spec=ClusterThrottleSpec(
                    throttler_name="kube-throttler",
                    threshold=ResourceAmount.of(pod=10),
                    selector=ClusterThrottleSelector(
                        selector_terms=(
                            ClusterThrottleSelectorTerm(
                                pod_selector=LabelSelector(match_labels={"grp": "a"}),
                                namespace_selector=LabelSelector(
                                    match_labels={"team": "x"}
                                ),
                            ),
                            ClusterThrottleSelectorTerm(
                                pod_selector=LabelSelector(match_labels={"grp": "b"}),
                                namespace_selector=LabelSelector(
                                    match_labels={"team": "y"}
                                ),
                            ),
                        )
                    ),
                ),
            )
        )
        store.create_namespace(Namespace("team-ns", labels={"team": "x"}))
        store.create_pod(
            _bound(
                make_pod(
                    "pa", namespace="team-ns", labels={"grp": "a"}, requests={"cpu": "1"}
                )
            )
        )
        store.create_pod(
            _bound(
                make_pod(
                    "pb", namespace="team-ns", labels={"grp": "b"}, requests={"cpu": "2"}
                )
            )
        )
        _assert_status_matches_oracle(store, plugin)
        ct = store.get_cluster_throttle("ct2")
        assert ct.status.used.resource_counts == 1  # only pa (term 1)

        # term-1 match flips off, term-2 flips on: counted set pa → pb,
        # with NO pod poke
        store.update_namespace(Namespace("team-ns", labels={"team": "y"}))
        _assert_status_matches_oracle(store, plugin)
        ct = store.get_cluster_throttle("ct2")
        assert ct.status.used.resource_counts == 1
        assert ct.status.used.resource_requests == {"cpu": 2}

    def test_resync_backstop_converges_after_missed_event(self):
        """reconcileTemporaryThresholdInterval as the eventual-consistency
        backstop (the analog of the reference's 5-min informer resync,
        plugin.go:77): with the namespace handler detached to simulate a
        missed watch event, the status is stale until the FakeClock crosses
        the resync interval — then it converges with NO pod poke."""
        import time

        store, plugin, clock = _stack()
        ctr = plugin.cluster_throttle_ctr
        store.create_cluster_throttle(_ct_team_x("ct1"))
        store.create_namespace(Namespace("team-ns", labels={"team": "x"}))
        store.create_pod(
            _bound(
                make_pod(
                    "p1", namespace="team-ns", labels={"grp": "a"}, requests={"cpu": "1"}
                )
            )
        )
        _assert_status_matches_oracle(store, plugin)
        assert store.get_cluster_throttle("ct1").status.used.resource_counts == 1

        plugin.informers.namespaces().remove_event_handler(ctr._on_namespace_event)
        store.update_namespace(Namespace("team-ns", labels={"team": "y"}))
        plugin.run_pending_once()
        # event missed → stale (exactly the round-2 bug, now confined to a
        # simulated watch-stream failure)
        assert store.get_cluster_throttle("ct1").status.used.resource_counts == 1

        # default interval is 15s; cross it and wait for the delayed-queue
        # waker to promote the resync sentinel (polls the clock at ~2ms)
        clock.advance(decode_plugin_args(
            {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
        ).reconcile_temporary_threshold_interval + __import__("datetime").timedelta(seconds=1))
        deadline = time.time() + 5
        while time.time() < deadline:
            plugin.run_pending_once()
            if store.get_cluster_throttle("ct1").status.used == ResourceAmount():
                break
            time.sleep(0.01)
        assert store.get_cluster_throttle("ct1").status.used == ResourceAmount()
        for thr in store.list_cluster_throttles():
            assert thr.status.used == _oracle_used(store, thr)

    def test_delta_burst_overflow_forces_full_rebase(self):
        store, plugin, _ = _stack()
        dm = plugin.device_manager
        dm.throttle._agg_pending_max = 16  # force the overflow path
        store.create_throttle(_throttle("t1", {"grp": "a"}, pod=1000))
        for i in range(40):
            store.create_pod(
                _bound(make_pod(f"p{i}", labels={"grp": "a"}, requests={"cpu": "50m"}))
            )
        assert dm.throttle._agg_full_rebase  # cap tripped before any flush
        _assert_status_matches_oracle(store, plugin)
        assert store.get_throttle("default", "t1").status.used.resource_counts == 40

    def test_new_resource_dimension_mid_stream(self):
        store, plugin, _ = _stack()
        store.create_throttle(_throttle("t1", {"grp": "a"}, pod=10, requests={"cpu": "1"}))
        store.create_pod(
            _bound(make_pod("p1", labels={"grp": "a"}, requests={"cpu": "100m"}))
        )
        _assert_status_matches_oracle(store, plugin)
        # a resource name no prior object used: R grows, aggregates rebase
        store.create_pod(
            _bound(
                make_pod(
                    "p2",
                    labels={"grp": "a"},
                    requests={"cpu": "100m", "example.com/widgets": "3"},
                )
            )
        )
        _assert_status_matches_oracle(store, plugin)
        used = store.get_throttle("default", "t1").status.used
        assert used.resource_requests["example.com/widgets"] == 3


class TestThrottlerNameHandover:
    def test_handover_to_this_throttler_builds_the_column(self):
        """A MODIFIED that flips throttlerName TO this throttler without
        touching the selector must still build the mask column and the
        aggregate — the selector-unchanged fast path (a status-echo
        optimization) must not swallow it, or the throttle is silently
        unenforced."""
        store, plugin, _ = _stack()
        foreign = Throttle(
            name="t1",
            spec=ThrottleSpec(
                throttler_name="someone-else",
                threshold=ResourceAmount.of(pod=0),  # throttles immediately
                selector=ThrottleSelector(
                    selector_terms=(
                        ThrottleSelectorTerm(LabelSelector(match_labels={"grp": "a"})),
                    )
                ),
            ),
        )
        store.create_throttle(foreign)
        store.create_pod(
            _bound(make_pod("p1", labels={"grp": "a"}, requests={"cpu": "100m"}))
        )
        plugin.run_pending_once()
        # not ours: no status management (the OTHER throttler owns it, so
        # the used stays nil here), and pods are not throttled by it
        assert store.get_throttle("default", "t1").status.used == ResourceAmount()
        assert plugin.pre_filter(
            make_pod("p2", labels={"grp": "a"}, requests={"cpu": "1m"})
        ).is_success()

        # handover: same selector, new owner
        store.update_throttle_spec(
            replace(foreign, spec=replace(foreign.spec, throttler_name="kube-throttler"))
        )
        _assert_status_matches_oracle(store, plugin)
        thr = store.get_throttle("default", "t1")
        assert thr.status.used.resource_counts == 1
        verdict = plugin.pre_filter(
            make_pod("p2", labels={"grp": "a"}, requests={"cpu": "1m"})
        )
        assert not verdict.is_success()


class TestIndexBackedCollections:
    def test_affected_keys_for_stale_pod_version(self):
        store, plugin, _ = _stack()
        store.create_throttle(_throttle("ta", {"grp": "a"}, pod=10))
        store.create_throttle(_throttle("tb", {"grp": "b"}, pod=10))
        pod = _bound(make_pod("p1", labels={"grp": "a"}))
        store.create_pod(pod)
        moved = replace(pod, labels={"grp": "b"})
        store.update_pod(moved)
        # the index has moved to `moved`; querying the OLD object must
        # evaluate it fresh, not return the new row
        ctr = plugin.throttle_ctr
        assert ctr.affected_throttle_keys(pod) == ["default/ta"]
        assert ctr.affected_throttle_keys(moved) == ["default/tb"]

    def test_batch_drain_reconciles_all_keys_in_one_call(self):
        store, plugin, _ = _stack()
        calls = []
        dm = plugin.device_manager
        orig = dm.aggregate_used_for

        def spy(kind, keys, reserved=None, flips_out=None):
            calls.append((kind, tuple(sorted(keys))))
            return orig(kind, keys, reserved, flips_out=flips_out)

        dm.aggregate_used_for = spy
        for i in range(20):
            store.create_throttle(_throttle(f"t{i}", {"grp": f"g{i % 3}"}, pod=5))
        for i in range(10):
            store.create_pod(
                _bound(
                    make_pod(f"p{i}", labels={"grp": f"g{i % 3}"}, requests={"cpu": "10m"})
                )
            )
        plugin.run_pending_once()
        throttle_calls = [keys for kind, keys in calls if kind == "throttle"]
        # every enqueued key reconciled, in far fewer aggregate calls than keys
        reconciled = set().union(*throttle_calls)
        assert len(reconciled) == 20
        assert len(throttle_calls) < 20
        _assert_status_matches_oracle(store, plugin)
