"""Multiprocess keyspace sharding: ring, router, scatter-gather merge,
two-phase reserve, gang one-owner ledger, resync.

The equivalence suite builds IDENTICAL object populations in (a) a
sharded front over N in-process shard cores (LocalShard transport —
deterministic, no sockets; the real IPC is covered by the framing tests
here and the subprocess chaos smoke in test_shard_chaos.py) and (b) a
single-process KubeThrottler oracle, then pins:

    sharded pre_filter ≡ single-process pre_filter

on status code + normalized reasons (name lists sorted — the
single-process ordering is index-column order, which does not exist
across shards) for every pod, including multi-shard-matching pods,
gang groups, and accel-class pods.
"""

from __future__ import annotations

import socket
import threading
import time

import pytest

import tools.harness as H
from kube_throttler_tpu.api.pod import Namespace, make_pod
from kube_throttler_tpu.api.types import (
    AccelClassThreshold,
    LabelSelector,
    ClusterThrottle,
    ClusterThrottleSelector,
    ClusterThrottleSelectorTerm,
    ClusterThrottleSpec,
    ResourceAmount,
    ThrottleSelector,
    ThrottleSelectorTerm,
)
from kube_throttler_tpu.engine.store import Store
from kube_throttler_tpu.plugin.framework import StatusCode
from kube_throttler_tpu.sharding.front import AdmissionFront
from kube_throttler_tpu.sharding.ipc import (
    LocalShard,
    ShardUnavailable,
    read_frame,
    send_frame,
)
from kube_throttler_tpu.sharding.ring import (
    HashRing,
    route_key_for,
    selector_fingerprint,
    stable_hash64,
)
from kube_throttler_tpu.sharding.worker import ShardCore


def make_cluster_throttle(name, labels, threshold=None, accel=()):
    return ClusterThrottle(
        name=name,
        spec=ClusterThrottleSpec(
            throttler_name="kube-throttler",
            threshold=threshold
            or ResourceAmount.of(pod=2, requests={"cpu": "1"}),
            selector=ClusterThrottleSelector(
                selector_terms=(
                    ClusterThrottleSelectorTerm(
                        LabelSelector(match_labels=dict(labels)),
                        LabelSelector(),
                    ),
                )
            ),
            accel_class_thresholds=tuple(accel),
        ),
    )


def build_sharded(n_shards, prepare_ttl=30.0, use_device=False):
    front = AdmissionFront(n_shards)
    cores = [
        ShardCore(i, n_shards, use_device=use_device, prepare_ttl=prepare_ttl)
        for i in range(n_shards)
    ]
    for i, core in enumerate(cores):
        front.attach_shard(i, LocalShard(i, core, on_push=front.apply_status_push))
    return front, cores


def teardown_sharded(front, cores):
    for core in cores:
        core.stop()
    front.stop()


def settle(front, timeout=30.0):
    assert front.drain(timeout=timeout)
    time.sleep(0.3)  # push loops flush on their own cadence


def apply_all(stores, fn):
    for store in stores:
        fn(store)


# --------------------------------------------------------------------------
# ring
# --------------------------------------------------------------------------


class TestRing:
    def test_stable_across_instances(self):
        a, b = HashRing(4), HashRing(4)
        keys = [f"k{i}" for i in range(500)]
        assert [a.shard_of(k) for k in keys] == [b.shard_of(k) for k in keys]

    def test_stable_hash_is_process_stable(self):
        # pinned value: blake2b, not the salted builtin hash
        assert stable_hash64("kube-throttler") == stable_hash64("kube-throttler")
        assert stable_hash64("a") != stable_hash64("b")

    def test_spread_is_balanced(self):
        ring = HashRing(4)
        counts = ring.spread(f"key-{i}" for i in range(4000))
        assert min(counts) > 0
        assert max(counts) / (sum(counts) / len(counts)) < 1.6

    def test_selector_affinity_colocates_same_selector(self):
        ring = HashRing(4)
        thrs = [H.make_throttle(7) for _ in range(5)]
        # same selector → same fingerprint → same shard, regardless of name
        import dataclasses

        thrs = [dataclasses.replace(t, name=f"t7-{i}") for i, t in enumerate(thrs)]
        owners = {ring.shard_of(route_key_for("Throttle", t)) for t in thrs}
        assert len(owners) == 1

    def test_fingerprint_scopes_namespace_and_kind(self):
        import dataclasses

        t = H.make_throttle(1)
        t2 = dataclasses.replace(t, namespace="other")
        assert selector_fingerprint(t) != selector_fingerprint(t2)
        ct = make_cluster_throttle("c1", {"grp": "g1"})
        assert selector_fingerprint(t) != selector_fingerprint(ct)

    def test_gang_route_key(self):
        assert route_key_for("Gang", "default/job") == "gang|default/job"
        ring = HashRing(8)
        assert ring.shard_of(route_key_for("Gang", "default/job")) == ring.shard_of(
            route_key_for("Gang", "default/job")
        )


# --------------------------------------------------------------------------
# ipc framing
# --------------------------------------------------------------------------


class TestFraming:
    def test_frame_roundtrip(self):
        a, b = socket.socketpair()
        try:
            lock = threading.Lock()
            pod = make_pod("p", labels={"x": "y"}, requests={"cpu": "1"})
            send_frame(a, lock, "evt", 7, [("upsert", "Pod", pod)], epoch=3)
            rfile = b.makefile("rb")
            mtype, rid, body, epoch = read_frame(rfile)
            assert (mtype, rid, epoch) == ("evt", 7, 3)
            verb, kind, got = body[0]
            assert (verb, kind, got.key, got.labels) == (
                "upsert", "Pod", pod.key, {"x": "y"},
            )
        finally:
            a.close()
            b.close()

    def test_read_frame_eof(self):
        a, b = socket.socketpair()
        a.close()
        assert read_frame(b.makefile("rb")) is None
        b.close()


# --------------------------------------------------------------------------
# verdict-merge equivalence: sharded ≡ single-process
# --------------------------------------------------------------------------


def seeded_population(seed, n_groups=6, n_pods=40):
    """Deterministic op list: namespaced throttles per group, a couple of
    cluster throttles (one with accel-class thresholds), and pods — some
    matching several selector classes at once (multi-shard pods), some
    gang-annotated, some accel-class, some in an unknown namespace."""
    import random

    rng = random.Random(seed)
    ops = []
    ops.append(("ns", Namespace("default")))
    for i in range(n_groups):
        ops.append(("thr", H.make_throttle(i)))
    ops.append(("cthr", make_cluster_throttle("cwide", {"tier": "hot"})))
    ops.append(
        (
            "cthr",
            make_cluster_throttle(
                "caccel",
                {"grp": "g1"},
                accel=(
                    AccelClassThreshold(
                        accel_class="tpu-v5e",
                        threshold=ResourceAmount.of(pod=1),
                    ),
                ),
            ),
        )
    )
    for i in range(n_pods):
        labels = {"grp": f"g{rng.randrange(n_groups)}"}
        if rng.random() < 0.4:
            labels["tier"] = "hot"  # matches cwide too → multi-shard pod
        kwargs = {}
        if rng.random() < 0.2:
            kwargs["accel_class"] = "tpu-v5e"
        if rng.random() < 0.2:
            kwargs["group"] = f"job{rng.randrange(3)}"
            kwargs["group_size"] = 3
        pod = make_pod(
            f"p{i}",
            labels=labels,
            requests={"cpu": f"{rng.randrange(1, 9) * 250}m"},
            node_name="node-1" if rng.random() < 0.8 else "",
            phase="Running" if rng.random() < 0.8 else "Pending",
            **kwargs,
        )
        ops.append(("pod", pod))
    return ops


def apply_population(store, ops):
    for what, obj in ops:
        if what == "ns":
            store.create_namespace(obj)
        elif what == "thr":
            store.create_throttle(obj)
        elif what == "cthr":
            store.create_cluster_throttle(obj)
        else:
            store.create_pod(obj)


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("n_shards", [2, 3])
def test_sharded_pre_filter_equivalence(seed, n_shards):
    """Seeded sweep: sharded pre_filter ≡ single-process pre_filter on
    identical stores — multi-shard-matching pods, accel-class pods, and
    probe pods included. Reasons compared via normalized_reasons."""
    ops = seeded_population(seed)
    front, cores = build_sharded(n_shards)
    oracle_store = Store()
    try:
        apply_population(front.store, ops)
        apply_population(oracle_store, ops)
        oracle = H.build_plugin(oracle_store)
        oracle.run_pending_once()  # shards reconcile; the oracle must too
        settle(front)
        # stored pods AND unstored probes (the scheduler's common case)
        probes = [
            make_pod("probe-multi", labels={"grp": "g1", "tier": "hot"},
                     requests={"cpu": "500m"}),
            make_pod("probe-accel", labels={"grp": "g1"},
                     requests={"cpu": "250m"}, accel_class="tpu-v5e"),
            make_pod("probe-nomatch", labels={"zz": "qq"},
                     requests={"cpu": "250m"}),
        ]
        for pod in list(oracle_store.list_pods()) + probes:
            got = front.pre_filter(pod)
            want = oracle.pre_filter(pod)
            assert got.code == want.code, (
                pod.key, got.code, got.reasons, want.code, want.reasons,
            )
            assert H.normalized_reasons(got.reasons) == H.normalized_reasons(
                want.reasons
            ), pod.key
    finally:
        teardown_sharded(front, cores)


def test_missing_namespace_is_error_like_single_process():
    front, cores = build_sharded(2)
    oracle_store = Store()
    try:
        for store in (front.store, oracle_store):
            store.create_namespace(Namespace("default"))
            store.create_throttle(H.make_throttle(0))
        oracle = H.build_plugin(oracle_store)
        oracle.run_pending_once()
        settle(front)
        ghost = make_pod("ghost", namespace="nowhere", labels={"grp": "g0"},
                         requests={"cpu": "100m"})
        got, want = front.pre_filter(ghost), oracle.pre_filter(ghost)
        assert got.code == want.code == StatusCode.ERROR
        assert got.reasons == want.reasons
    finally:
        teardown_sharded(front, cores)


def test_pre_filter_batch_equivalence():
    ops = seeded_population(5)
    front, cores = build_sharded(3)
    oracle_store = Store()
    try:
        apply_population(front.store, ops)
        apply_population(oracle_store, ops)
        oracle = H.build_plugin(oracle_store)
        oracle.run_pending_once()
        settle(front)
        got = front.pre_filter_batch()
        want = oracle.pre_filter_batch()
        assert got["schedulable"] == want["schedulable"]
        assert sorted(got["errors"]) == sorted(want["errors"])
    finally:
        teardown_sharded(front, cores)


def test_equivalence_with_reservations():
    """Reservations change 'insufficient' verdicts; a two-phase reserve on
    the sharded stack must produce the same downstream verdicts as the
    oracle's local reserve."""
    front, cores = build_sharded(2)
    oracle_store = Store()
    try:
        for store in (front.store, oracle_store):
            store.create_namespace(Namespace("default"))
            for i in range(4):
                store.create_throttle(H.make_throttle(i))
        oracle = H.build_plugin(oracle_store)
        oracle.run_pending_once()
        settle(front)
        held = [
            make_pod(f"r{i}", labels={"grp": f"g{i % 4}"},
                     requests={"cpu": "600m"})
            for i in range(6)
        ]
        for pod in held:
            assert front.reserve(pod).is_success()
            assert oracle.reserve(pod).is_success()
        probe = make_pod("probe", labels={"grp": "g2"}, requests={"cpu": "600m"})
        got, want = front.pre_filter(probe), oracle.pre_filter(probe)
        assert got.code == want.code
        assert H.normalized_reasons(got.reasons) == H.normalized_reasons(want.reasons)
        # and unreserve restores symmetry
        for pod in held:
            front.unreserve(pod)
            oracle.unreserve(pod)
        got2, want2 = front.pre_filter(probe), oracle.pre_filter(probe)
        assert got2.code == want2.code
    finally:
        teardown_sharded(front, cores)


# --------------------------------------------------------------------------
# two-phase reserve
# --------------------------------------------------------------------------


class TestTwoPhaseReserve:
    def test_prepare_failure_aborts_everywhere(self):
        """A pod matching throttles on two shards, one shard dead: the
        prepare on the live shard must be ABORTED — zero reservations
        survive anywhere."""
        front, cores = build_sharded(2)
        try:
            front.store.create_namespace(Namespace("default"))
            for i in range(4):
                front.store.create_throttle(H.make_throttle(i))
            front.store.create_cluster_throttle(
                make_cluster_throttle("cwide", {"tier": "hot"})
            )
            settle(front)
            cw_owner = front.owner_of("ClusterThrottle", "/cwide")
            g = next(
                i for i in range(4)
                if front.owner_of("Throttle", f"default/t{i}") != cw_owner
            )
            pod = make_pod("multi", labels={"grp": f"g{g}", "tier": "hot"},
                           requests={"cpu": "100m"})
            targets = sorted(front._pod_target_shards(pod))
            assert len(targets) == 2, "population must split across shards"
            front.shards[targets[1]].close()  # shard dies pre-prepare
            status = front.reserve(pod)
            assert status.code == StatusCode.ERROR
            live = cores[targets[0]]
            for cache in (
                live.plugin.throttle_ctr.cache,
                live.plugin.cluster_throttle_ctr.cache,
            ):
                for key in (
                    [t.key for t in live.store.list_throttles()]
                    + [t.key for t in live.store.list_cluster_throttles()]
                ):
                    amount, _ = cache.reserved_resource_amount(key)
                    assert not amount.resource_counts, (key, amount)
            assert front.stats()["two_phase_aborts"] == 1
        finally:
            teardown_sharded(front, cores)

    def test_orphaned_prepare_is_reaped(self):
        """Prepare lands, the front 'crashes' before commit/abort: the
        shard-side reaper aborts the stale transaction — no orphan
        reservation outlives prepare_ttl."""
        front, cores = build_sharded(2, prepare_ttl=0.2)
        try:
            front.store.create_namespace(Namespace("default"))
            front.store.create_throttle(H.make_throttle(1))
            settle(front)
            pod = make_pod("probe", labels={"grp": "g1"}, requests={"cpu": "100m"})
            (sid,) = front._pod_target_shards(pod)
            front.shards[sid].request(
                "reserve_prepare", {"txn": "orphan", "pod": pod}
            )
            amount, _ = cores[sid].plugin.throttle_ctr.cache.reserved_resource_amount(
                "default/t1"
            )
            assert amount.resource_counts == 1
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                amount, _ = cores[
                    sid
                ].plugin.throttle_ctr.cache.reserved_resource_amount("default/t1")
                if not amount.resource_counts:
                    break
                time.sleep(0.05)
            assert not amount.resource_counts
            assert cores[sid].reaped_txns == 1
        finally:
            teardown_sharded(front, cores)

    def test_commit_keeps_reservation(self):
        front, cores = build_sharded(2)
        try:
            front.store.create_namespace(Namespace("default"))
            front.store.create_throttle(H.make_throttle(1))
            settle(front)
            pod = make_pod("probe", labels={"grp": "g1"}, requests={"cpu": "100m"})
            assert front.reserve(pod).is_success()
            (sid,) = front._pod_target_shards(pod)
            amount, _ = cores[sid].plugin.throttle_ctr.cache.reserved_resource_amount(
                "default/t1"
            )
            assert amount.resource_counts == 1
            # committed: the reaper must NOT touch it
            cores[sid].reap_stale_txns(now=time.monotonic() + 120.0)
            amount, _ = cores[sid].plugin.throttle_ctr.cache.reserved_resource_amount(
                "default/t1"
            )
            assert amount.resource_counts == 1
            front.unreserve(pod)
        finally:
            teardown_sharded(front, cores)


# --------------------------------------------------------------------------
# gang admission
# --------------------------------------------------------------------------


class TestShardedGang:
    def _population(self, front_store, oracle_store):
        for store in (front_store, oracle_store):
            store.create_namespace(Namespace("default"))
            for i in range(4):
                store.create_throttle(H.make_throttle(i))
            store.create_cluster_throttle(
                make_cluster_throttle("cwide", {"tier": "hot"})
            )

    def _members(self, n=3, cpu="100m"):
        return [
            make_pod(f"gm{i}", labels={"grp": "g2", "tier": "hot"},
                     requests={"cpu": cpu}, group="job1", group_size=n)
            for i in range(n)
        ]

    def test_gang_check_equivalence(self):
        front, cores = build_sharded(2)
        oracle_store = Store()
        try:
            self._population(front.store, oracle_store)
            oracle = H.build_plugin(oracle_store)
            oracle.run_pending_once()
            settle(front)
            for cpu in ("100m", "5000m"):
                pods = self._members(cpu=cpu)
                got = front.pre_filter_gang("default/job1", pods)
                want = oracle.pre_filter_gang("default/job1", pods)
                assert got.is_success() == want.is_success(), (
                    cpu, got.reasons, want.reasons,
                )
        finally:
            teardown_sharded(front, cores)

    def test_ledger_record_on_exactly_one_shard(self):
        front, cores = build_sharded(3)
        try:
            self._population(front.store, Store())
            settle(front)
            pods = self._members()
            assert front.reserve_gang("default/job1", pods).is_success()
            records = {
                sid: front.shards[sid].request("gang_groups")
                for sid in range(3)
            }
            holders = [sid for sid, recs in records.items() if recs]
            assert holders == [front.gang_owner("default/job1")]
            front.unreserve_gang("default/job1")
            for sid in range(3):
                assert front.shards[sid].request("gang_groups") == []
        finally:
            teardown_sharded(front, cores)

    def test_gang_prepare_crash_leaves_no_orphans(self):
        """Gang prepare on every shard, front dies before commit: the
        reapers roll back the owner's ledger record AND the non-owner
        member reservations."""
        front, cores = build_sharded(2, prepare_ttl=0.2)
        try:
            self._population(front.store, Store())
            settle(front)
            pods = self._members()
            owner = front.gang_owner("default/job1")
            for sid in sorted(front._gang_targets("default/job1", pods)):
                front.shards[sid].request(
                    "gang_prepare",
                    {"txn": f"orphan-{sid}", "group": "default/job1",
                     "pods": pods, "owner": sid == owner},
                )
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                total = 0
                for core in cores:
                    for cache in (
                        core.plugin.throttle_ctr.cache,
                        core.plugin.cluster_throttle_ctr.cache,
                    ):
                        for thr in core.store.list_throttles():
                            a, _ = cache.reserved_resource_amount(thr.key)
                            total += a.resource_counts or 0
                        for thr in core.store.list_cluster_throttles():
                            a, _ = cache.reserved_resource_amount(thr.key)
                            total += a.resource_counts or 0
                if total == 0 and all(
                    front.shards[s].request("gang_groups") == [] for s in range(2)
                ):
                    break
                time.sleep(0.05)
            assert total == 0
            for sid in range(2):
                assert front.shards[sid].request("gang_groups") == []
        finally:
            teardown_sharded(front, cores)


# --------------------------------------------------------------------------
# router behavior
# --------------------------------------------------------------------------


class TestRouter:
    def test_pod_routes_follow_label_changes(self):
        front, cores = build_sharded(2)
        try:
            front.store.create_namespace(Namespace("default"))
            for i in range(4):
                front.store.create_throttle(H.make_throttle(i))
            settle(front)
            owners = {
                i: front.ring.shard_of(route_key_for("Throttle", H.make_throttle(i)))
                for i in range(4)
            }
            g_a = next(i for i in range(4) if owners[i] == 0)
            g_b = next(i for i in range(4) if owners[i] == 1)
            pod = make_pod("mover", labels={"grp": f"g{g_a}"}, requests={"cpu": "1"})
            front.store.create_pod(pod)
            settle(front)
            assert any(p.key == "default/mover" for p in cores[0].store.list_pods())
            assert not any(p.key == "default/mover" for p in cores[1].store.list_pods())
            moved = make_pod("mover", labels={"grp": f"g{g_b}"}, requests={"cpu": "1"})
            front.store.update_pod(moved)
            settle(front)
            # moved INTO shard 1, DELETED from shard 0 (no stale aggregate)
            assert not any(p.key == "default/mover" for p in cores[0].store.list_pods())
            assert any(p.key == "default/mover" for p in cores[1].store.list_pods())
            front.store.delete_pod("default", "mover")
            settle(front)
            assert not any(p.key == "default/mover" for p in cores[1].store.list_pods())
        finally:
            teardown_sharded(front, cores)

    def test_selector_edit_migrates_throttle_and_pods(self):
        import dataclasses

        front, cores = build_sharded(2)
        try:
            front.store.create_namespace(Namespace("default"))
            for i in range(4):
                front.store.create_throttle(H.make_throttle(i))
            pods = [
                make_pod(f"p{i}", labels={"grp": f"g{i % 4}"}, requests={"cpu": "1"})
                for i in range(8)
            ]
            for p in pods:
                front.store.create_pod(p)
            settle(front)
            owners = {
                i: front.ring.shard_of(route_key_for("Throttle", H.make_throttle(i)))
                for i in range(4)
            }
            g_a = next(i for i in range(4) if owners[i] == 0)
            g_b = next(i for i in range(4) if owners[i] == 1)
            # repoint t<g_a>'s selector at group g_b: the throttle must move
            # to g_b's selector-class shard and find its pods there
            old = front.store.get_throttle("default", f"t{g_a}")
            moved = dataclasses.replace(
                old,
                spec=dataclasses.replace(
                    old.spec,
                    selector=ThrottleSelector(
                        selector_terms=(
                            ThrottleSelectorTerm(
                                LabelSelector(match_labels={"grp": f"g{g_b}"})
                            ),
                        )
                    ),
                ),
            )
            front.store.update_throttle_spec(moved)
            settle(front)
            assert front.owner_of("Throttle", f"default/t{g_a}") == 1
            assert not any(
                t.key == f"default/t{g_a}" for t in cores[0].store.list_throttles()
            )
            assert any(
                t.key == f"default/t{g_a}" for t in cores[1].store.list_throttles()
            )
            probe = make_pod("probe", labels={"grp": f"g{g_b}"}, requests={"cpu": "9"})
            status = front.pre_filter(probe)
            # both g_b-selecting throttles answer from shard 1
            names = ";".join(status.reasons)
            assert f"default/t{g_a}" in names and f"default/t{g_b}" in names
        finally:
            teardown_sharded(front, cores)

    def test_status_pushes_are_not_rerouted(self):
        """A shard's status write streams into the front store as a
        status-only MODIFIED — the Router must not echo it back (event
        counts stay flat once drained)."""
        front, cores = build_sharded(2)
        try:
            front.store.create_namespace(Namespace("default"))
            front.store.create_throttle(H.make_throttle(0))
            pod = make_pod("p0", labels={"grp": "g0"}, requests={"cpu": "900m"},
                           node_name="node-1", phase="Running")
            front.store.create_pod(pod)
            settle(front)
            # statuses arrived at the front
            thr = front.store.get_throttle("default", "t0")
            assert thr.status.used.resource_counts == 1
            sent_before = sum(h.events_sent for h in front.shards.values())
            time.sleep(0.5)
            sent_after = sum(h.events_sent for h in front.shards.values())
            assert sent_before == sent_after
        finally:
            teardown_sharded(front, cores)

    def test_resync_after_shard_replacement(self):
        """Kill a LocalShard, attach a fresh empty core, resync: the new
        shard must reach the same verdicts and reconverge statuses."""
        front, cores = build_sharded(2)
        try:
            front.store.create_namespace(Namespace("default"))
            for i in range(4):
                front.store.create_throttle(H.make_throttle(i))
            for i in range(8):
                front.store.create_pod(
                    make_pod(f"p{i}", labels={"grp": f"g{i % 4}"},
                             requests={"cpu": "900m"}, node_name="node-1",
                             phase="Running")
                )
            settle(front)
            probe = make_pod("probe", labels={"grp": "g1"}, requests={"cpu": "1"})
            want = front.pre_filter(probe)
            (sid,) = {
                front.owner_of("Throttle", "default/t1"),
            }
            front.shards[sid].close()
            got_degraded = front.pre_filter(probe)
            assert got_degraded.code == StatusCode.UNSCHEDULABLE_AND_UNRESOLVABLE
            assert any("shard[unavailable]" in r for r in got_degraded.reasons)
            state, detail = front._shards_health()
            assert state == "degraded"
            replacement = ShardCore(sid, 2, use_device=False)
            cores.append(replacement)
            front.attach_shard(
                sid,
                LocalShard(sid, replacement, on_push=front.apply_status_push),
                resync=True,
            )
            settle(front)
            got = front.pre_filter(probe)
            assert got.code == want.code
            assert H.normalized_reasons(got.reasons) == H.normalized_reasons(
                want.reasons
            )
            state, _ = front._shards_health()
            assert state == "ok"
            # pruning: the replacement holds exactly its slice, nothing else
            stats = front.stats()["shards"][sid]
            assert stats["objects"]["throttles"] == len(
                [
                    k
                    for (kind, k), owner in front._owner.items()
                    if kind == "Throttle" and owner == sid
                ]
            )
        finally:
            teardown_sharded(front, cores)


# --------------------------------------------------------------------------
# degraded batch + health surfaces
# --------------------------------------------------------------------------


def test_batch_fails_safe_for_dead_shard_pods():
    front, cores = build_sharded(2)
    try:
        front.store.create_namespace(Namespace("default"))
        for i in range(4):
            front.store.create_throttle(H.make_throttle(i))
        for i in range(8):
            front.store.create_pod(
                make_pod(f"p{i}", labels={"grp": f"g{i % 4}"},
                         requests={"cpu": "100m"})
            )
        settle(front)
        dead = 0
        front.shards[dead].close()
        out = front.pre_filter_batch()
        with front._route_lock:
            routed = dict(front._pod_routes)
        for pkey, sids in routed.items():
            if dead in sids:
                assert out["schedulable"][pkey] is False
    finally:
        teardown_sharded(front, cores)


def test_shard_unavailable_raises_for_rpc():
    front, cores = build_sharded(1)
    try:
        front.shards[0].close()
        with pytest.raises(ShardUnavailable):
            front.shards[0].request("ping")
    finally:
        teardown_sharded(front, cores)
