"""Aggregation / streaming-delta / override-resolution kernels vs oracle."""

import random
from datetime import datetime, timedelta, timezone

import numpy as np

from kube_throttler_tpu.api import ResourceAmount, TemporaryThresholdOverride
from kube_throttler_tpu.api.pod import make_pod
from kube_throttler_tpu.api.types import ThrottleSpecBase, resource_amount_of_pod
from kube_throttler_tpu.ops import DimRegistry, encode_pods
from kube_throttler_tpu.ops.aggregate import (
    aggregate_used,
    apply_pod_delta,
    throttled_flags,
)
from kube_throttler_tpu.ops.overrides import (
    calculate_thresholds,
    encode_override_schedule,
)
from kube_throttler_tpu.quantity import from_milli, to_milli

NOW = datetime(2024, 1, 15, 12, 0, 0, tzinfo=timezone.utc)


def rfc(dt):
    return dt.strftime("%Y-%m-%dT%H:%M:%SZ")


def ns(dt):
    return int(dt.timestamp() * 1e9)


class TestAggregateUsed:
    def _oracle_used(self, pods, mask, counted, j):
        used = ResourceAmount()
        for i, p in enumerate(pods):
            if counted[i] and mask[i][j]:
                used = used.add(resource_amount_of_pod(p))
        return used

    def test_matches_oracle_accumulation(self):
        rng = random.Random(3)
        pods = []
        for i in range(30):
            reqs = {}
            for r in ["cpu", "memory"]:
                if rng.random() < 0.7:
                    reqs[r] = rng.choice(["100m", "1", "0"])
            pods.append(make_pod(f"p{i}", requests=reqs))
        mask = np.array([[rng.random() < 0.5 for _ in range(8)] for _ in pods])
        counted = np.array([rng.random() < 0.7 for _ in pods])

        dims = DimRegistry()
        batch = encode_pods(pods, dims)
        used_cnt, used_req, contrib = aggregate_used(batch, mask, counted)
        used_cnt, used_req, contrib = map(np.asarray, (used_cnt, used_req, contrib))

        for j in range(8):
            want = self._oracle_used(pods, mask, counted, j)
            if want.resource_counts is None:
                assert used_cnt[j] == 0
            else:
                assert used_cnt[j] == want.resource_counts
            assert (used_cnt[j] > 0) == (want.resource_counts is not None)
            for name, q in (want.resource_requests or {}).items():
                r = dims.index_of(name)
                assert from_milli(int(used_req[j, r])) == q
                assert contrib[j, r] > 0
            # dims with zero contributors must read absent
            for r in range(len(dims)):
                name = dims.names[r]
                if want.resource_requests is None or name not in want.resource_requests:
                    assert contrib[j, r] == 0

    def test_streaming_delta_equals_recompute(self):
        rng = random.Random(11)
        pods = [
            make_pod(f"p{i}", requests={"cpu": rng.choice(["100m", "200m"])})
            for i in range(10)
        ]
        mask = np.array([[rng.random() < 0.6 for _ in range(5)] for _ in pods])
        counted = np.ones(len(pods), dtype=bool)
        dims = DimRegistry()
        batch = encode_pods(pods, dims)
        used_cnt, used_req, contrib = aggregate_used(batch, mask, counted)

        # remove pod 3 and add a new pod via scatter deltas
        new_pod = make_pod("new", requests={"cpu": "300m", "memory": "1Gi"})
        dims.index_of("memory")
        affected_old = np.where(mask[3])[0].astype(np.int32)
        K = 5
        ids = np.full(K, mask.shape[1], dtype=np.int32)  # pad out-of-range
        ids[: len(affected_old)] = affected_old
        sign = np.zeros(K, dtype=np.int64)
        sign[: len(affected_old)] = -1
        pod_req = np.asarray(batch.req[3])
        pod_present = np.asarray(batch.req_present[3])
        used_cnt, used_req, contrib = apply_pod_delta(
            used_cnt, used_req, contrib, ids, sign, pod_req, pod_present
        )

        new_mask_row = np.array([rng.random() < 0.6 for _ in range(5)])
        affected_new = np.where(new_mask_row)[0].astype(np.int32)
        ids = np.full(K, mask.shape[1], dtype=np.int32)
        ids[: len(affected_new)] = affected_new
        sign = np.zeros(K, dtype=np.int64)
        sign[: len(affected_new)] = 1
        R = dims.capacity
        new_req = np.zeros(R, dtype=np.int64)
        new_present = np.zeros(R, dtype=bool)
        from kube_throttler_tpu import resourcelist as rl

        for name, q in rl.pod_request_resource_list(new_pod).items():
            new_req[dims.index_of(name)] = to_milli(q)
            new_present[dims.index_of(name)] = True
        used_cnt, used_req, contrib = apply_pod_delta(
            used_cnt, used_req, contrib, ids, sign, new_req, new_present
        )

        # recompute from scratch with pod3 dropped and new pod appended
        pods2 = [p for i, p in enumerate(pods) if i != 3] + [new_pod]
        mask2 = np.vstack([mask[[i for i in range(len(pods)) if i != 3]], new_mask_row])
        batch2 = encode_pods(pods2, dims)
        want_cnt, want_req, want_contrib = aggregate_used(
            batch2, mask2, np.ones(len(pods2), dtype=bool)
        )
        np.testing.assert_array_equal(np.asarray(used_cnt), np.asarray(want_cnt))
        np.testing.assert_array_equal(
            np.asarray(used_req)[:, : len(dims)], np.asarray(want_req)[:, : len(dims)]
        )
        np.testing.assert_array_equal(
            np.asarray(contrib)[:, : len(dims)], np.asarray(want_contrib)[:, : len(dims)]
        )


class TestThrottledFlags:
    def test_matches_oracle(self):
        rng = random.Random(5)
        T, R = 20, 3
        thr_cnt = np.array([rng.randrange(0, 5) for _ in range(T)], dtype=np.int64)
        thr_cnt_present = np.array([rng.random() < 0.7 for _ in range(T)])
        used_cnt = np.array([rng.randrange(0, 5) for _ in range(T)], dtype=np.int64)
        used_cnt_present = np.array([rng.random() < 0.7 for _ in range(T)])
        thr_req = np.array([[rng.randrange(0, 4) * 100 for _ in range(R)] for _ in range(T)], dtype=np.int64)
        thr_req_present = np.array([[rng.random() < 0.7 for _ in range(R)] for _ in range(T)])
        used_req = np.array([[rng.randrange(0, 4) * 100 for _ in range(R)] for _ in range(T)], dtype=np.int64)
        used_req_present = np.array([[rng.random() < 0.7 for _ in range(R)] for _ in range(T)])

        cnt_flag, req_flag, flag_present = throttled_flags(
            thr_cnt, thr_cnt_present, thr_req, thr_req_present,
            used_cnt, used_cnt_present, used_req, used_req_present,
        )
        names = ["r0", "r1", "r2"]
        for t in range(T):
            thr = ResourceAmount.of(
                pod=int(thr_cnt[t]) if thr_cnt_present[t] else None,
                requests={names[r]: from_milli(int(thr_req[t, r])) for r in range(R) if thr_req_present[t, r]} if thr_req_present[t].any() else None,
            )
            used = ResourceAmount.of(
                pod=int(used_cnt[t]) if used_cnt_present[t] else None,
                requests={names[r]: from_milli(int(used_req[t, r])) for r in range(R) if used_req_present[t, r]} if used_req_present[t].any() else None,
            )
            want = thr.is_throttled(used, True)
            assert bool(cnt_flag[t]) == want.resource_counts_pod
            for r in range(R):
                if flag_present[t, r]:
                    assert bool(req_flag[t, r]) == want.resource_requests[names[r]]
                else:
                    assert want.resource_requests is None or names[r] not in want.resource_requests


class TestCalculateThresholdsKernel:
    def test_matches_oracle_over_time(self):
        rng = random.Random(9)
        specs = []
        for i in range(25):
            overrides = []
            for k in range(rng.randrange(0, 4)):
                begin = NOW + timedelta(minutes=rng.randrange(-120, 120))
                end = begin + timedelta(minutes=rng.randrange(0, 120))
                threshold = ResourceAmount.of(
                    pod=rng.randrange(0, 5) if rng.random() < 0.6 else None,
                    requests={"cpu": f"{rng.randrange(1, 9)*100}m"} if rng.random() < 0.7 else None,
                )
                overrides.append(
                    TemporaryThresholdOverride(
                        begin=rfc(begin) if rng.random() < 0.8 else "",
                        end=rfc(end) if rng.random() < 0.8 else "",
                        threshold=threshold,
                    )
                )
            if rng.random() < 0.15 and overrides:
                overrides[0] = TemporaryThresholdOverride(begin="garbage", threshold=ResourceAmount.of(pod=1))
            specs.append(
                ThrottleSpecBase(
                    threshold=ResourceAmount.of(pod=3, requests={"cpu": "500m", "memory": "1Gi"}),
                    temporary_threshold_overrides=tuple(overrides),
                )
            )

        dims = DimRegistry()
        sched = encode_override_schedule(specs, dims)
        for probe in [NOW, NOW + timedelta(minutes=30), NOW + timedelta(hours=3)]:
            cnt, cnt_p, req, req_p = map(
                np.asarray, calculate_thresholds(sched, np.int64(ns(probe)))
            )
            for i, spec in enumerate(specs):
                want = spec.calculate_threshold(probe).threshold
                if want.resource_counts is None:
                    assert not cnt_p[i], f"throttle {i} at {probe}"
                else:
                    assert cnt_p[i] and cnt[i] == want.resource_counts, f"throttle {i} at {probe}"
                want_reqs = want.resource_requests or {}
                for r in range(len(dims)):
                    name = dims.names[r]
                    if name in want_reqs:
                        assert req_p[i, r], f"throttle {i} dim {name} at {probe}"
                        assert from_milli(int(req[i, r])) == want_reqs[name]
                    else:
                        assert not req_p[i, r], f"throttle {i} dim {name} at {probe}"


class TestOverrideEncodingRegressions:
    def test_far_future_end_clamps_not_crashes(self):
        spec = ThrottleSpecBase(
            temporary_threshold_overrides=(
                TemporaryThresholdOverride(
                    begin=rfc(NOW - timedelta(hours=1)),
                    end="9999-12-31T23:59:59Z",
                    threshold=ResourceAmount.of(pod=1),
                ),
            )
        )
        dims = DimRegistry()
        sched = encode_override_schedule([spec], dims)
        cnt, cnt_p, _, _ = map(np.asarray, calculate_thresholds(sched, np.int64(ns(NOW))))
        assert cnt_p[0] and cnt[0] == 1  # still active at NOW

    def test_fractional_second_boundary_exact(self):
        from kube_throttler_tpu.ops.overrides import _datetime_to_ns
        from kube_throttler_tpu.api.types import parse_rfc3339

        dt = parse_rfc3339("2024-01-15T12:00:00.000013Z")
        assert int(_datetime_to_ns(dt)) % 10**9 == 13_000

    def test_capacity_overflow_raises(self):
        import pytest

        spec = ThrottleSpecBase(
            temporary_threshold_overrides=tuple(
                TemporaryThresholdOverride(threshold=ResourceAmount.of(pod=i))
                for i in range(3)
            )
        )
        with pytest.raises(ValueError, match="override_capacity"):
            encode_override_schedule([spec], DimRegistry(), override_capacity=2)


class TestDimMismatchGuard:
    def test_actionable_error_on_registry_growth(self):
        import pytest
        from kube_throttler_tpu.api import Throttle, ThrottleSpec
        from kube_throttler_tpu.ops import check_pods, encode_throttle_state

        dims = DimRegistry(capacity=2)
        state = encode_throttle_state(
            [Throttle(name="t", spec=ThrottleSpec(threshold=ResourceAmount.of(requests={"a": "1", "b": "1"})))],
            dims,
        )
        # pod introduces a 3rd dim → capacity doubles → R mismatch
        batch = encode_pods([make_pod("p", requests={"a": "1", "b": "1", "c": "1"})], dims)
        with pytest.raises(ValueError, match="resource-dim mismatch"):
            check_pods(state, batch, np.ones((1, 1), dtype=bool))
