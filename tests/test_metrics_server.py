"""Metrics recorders (reference value semantics) + HTTP daemon surface."""

import json
import urllib.request

import pytest

from kube_throttler_tpu.api import (
    IsResourceAmountThrottled,
    LabelSelector,
    Namespace,
    ResourceAmount,
    Throttle,
    ThrottleSelector,
    ThrottleSelectorTerm,
    ThrottleSpec,
)
from kube_throttler_tpu.api.types import ThrottleStatus
from kube_throttler_tpu.engine.store import Store
from kube_throttler_tpu.metrics import Registry, ThrottleMetricsRecorder
from kube_throttler_tpu.plugin import KubeThrottler, RecordingEventRecorder, decode_plugin_args
from kube_throttler_tpu.server import ThrottlerHTTPServer


class TestMetrics:
    def test_reference_value_semantics(self):
        registry = Registry()
        recorder = ThrottleMetricsRecorder(registry)
        thr = Throttle(
            name="t1",
            namespace="ns1",
            uid="u1",
            spec=ThrottleSpec(
                threshold=ResourceAmount.of(pod=5, requests={"cpu": "1500m", "memory": "1Gi"})
            ),
            status=ThrottleStatus(
                used=ResourceAmount.of(pod=2, requests={"cpu": "300m", "memory": "512Mi"}),
                throttled=IsResourceAmountThrottled(False, {"cpu": True, "memory": False}),
            ),
        )
        recorder.record(thr)
        text = registry.exposition()
        labels = 'namespace="ns1",name="t1",uid="u1"'
        # CPU in milli (MilliValue), memory in whole bytes (Value)
        assert f'throttle_spec_threshold_resourceRequests{{{labels},resource="cpu"}} 1500' in text
        assert f'throttle_spec_threshold_resourceRequests{{{labels},resource="memory"}} {1024**3}' in text
        assert f'throttle_spec_threshold_resourceCounts{{{labels},resource="pod"}} 5' in text
        assert f'throttle_status_used_resourceRequests{{{labels},resource="cpu"}} 300' in text
        assert f'throttle_status_throttled_resourceRequests{{{labels},resource="cpu"}} 1' in text
        assert f'throttle_status_throttled_resourceRequests{{{labels},resource="memory"}} 0' in text

    def test_nil_counts_records_zero(self):
        registry = Registry()
        recorder = ThrottleMetricsRecorder(registry)
        recorder.record(Throttle(name="t2", namespace="ns1", uid="u2"))
        text = registry.exposition()
        assert 'throttle_spec_threshold_resourceCounts{namespace="ns1",name="t2",uid="u2",resource="pod"} 0' in text


@pytest.fixture
def server():
    store = Store()
    store.create_namespace(Namespace("default"))
    plugin = KubeThrottler(
        decode_plugin_args(
            {"name": "kube-throttler", "targetSchedulerName": "my-scheduler", "controllerThrediness": 2}
        ),
        store,
        event_recorder=RecordingEventRecorder(),
        start_workers=True,
    )
    srv = ThrottlerHTTPServer(plugin, port=0)  # ephemeral port
    srv.start()
    yield srv
    srv.stop()
    plugin.stop()


def _req(srv, method, path, body=None):
    url = f"http://127.0.0.1:{srv.port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    with urllib.request.urlopen(req, timeout=10) as resp:
        payload = resp.read().decode()
        try:
            return resp.status, json.loads(payload)
        except json.JSONDecodeError:
            return resp.status, payload


class TestHTTPServer:
    def test_end_to_end_over_http(self, server):
        import time

        code, _ = _req(server, "GET", "/healthz")
        assert code == 200
        code, ready = _req(server, "GET", "/readyz")
        assert code == 200 and ready["ok"]
        assert ready["device"]["enabled"] and ready["device"]["available"]
        assert set(ready["workqueues"]) == {"throttle", "clusterthrottle"}

        # apply a throttle and two pods via manifests
        code, out = _req(
            server,
            "POST",
            "/v1/objects",
            {
                "kind": "Throttle",
                "metadata": {"name": "t1", "namespace": "default"},
                "spec": {
                    "throttlerName": "kube-throttler",
                    "threshold": {"resourceRequests": {"cpu": "200m"}},
                    "selector": {"selectorTerms": [{"podSelector": {"matchLabels": {"throttle": "t1"}}}]},
                },
            },
        )
        assert code == 200

        pod1 = {
            "kind": "Pod",
            "metadata": {"name": "pod1", "namespace": "default", "labels": {"throttle": "t1"}},
            "spec": {
                "schedulerName": "my-scheduler",
                "containers": [{"name": "c", "resources": {"requests": {"cpu": "200m"}}}],
            },
        }
        code, _ = _req(server, "POST", "/v1/objects", pod1)
        assert code == 200

        code, out = _req(server, "POST", "/v1/prefilter", {"podKey": "default/pod1"})
        assert code == 200 and out["code"] == "Success"
        code, _ = _req(server, "POST", "/v1/reserve", {"podKey": "default/pod1"})
        assert code == 200
        code, _ = _req(server, "POST", "/v1/bind", {"podKey": "default/pod1", "nodeName": "n1"})
        assert code == 200

        # wait for the async reconcile to mark the throttle active
        deadline = time.time() + 10
        while time.time() < deadline:
            code, thrs = _req(server, "GET", "/v1/throttles")
            # .get chains: before the first reconcile lands (cold-JIT runs
            # take ~1s standalone) the stored status is the pre-reconcile
            # default, whose throttled map has no resourceRequests key
            if thrs and thrs[0]["status"]["throttled"].get("resourceRequests", {}).get("cpu"):
                break
            time.sleep(0.05)
        assert thrs[0]["status"]["used"]["resourceRequests"]["cpu"] == "200m"
        assert thrs[0]["status"]["throttled"]["resourceRequests"]["cpu"] is True

        # second pod is blocked with the reference reason string
        pod2 = dict(pod1, metadata={"name": "pod2", "namespace": "default", "labels": {"throttle": "t1"}})
        code, _ = _req(server, "POST", "/v1/objects", pod2)
        code, out = _req(server, "POST", "/v1/prefilter", {"podKey": "default/pod2"})
        assert out["code"] == "UnschedulableAndUnresolvable"
        assert out["reasons"] == ["throttle[active]=default/t1"]

        # metrics exposition includes the live gauge families
        code, text = _req(server, "GET", "/metrics")
        assert code == 200
        assert "throttle_status_used_resourceRequests" in text

        # spec edit via re-apply does not clobber status
        code, _ = _req(
            server,
            "POST",
            "/v1/objects",
            {
                "kind": "Throttle",
                "metadata": {"name": "t1", "namespace": "default"},
                "spec": {
                    "throttlerName": "kube-throttler",
                    "threshold": {"resourceRequests": {"cpu": "700m"}},
                    "selector": {"selectorTerms": [{"podSelector": {"matchLabels": {"throttle": "t1"}}}]},
                },
            },
        )
        deadline = time.time() + 10
        while time.time() < deadline:
            code, out = _req(server, "POST", "/v1/prefilter", {"podKey": "default/pod2"})
            if out["code"] == "Success":
                break
            time.sleep(0.05)
        assert out["code"] == "Success"

        # delete the pod; unreserve + reconcile clears usage
        code, _ = _req(server, "DELETE", "/v1/objects/pods/default/pod1")
        assert code == 200

    def test_prefilter_batch_agrees_with_per_pod(self, server):
        """/v1/prefilter-batch (one device pass over every stored pod) must
        agree with per-pod /v1/prefilter for each pod's schedulability."""
        import time

        _req(
            server,
            "POST",
            "/v1/objects",
            {
                "kind": "Throttle",
                "metadata": {"name": "tb", "namespace": "default"},
                "spec": {
                    "throttlerName": "kube-throttler",
                    "threshold": {"resourceRequests": {"cpu": "300m"}},
                    "selector": {"selectorTerms": [{"podSelector": {"matchLabels": {"grp": "b"}}}]},
                },
            },
        )
        for name, cpu, labeled in [
            ("bp1", "200m", True),   # fits
            ("bp2", "400m", True),   # alone exceeds threshold
            ("bp3", "200m", False),  # unmatched — always schedulable
        ]:
            _req(
                server,
                "POST",
                "/v1/objects",
                {
                    "kind": "Pod",
                    "metadata": {
                        "name": name,
                        "namespace": "default",
                        "labels": {"grp": "b"} if labeled else {},
                    },
                    "spec": {
                        "schedulerName": "my-scheduler",
                        "containers": [{"name": "c", "resources": {"requests": {"cpu": cpu}}}],
                    },
                },
            )
        # wait until the async reconcile has observed the objects
        deadline = time.time() + 10
        while time.time() < deadline:
            code, batch = _req(server, "POST", "/v1/prefilter-batch", {})
            if len(batch["schedulable"]) >= 3:
                break
            time.sleep(0.05)
        assert code == 200
        for key in ("default/bp1", "default/bp2", "default/bp3"):
            code, single = _req(server, "POST", "/v1/prefilter", {"podKey": key})
            assert batch["schedulable"][key] == (single["code"] == "Success"), key

    @pytest.mark.parametrize("use_device", [True, False])
    def test_prefilter_batch_modes_and_missing_namespace(self, use_device):
        """Device and host-oracle batch paths agree, and a pod whose
        Namespace object is missing lands in errors (the per-pod path
        returns ERROR for it — review finding)."""
        from kube_throttler_tpu.api import (
            LabelSelector,
            ResourceAmount,
            Throttle,
            ThrottleSelector,
            ThrottleSelectorTerm,
            ThrottleSpec,
        )
        from kube_throttler_tpu.api.pod import make_pod

        store = Store()
        store.create_namespace(Namespace("default"))
        plugin = KubeThrottler(
            decode_plugin_args({"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}),
            store,
            use_device=use_device,
        )
        store.create_throttle(
            Throttle(
                name="t",
                spec=ThrottleSpec(
                    throttler_name="kube-throttler",
                    threshold=ResourceAmount.of(requests={"cpu": "300m"}),
                    selector=ThrottleSelector(
                        selector_terms=(
                            ThrottleSelectorTerm(LabelSelector(match_labels={"g": "x"})),
                        )
                    ),
                ),
            )
        )
        store.create_pod(make_pod("ok", labels={"g": "x"}, requests={"cpu": "100m"}))
        store.create_pod(make_pod("big", labels={"g": "x"}, requests={"cpu": "400m"}))
        # namespace object "ghost" is never created
        store.create_pod(make_pod("orphan", namespace="ghost", requests={"cpu": "100m"}))
        plugin.run_pending_once()

        out = plugin.pre_filter_batch()
        assert out["schedulable"]["default/ok"] is True
        assert out["schedulable"]["default/big"] is False
        assert "ghost/orphan" in out["errors"]
        assert "ghost/orphan" not in out["schedulable"]

    def test_pod_reapply_preserves_bound_state(self, server):
        """Re-applying a pod manifest must not clobber nodeName/phase."""
        import time

        pod = {
            "kind": "Pod",
            "metadata": {"name": "podx", "namespace": "default", "labels": {"a": "1"}},
            "spec": {
                "schedulerName": "my-scheduler",
                "containers": [{"name": "c", "resources": {"requests": {"cpu": "100m"}}}],
            },
        }
        _req(server, "POST", "/v1/objects", pod)
        _req(server, "POST", "/v1/bind", {"podKey": "default/podx", "nodeName": "n7"})
        # re-apply with a label tweak, no nodeName/status in the manifest
        pod["metadata"]["labels"] = {"a": "2"}
        _req(server, "POST", "/v1/objects", pod)
        _, pods = _req(server, "GET", "/v1/pods")
        got = [p for p in pods if p["key"] == "default/podx"][0]
        assert got["nodeName"] == "n7"
        assert got["phase"] == "Running"
        assert got["labels"] == {"a": "2"}
