"""Pallas tiled check kernel (interpret mode on CPU) vs the direct kernel."""

import random

import numpy as np
import pytest

from kube_throttler_tpu.ops import DimRegistry, check_pods, encode_pods, encode_throttle_state
from kube_throttler_tpu.ops.fastcheck import precompute_check_state
from kube_throttler_tpu.ops.pallas_check import BP, BT, pallas_check_pods
from kube_throttler_tpu.ops.schema import PodBatch

from tests.test_check_kernel import _build_objects


@pytest.mark.parametrize("kind", ["throttle", "clusterthrottle"])
@pytest.mark.parametrize("on_equal", [False, True])
def test_pallas_matches_direct(kind, on_equal):
    rng = random.Random(5)
    throttles, reserved, pods = _build_objects(rng, n_throttles=60, n_pods=40, kind=kind)
    dims = DimRegistry()
    # pad capacities straight to one block
    state = encode_throttle_state(throttles, dims, reserved=reserved, capacity=BT)
    batch = encode_pods(pods, dims, capacity=BP)
    # randomize the FULL padded mask, including bits over invalid/padded pod
    # and throttle rows — the kernel must report those as NOT_AFFECTED
    # exactly like check_pods (round-1 review regression)
    mask = np.asarray(rng.choices([True, False], k=BP * BT)).reshape(BP, BT)
    step3 = True if kind == "throttle" else on_equal

    direct = np.asarray(check_pods(state, batch, mask, on_equal=on_equal, step3_on_equal=step3))
    pre = precompute_check_state(state)
    got = np.asarray(
        pallas_check_pods(
            pre, batch, mask, on_equal=on_equal, step3_on_equal=step3, interpret=True
        )
    )
    np.testing.assert_array_equal(got, direct)


def test_limb_compare_extremes():
    """Limb-split compares must hold at int64 extremes and negatives."""
    import jax.numpy as jnp

    from kube_throttler_tpu.ops.pallas_check import _limb_ge, _limb_gt, _split_limbs

    vals = np.array(
        [0, 1, -1, 2**31, -(2**31), 2**32, -(2**32), 2**62, -(2**62),
         2**63 - 1, -(2**63), 123456789012345, -987654321098765],
        dtype=np.int64,
    )
    a = jnp.asarray(vals)[:, None]
    b = jnp.asarray(vals)[None, :]
    a_hi, a_lo = _split_limbs(a)
    b_hi, b_lo = _split_limbs(b)
    np.testing.assert_array_equal(np.asarray(_limb_gt(a_hi, a_lo, b_hi, b_lo)), vals[:, None] > vals[None, :])
    np.testing.assert_array_equal(np.asarray(_limb_ge(a_hi, a_lo, b_hi, b_lo)), vals[:, None] >= vals[None, :])
