"""Gang & heterogeneity-aware admission (engine/gang.py, ops/gang_check.py,
scheduler gang cycles, workqueue ordered lane, snapshot/recovery wiring).

The hypothesis equivalence property (batched kernel ≡ sequential oracle)
lives in tests/test_gang_property.py; the SIGKILL crash matrix coverage in
tests/test_crash_recovery.py. This file is the deterministic tier.
"""

from __future__ import annotations

import json
from datetime import datetime, timedelta, timezone

import pytest

from kube_throttler_tpu.api.pod import (
    Namespace,
    accel_class_of,
    make_pod,
    pod_group_of,
    priority_of,
)
from kube_throttler_tpu.api.types import (
    AccelClassThreshold,
    LabelSelector,
    ResourceAmount,
    Throttle,
    ThrottleSelector,
    ThrottleSelectorTerm,
    ThrottleSpec,
)
from kube_throttler_tpu.engine.gang import GangLedger
from kube_throttler_tpu.engine.journal import attach
from kube_throttler_tpu.engine.recovery import RecoveryManager
from kube_throttler_tpu.engine.reservations import ReservedResourceAmounts
from kube_throttler_tpu.engine.snapshot import SnapshotManager
from kube_throttler_tpu.engine.store import Store
from kube_throttler_tpu.engine.workqueue import RateLimitingQueue
from kube_throttler_tpu.faults.plan import FaultPlan
from kube_throttler_tpu.plugin import KubeThrottler, decode_plugin_args
from kube_throttler_tpu.plugin.framework import RecordingEventRecorder
from kube_throttler_tpu.scheduler import Node, Scheduler
from kube_throttler_tpu.utils.clock import FakeClock


def _throttle(name, pod=None, cpu=None, accel=(), labels=None):
    requests = {"cpu": cpu} if cpu else None
    return Throttle(
        name=name,
        spec=ThrottleSpec(
            throttler_name="kube-throttler",
            threshold=ResourceAmount.of(pod=pod, requests=requests),
            accel_class_thresholds=tuple(accel),
            selector=ThrottleSelector(
                selector_terms=(
                    ThrottleSelectorTerm(
                        LabelSelector(match_labels=labels or {"throttle": name})
                    ),
                )
            ),
        ),
    )


def _setup(nodes=None, use_device=True):
    store = Store()
    store.create_namespace(Namespace("default"))
    recorder = RecordingEventRecorder()
    plugin = KubeThrottler(
        decode_plugin_args(
            {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
        ),
        store,
        event_recorder=recorder,
        use_device=use_device,
    )
    sched = Scheduler(plugin, store, nodes=nodes)
    return store, plugin, sched, recorder


def _member(name, group, size, cpu="100m", labels=None, **kw):
    return make_pod(
        name,
        labels=labels or {"throttle": "t1"},
        requests={"cpu": cpu},
        group=group,
        group_size=size,
        **kw,
    )


# ---------------------------------------------------------------- contract


class TestPodGroupContract:
    def test_group_parse(self):
        p = make_pod("a", namespace="ns1", group="job", group_size=4)
        g = pod_group_of(p)
        assert g.key == "ns1/job" and g.name == "job" and g.size == 4

    def test_no_annotations_is_per_pod(self):
        assert pod_group_of(make_pod("a")) is None

    @pytest.mark.parametrize("size", ["", "zero", "0", "-3"])
    def test_malformed_size_degrades_to_per_pod(self, size):
        p = make_pod("a", group="job")
        p.annotations["kube-throttler.github.io/pod-group-size"] = size
        assert pod_group_of(p) is None

    def test_accel_class_and_priority(self):
        p = make_pod("a", accel_class="tpu-v5e", priority=9)
        assert accel_class_of(p) == "tpu-v5e"
        assert priority_of(p) == 9
        q = make_pod("b")
        q.annotations["kube-throttler.github.io/priority"] = "not-a-number"
        assert priority_of(q) == 0

    def test_annotations_roundtrip_serialization(self):
        from kube_throttler_tpu.api.serialization import pod_from_dict, pod_to_dict

        p = make_pod("a", group="job", group_size=2, accel_class="v5p", priority=3)
        back = pod_from_dict(pod_to_dict(p))
        assert pod_group_of(back) == pod_group_of(p)
        assert accel_class_of(back) == "v5p" and priority_of(back) == 3

    def test_accel_thresholds_roundtrip_serialization(self):
        from kube_throttler_tpu.api.serialization import (
            throttle_from_dict,
            throttle_to_dict,
        )

        thr = _throttle(
            "t1", pod=10, accel=[AccelClassThreshold("v5e", ResourceAmount.of(pod=2))]
        )
        back = throttle_from_dict(throttle_to_dict(thr))
        assert back.spec.accel_class_thresholds == thr.spec.accel_class_thresholds
        assert back.spec.accel_threshold_for("v5e") == ResourceAmount.of(pod=2)
        assert back.spec.accel_threshold_for("v5p") is None


# ---------------------------------------------------------- ordered lane


class TestOrderedPriorityLane:
    def test_priority_then_age_order(self):
        q = RateLimitingQueue("test")
        q.add_all_priority(["low-old"], priorities={"low-old": 1})
        q.add_all_priority(["hi"], priorities={"hi": 5})
        q.add_all_priority(["low-new"], priorities={"low-new": 1})
        assert [q.get(timeout=1) for _ in range(3)] == ["hi", "low-old", "low-new"]
        q.shut_down()

    def test_default_stays_fifo(self):
        q = RateLimitingQueue("test")
        q.add_all_priority(["a", "b", "c"])
        assert [q.get(timeout=1) for _ in range(3)] == ["a", "b", "c"]
        q.shut_down()

    def test_promote_from_normal_lane_keeps_single_queueing(self):
        q = RateLimitingQueue("test")
        q.add("x")
        q.add("y")
        q.add_all_priority(["y"], priorities={"y": 2})
        got = [q.get(timeout=1), q.get(timeout=1)]
        assert got == ["y", "x"]
        assert len(q) == 0
        q.shut_down()

    def test_processing_requeues_with_priority_at_done(self):
        q = RateLimitingQueue("test")
        q.add("a")
        assert q.get(timeout=1) == "a"
        q.add_all_priority(["a"], priorities={"a": 3})  # while processing
        q.add_all_priority(["b"], priorities={"b": 1})
        q.done("a")
        # a re-enters the hi lane at priority 3, beating b's 1
        assert q.get(timeout=1) == "a"
        assert q.get(timeout=1) == "b"
        q.shut_down()

    def test_hi_lane_drains_before_normal(self):
        q = RateLimitingQueue("test")
        q.add("norm")
        q.add_priority("flip")
        assert q.get(timeout=1) == "flip"
        q.shut_down()


# ---------------------------------------------------------------- ledger


def _caches(clock=None):
    return {
        "throttle": ReservedResourceAmounts(8, clock=clock),
        "clusterthrottle": ReservedResourceAmounts(8, clock=clock),
    }


def _mk_members(n, prefix="m"):
    pods = [_member(f"{prefix}{i}", "job", n) for i in range(n)]
    member_keys = {p.key: {"throttle": ["default/t1"]} for p in pods}
    return pods, member_keys


class TestGangLedger:
    def test_reserve_then_rollback_releases_everything(self):
        caches = _caches()
        ledger = GangLedger(caches)
        pods, keys = _mk_members(3)
        assert ledger.reserve_group("default/job", pods, keys) is True
        assert caches["throttle"].reserved_pod_keys("default/t1") == {
            p.key for p in pods
        }
        assert ledger.pending_groups() == 1
        assert ledger.rollback_group("default/job") is True
        assert caches["throttle"].reserved_pod_keys("default/t1") == set()
        assert ledger.groups_rolled_back_total == 1

    def test_reserve_is_idempotent_for_pending_group(self):
        caches = _caches()
        ledger = GangLedger(caches)
        pods, keys = _mk_members(2)
        assert ledger.reserve_group("default/job", pods, keys)
        assert ledger.reserve_group("default/job", pods, keys)
        assert ledger.groups_reserved_total == 1

    def test_member_failure_rolls_back_already_added(self):
        """Fault site gang.reserve.partial: the 3rd member-key add raises —
        the first two members' reservations must be gone afterwards."""
        caches = _caches()
        plan = FaultPlan(seed=1).rule("gang.reserve.partial", schedule=[3])
        ledger = GangLedger(caches, faults=plan)
        pods, keys = _mk_members(4)
        assert ledger.reserve_group("default/job", pods, keys) is False
        assert caches["throttle"].reserved_pod_keys("default/t1") == set()
        assert ledger.pending_groups() == 0
        assert ledger.groups_rolled_back_total == 1

    def test_group_ttl_expiry_frees_all_members(self):
        clock = FakeClock(datetime(2026, 1, 1, tzinfo=timezone.utc))
        caches = _caches(clock)
        ledger = GangLedger(caches, clock=clock, default_ttl=30.0)
        pods, keys = _mk_members(3)
        assert ledger.reserve_group("default/job", pods, keys)
        clock.advance(timedelta(seconds=31))
        assert ledger.pending_groups() == 0
        assert ledger.groups_expired_total == 1
        # member reservations carried the same TTL — expired with the group
        assert caches["throttle"].reserved_pod_keys("default/t1") == set()

    def test_bound_members_admit_and_group_retires(self):
        from kube_throttler_tpu.engine.store import Event, EventType

        caches = _caches()
        ledger = GangLedger(caches)
        pods, keys = _mk_members(2)
        ledger.reserve_group("default/job", pods, keys)
        for p in pods:
            bound = make_pod(p.name, node_name="node-1")
            ledger.on_pod_event(Event(EventType.MODIFIED, "Pod", bound, old_obj=p))
        assert ledger.pending_groups() == 0
        assert ledger.groups_admitted_total == 1

    def test_member_deleted_preadmission_rolls_whole_group_back(self):
        from kube_throttler_tpu.engine.store import Event, EventType

        caches = _caches()
        ledger = GangLedger(caches)
        pods, keys = _mk_members(3)
        ledger.reserve_group("default/job", pods, keys)
        ledger.on_pod_event(Event(EventType.DELETED, "Pod", pods[1]))
        assert ledger.pending_groups() == 0
        assert ledger.groups_rolled_back_total == 1
        assert caches["throttle"].reserved_pod_keys("default/t1") == set()

    def test_note_unreserved_counts_member_admitted(self):
        caches = _caches()
        ledger = GangLedger(caches)
        pods, keys = _mk_members(2)
        ledger.reserve_group("default/job", pods, keys)
        ledger.note_unreserved("throttle", "default/t1", pods[0].key)
        ledger.note_unreserved("throttle", "default/t1", pods[1].key)
        assert ledger.groups_admitted_total == 1
        assert ledger.pending_groups() == 0

    def test_snapshot_restore_roundtrip_rebases_ttl(self):
        clock = FakeClock(datetime(2026, 1, 1, tzinfo=timezone.utc))
        caches = _caches(clock)
        ledger = GangLedger(caches, clock=clock, default_ttl=60.0)
        pods, keys = _mk_members(2)
        ledger.reserve_group("default/job", pods, keys)
        state = ledger.snapshot_state()
        assert state["default/job"]["ttlRemainingSeconds"] == pytest.approx(60.0)

        clock2 = FakeClock(datetime(2026, 6, 1, tzinfo=timezone.utc))
        caches2 = _caches(clock2)
        for p in pods:
            caches2["throttle"].add_pod("default/t1", p, ttl=60.0)
        ledger2 = GangLedger(caches2, clock=clock2)
        restored, dropped = ledger2.restore_state(state, elapsed_s=20.0)
        assert (restored, dropped) == (1, 0)
        rec = ledger2.group_record("default/job")
        remaining = (rec.deadline - clock2.now()).total_seconds()
        assert remaining == pytest.approx(40.0)

    def test_restore_drops_expired_group_and_its_members(self):
        clock = FakeClock(datetime(2026, 1, 1, tzinfo=timezone.utc))
        caches = _caches(clock)
        ledger = GangLedger(caches, clock=clock, default_ttl=10.0)
        pods, keys = _mk_members(2)
        ledger.reserve_group("default/job", pods, keys)
        state = ledger.snapshot_state()

        clock2 = FakeClock(datetime(2026, 6, 1, tzinfo=timezone.utc))
        caches2 = _caches(clock2)
        for p in pods:
            caches2["throttle"].add_pod("default/t1", p)  # no TTL: survived restore
        ledger2 = GangLedger(caches2, clock=clock2)
        restored, dropped = ledger2.restore_state(state, elapsed_s=99.0)
        assert (restored, dropped) == (0, 1)
        # the dead gang's members were pruned back out of the caches
        assert caches2["throttle"].reserved_pod_keys("default/t1") == set()


# ------------------------------------------------------- journal stamping


class TestGangJournal:
    def test_stamps_replay_into_gang_ops(self, tmp_path):
        store = Store()
        journal = attach(store, str(tmp_path / "store.journal"))
        ledger = GangLedger(_caches(), journal=journal)
        pods, keys = _mk_members(2)
        ledger.reserve_group("default/job", pods, keys)
        ledger.rollback_group("default/job")
        journal.close()

        store2 = Store()
        journal2 = attach(store2, str(tmp_path / "store.journal"))
        entry = journal2.gang_ops["default/job"]
        assert entry["op"] == "rollback"
        # members inherited from the begin line through commit+rollback
        assert sorted(entry["members"]) == sorted(p.key for p in pods)
        journal2.close()

    def test_gang_lines_have_no_store_effect(self, tmp_path):
        store = Store()
        journal = attach(store, str(tmp_path / "store.journal"))
        store.create_namespace(Namespace("default"))
        journal.append_gang("begin", "default/job", members=["default/m0"])
        journal.close()
        with open(tmp_path / "store.journal") as f:
            lines = [json.loads(line) for line in f]
        assert lines[-1]["type"] == "GANG"
        replayed = Store()
        attach(replayed, str(tmp_path / "store.journal")).close()
        assert [n.name for n in replayed.list_namespaces()] == ["default"]

    def test_recovery_rolls_back_begin_without_commit(self, tmp_path):
        """Mid-reserve crash shape, driven without SIGKILL: journal says
        begin (no commit) while the caches still carry a member — recovery
        must remove it."""
        store = Store()
        journal = attach(store, str(tmp_path / "store.journal"))
        journal.append_gang("begin", "default/job", members=["default/m0", "default/m1"])
        journal.close()

        recovered = Store()
        rec = RecoveryManager(str(tmp_path))
        journal2 = rec.recover_store(recovered)
        caches = _caches()
        caches["throttle"].add_pod("default/t1", make_pod("m0"))
        ledger = GangLedger(caches)
        rec.restore_gangs(ledger, journal2)
        journal2.close()
        assert rec.report.gangs_rolled_back == 1
        assert caches["throttle"].reserved_pod_keys("default/t1") == set()


# ----------------------------------------------------- snapshot atomicity


class TestGangSnapshot:
    def test_snapshot_carries_gangs_and_restore_rebuilds(self, tmp_path):
        store = Store()
        store.create_namespace(Namespace("default"))
        journal = attach(store, str(tmp_path / "store.journal"))
        caches = _caches()
        ledger = GangLedger(caches, journal=journal)
        pods, keys = _mk_members(3)
        ledger.reserve_group("default/job", pods, keys)
        mgr = SnapshotManager(
            str(tmp_path), store, reservations=caches, gang_ledger=ledger
        )
        mgr.journal = journal
        assert mgr.write() is not None
        journal.close()

        recovered = Store()
        rec = RecoveryManager(str(tmp_path))
        journal2 = rec.recover_store(recovered)
        caches2 = _caches()
        rec.restore_reservations(caches2)
        ledger2 = GangLedger(caches2)
        rec.restore_gangs(ledger2, journal2)
        journal2.close()
        assert rec.report.gangs_restored == 1
        rec2 = ledger2.group_record("default/job")
        assert set(rec2.members) == {p.key for p in pods}
        # members' reservations restored alongside — fully reserved
        assert caches2["throttle"].reserved_pod_keys("default/t1") == {
            p.key for p in pods
        }


# ----------------------------------------------------- admission surfaces


class TestGangAdmission:
    def test_device_and_host_verdicts_agree(self):
        """pre_filter_gang through the batched kernel (device plugin) and
        through the sequential host oracle (use_device=False) must agree
        on feasible and infeasible groups alike."""
        scenarios = [
            (3, 4, True),  # 3 ranks under pod=4 → fits
            (5, 4, False),  # 5 ranks under pod=4 → all-or-nothing reject
            (4, 4, True),  # exact fit (onEqual=False admission)
        ]
        for n, cap, want in scenarios:
            for use_device in (True, False):
                store = Store()
                store.create_namespace(Namespace("default"))
                plugin = KubeThrottler(
                    decode_plugin_args(
                        {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
                    ),
                    store,
                    use_device=use_device,
                )
                store.create_throttle(_throttle("t1", pod=cap))
                pods = [_member(f"m{i}", "job", n) for i in range(n)]
                st = plugin.pre_filter_gang("default/job", pods)
                assert st.is_success() is want, (
                    f"n={n} cap={cap} device={use_device}: {st.reasons}"
                )
                plugin.stop()

    def test_partial_fit_rejects_whole_group(self):
        """Per-pod admission would admit 2 of 5 — gang admission admits 0."""
        store, plugin, _sched, _ = _setup()
        store.create_throttle(_throttle("t1", pod=2))
        pods = [_member(f"m{i}", "job", 5) for i in range(5)]
        st = plugin.pre_filter_gang("default/job", pods)
        assert not st.is_success()
        # the members would pass per-pod pre_filter individually
        assert plugin.pre_filter(pods[0]).is_success()
        plugin.stop()

    def test_accel_class_threshold_resolves_per_pod_check(self):
        store, plugin, _sched, _ = _setup()
        store.create_throttle(
            _throttle(
                "t1",
                pod=10,
                accel=[AccelClassThreshold("v5e", ResourceAmount.of(pod=0))],
            )
        )
        base_pod = make_pod("p", labels={"throttle": "t1"})
        accel_pod = make_pod("q", labels={"throttle": "t1"}, accel_class="v5e")
        assert plugin.pre_filter(base_pod).is_success()
        st = plugin.pre_filter(accel_pod)
        assert not st.is_success()
        assert "pod-requests-exceeds-threshold" in ";".join(st.reasons)
        plugin.stop()

    def test_gang_accel_class_uses_class_threshold(self):
        store, plugin, _sched, _ = _setup()
        store.create_throttle(
            _throttle(
                "t1",
                pod=8,
                accel=[AccelClassThreshold("v5p", ResourceAmount.of(pod=2))],
            )
        )
        pods = [
            _member(f"m{i}", "job", 3, accel_class="v5p") for i in range(3)
        ]
        st = plugin.pre_filter_gang("default/job", pods)
        assert not st.is_success()
        # same group without the class rides the base pod=8 threshold
        plain = [_member(f"n{i}", "job2", 3) for i in range(3)]
        assert plugin.pre_filter_gang("default/job2", plain).is_success()
        plugin.stop()


# ---------------------------------------------------------- scheduler e2e


class TestGangScheduling:
    def test_gang_waits_for_members_then_binds_all(self):
        store, plugin, sched, recorder = _setup()
        store.create_throttle(_throttle("t1", pod=10))
        store.create_pod(_member("r0", "job", 3))
        store.create_pod(_member("r1", "job", 3))
        assert sched.run_until_idle() == 0
        assert any(
            e.reason == "FailedScheduling" and "waiting for members" in e.note
            for e in recorder.events
        )
        # third rank arrives → the whole gang binds in one cycle
        store.create_pod(_member("r2", "job", 3))
        bound = sched.run_until_idle()
        assert bound >= 1
        for name in ("r0", "r1", "r2"):
            assert store.get_pod("default", name).spec.node_name != ""
        # ledger retired the group once every rank was observed bound
        assert plugin.gang.pending_groups() == 0
        assert plugin.gang.groups_admitted_total == 1
        plugin.stop()

    def test_gang_all_or_nothing_under_throttle(self):
        store, plugin, sched, recorder = _setup()
        store.create_throttle(_throttle("t1", pod=2))
        for i in range(3):
            store.create_pod(_member(f"r{i}", "job", 3))
        assert sched.run_until_idle(max_cycles=50) == 0
        for i in range(3):
            assert store.get_pod("default", f"r{i}").spec.node_name == ""
        assert plugin.gang.pending_groups() == 0
        assert any(
            e.reason == "FailedScheduling" and "gang" in e.note for e in recorder.events
        )
        plugin.stop()

    def test_gang_all_or_nothing_under_node_capacity(self):
        store, plugin, sched, _ = _setup(nodes=[Node("tiny", max_pods=2)])
        store.create_throttle(_throttle("t1", pod=10))
        for i in range(3):
            store.create_pod(_member(f"r{i}", "job", 3))
        assert sched.run_until_idle(max_cycles=50) == 0
        for i in range(3):
            assert store.get_pod("default", f"r{i}").spec.node_name == ""
        plugin.stop()

    def test_gang_admits_when_capacity_opens(self):
        store, plugin, sched, _ = _setup()
        store.create_throttle(_throttle("t1", pod=2))
        for i in range(3):
            store.create_pod(_member(f"r{i}", "job", 3))
        assert sched.run_until_idle(max_cycles=50) == 0
        # capacity opens: threshold raised → event-driven requeue fires
        from dataclasses import replace

        thr = store.get_throttle("default", "t1")
        store.update_throttle_spec(
            replace(
                thr,
                spec=replace(thr.spec, threshold=ResourceAmount.of(pod=5)),
            )
        )
        assert sched.run_until_idle() >= 1
        for i in range(3):
            assert store.get_pod("default", f"r{i}").spec.node_name != ""
        plugin.stop()

    def test_priority_order_when_capacity_opens(self):
        """Preemption-ordered admission: two parked pods, the YOUNGER one
        carrying higher priority — when the throttle opens one slot, the
        high-priority pod takes it."""
        store, plugin, sched, _ = _setup()
        store.create_throttle(_throttle("t1", pod=0))
        store.create_pod(
            make_pod("old-low", labels={"throttle": "t1"}, priority=0)
        )
        store.create_pod(
            make_pod("young-high", labels={"throttle": "t1"}, priority=5)
        )
        assert sched.run_until_idle(max_cycles=50) == 0
        from dataclasses import replace

        thr = store.get_throttle("default", "t1")
        store.update_throttle_spec(
            replace(thr, spec=replace(thr.spec, threshold=ResourceAmount.of(pod=1)))
        )
        assert sched.run_until_idle() == 1
        assert store.get_pod("default", "young-high").spec.node_name != ""
        assert store.get_pod("default", "old-low").spec.node_name == ""
        plugin.stop()

    def test_gang_members_share_age_order_with_equal_priority(self):
        """Two plain pods, equal priority: the older binds first when one
        slot opens (the age tiebreak)."""
        store, plugin, sched, _ = _setup()
        store.create_throttle(_throttle("t1", pod=0))
        store.create_pod(make_pod("first", labels={"throttle": "t1"}))
        store.create_pod(make_pod("second", labels={"throttle": "t1"}))
        assert sched.run_until_idle(max_cycles=50) == 0
        from dataclasses import replace

        thr = store.get_throttle("default", "t1")
        store.update_throttle_spec(
            replace(thr, spec=replace(thr.spec, threshold=ResourceAmount.of(pod=1)))
        )
        assert sched.run_until_idle() == 1
        assert store.get_pod("default", "first").spec.node_name != ""
        assert store.get_pod("default", "second").spec.node_name == ""
        plugin.stop()


# ----------------------------------------- seeded kernel ↔ oracle sweep


class TestKernelOracleSeeded:
    """Deterministic mini-twin of tests/test_gang_property.py (which needs
    hypothesis): 40 seeded random scenarios, batched kernel verdict ==
    sequential per-pod oracle. Runs in tier-1 on environments without
    hypothesis so the equivalence never goes untested."""

    def test_randomized_scenarios(self):
        import random

        from kube_throttler_tpu.engine.gang import sequential_gang_check

        rng = random.Random(20260804)

        def amount():
            cnt = rng.choice([None, 0, 1, 2, 3, 5])
            cpu = rng.choice([None, 0, 500, 1000, 2500])
            return ResourceAmount.of(
                pod=cnt, requests={"cpu": f"{cpu}m"} if cpu is not None else None
            )

        for case in range(40):
            store = Store()
            store.create_namespace(Namespace("default"))
            plugin = KubeThrottler(
                decode_plugin_args(
                    {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
                ),
                store,
                use_device=True,
            )
            throttles = []
            for j in range(rng.randint(1, 3)):
                threshold = amount()
                used = amount()
                accel = tuple(
                    AccelClassThreshold(cls, amount())
                    for cls in ("v5e",)
                    if rng.random() < 0.4
                )
                grp = rng.choice(["g0", "g1", "*"])
                from kube_throttler_tpu.api.types import ThrottleStatus

                thr = Throttle(
                    name=f"t{j}",
                    spec=ThrottleSpec(
                        throttler_name="kube-throttler",
                        threshold=threshold,
                        accel_class_thresholds=accel,
                        selector=ThrottleSelector(
                            selector_terms=(
                                ThrottleSelectorTerm(
                                    LabelSelector(
                                        match_labels=(
                                            {} if grp == "*" else {"grp": grp}
                                        )
                                    )
                                ),
                            )
                        ),
                    ),
                    status=ThrottleStatus(
                        used=used, throttled=threshold.is_throttled(used, True)
                    ),
                )
                store.create_throttle(thr)
                throttles.append(thr)
            if rng.random() < 0.5:
                plugin.reserve(
                    make_pod(
                        "filler",
                        labels={"grp": rng.choice(["g0", "g1"])},
                        requests={"cpu": f"{rng.randint(0, 1500)}m"},
                    )
                )
            accel_cls = rng.choice([None, "v5e"])
            members = [
                make_pod(
                    f"m{i}",
                    labels={"grp": rng.choice(["g0", "g1"])},
                    requests={"cpu": f"{rng.choice([0, 250, 800, 1500])}m"},
                    group="job",
                    group_size=4,
                    accel_class=accel_cls,
                )
                for i in range(rng.randint(1, 5))
            ]
            kernel = plugin.device_manager.gang_check_groups(
                [("default/job", members, accel_cls)]
            )["default/job"]
            oracle_ok, blocked = sequential_gang_check(
                members,
                (
                    ("throttle", plugin.throttle_ctr, False),
                    ("clusterthrottle", plugin.cluster_throttle_ctr, False),
                ),
            )
            assert kernel["ok"] == oracle_ok, (
                f"case {case}: kernel={kernel} oracle={oracle_ok} "
                f"blocked={blocked} accel={accel_cls} members="
                f"{[(m.name, m.labels, m.spec.containers[0].requests) for m in members]} "
                f"throttles={[(t.key, t.spec.threshold, t.status.used, t.spec.accel_class_thresholds) for t in throttles]}"
            )
            plugin.stop()


# ------------------------------------------------------------- metrics


class TestGangMetrics:
    def test_families_export(self):
        store, plugin, _sched, _ = _setup()
        store.create_throttle(_throttle("t1", pod=10))
        pods = [_member(f"m{i}", "job", 2) for i in range(2)]
        assert plugin.pre_filter_gang("default/job", pods).is_success()
        assert plugin.reserve_gang("default/job", pods).is_success()
        text = plugin.metrics_registry.exposition()
        assert "kube_throttler_gang_groups_pending 1" in text
        assert "kube_throttler_gang_check_duration_seconds_count 1" in text
        plugin.unreserve_gang("default/job")
        text = plugin.metrics_registry.exposition()
        assert "kube_throttler_gang_groups_pending 0" in text
        assert "kube_throttler_gang_groups_rolled_back_total 1" in text
        plugin.stop()
