"""Device circuit breaker: a failed device dispatch (tunnel drop, backend
death) must degrade LATENCY, never availability — decisions and reconciles
fall back to the host-oracle paths, the breaker skips the device for a
cooldown, and service resumes on the device after it.
"""

import pytest

from kube_throttler_tpu.api.pod import Namespace, make_pod
from kube_throttler_tpu.api.types import (
    LabelSelector,
    ResourceAmount,
    Throttle,
    ThrottleSelector,
    ThrottleSelectorTerm,
    ThrottleSpec,
)
from kube_throttler_tpu.engine.store import Store
from kube_throttler_tpu.plugin import KubeThrottler, decode_plugin_args
from kube_throttler_tpu.plugin.framework import StatusCode


def _throttle(name="t1", cpu="200m"):
    return Throttle(
        name=name,
        spec=ThrottleSpec(
            throttler_name="kube-throttler",
            threshold=ResourceAmount.of(requests={"cpu": cpu}),
            selector=ThrottleSelector(
                selector_terms=(
                    ThrottleSelectorTerm(
                        pod_selector=LabelSelector(match_labels={"grp": "a"})
                    ),
                )
            ),
        ),
    )


@pytest.fixture()
def stack():
    store = Store()
    plugin = KubeThrottler(
        decode_plugin_args(
            {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
        ),
        store,
        use_device=True,
        start_workers=False,
    )
    store.create_namespace(Namespace("default"))
    store.create_throttle(_throttle())
    store.create_pod(
        make_pod(
            "running",
            labels={"grp": "a"},
            requests={"cpu": "150m"},
            node_name="n1",
            phase="Running",
        )
    )
    plugin.run_pending_once()
    return store, plugin


class _Boom(RuntimeError):
    pass


def _break_device(dm, method):
    calls = []

    def boom(*a, **k):
        calls.append(1)
        raise _Boom("tunnel died")

    setattr(dm, method, boom)
    return calls


class TestCheckFallback:
    def test_prefilter_survives_device_failure(self, stack):
        store, plugin = stack
        dm = plugin.device_manager
        pending = make_pod("pending", labels={"grp": "a"}, requests={"cpu": "100m"})

        # healthy: device path serves, and the verdict is 'insufficient'
        # (150m used of 200m, +100m would exceed)
        st = plugin.pre_filter(pending)
        assert st.code == StatusCode.UNSCHEDULABLE_AND_UNRESOLVABLE
        assert st.reasons == ("throttle[insufficient]=default/t1",)

        calls = _break_device(dm, "check_pod")
        st = plugin.pre_filter(pending)
        assert st.code == StatusCode.UNSCHEDULABLE_AND_UNRESOLVABLE
        assert st.reasons == ("throttle[insufficient]=default/t1",)
        assert calls == [1], "first failing dispatch opens the breaker"

        # breaker open: the device is not touched again within the cooldown
        st = plugin.pre_filter(pending)
        assert st.code == StatusCode.UNSCHEDULABLE_AND_UNRESOLVABLE
        assert calls == [1]

        # fallback counted per surface
        counter = plugin.metrics_registry.counter_vec(
            "kube_throttler_device_fallback_total", "", ["surface"]
        )
        assert counter.collect()[("check",)] == 1.0

        # a schedulable pod stays schedulable host-side
        small = make_pod("small", labels={"grp": "a"}, requests={"cpu": "10m"})
        assert plugin.pre_filter(small).code == StatusCode.SUCCESS

    def test_breaker_reopens_after_cooldown(self, stack):
        _, plugin = stack
        dm = plugin.device_manager
        now = [100.0]
        dm._monotonic = lambda: now[0]

        calls = _break_device(dm, "check_pod")
        pending = make_pod("pending", labels={"grp": "a"}, requests={"cpu": "100m"})
        plugin.pre_filter(pending)
        assert calls == [1] and not dm.device_available()

        now[0] += dm.device_retry_cooldown + 1
        assert dm.device_available()
        plugin.pre_filter(pending)  # device retried (and fails again)
        assert calls == [1, 1]


class TestBatchFallback:
    def test_prefilter_batch_survives_device_failure(self, stack):
        store, plugin = stack
        dm = plugin.device_manager
        healthy = plugin.pre_filter_batch()
        # the running pod classifies against state already containing it
        # (used 150m + own 150m > 200m → insufficient): not schedulable
        assert healthy["schedulable"] == {"default/running": False}

        calls = _break_device(dm, "check_batch_all")
        out = plugin.pre_filter_batch()
        assert out["schedulable"] == healthy["schedulable"]
        assert calls == [1]
        out = plugin.pre_filter_batch()  # breaker open: device untouched
        assert out["schedulable"] == healthy["schedulable"]
        assert calls == [1]


class TestReconcileFallback:
    def test_status_converges_host_side(self, stack):
        store, plugin = stack
        dm = plugin.device_manager
        _break_device(dm, "aggregate_used_for")

        store.create_pod(
            make_pod(
                "running2",
                labels={"grp": "a"},
                requests={"cpu": "40m"},
                node_name="n1",
                phase="Running",
            )
        )
        plugin.run_pending_once()
        thr = store.get_throttle("default", "t1")
        # host-walk reconcile landed the fresh aggregate: 150m + 40m
        assert thr.status.used.resource_counts == 2
        assert str(thr.status.used.resource_requests["cpu"]) == "19/100"
        counter = plugin.metrics_registry.counter_vec(
            "kube_throttler_device_fallback_total", "", ["surface"]
        )
        assert counter.collect()[("reconcile",)] >= 1.0
