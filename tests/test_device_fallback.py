"""Device circuit breaker: a failed device dispatch (tunnel drop, backend
death) must degrade LATENCY, never availability — decisions and reconciles
fall back to the host-oracle paths, the breaker skips the device for a
cooldown, and service resumes on the device after it.
"""

import pytest

from kube_throttler_tpu.api.pod import Namespace, make_pod
from kube_throttler_tpu.api.types import (
    LabelSelector,
    ResourceAmount,
    Throttle,
    ThrottleSelector,
    ThrottleSelectorTerm,
    ThrottleSpec,
)
from kube_throttler_tpu.engine.store import Store
from kube_throttler_tpu.plugin import KubeThrottler, decode_plugin_args
from kube_throttler_tpu.plugin.framework import StatusCode


def _throttle(name="t1", cpu="200m"):
    return Throttle(
        name=name,
        spec=ThrottleSpec(
            throttler_name="kube-throttler",
            threshold=ResourceAmount.of(requests={"cpu": cpu}),
            selector=ThrottleSelector(
                selector_terms=(
                    ThrottleSelectorTerm(
                        pod_selector=LabelSelector(match_labels={"grp": "a"})
                    ),
                )
            ),
        ),
    )


@pytest.fixture()
def stack():
    store = Store()
    plugin = KubeThrottler(
        decode_plugin_args(
            {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
        ),
        store,
        use_device=True,
        start_workers=False,
    )
    store.create_namespace(Namespace("default"))
    store.create_throttle(_throttle())
    store.create_pod(
        make_pod(
            "running",
            labels={"grp": "a"},
            requests={"cpu": "150m"},
            node_name="n1",
            phase="Running",
        )
    )
    plugin.run_pending_once()
    # these tests count device dispatches to drive the circuit breaker —
    # the interned-verdict cache would (correctly) serve repeats without
    # dispatching at all, so it must sit out
    plugin.verdict_cache = None
    return store, plugin


class _Boom(RuntimeError):
    pass


def _break_device(dm, method):
    calls = []

    def boom(*a, **k):
        calls.append(1)
        raise _Boom("tunnel died")

    setattr(dm, method, boom)
    return calls


class TestCheckFallback:
    def test_prefilter_survives_device_failure(self, stack):
        store, plugin = stack
        dm = plugin.device_manager
        pending = make_pod("pending", labels={"grp": "a"}, requests={"cpu": "100m"})

        # healthy: device path serves, and the verdict is 'insufficient'
        # (150m used of 200m, +100m would exceed)
        st = plugin.pre_filter(pending)
        assert st.code == StatusCode.UNSCHEDULABLE_AND_UNRESOLVABLE
        assert st.reasons == ("throttle[insufficient]=default/t1",)

        calls = _break_device(dm, "check_pod")
        st = plugin.pre_filter(pending)
        assert st.code == StatusCode.UNSCHEDULABLE_AND_UNRESOLVABLE
        assert st.reasons == ("throttle[insufficient]=default/t1",)
        assert calls == [1], "first failing dispatch opens the breaker"

        # breaker open: the device is not touched again within the cooldown
        st = plugin.pre_filter(pending)
        assert st.code == StatusCode.UNSCHEDULABLE_AND_UNRESOLVABLE
        assert calls == [1]

        # fallback counted per surface
        counter = plugin.metrics_registry.counter_vec(
            "kube_throttler_device_fallback_total", "", ["surface"]
        )
        assert counter.collect()[("check",)] == 1.0

        # a schedulable pod stays schedulable host-side
        small = make_pod("small", labels={"grp": "a"}, requests={"cpu": "10m"})
        assert plugin.pre_filter(small).code == StatusCode.SUCCESS

    def test_breaker_reopens_after_cooldown(self, stack):
        _, plugin = stack
        dm = plugin.device_manager
        now = [100.0]
        dm._monotonic = lambda: now[0]

        calls = _break_device(dm, "check_pod")
        pending = make_pod("pending", labels={"grp": "a"}, requests={"cpu": "100m"})
        plugin.pre_filter(pending)
        assert calls == [1] and not dm.device_available()

        now[0] += dm.device_retry_cooldown + 1
        assert dm.device_available()
        plugin.pre_filter(pending)  # device retried (and fails again)
        assert calls == [1, 1]


class TestBatchFallback:
    def test_prefilter_batch_survives_device_failure(self, stack):
        store, plugin = stack
        dm = plugin.device_manager
        healthy = plugin.pre_filter_batch()
        # the running pod classifies against state already containing it
        # (used 150m + own 150m > 200m → insufficient): not schedulable
        assert healthy["schedulable"] == {"default/running": False}

        calls = _break_device(dm, "check_batch_all")
        out = plugin.pre_filter_batch()
        assert out["schedulable"] == healthy["schedulable"]
        assert calls == [1]
        out = plugin.pre_filter_batch()  # breaker open: device untouched
        assert out["schedulable"] == healthy["schedulable"]
        assert calls == [1]


class TestFlakyDeviceSoak:
    @pytest.mark.parametrize("seed", [11, 23])
    def test_verdicts_stay_oracle_correct_through_outages(self, seed):
        """Random churn while the device flips between healthy and failing:
        at every checkpoint the (possibly degraded) device stack must agree
        with a pure host-oracle stack — across outage windows, breaker
        cooldown reopenings, and post-recovery device serving (the staged
        aggregates must self-heal when the device returns)."""
        import random

        from dataclasses import replace

        rng = random.Random(seed)

        def _mk(use_device):
            store = Store()
            plugin = KubeThrottler(
                decode_plugin_args(
                    {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
                ),
                store,
                use_device=use_device,
                start_workers=False,
            )
            store.create_namespace(Namespace("default"))
            return store, plugin

        (store_d, plug_d), (store_h, plug_h) = _mk(True), _mk(False)
        dm = plug_d.device_manager
        now = [1000.0]
        dm._monotonic = lambda: now[0]
        down = [False]

        def flaky(real):
            def f(*a, **k):
                if down[0]:
                    raise RuntimeError("injected tunnel failure")
                return real(*a, **k)

            return f

        dm.check_pod = flaky(dm.check_pod)
        dm.aggregate_used_for = flaky(dm.aggregate_used_for)

        pods = []

        def both(fn):
            fn(store_d)
            fn(store_h)

        from conftest import normalize_reasons as norm

        def checkpoint():
            plug_d.run_pending_once()
            plug_h.run_pending_once()
            for pod in pods:
                sd, sh = plug_d.pre_filter(pod), plug_h.pre_filter(pod)
                assert sd.code == sh.code, (pod.key, down[0], sd.reasons, sh.reasons)
                assert norm(sd.reasons) == norm(sh.reasons), pod.key
            for thr_d in store_d.list_throttles():
                thr_h = store_h.get_throttle(thr_d.namespace, thr_d.name)
                assert thr_d.status.used.to_dict() == thr_h.status.used.to_dict(), (
                    thr_d.key,
                    down[0],
                )

        for step in range(90):
            op = rng.random()
            if op < 0.2:
                name = f"t{rng.randint(0, 4)}"
                thr = _throttle(name, cpu=f"{rng.randint(1, 6)}00m")

                def apply_thr(s, thr=thr):
                    try:
                        s.create_throttle(thr)
                    except ValueError:
                        cur = s.get_throttle("default", thr.name)
                        s.update_throttle(replace(thr, status=cur.status))

                both(apply_thr)
            elif op < 0.55 or not pods:
                pod = make_pod(
                    f"p{step}",
                    labels={"grp": rng.choice("ab")},
                    requests={"cpu": f"{rng.randint(1, 5)}00m"},
                    node_name="n1" if rng.random() < 0.6 else "",
                    phase="Running" if rng.random() < 0.5 else "Pending",
                )
                pods.append(pod)
                both(lambda s, pod=pod: s.create_pod(pod))
            elif op < 0.75:
                old = rng.choice(pods)
                moved = replace(old, labels={"grp": rng.choice("ab")})
                pods[pods.index(old)] = moved
                both(lambda s, moved=moved: s.update_pod(moved))
            elif op < 0.85:
                pod = rng.choice(pods)
                sd, sh = plug_d.reserve(pod), plug_h.reserve(pod)
                assert sd.code == sh.code
            else:
                pod = pods.pop(rng.randrange(len(pods)))
                both(lambda s, pod=pod: s.delete_pod(pod.namespace, pod.name))

            if step % 15 == 14:
                # flip device health; advancing past the cooldown lets the
                # breaker retry (and re-open if still down)
                down[0] = not down[0]
                now[0] += dm.device_retry_cooldown + 1
            if step % 9 == 8:
                checkpoint()
        down[0] = False
        now[0] += dm.device_retry_cooldown + 1
        checkpoint()  # final: device healthy again, healed state serves


class TestReconcileFallback:
    def test_status_converges_host_side(self, stack):
        store, plugin = stack
        dm = plugin.device_manager
        _break_device(dm, "aggregate_used_for")

        store.create_pod(
            make_pod(
                "running2",
                labels={"grp": "a"},
                requests={"cpu": "40m"},
                node_name="n1",
                phase="Running",
            )
        )
        plugin.run_pending_once()
        thr = store.get_throttle("default", "t1")
        # host-walk reconcile landed the fresh aggregate: 150m + 40m
        assert thr.status.used.resource_counts == 2
        assert str(thr.status.used.resource_requests["cpu"]) == "19/100"
        counter = plugin.metrics_registry.counter_vec(
            "kube_throttler_device_fallback_total", "", ["surface"]
        )
        assert counter.collect()[("reconcile",)] >= 1.0


def test_reservation_survives_throttle_recreation_on_device():
    """Reservations outlive the throttle object (the reference cache is
    keyed by name and never cleared on deletion): after delete + re-create,
    the device mirror's reserved row must be replayed from the cache, or
    the device check under-counts until the next reserve/unreserve (found
    by differential soak seed 20)."""
    from kube_throttler_tpu.api.pod import Namespace, make_pod
    from kube_throttler_tpu.api.types import (
        LabelSelector,
        ResourceAmount,
        Throttle,
        ThrottleSelector,
        ThrottleSelectorTerm,
        ThrottleSpec,
    )
    from kube_throttler_tpu.engine.store import Store
    from kube_throttler_tpu.plugin import KubeThrottler, decode_plugin_args

    def throttle():
        return Throttle(
            name="t1",
            spec=ThrottleSpec(
                throttler_name="kube-throttler",
                threshold=ResourceAmount.of(pod=2),
                selector=ThrottleSelector(
                    selector_terms=(
                        ThrottleSelectorTerm(LabelSelector(match_labels={"g": "a"})),
                    )
                ),
            ),
        )

    store = Store()
    store.create_namespace(Namespace("default"))
    plugin = KubeThrottler(
        decode_plugin_args(
            {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
        ),
        store,
        use_device=True,
        start_workers=False,
    )
    store.create_throttle(throttle())
    plugin.run_pending_once()

    # two reservations fill the pod=2 threshold
    for name in ("r1", "r2"):
        assert plugin.reserve(make_pod(name, labels={"g": "a"})).is_success()

    probe = make_pod("probe", labels={"g": "a"})
    assert not plugin.pre_filter(probe).is_success()  # 2 reserved + 1 > 2

    # delete + re-create the throttle: reservations must still bind
    store.delete_throttle("default", "t1")
    store.create_throttle(throttle())
    plugin.run_pending_once()

    # 2 reserved ≥ pod=2 with the Throttle kind's hardcoded step-3
    # onEqual=True → active (throttle_types.go:143)
    verdict = plugin.pre_filter(probe)
    assert not verdict.is_success(), verdict.reasons
    assert "throttle[active]=default/t1" in verdict.reasons
    # host oracle agrees cell-for-cell
    active, insufficient, _, _ = plugin.throttle_ctr.check_throttled(probe, False)
    assert active and not insufficient
