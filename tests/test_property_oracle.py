"""Hypothesis property tests: oracle algebraic laws and oracle ↔ kernel
equivalence over generated states.

SURVEY §4's template calls for "hypothesis/property tests for
Add/IsThrottled" — these cover: arbitrary quantities through the exact
decimal parser, ResourceAmount algebra (the reference's clamp/negative
quirks preserved — resource_amount.go:83-125), IsThrottled dimension
scoping (resource_amount.go:147-155), and randomized single-cell agreement
between ``_check_throttled_for`` and the batched kernel for all onEqual
variants.
"""

from __future__ import annotations

import numpy as np
import pytest

# requirements-ci.txt lists hypothesis, but ad-hoc dev environments may
# lack it — skip at collection instead of erroring the whole session
pytest.importorskip("hypothesis")
from hypothesis import assume, given, settings, strategies as st

from kube_throttler_tpu import quantity as qt
from kube_throttler_tpu.api.pod import make_pod
from kube_throttler_tpu.api.types import (
    ResourceAmount,
    Throttle,
    ThrottleSpec,
    ThrottleStatus,
    _check_throttled_for,
)
from kube_throttler_tpu.ops.check import STATUS_NAMES, check_pods
from kube_throttler_tpu.ops.schema import DimRegistry, encode_pods, encode_throttle_state

# ---------------------------------------------------------------- strategies

SUFFIXES = ["", "m", "k", "M", "G", "Ki", "Mi", "Gi"]


@st.composite
def quantities(draw):
    n = draw(st.integers(min_value=0, max_value=10**12))
    if draw(st.integers(min_value=0, max_value=4)) == 0:
        # decimal forms — many are sub-milli (e.g. "100.5m", "0.0001"),
        # exercising the loud SubMilliPrecisionError rejection path
        frac = draw(st.integers(min_value=1, max_value=9999))
        return f"{n}.{frac}{draw(st.sampled_from(SUFFIXES))}"
    return f"{n}{draw(st.sampled_from(SUFFIXES))}"


RESOURCES = ["cpu", "memory", "nvidia.com/gpu", "storage"]


@st.composite
def amounts(draw):
    cnt = draw(st.one_of(st.none(), st.integers(min_value=0, max_value=50)))
    reqs = draw(
        st.one_of(
            st.none(),
            st.dictionaries(st.sampled_from(RESOURCES), quantities(), max_size=3),
        )
    )
    return ResourceAmount.of(pod=cnt, requests=reqs)


# ----------------------------------------------------------------- quantity


@given(quantities())
@settings(max_examples=200, deadline=None)
def test_quantity_milli_roundtrip_exact(s):
    """to_milli is exact: re-parsing the milli value yields an equal
    quantity (never silently rounded)."""
    q = qt.parse_quantity(s)
    try:
        m = qt.to_milli(q)
    except qt.SubMilliPrecisionError:
        # the loud-rejection property itself: the value truly is
        # unrepresentable — sub-milli precision or outside int64 (never a
        # silent round/truncate)
        milli = q * 1000
        assert milli != int(milli) or not (-(2**63) <= int(milli) < 2**63)
        return
    assert qt.parse_quantity(f"{m}m") == q


@given(quantities(), quantities())
@settings(max_examples=100, deadline=None)
def test_quantity_ordering_matches_milli(a, b):
    qa, qb = qt.parse_quantity(a), qt.parse_quantity(b)
    try:
        ma, mb = qt.to_milli(qa), qt.to_milli(qb)
    except qt.SubMilliPrecisionError:
        assume(False)  # count as filtered, not passed (health-checked)
    assert (qa < qb) == (ma < mb) and (qa == qb) == (ma == mb)


# ---------------------------------------------------------- amount algebra


@given(amounts(), amounts())
@settings(max_examples=150, deadline=None)
def test_add_sub_round_trip_quirks(a, b):
    """a.add(b).sub(b) restores ``a``'s dims EXCEPT the reference's
    deliberate asymmetries: pod count clamps at 0 on sub while request
    quantities may go negative; keys only in ``b`` remain at 0."""
    back = a.add(b).sub(b)
    if a.resource_counts is None and b.resource_counts is None:
        assert back.resource_counts is None
    else:
        assert back.resource_counts == max(a.resource_counts or 0, 0)
    for k, v in (a.resource_requests or {}).items():
        assert back.resource_requests[k] == v
    for k in (b.resource_requests or {}):
        if k not in (a.resource_requests or {}):
            assert back.resource_requests[k] == 0


@given(amounts(), amounts(), st.booleans())
@settings(max_examples=150, deadline=None)
def test_is_throttled_dimension_scoping(threshold, used, on_equal):
    """Only dims present in the threshold are evaluated; threshold dims
    absent from used evaluate to not-throttled; empty-but-present threshold
    request map yields a nil flag map (Go allocation quirk, preserved)."""
    flags = threshold.is_throttled(used, on_equal)
    treqs = threshold.resource_requests
    if treqs is None or not treqs:
        assert flags.resource_requests is None
    else:
        assert set(flags.resource_requests.keys()) == set(treqs.keys())
        for k in treqs:
            if k not in (used.resource_requests or {}):
                assert flags.resource_requests[k] is False
    if threshold.resource_counts is None or used.resource_counts is None:
        assert flags.resource_counts_pod is False


# ----------------------------------------------------- oracle ↔ kernel e2e


@st.composite
def pod_requests(draw):
    return draw(st.dictionaries(st.sampled_from(RESOURCES), quantities(), max_size=3))


@given(amounts(), amounts(), amounts(), pod_requests(), st.booleans(), st.booleans())
@settings(max_examples=200, deadline=None)
def test_kernel_matches_oracle_single_cell(
    threshold, used, reserved, pod_reqs, on_equal, step3_on_equal
):
    """One (pod, throttle) cell through the batched kernel equals the
    ordered 4-state oracle for arbitrary generated amounts and both
    onEqual flags (covering the Throttle/ClusterThrottle asymmetry)."""
    # filter sub-milli-unrepresentable quantities up front (the encoder
    # rejects them loudly; the oracle works in exact Fractions) — assume()
    # so Hypothesis health-checks the filter rate instead of passing
    # vacuously
    def representable(v) -> bool:
        try:
            qt.to_milli(v)
            return True
        except qt.SubMilliPrecisionError:
            return False

    for amt in (threshold, used, reserved):
        assume(all(representable(v) for v in (amt.resource_requests or {}).values()))
    assume(all(representable(qt.parse_quantity(v)) for v in pod_reqs.values()))

    pod = make_pod("p", requests=pod_reqs)
    status = ThrottleStatus(used=used, throttled=threshold.is_throttled(used, True))
    thr = Throttle(
        name="t",
        spec=ThrottleSpec(throttler_name="x", threshold=threshold),
        status=status,
    )

    oracle = _check_throttled_for(
        threshold, status, pod, reserved, on_equal, step3_on_equal
    )

    dims = DimRegistry()
    for name in pod_reqs:
        dims.index_of(name)
    state = encode_throttle_state([thr], dims, reserved=[reserved])
    batch = encode_pods([pod], dims)
    assert batch.req.shape[1] == state.thr_req.shape[1]  # ≤4 names, cap 8
    mask = np.ones((1, 1), dtype=bool)
    got = int(np.asarray(check_pods(state, batch, mask, on_equal, step3_on_equal))[0, 0])
    assert STATUS_NAMES[got] == oracle, (
        f"kernel={STATUS_NAMES[got]} oracle={oracle} thr={threshold} used={used} "
        f"res={reserved} pod={pod_reqs} onEqual={on_equal} step3={step3_on_equal}"
    )
