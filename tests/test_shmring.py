"""Zero-copy shared-memory event plane: ring mechanics + fleet contract.

Unit half exercises the SPSC ring and the columnar codec directly:
wraparound, slot exhaustion (counted backpressure, never a silent
drop), torn-commit detection, close-under-peek, and codec round-trips
including the ``ROW_BLOB`` escape hatch and the whole-pod row cache.

Fleet half drives the REAL multiprocess stack (front + ShardSupervisor
+ worker subprocesses) and pins the repair/fallback contract:

- a worker SIGKILLed mid-stream comes back on a FRESH segment (the old
  one unlinked — no ``/dev/shm`` leak) with the lane active again;
- a peer that masks the ``evt-shm`` capability never gets a lane: evt
  batches ride the HMAC-framed pickle socket (the fallback counter
  proves it) and verdicts still equal the single-process oracle.
"""

from __future__ import annotations

import os
import pickle
import signal
import time

import pytest

import tools.harness as H
from kube_throttler_tpu.api.pod import Namespace, Pod, make_pod
from kube_throttler_tpu.engine.store import Store
from kube_throttler_tpu.faults.plan import FaultPlan
from kube_throttler_tpu.sharding import ipc
from kube_throttler_tpu.sharding.front import AdmissionFront
from kube_throttler_tpu.sharding.shmring import (
    FrameDecoder,
    FrameEncoder,
    ShmEventLane,
    ShmRingReader,
    ShmRingWriter,
    TornSlotError,
    shm_available,
    sweep_segments,
)
from kube_throttler_tpu.sharding.supervisor import ShardSupervisor

pytestmark = pytest.mark.skipif(
    not shm_available(), reason="POSIX shared memory unavailable"
)


def _ring(slots=8, arena=1 << 16, faults=None):
    w = ShmRingWriter(slots=slots, arena_bytes=arena, faults=faults)
    r = ShmRingReader(w.name, faults=faults)
    return w, r


def _drain_one(r, timeout=1.0):
    view = r.peek(timeout=timeout)
    assert view is not None
    out = bytes(view)
    view.release()
    r.advance()
    return out


# ------------------------------------------------------------ ring mechanics


class TestRingMechanics:
    def test_fifo_roundtrip(self):
        w, r = _ring()
        try:
            frames = [bytes([i]) * (100 + i) for i in range(5)]
            for f in frames:
                assert w.push(f, timeout=1.0)
            assert [_drain_one(r) for _ in frames] == frames
            assert r.depth() == 0
        finally:
            r.close()
            w.close()

    def test_wraparound_preserves_frames(self):
        # arena fits ~4 frames: steady-state streaming must wrap the
        # allocator cursor and every frame must still arrive intact
        # keep 2 frames in flight so the allocator can't reset to
        # offset 0 on a drained arena — it must WRAP past live bytes
        w, r = _ring(slots=64, arena=4096)
        try:
            expected = []
            for i in range(64):
                payload = bytes([i % 251]) * 900
                assert w.push(payload, timeout=2.0), f"push {i} stalled"
                expected.append(payload)
                if len(expected) > 2:
                    assert _drain_one(r) == expected.pop(0)
            while expected:
                assert _drain_one(r) == expected.pop(0)
            assert w.stats()["wraps"] >= 1, "arena never wrapped — vacuous"
            assert w.stats()["frames"] == 64
        finally:
            r.close()
            w.close()

    def test_slot_exhaustion_is_counted_backpressure_not_a_drop(self):
        w, r = _ring(slots=4, arena=1 << 16)
        try:
            for i in range(4):
                assert w.push(b"x" * 64, timeout=1.0)
            # no reader progress: the 5th frame must block, count the
            # wait, and report failure — never silently vanish
            t0 = time.monotonic()
            assert w.push(b"y" * 64, timeout=0.25) is False
            assert time.monotonic() - t0 >= 0.2
            stats = w.stats()
            assert stats["backpressure"] >= 1
            assert stats["frames"] == 4  # the failed frame was not committed
            # a consuming reader unblocks the writer again
            _drain_one(r)
            assert w.push(b"y" * 64, timeout=1.0)
        finally:
            r.close()
            w.close()

    def test_torn_commit_raises_and_counts(self):
        plan = FaultPlan(seed=3).rule(
            "shm.slot.torn_commit", mode="torn", times=1
        )
        w, r = _ring(faults=plan)
        try:
            assert w.push(b"doomed", timeout=1.0)  # commit word is garbage
            with pytest.raises(TornSlotError):
                r.peek(timeout=0.5)
            assert r.torn == 1
            assert plan.fired("shm.slot.torn_commit") == 1
        finally:
            r.close()
            w.close()

    def test_push_after_close_returns_false(self):
        w, r = _ring()
        r.close()
        w.close()
        assert w.push(b"late", timeout=0.1) is False

    def test_reader_close_under_peek_reports_empty(self):
        w, r = _ring()
        w.push(b"frame", timeout=1.0)
        _drain_one(r)
        r.close()
        # teardown race: a racing peek on a released buffer must read
        # as empty, never as a torn slot
        assert r.peek(timeout=0.05) is None
        w.close()

    def test_frame_larger_than_arena_rejected(self):
        w, r = _ring(slots=4, arena=4096)
        try:
            with pytest.raises(ValueError):
                w.push(b"z" * 8192, timeout=0.1)
        finally:
            r.close()
            w.close()


# ------------------------------------------------------------------- codec


def _canonical_store(n_pods=6):
    store = Store()
    store.create_namespace(Namespace("default"))
    for i in range(n_pods):
        store.create_pod(
            make_pod(
                f"p{i}",
                labels={"grp": f"g{i % 3}", "tier": "web"},
                requests={"cpu": f"{(i + 1) * 100}m", "memory": "64Mi"},
                node_name=f"node-{i % 2}",
                phase="Running",
            )
        )
    return store


def _assert_pod_equal(got: Pod, want: Pod):
    assert got.name == want.name and got.namespace == want.namespace
    assert got.labels == want.labels and got.annotations == want.annotations
    assert got.uid == want.uid
    assert got.status.phase == want.status.phase
    assert got.spec.node_name == want.spec.node_name
    assert got.spec.scheduler_name == want.spec.scheduler_name
    assert [c.requests for c in got.spec.containers or ()] == [
        c.requests for c in want.spec.containers or ()
    ]


class TestColumnarCodec:
    def test_roundtrip_canonical_pods_keys_and_blobs(self):
        store = _canonical_store()
        pods = sorted(store.list_pods(), key=lambda p: p.name)
        sparse = Pod(name="sparse", namespace="default")  # no spec: blob row
        throttle = H.make_throttle(0)
        ops = (
            [("update", "Pod", p) for p in pods]
            + [
                ("delete", "Pod", "default/p0"),
                ("update", "Pod", sparse),
                ("update", "Throttle", throttle),
            ]
        )
        enc, dec = FrameEncoder(), FrameDecoder()
        epoch, seq, out = dec.decode(enc.encode(ops, epoch=7, seq=0))
        assert (epoch, seq) == (7, 0)
        assert len(out) == len(ops)
        for got, want in zip(out[: len(pods)], pods):
            assert got[:2] == ("update", "Pod")
            _assert_pod_equal(got[2], want)
        assert out[len(pods)] == ("delete", "Pod", "default/p0")
        assert out[len(pods) + 1][2].name == "sparse"  # blob round-trip
        assert out[len(pods) + 2][2].key == throttle.key

    def test_row_cache_reencodes_identically(self):
        store = _canonical_store(n_pods=3)
        pods = store.list_pods()
        ops = [("update", "Pod", p) for p in pods]
        enc, dec = FrameEncoder(), FrameDecoder()
        _, _, first = dec.decode(enc.encode(ops, epoch=1, seq=0))
        assert enc._row_by_obj  # second pass hits the whole-pod cache
        _, _, second = dec.decode(enc.encode(ops, epoch=1, seq=1))
        for (_, _, a), (_, _, b) in zip(first, second):
            _assert_pod_equal(a, b)

    def test_lane_splits_oversized_batches(self):
        w = ShmRingWriter(slots=256, arena_bytes=1 << 20)
        r = ShmRingReader(w.name)
        lane = ShmEventLane(w)
        try:
            store = _canonical_store(n_pods=4)
            pods = store.list_pods()
            ops = [("update", "Pod", pods[i % 4]) for i in range(300)]
            assert lane.send(ops, epoch=1)
            dec = FrameDecoder()
            got = []
            frames = 0
            while len(got) < len(ops):
                view = r.peek(timeout=2.0)
                assert view is not None, "lane lost events across the split"
                _, _, decoded = dec.decode(view)
                got.extend(decoded)
                view.release()
                r.advance()
                frames += 1
            assert frames >= 2  # the batch really split
            assert len(got) == len(ops)
        finally:
            r.close()
            lane.close()


# --------------------------------------------------- fan-out dedup (pickle)


def test_fanout_dedup_serializes_shared_payload_once():
    store = _canonical_store(n_pods=1)
    pod = store.list_pods()[0]
    # the router fanned the same payload object into three shard buffers
    buffers = {sid: [("update", "Pod", pod)] for sid in range(3)}
    AdmissionFront._dedup_fanout(buffers)
    wrapped = {id(buffers[sid][0][2]) for sid in range(3)}
    assert len(wrapped) == 1, "fan-out must share ONE wrapper"
    payload = buffers[0][0][2]
    assert isinstance(payload, ipc.PrepickledPayload)
    before = ipc.PREPICKLE_SERIALIZATIONS
    for sid in range(3):  # each shard sender pickles its own evt frame
        pickle.loads(
            pickle.dumps(ipc.encode_evt_batch(buffers[sid]),
                         protocol=ipc.PICKLE_PROTO)
        )
    assert ipc.PREPICKLE_SERIALIZATIONS - before == 1, (
        "shared payload must serialize exactly once across the fan-out"
    )


def test_fanout_dedup_leaves_singletons_alone():
    store = _canonical_store(n_pods=2)
    a, b = sorted(store.list_pods(), key=lambda p: p.name)
    buffers = {0: [("update", "Pod", a)], 1: [("update", "Pod", b)],
               2: [("delete", "Pod", "default/p0")]}
    AdmissionFront._dedup_fanout(buffers)
    assert buffers[0][0][2] is a  # single-shard payloads stay unwrapped
    assert buffers[1][0][2] is b
    assert buffers[2][0][2] == "default/p0"


# ------------------------------------------------------------- fleet tests


N_SHARDS = 2


def _seed(front, n_pods=24):
    front.store.create_namespace(Namespace("default"))
    for i in range(3):
        front.store.create_throttle(H.make_throttle(i))
    for i in range(n_pods):
        front.store.create_pod(
            make_pod(
                f"p{i}",
                labels={"grp": f"g{i % 3}"},
                requests={"cpu": "200m"},
                node_name="node-1",
                phase="Running",
            )
        )


def _wait_health(front, state, timeout=90.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got, _ = front._shards_health()
        if got == state:
            return True
        time.sleep(0.1)
    return False


def _fleet(env_extra=None):
    front = AdmissionFront(N_SHARDS)
    env = {**os.environ, "KT_SHARD_QUIET": "1", "KT_LOCK_ASSERT": "0"}
    env.update(env_extra or {})
    sup = ShardSupervisor(
        front, use_device=False, restart_backoff=0.3, env=env
    )
    sup.start(ready_timeout=180.0)
    return front, sup


def _lanes_active(front, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if all(
            getattr(front.shards[s], "_shm_active", False)
            and getattr(front.shards[s], "shm_lane", None) is not None
            for s in range(front.n_shards)
        ):
            return True
        time.sleep(0.05)
    return False


def test_worker_crash_restarts_on_fresh_segment_no_shm_leak():
    front, sup = _fleet()
    try:
        _seed(front)
        assert front.drain(60.0)
        assert _lanes_active(front), "event plane never went live"
        victim = 0
        old_name = front.shards[victim].shm_lane.writer.name
        os.kill(sup.shard_proc(victim).pid, signal.SIGKILL)
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            if sup.restart_counts()[victim] >= 1:
                break
            time.sleep(0.1)
        assert sup.restart_counts()[victim] >= 1, "monitor never restarted"
        assert _wait_health(front, "ok", timeout=120.0)
        # the replacement worker must ride a FRESH segment (the ring is
        # die-as-a-unit: a restarted reader never resumes a stale ring)
        assert _lanes_active(front), "lane not re-promoted after restart"
        new_name = front.shards[victim].shm_lane.writer.name
        assert new_name != old_name
        # the dead incarnation's segment is gone from /dev/shm already
        assert not os.path.exists(os.path.join("/dev/shm", old_name))
        front.store.update_pod(
            make_pod("p0", labels={"grp": "g0"}, requests={"cpu": "300m"},
                     node_name="node-1", phase="Running")
        )
        assert front.drain(60.0)
    finally:
        sup.stop()
        front.stop()
    leftovers = [
        n for n in os.listdir("/dev/shm") if n.startswith(f"kt_evt_{os.getpid()}_")
    ] if os.path.isdir("/dev/shm") else []
    assert not leftovers, f"leaked segments after stop: {leftovers}"


def test_capability_masked_peer_falls_back_to_pickle_equivalently():
    from kube_throttler_tpu.version import advertised_capabilities

    masked = ",".join(sorted(advertised_capabilities() - {"evt-shm"}))
    front, sup = _fleet(env_extra={"KT_PROTO_CAPS_MASK": masked})
    try:
        _seed(front)
        for i in range(12):  # churn so evt batches actually flow
            front.store.update_pod(
                make_pod(f"p{i}", labels={"grp": f"g{i % 3}"},
                         requests={"cpu": f"{(i % 8 + 1) * 100}m"},
                         node_name="node-1", phase="Running")
            )
        assert front.drain(60.0)
        time.sleep(0.5)
        for sid in range(front.n_shards):
            handle = front.shards[sid]
            assert not getattr(handle, "_shm_active", False), (
                f"shard {sid}: lane promoted despite masked evt-shm"
            )
            assert getattr(handle, "shm_fallback_batches", 0) > 0, (
                f"shard {sid}: no evt batches took the pickle fallback"
            )
        # fallback path is verdict-equivalent to a single-process oracle
        oracle_store = Store()
        oracle_store.create_namespace(Namespace("default"))
        for thr in front.store.list_throttles():
            oracle_store.create_throttle(thr)
        for pod in front.store.list_pods():
            oracle_store.create_pod(pod)
        oracle = H.build_plugin(oracle_store)
        oracle.run_pending_once()
        for pod in oracle_store.list_pods():
            got, want = front.pre_filter(pod), oracle.pre_filter(pod)
            assert got.code == want.code, (pod.key, got.reasons, want.reasons)
    finally:
        sup.stop()
        front.stop()


def test_sweep_segments_removes_only_our_prefix():
    w1 = ShmRingWriter(name=f"kt_evt_swp_{os.getpid()}_a")
    w2 = ShmRingWriter(name=f"kt_other_{os.getpid()}_b")
    try:
        # simulate a creator killed before cleanup: nobody unlinks w1
        w1.close(unlink=False)
        removed = sweep_segments(f"kt_evt_swp_{os.getpid()}_")
        assert f"kt_evt_swp_{os.getpid()}_a" in removed
        assert not os.path.exists(f"/dev/shm/kt_evt_swp_{os.getpid()}_a")
        assert os.path.exists(f"/dev/shm/kt_other_{os.getpid()}_b")
    finally:
        try:
            from multiprocessing import resource_tracker

            resource_tracker.unregister(w1._shm._name, "shared_memory")
        except Exception:
            pass
        w2.close(unlink=True)
