"""Parity tests: native C++ row-match tier vs the pure-Python SelectorIndex.

The native engine (kube_throttler_tpu/native/ktnative.cpp) must reproduce the Python tier's
mask bit-for-bit over every selector shape the reference supports:
matchLabels-only terms (throttle_selector.go:30-54), ClusterThrottle
namespace selectors (clusterthrottle_selector.go:112-141), matchExpressions
falling back to the general tier, empty selectors (match nothing), empty
terms (match everything), unknown namespaces, and object churn.
"""

from __future__ import annotations

import random

import numpy as np
import pytest

from kube_throttler_tpu.api.pod import Namespace, make_pod
from kube_throttler_tpu.api.types import (
    ClusterThrottle,
    ClusterThrottleSelector,
    ClusterThrottleSelectorTerm,
    ClusterThrottleSpec,
    LabelSelector,
    LabelSelectorRequirement,
    ResourceAmount,
    Throttle,
    ThrottleSelector,
    ThrottleSelectorTerm,
    ThrottleSpec,
)
from kube_throttler_tpu.engine.index import SelectorIndex
from kube_throttler_tpu.native import NativeRowEngine, available

pytestmark = pytest.mark.skipif(not available(), reason="native library unavailable")


def _throttle(name, ns, terms):
    return Throttle(
        name=name,
        namespace=ns,
        spec=ThrottleSpec(
            throttler_name="kube-throttler",
            threshold=ResourceAmount.of(pod=10),
            selector=ThrottleSelector(selector_terms=tuple(terms)),
        ),
    )


def _cluster(name, terms):
    return ClusterThrottle(
        name=name,
        spec=ClusterThrottleSpec(
            throttler_name="kube-throttler",
            threshold=ResourceAmount.of(pod=10),
            selector=ClusterThrottleSelector(selector_terms=tuple(terms)),
        ),
    )


def _pod(name, ns, labels):
    return make_pod(name, namespace=ns, labels=labels)


def test_native_engine_loads():
    assert NativeRowEngine("throttle") is not None


def test_engine_matchlabels_semantics():
    eng = NativeRowEngine("throttle")
    # col 0: ns 7, one term {1:2}; col 1: ns 7, empty selector (no terms)
    eng.set_col(0, 7, [([(1, NativeRowEngine.OP_EQ, (2,))], [])])
    eng.set_col(1, 7, [])
    # col 2: empty TERM — matches every pod in ns 7
    eng.set_col(2, 7, [([], [])])
    match, general = eng.match_row(7, True, {1: 2, 3: 4}, {})
    assert list(match) == [1, 0, 1] and not general.any()
    # wrong namespace gates everything off
    match, _ = eng.match_row(8, True, {1: 2}, {})
    assert list(match) == [0, 0, 0]
    # missing label key → no match
    match, _ = eng.match_row(7, True, {3: 4}, {})
    assert list(match) == [0, 0, 1]


def test_engine_cluster_ns_gate():
    eng = NativeRowEngine("clusterthrottle")
    eng.set_col(0, -1, [([(1, NativeRowEngine.OP_EQ, (1,))], [(5, NativeRowEngine.OP_EQ, (6,))])])
    eng.set_col_general(1, -1)
    # namespace labels must satisfy the ns requirement
    match, general = eng.match_row(0, True, {1: 1}, {5: 6})
    assert match[0] == 1 and general[1] == 1
    match, general = eng.match_row(0, True, {1: 1}, {5: 7})
    assert match[0] == 0
    # unknown namespace: nothing matches, general tier not consulted
    match, general = eng.match_row(0, False, {1: 1}, {5: 6})
    assert not match.any() and not general.any()


def test_engine_clear_and_or_terms():
    eng = NativeRowEngine("throttle")
    eng.set_col(0, 1, [([(1, NativeRowEngine.OP_EQ, (1,))], []), ([(2, NativeRowEngine.OP_EQ, (2,))], [])])  # OR of two terms
    match, _ = eng.match_row(1, True, {2: 2}, {})
    assert match[0] == 1
    eng.clear_col(0)
    match, _ = eng.match_row(1, True, {2: 2}, {})
    assert match[0] == 0


def _rand_expr(rng, keys, values):
    op = rng.choice(["In", "NotIn", "Exists", "DoesNotExist"])
    if op in ("In", "NotIn"):
        vals = tuple(
            rng.choice(values) for _ in range(rng.randint(1, len(values)))
        )
    else:
        vals = ()
    return LabelSelectorRequirement(key=rng.choice(keys), operator=op, values=vals)


def _rand_term(rng, keys, values, with_ns):
    pod_sel = LabelSelector(
        match_labels={rng.choice(keys): rng.choice(values) for _ in range(rng.randint(0, 2))},
        match_expressions=(
            tuple(_rand_expr(rng, keys, values) for _ in range(rng.randint(1, 2)))
            if rng.random() < 0.4
            else ()
        ),
    )
    if with_ns:
        ns_sel = LabelSelector(
            match_labels={"env": rng.choice(values)} if rng.random() < 0.5 else {}
        )
        return ClusterThrottleSelectorTerm(pod_selector=pod_sel, namespace_selector=ns_sel)
    return ThrottleSelectorTerm(pod_selector=pod_sel)


def test_match_expressions_compile_natively():
    """In/NotIn/Exists/DoesNotExist evaluate in the C++ tier (no general
    flag); only selectors failing validation stay general."""
    idx = SelectorIndex("throttle", use_native=True)
    assert idx._native is not None
    idx.upsert_namespace(Namespace("default"))
    exprs = {
        "in": LabelSelectorRequirement("tier", "In", ("web", "api")),
        "notin": LabelSelectorRequirement("tier", "NotIn", ("db",)),
        "exists": LabelSelectorRequirement("canary", "Exists"),
        "dne": LabelSelectorRequirement("legacy", "DoesNotExist"),
    }
    for name, expr in exprs.items():
        idx.upsert_throttle(
            _throttle(name, "default", [
                ThrottleSelectorTerm(LabelSelector(match_expressions=(expr,)))
            ])
        )
    # evaluate via the row path and compare against the Python oracle
    for labels in (
        {"tier": "web"},
        {"tier": "db"},
        {"canary": "yes"},
        {"legacy": "x", "tier": "api"},
        {},
    ):
        pod = _pod("probe", "default", labels)
        got = set(idx.affected_throttle_keys_for(pod))
        want = {
            t.key
            for t in [
                _throttle(n, "default", [
                    ThrottleSelectorTerm(LabelSelector(match_expressions=(e,)))
                ])
                for n, e in exprs.items()
            ]
            if t.spec.selector.matches_to_pod(pod)
        }
        assert got == want, (labels, got, want)


def test_invalid_selector_stays_general_and_matches_nothing():
    idx = SelectorIndex("throttle", use_native=True)
    idx.upsert_namespace(Namespace("default"))
    bad = _throttle("bad", "default", [
        ThrottleSelectorTerm(
            LabelSelector(
                match_expressions=(
                    LabelSelectorRequirement("k", "In", ()),  # In needs values
                )
            )
        )
    ])
    idx.upsert_throttle(bad)
    pod = _pod("p", "default", {"k": "v"})
    assert idx.affected_throttle_keys_for(pod) == []


@pytest.mark.parametrize("kind", ["throttle", "clusterthrottle"])
def test_randomized_parity_with_python_tier(kind):
    """Drive identical event sequences through a native-backed and a pure-
    Python index; the [P,T] masks must stay identical at every step."""
    rng = random.Random(12345)
    keys = ["app", "tier", "team"]
    values = ["a", "b", "c"]
    namespaces = ["ns0", "ns1", "ns2"]

    nat = SelectorIndex(kind, pod_capacity=4, throttle_capacity=2, use_native=True)
    pure = SelectorIndex(kind, pod_capacity=4, throttle_capacity=2, use_native=False)
    assert nat._native is not None and pure._native is None

    def check():
        p = min(nat.mask.shape[0], pure.mask.shape[0])
        t = min(nat.mask.shape[1], pure.mask.shape[1])
        np.testing.assert_array_equal(nat.mask[:p, :t], pure.mask[:p, :t])
        assert not nat.mask[p:].any() and not pure.mask[p:].any()

    # known namespaces land first for two of three (ns2 stays unknown a while)
    for ns in namespaces[:2]:
        n = Namespace(ns, labels={"env": rng.choice(values)})
        nat.upsert_namespace(n)
        pure.upsert_namespace(n)

    pods, thrs = [], []
    for step in range(120):
        op = rng.random()
        if op < 0.35 or not pods:
            name = f"p{rng.randint(0, 20)}"
            ns = rng.choice(namespaces)
            pod = _pod(name, ns, {rng.choice(keys): rng.choice(values) for _ in range(rng.randint(0, 3))})
            pods.append(pod.key)
            nat.upsert_pod(pod)
            pure.upsert_pod(pod)
        elif op < 0.6 or not thrs:
            name = f"t{rng.randint(0, 10)}"
            terms = [
                _rand_term(rng, keys, values, with_ns=kind == "clusterthrottle")
                for _ in range(rng.randint(0, 2))
            ]
            thr = (
                _throttle(name, rng.choice(namespaces), terms)
                if kind == "throttle"
                else _cluster(name, terms)
            )
            thrs.append(thr.key)
            nat.upsert_throttle(thr)
            pure.upsert_throttle(thr)
        elif op < 0.75:
            key = rng.choice(pods)
            nat.remove_pod(key)
            pure.remove_pod(key)
        elif op < 0.9:
            key = rng.choice(thrs)
            nat.remove_throttle(key)
            pure.remove_throttle(key)
        else:
            ns = Namespace(rng.choice(namespaces), labels={"env": rng.choice(values)})
            nat.upsert_namespace(ns)
            pure.upsert_namespace(ns)
        check()

    # queries agree too
    for key in pods[:5]:
        assert nat.affected_throttle_keys(key) == pure.affected_throttle_keys(key)
    for key in thrs[:5]:
        assert sorted(nat.matched_pod_keys(key)) == sorted(pure.matched_pod_keys(key))
