"""Tracing/profiling subsystem (SURVEY §5 TPU-native equivalent): phase
histograms through the metrics registry, klog-style verbosity, the
/debug/flags/v endpoint, and tracer wiring through plugin/controllers."""

import urllib.request

from kube_throttler_tpu.api import (
    LabelSelector,
    ResourceAmount,
    Throttle,
    ThrottleSelector,
    ThrottleSelectorTerm,
    ThrottleSpec,
)
from kube_throttler_tpu.api.pod import Namespace, make_pod
from kube_throttler_tpu.engine.store import Store
from kube_throttler_tpu.metrics import Registry
from kube_throttler_tpu.plugin import KubeThrottler, decode_plugin_args
from kube_throttler_tpu.utils import tracing


def _plugin(use_device=False):
    store = Store()
    store.create_namespace(Namespace("default"))
    plugin = KubeThrottler(
        decode_plugin_args({"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}),
        store,
        use_device=use_device,
    )
    return store, plugin


class TestHistogram:
    def test_observe_buckets_sum_count(self):
        reg = Registry()
        h = reg.histogram_vec("h_test_seconds", "help", ["phase"], buckets=[0.1, 1.0])
        h.observe({"phase": "x"}, 0.05)
        h.observe({"phase": "x"}, 0.5)
        h.observe({"phase": "x"}, 5.0)
        counts, total, count = h.collect()[("x",)]
        assert counts == [1, 2]  # cumulative: ≤0.1 → 1, ≤1.0 → 2
        assert count == 3 and abs(total - 5.55) < 1e-9

    def test_exposition_format(self):
        reg = Registry()
        h = reg.histogram_vec("h_fmt_seconds", "help", ["phase"], buckets=[0.1])
        h.observe({"phase": "p"}, 0.01)
        text = reg.exposition()
        assert '# TYPE h_fmt_seconds histogram' in text
        assert 'h_fmt_seconds_bucket{phase="p",le="0.1"} 1' in text
        assert 'h_fmt_seconds_bucket{phase="p",le="+Inf"} 1' in text
        assert 'h_fmt_seconds_count{phase="p"} 1' in text


class TestVerbosity:
    def test_set_get_and_gate(self):
        prev = tracing.set_verbosity(3)
        try:
            assert tracing.get_verbosity() == 3
            assert tracing.v_enabled(2) and tracing.v_enabled(3)
            assert not tracing.v_enabled(4)
        finally:
            tracing.set_verbosity(prev)


class TestPhaseTracer:
    def test_trace_records_and_snapshot(self):
        reg = Registry()
        tr = tracing.PhaseTracer(reg)
        with tr.trace("phase_a"):
            pass
        snap = tr.snapshot("phase_a")
        assert snap is not None and snap["count"] == 1
        assert tr.snapshot("never") is None
        assert "kube_throttler_phase_duration_seconds" in reg.exposition()

    def test_noop_tracer(self):
        tr = tracing.NoopTracer()
        with tr.trace("x"):
            pass
        assert tr.snapshot("x") is None


class TestWiring:
    def test_plugin_phases_land_in_registry(self):
        store, plugin = _plugin()
        store.create_throttle(
            Throttle(
                name="t1",
                spec=ThrottleSpec(
                    throttler_name="kube-throttler",
                    threshold=ResourceAmount.of(requests={"cpu": "100m"}),
                    selector=ThrottleSelector(
                        selector_terms=(
                            ThrottleSelectorTerm(LabelSelector(match_labels={"throttle": "t1"})),
                        )
                    ),
                ),
            )
        )
        plugin.run_pending_once()
        pod = make_pod("p1", labels={"throttle": "t1"}, requests={"cpu": "50m"})
        store.create_pod(pod)
        plugin.pre_filter(pod)
        plugin.reserve(pod)
        plugin.unreserve(pod)
        for phase in ("prefilter", "reserve", "unreserve", "reconcile"):
            snap = plugin.tracer.snapshot(phase)
            assert snap is not None and snap["count"] >= 1, phase
        text = plugin.metrics_registry.exposition()
        assert 'phase="prefilter"' in text

    def test_device_check_phase(self):
        store, plugin = _plugin(use_device=True)
        store.create_throttle(
            Throttle(
                name="t1",
                spec=ThrottleSpec(
                    throttler_name="kube-throttler",
                    threshold=ResourceAmount.of(requests={"cpu": "100m"}),
                    selector=ThrottleSelector(
                        selector_terms=(
                            ThrottleSelectorTerm(LabelSelector(match_labels={"throttle": "t1"})),
                        )
                    ),
                ),
            )
        )
        plugin.run_pending_once()
        pod = make_pod("p1", labels={"throttle": "t1"}, requests={"cpu": "50m"})
        store.create_pod(pod)
        plugin.pre_filter(pod)
        snap = plugin.tracer.snapshot("device_check")
        assert snap is not None and snap["count"] >= 1


class TestDebugFlagsEndpoint:
    def test_put_debug_flags_v(self):
        from kube_throttler_tpu.server import ThrottlerHTTPServer

        store, plugin = _plugin()
        server = ThrottlerHTTPServer(plugin, host="127.0.0.1", port=0)
        server.start()
        try:
            prev = tracing.get_verbosity()
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/debug/flags/v",
                data=b"4",
                method="PUT",
            )
            body = urllib.request.urlopen(req, timeout=5).read().decode()
            assert "verbosity to 4" in body
            assert tracing.get_verbosity() == 4
            tracing.set_verbosity(prev)
        finally:
            server.stop()
