"""ResourceList arithmetic vs reference resourcelist_test.go semantics."""

from fractions import Fraction

from kube_throttler_tpu import resourcelist as rl
from kube_throttler_tpu.api.pod import Container, make_pod
from kube_throttler_tpu.quantity import parse_quantity as q


def RL(**kwargs):
    return {k: q(v) for k, v in kwargs.items()}


class TestPodRequestResourceList:
    def test_containers_sum(self):
        pod = make_pod("p", requests={"cpu": "100m"})
        pod.spec.containers.append(Container.of({"cpu": "200m", "memory": "1Gi"}))
        got = rl.pod_request_resource_list(pod)
        assert got == RL(cpu="300m", memory="1Gi")

    def test_init_containers_max_wins_over_sum(self):
        # effective = max(max(initContainers), sum(containers))
        pod = make_pod(
            "p",
            requests={"cpu": "100m"},
            init_requests=[{"cpu": "500m"}, {"cpu": "300m", "memory": "2Gi"}],
        )
        got = rl.pod_request_resource_list(pod)
        assert got == RL(cpu="500m", memory="2Gi")

    def test_overhead_added(self):
        pod = make_pod("p", requests={"cpu": "100m"}, overhead={"cpu": "10m"})
        assert rl.pod_request_resource_list(pod) == RL(cpu="110m")

    def test_no_requests(self):
        pod = make_pod("p")
        assert rl.pod_request_resource_list(pod) == {}


class TestArithmetic:
    def test_add_merges_missing_keys(self):
        a = RL(cpu="1")
        rl.add(a, RL(cpu="1", memory="1Gi"))
        assert a == RL(cpu="2", memory="1Gi")

    def test_sub_can_go_negative(self):
        a = RL(cpu="1")
        rl.sub(a, RL(cpu="2", memory="1Gi"))
        assert a == {"cpu": q("-1"), "memory": -q("1Gi")}

    def test_greater_or_equal(self):
        assert rl.greater_or_equal(RL(cpu="2", memory="1Gi"), RL(cpu="1"))
        assert rl.greater_or_equal(RL(cpu="1"), RL(cpu="1"))
        assert not rl.greater_or_equal(RL(cpu="1"), RL(cpu="2"))
        # key missing from lhs fails regardless of value
        assert not rl.greater_or_equal(RL(cpu="5"), RL(memory="0"))
        # empty rhs always satisfied
        assert rl.greater_or_equal({}, {})

    def test_set_max(self):
        a = RL(cpu="1", memory="2Gi")
        rl.set_max(a, RL(cpu="3", gpu="1"))
        assert a == RL(cpu="3", memory="2Gi", gpu="1")

    def test_set_min_drops_lhs_only_keys(self):
        a = RL(cpu="3", memory="2Gi")
        rl.set_min(a, RL(cpu="1", gpu="7"))
        assert a == RL(cpu="1")

    def test_equal_to_missing_reads_zero(self):
        assert rl.equal_to(RL(cpu="0"), {})
        assert rl.equal_to({}, RL(cpu="0"))
        assert not rl.equal_to(RL(cpu="1"), {})
        assert rl.equal_to(RL(cpu="100m"), RL(cpu="0.1"))
