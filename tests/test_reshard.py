"""Live elastic resharding: retarget plans, dual-ring transition routing,
the fenced two-phase handoff, and its abort/reap paths.

Ring-retarget properties (satellite of the PR 13 tentpole):

- **determinism** — ``plan_reshard`` is a pure function of the two ring
  parameter tuples (equal across instances and processes);
- **minimality** — a route key appears in a moving range IFF its owner
  differs between the rings (nothing else transfers);
- **zero-owner-never** — at EVERY intermediate cutover state the
  transition router maps every key to exactly one authoritative owner
  (src before its range cuts, dst after — never neither).

Handoff behavior runs over in-process shard cores (LocalShard), the
same deterministic transport the sharding equivalence suite uses; the
real-process SIGKILL variant is scenarios/resharding.py + the
tools/reshardtest.py matrix.
"""

from __future__ import annotations

import time

import pytest

import tools.harness as H
from kube_throttler_tpu.api.pod import Namespace, make_pod
from kube_throttler_tpu.engine.replication import (
    RangeFence,
    ReplicationDiverged,
    SliceChunkSink,
    SliceChunkSource,
)
from kube_throttler_tpu.engine.store import Store
from kube_throttler_tpu.faults.plan import FaultPlan
from kube_throttler_tpu.sharding.front import AdmissionFront
from kube_throttler_tpu.sharding.ipc import LocalShard
from kube_throttler_tpu.sharding.reshard import (
    CoordinatorCrash,
    ReshardCoordinator,
)
from kube_throttler_tpu.sharding.ring import (
    HashRing,
    TransitionRouting,
    plan_reshard,
    route_key_for,
    stable_hash64,
)
from kube_throttler_tpu.sharding.worker import ShardCore

KEYS = [f"key-{i}" for i in range(2000)]


# --------------------------------------------------------------------------
# retarget plans
# --------------------------------------------------------------------------


class TestReshardPlan:
    def test_plan_is_deterministic(self):
        a = plan_reshard(HashRing(2), HashRing(4))
        b = plan_reshard(HashRing(2), HashRing(4))
        assert a == b
        assert a.moves  # a 2->4 split MUST move something

    @pytest.mark.parametrize("n_old,n_new", [(2, 4), (4, 3), (1, 2), (3, 8)])
    def test_plan_is_minimal(self, n_old, n_new):
        """A key transfers IFF its owner differs between the rings."""
        old, new = HashRing(n_old), HashRing(n_new)
        plan = plan_reshard(old, new)
        for key in KEYS:
            h = stable_hash64(key)
            move = plan.move_for_hash(h)
            if old.shard_of(key) == new.shard_of(key):
                assert move is None, key
            else:
                assert move is not None, key
                assert move.src == old.shard_of(key)
                assert move.dst == new.shard_of(key)

    def test_moves_partition_cleanly(self):
        plan = plan_reshard(HashRing(2), HashRing(3))
        for a, b in zip(plan.moves, plan.moves[1:]):
            assert a.hi <= b.lo  # sorted, non-overlapping
        for move in plan.moves:
            assert move.lo < move.hi
            assert move.src != move.dst

    @pytest.mark.parametrize("n_old,n_new", [(2, 4), (4, 3)])
    def test_zero_owners_never_at_any_intermediate_step(self, n_old, n_new):
        """Walk the cutover one range at a time: every key always has
        exactly one authoritative owner, equal to the old owner before
        its range cuts and the new owner after."""
        old, new = HashRing(n_old), HashRing(n_new)
        tr = TransitionRouting(old, new)
        hashes = [stable_hash64(k) for k in KEYS]
        valid = set(range(max(n_old, n_new)))
        for step in range(len(tr.plan.moves) + 1):
            for key, h in zip(KEYS, hashes):
                owner = tr.owner_of_hash(h)
                assert owner in valid
                move = tr.plan.move_for_hash(h)
                if move is None:
                    assert owner == old.shard_of(key) == new.shard_of(key)
                elif tr.state[move.index] == TransitionRouting.CUT:
                    assert owner == new.shard_of(key)
                else:
                    assert owner == old.shard_of(key)
            if step < len(tr.plan.moves):
                tr.set_state(tr.plan.moves[step].index, TransitionRouting.CUT)
        assert tr.complete()
        for key, h in zip(KEYS, hashes):
            assert tr.owner_of_hash(h) == new.shard_of(key)

    def test_mirror_only_while_mirroring(self):
        tr = TransitionRouting(HashRing(2), HashRing(3))
        move = tr.plan.moves[0]
        mid = (move.lo + move.hi) // 2
        assert tr.mirror_of_hash(mid) is None
        tr.set_state(move.index, TransitionRouting.MIRRORING)
        assert tr.mirror_of_hash(mid) is move
        assert tr.owner_of_hash(mid) == move.src  # authority unchanged
        tr.set_state(move.index, TransitionRouting.CUT)
        assert tr.mirror_of_hash(mid) is None
        assert tr.owner_of_hash(mid) == move.dst


# --------------------------------------------------------------------------
# the chunk protocol + range fence primitives
# --------------------------------------------------------------------------


class TestSlicePrimitives:
    def test_chunk_roundtrip_and_torn_detection(self):
        blob = bytes(range(256)) * 100
        source = SliceChunkSource(blob, max_chunk=1000)
        sink = SliceChunkSink()
        while not sink.done:
            chunk = source.chunk(sink.offset(), sink.sha_hex())
            sink.feed(chunk)
        assert sink.payload() == blob
        # a corrupted chunk MUST be refused by the hash check
        source2 = SliceChunkSource(blob, max_chunk=1000)
        sink2 = SliceChunkSink()
        chunk = source2.chunk(0, "")
        data = bytearray(chunk["data"])
        data[10] ^= 0xFF
        with pytest.raises(ReplicationDiverged):
            sink2.feed(dict(chunk, data=bytes(data)))
        assert sink2.offset() == 0  # nothing of the bad chunk kept

    def test_range_fence_covers_and_lifts(self):
        fence = RangeFence()
        fence.fence("h1", [(100, 200), (300, 400)], epoch=1)
        assert fence.covers(150) and fence.covers(399)
        assert not fence.covers(200) and not fence.covers(250)
        fence.refuse(3)
        assert fence.refused() == 3
        assert fence.lift("h1")
        assert not fence.covers(150)
        assert not fence.lift("h1")  # idempotent


# --------------------------------------------------------------------------
# in-process handoff end to end
# --------------------------------------------------------------------------


def build_front(n_shards, core_faults=None, prepare_ttl=30.0):
    front = AdmissionFront(n_shards)
    cores = []
    for i in range(n_shards):
        core = ShardCore(
            i, n_shards, use_device=False, faults=core_faults,
            prepare_ttl=prepare_ttl,
        )
        cores.append(core)
        front.attach_shard(i, LocalShard(i, core, on_push=front.apply_status_push))
    return front, cores


def seed_population(front, n_throttles=24, n_pods=150):
    front.store.create_namespace(Namespace("default"))
    for i in range(n_throttles):
        front.store.create_throttle(H.make_throttle(i))
    pods = []
    for i in range(n_pods):
        pod = make_pod(
            f"p{i}", labels={"grp": f"g{i % n_throttles}"},
            requests={"cpu": "100m"},
        )
        front.store.create_pod(pod)
        pods.append(pod)
    assert front.drain(60.0)
    time.sleep(0.3)
    return pods


def grow(front, cores, n_new, coord_faults=None):
    n_old = front.n_shards
    front.n_shards = max(n_old, n_new)
    for sid in range(n_old, n_new):
        core = ShardCore(sid, n_new, use_device=False)
        cores.append(core)
        front.attach_shard(sid, LocalShard(sid, core, on_push=front.apply_status_push))
        front.resync_shard(sid)
    return ReshardCoordinator(front, faults=coord_faults)


def assert_oracle_equivalent(front):
    store = Store()
    store.create_namespace(Namespace("default"))
    for thr in front.store.list_throttles():
        store.create_throttle(thr)
    for pod in front.store.list_pods():
        store.create_pod(pod)
    oracle = H.build_plugin(store)
    oracle.run_pending_once()
    try:
        for pod in store.list_pods():
            got = front.pre_filter(pod)
            want = oracle.pre_filter(pod)
            assert got.code == want.code, pod.key
            assert H.normalized_reasons(got.reasons) == H.normalized_reasons(
                want.reasons
            ), pod.key
    finally:
        oracle.stop()


def assert_audits_clean(front, n_shards):
    for sid in range(n_shards):
        audit = front.shards[sid].request("reshard_audit", None)
        assert audit["orphan_reservations"] == [], (sid, audit)
        assert audit["pending_handoffs"] == 0, (sid, audit)
        assert audit["fenced_handoffs"] == [], (sid, audit)


def teardown(front, cores):
    for core in cores:
        core.stop()
    front.stop()


class TestLiveReshard:
    def test_split_then_merge_keeps_verdicts_and_moves_keys(self):
        front, cores = build_front(2)
        try:
            seed_population(front)
            report = grow(front, cores, 3).rescale(HashRing(3), deadline_s=60.0)
            assert report["aborts"] == 0
            assert report["keys_cut"] > 0
            assert front.n_shards == 3
            # shard 2 now authoritatively owns keys
            with front._route_lock:
                owners = set(front._owner.values())
            assert 2 in owners
            # merge back 3 -> 2: shard 2 must end up owning nothing
            report = ReshardCoordinator(front).rescale(
                HashRing(2), deadline_s=60.0
            )
            assert report["keys_cut"] > 0
            with front._route_lock:
                owners = set(front._owner.values())
            assert 2 not in owners
            assert front.drain(60.0)
            time.sleep(0.3)
            assert_oracle_equivalent(front)
            assert_audits_clean(front, 3)
        finally:
            teardown(front, cores)

    def test_reservations_and_gangs_move_with_their_ranges(self):
        front, cores = build_front(2)
        try:
            pods = seed_population(front)
            for pod in pods[:12]:
                assert front.reserve(pod).is_success()
            gang_pods = [
                make_pod(
                    f"gp{i}", labels={"grp": "g3"}, requests={"cpu": "50m"},
                    group="default/gg1", group_size=3,
                )
                for i in range(3)
            ]
            for pod in gang_pods:
                front.store.create_pod(pod)
            assert front.drain(60.0)
            assert front.reserve_gang("default/gg1", gang_pods).is_success()
            owner_before = front.gang_owner("default/gg1")
            assert front.shards[owner_before].request("gang_groups", None) == [
                "default/gg1"
            ]
            grow(front, cores, 3).rescale(HashRing(3), deadline_s=60.0)
            assert front.drain(60.0)
            time.sleep(0.3)
            # the authoritative ledger record lives on exactly the (new)
            # hash owner — moved if its range moved, untouched otherwise
            owner_after = front.gang_owner("default/gg1")
            holders = [
                sid for sid in range(3)
                if front.shards[sid].request("gang_groups", None)
            ]
            assert holders == [owner_after]
            assert_audits_clean(front, 3)
            # reservations stayed release-able after the move: unreserve
            # everywhere, then nothing may remain reserved anywhere
            for pod in pods[:12]:
                front.unreserve(pod)
            front.unreserve_gang("default/gg1")
            for pod in gang_pods:
                front.unreserve(pod)
            stats = front.stats()
            assert all(
                s.get("reservations", 0) == 0
                for s in stats["shards"].values()
                if s.get("alive")
            ), stats
        finally:
            teardown(front, cores)

    def test_torn_stream_aborts_back_to_source_then_retry_lands(self):
        plan = FaultPlan(seed=1).rule("reshard.handoff.torn", mode="torn", times=1)
        front, cores = build_front(2, core_faults=plan)
        try:
            seed_population(front)
            report = grow(front, cores, 3).rescale(HashRing(3), deadline_s=60.0)
            assert plan.fired("reshard.handoff.torn") == 1
            assert report["aborts"] >= 1
            assert report["retries"] >= 1
            assert front.drain(60.0)
            time.sleep(0.3)
            assert_oracle_equivalent(front)
            assert_audits_clean(front, 3)
        finally:
            teardown(front, cores)

    def test_fence_race_aborts_and_unfences(self):
        plan = FaultPlan(seed=3).rule("reshard.fence.race", mode="error", times=1)
        front, cores = build_front(2)
        try:
            seed_population(front)
            report = grow(front, cores, 3, coord_faults=plan).rescale(
                HashRing(3), deadline_s=60.0
            )
            assert plan.fired("reshard.fence.race") == 1
            assert report["aborts"] >= 1
            assert front.drain(60.0)
            time.sleep(0.3)
            # the abort lifted the fence: no standing fence anywhere
            assert_audits_clean(front, 3)
            assert_oracle_equivalent(front)
        finally:
            teardown(front, cores)

    def test_front_crash_orphans_are_ttl_reaped_with_zero_orphan_reservations(self):
        plan = FaultPlan(seed=2).rule("reshard.front.crash", mode="error", times=1)
        front, cores = build_front(2)
        try:
            pods = seed_population(front)
            for pod in pods[:8]:
                assert front.reserve(pod).is_success()
            coordinator = grow(front, cores, 3, coord_faults=plan)
            with pytest.raises(CoordinatorCrash):
                coordinator.rescale(HashRing(3), deadline_s=60.0)
            # the orphaned handoff is pending on both sides (staged blob
            # + fence on the source would follow; here prepare+import ran)
            pending = sum(
                front.shards[sid].request("reshard_audit", None)[
                    "pending_handoffs"
                ]
                for sid in range(3)
            )
            assert pending >= 1
            # the two-phase reaper TTLs it out on both ends
            for core in cores:
                core.prepare_ttl = 0.0
                core.reap_stale_txns()
            assert_audits_clean(front, 3)
            # the source never lost authority: a fresh coordinator (the
            # restarted front) completes the retarget cleanly
            report = ReshardCoordinator(front).rescale(
                HashRing(3), deadline_s=60.0
            )
            assert report["aborts"] == 0
            assert front.drain(60.0)
            time.sleep(0.3)
            assert_oracle_equivalent(front)
            assert_audits_clean(front, 3)
        finally:
            teardown(front, cores)

    def test_fenced_range_refuses_post_cutover_writes(self):
        front, cores = build_front(2)
        try:
            seed_population(front)
            # fence shard 0's entire keyspace by hand and push a spec
            # write at it: the worker must drop it and count the refusal
            core = cores[0]
            core.range_fence.fence("manual", [(0, 1 << 64)], epoch=99)
            thr = H.make_throttle(0)
            core.handle_events([("upsert", "Throttle", thr)])
            assert core.range_fence.refused() >= 1
            core.range_fence.lift("manual")
            core.handle_events([("upsert", "Throttle", thr)])
            assert core.range_fence.refused() == 1  # unchanged after lift
        finally:
            teardown(front, cores)


# --------------------------------------------------------------------------
# hunt integration (satellites): mutators + shard-tier routing
# --------------------------------------------------------------------------


class TestHuntReshardSurface:
    def test_reshard_sites_are_mutable_and_known(self):
        from kube_throttler_tpu.faults.plan import KNOWN_SITES
        from kube_throttler_tpu.scenarios.hunt.mutate import MUTABLE_FAULT_SITES

        for site in (
            "reshard.handoff.torn",
            "reshard.dest.crash",
            "reshard.fence.race",
            "reshard.front.crash",
            "shard.worker.kill",
        ):
            assert site in MUTABLE_FAULT_SITES
            assert site in KNOWN_SITES

    def test_needs_shard_tier_routing(self):
        from kube_throttler_tpu.scenarios.dsl import FaultSpec, Scenario
        from kube_throttler_tpu.scenarios.hunt.mutate import needs_shard_tier

        plain = Scenario(name="x", description="x")
        assert not needs_shard_tier(plain)
        armed = Scenario(
            name="x", description="x",
            faults=(FaultSpec(site="shard.worker.kill", mode="kill"),),
        )
        assert needs_shard_tier(armed)
        armed2 = Scenario(
            name="x", description="x",
            faults=(FaultSpec(site="reshard.dest.crash", mode="kill"),),
        )
        assert needs_shard_tier(armed2)

    def test_gang_accel_axes_reach_topology_and_trace(self):
        from kube_throttler_tpu.scenarios.dsl import Scenario, Topology
        from kube_throttler_tpu.scenarios.trace import build_topology, build_trace

        scn = Scenario(
            name="axes", description="x", duration_s=1.5,
            topology=Topology(
                pods=300, throttles=24, groups=12, gang_size=4,
                accel_classes=3, class_threshold_frac=0.4,
            ),
        )
        topo = build_topology(scn, 0)
        acls = {p.get("acl") for p in topo["pods"]}
        assert acls == {"ac0", "ac1", "ac2"}
        gangs = {p["gang"] for p in topo["pods"]}
        assert gangs and all(g.startswith("gg-") for g in gangs)
        _header, ops = build_trace(scn, 0)
        annotated = [op for op in ops if "acl" in op]
        assert annotated, "trace ops must carry the accel axis"

    def test_axes_off_keeps_committed_traces_byte_identical(self):
        """The new Topology fields default OFF and must not perturb one
        byte of an existing committed trace."""
        from kube_throttler_tpu.scenarios.corpus import get_scenario
        from kube_throttler_tpu.scenarios.trace import (
            build_trace,
            serialize_trace,
            trace_sha256,
        )

        scn = get_scenario("smoke")
        header, ops = build_trace(scn, 0)
        sha_a = trace_sha256(serialize_trace(header, ops))
        header2, ops2 = build_trace(scn, 0)
        sha_b = trace_sha256(serialize_trace(header2, ops2))
        assert sha_a == sha_b
        assert not any("acl" in op or "gang" in op for op in ops)

    def test_mutators_cover_gang_and_accel_axes(self):
        import random

        from kube_throttler_tpu.scenarios.hunt.loop import base_programs
        from kube_throttler_tpu.scenarios.hunt.mutate import (
            BOUNDS,
            _mut_topology_accel,
            _mut_topology_gang,
            normalize,
        )

        base = base_programs()[0]
        rng = random.Random(7)
        child = normalize(_mut_topology_gang(base, rng))
        assert BOUNDS["gang_size"][0] <= child.topology.gang_size <= BOUNDS["gang_size"][1]
        child2 = normalize(_mut_topology_accel(base, rng))
        assert 0 <= child2.topology.accel_classes <= BOUNDS["accel_classes"][1]
        if child2.topology.accel_classes:
            assert child2.topology.class_threshold_frac > 0

    def test_reshard_metrics_registered(self):
        from kube_throttler_tpu.metrics import METRIC_NAMES

        for name in (
            "kube_throttler_reshard_ranges_moving",
            "kube_throttler_reshard_handoff_bytes_total",
            "kube_throttler_reshard_handoff_events_total",
            "kube_throttler_reshard_cutover_duration_seconds",
            "kube_throttler_reshard_aborted_total",
        ):
            assert name in METRIC_NAMES
