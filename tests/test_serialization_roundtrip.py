"""Round-trip property test for the manifest serialization layer:
typed object → dict → typed object must be identity for every generated
Throttle/ClusterThrottle shape (selectors incl. matchExpressions,
overrides, thresholds, statuses)."""

import random

import pytest

from kube_throttler_tpu.api.serialization import (
    cluster_throttle_to_dict,
    object_from_dict,
    throttle_to_dict,
)
from kube_throttler_tpu.api.types import ResourceAmount, ThrottleStatus

from tests.test_differential_soak import (
    NOW,
    _rand_overrides,
    _rand_selector,
    _rand_threshold,
)


def _rand_status(rng):
    from kube_throttler_tpu.api.types import CalculatedThreshold

    used = _rand_threshold(rng)
    thr = _rand_threshold(rng)
    return ThrottleStatus(
        used=used,
        throttled=thr.is_throttled(used, True),
        calculated_threshold=CalculatedThreshold(
            threshold=thr, calculated_at=NOW if rng.random() < 0.5 else None,
            messages=("override window active",) if rng.random() < 0.3 else (),
        ),
    )


@pytest.mark.parametrize("seed", range(5))
def test_throttle_roundtrip(seed):
    from kube_throttler_tpu.api.types import Throttle, ThrottleSpec

    rng = random.Random(seed)
    for i in range(20):
        thr = Throttle(
            name=f"t{i}",
            namespace=rng.choice(["default", "ns1"]),
            uid=f"u{i}",
            spec=ThrottleSpec(
                throttler_name="kube-throttler",
                threshold=_rand_threshold(rng),
                temporary_threshold_overrides=_rand_overrides(rng),
                selector=_rand_selector(rng, cluster=False),
            ),
            status=_rand_status(rng),
        )
        back = object_from_dict(throttle_to_dict(thr))
        assert back == thr, f"seed={seed} i={i}"


@pytest.mark.parametrize("seed", range(5))
def test_cluster_throttle_roundtrip(seed):
    from kube_throttler_tpu.api.types import ClusterThrottle, ClusterThrottleSpec

    rng = random.Random(seed + 100)
    for i in range(20):
        ct = ClusterThrottle(
            name=f"ct{i}",
            uid=f"u{i}",
            spec=ClusterThrottleSpec(
                throttler_name="kube-throttler",
                threshold=_rand_threshold(rng),
                temporary_threshold_overrides=_rand_overrides(rng),
                selector=_rand_selector(rng, cluster=True),
            ),
            status=_rand_status(rng),
        )
        back = object_from_dict(cluster_throttle_to_dict(ct))
        assert back == ct, f"seed={seed} i={i}"


def test_reference_field_name_typo_accepted():
    """The reference's `selecterTerms` JSON typo (throttle_selector.go:27)
    must be accepted on input alongside the corrected spelling."""
    base = {
        "apiVersion": "schedule.k8s.everpeace.github.com/v1alpha1",
        "kind": "Throttle",
        "metadata": {"name": "t", "namespace": "default"},
        "spec": {
            "throttlerName": "kt",
            "threshold": {"resourceRequests": {"cpu": "1"}},
        },
    }
    sel = [{"podSelector": {"matchLabels": {"a": "b"}}}]
    d1 = {**base, "spec": {**base["spec"], "selectorTerms": None, "selector": {"selectorTerms": sel}}}
    d2 = {**base, "spec": {**base["spec"], "selector": {"selecterTerms": sel}}}
    t1, t2 = object_from_dict(d1), object_from_dict(d2)
    assert t1.spec.selector == t2.spec.selector
