"""The embedded scheduler loop: schedule-one cycle, burst admission with
reservation-based double-count prevention, event-driven requeue of
unschedulable pods (reference integration scenarios throttle_test.go and
the WakeupBackoffPod hint, driven here without a cluster)."""

import threading
import time
from dataclasses import replace

from kube_throttler_tpu.api import (
    LabelSelector,
    ResourceAmount,
    Throttle,
    ThrottleSelector,
    ThrottleSelectorTerm,
    ThrottleSpec,
)
from kube_throttler_tpu.api.pod import Namespace, make_pod
from kube_throttler_tpu.engine.store import Store
from kube_throttler_tpu.plugin import KubeThrottler, decode_plugin_args
from kube_throttler_tpu.plugin.framework import RecordingEventRecorder
from kube_throttler_tpu.scheduler import Node, Scheduler


def _setup(nodes=None, use_device=False):
    store = Store()
    store.create_namespace(Namespace("default"))
    recorder = RecordingEventRecorder()
    plugin = KubeThrottler(
        decode_plugin_args({"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}),
        store,
        event_recorder=recorder,
        use_device=use_device,
    )
    sched = Scheduler(plugin, store, nodes=nodes)
    return store, plugin, sched, recorder


def _throttle(name, pod=None, cpu=None):
    requests = {"cpu": cpu} if cpu else None
    return Throttle(
        name=name,
        spec=ThrottleSpec(
            throttler_name="kube-throttler",
            threshold=ResourceAmount.of(pod=pod, requests=requests),
            selector=ThrottleSelector(
                selector_terms=(
                    ThrottleSelectorTerm(LabelSelector(match_labels={"throttle": name})),
                )
            ),
        ),
    )


class TestScheduleOne:
    def test_binds_pending_pod_and_it_counts_into_used(self):
        store, plugin, sched, _ = _setup()
        store.create_throttle(_throttle("t1", pod=5))
        store.create_pod(make_pod("p1", labels={"throttle": "t1"}, requests={"cpu": "100m"}))
        bound = sched.run_until_idle()
        assert bound == 1
        pod = store.get_pod("default", "p1")
        assert pod.spec.node_name == "node-1"
        status = store.get_throttle("default", "t1").status
        assert status.used.resource_counts == 1

    def test_wrong_scheduler_name_ignored(self):
        store, plugin, sched, _ = _setup()
        store.create_pod(make_pod("alien", scheduler_name="other-scheduler"))
        assert sched.run_until_idle() == 0
        assert store.get_pod("default", "alien").spec.node_name == ""

    def test_node_capacity_limits_binding(self):
        store, plugin, sched, recorder = _setup(nodes=[Node("tiny", max_pods=2)])
        for i in range(3):
            store.create_pod(make_pod(f"p{i}"))
        assert sched.run_until_idle() == 2
        assert sched.pending_count() == 1
        assert any(
            e.reason == "FailedScheduling" and "nodes are available" in e.note
            for e in recorder.events
        )

    def test_node_allocatable_limits_binding(self):
        """NodeResourcesFit analog: cpu=1 node fits exactly two 500m pods;
        deleting one frees the capacity and the parked pod binds."""
        store, plugin, sched, recorder = _setup(
            nodes=[Node("small", allocatable={"cpu": "1"})]
        )
        for i in range(3):
            store.create_pod(make_pod(f"p{i}", requests={"cpu": "500m"}))
        assert sched.run_until_idle() == 2
        assert sched.pending_count() == 1
        assert any(
            e.reason == "FailedScheduling" and "nodes are available" in e.note
            for e in recorder.events
        )
        bound = [p for p in store.list_pods() if p.spec.node_name]
        store.delete_pod(bound[0].namespace, bound[0].name)
        assert sched.run_until_idle() == 1  # freed capacity admits the third
        assert sum(1 for p in store.list_pods() if p.spec.node_name) == 2

    def test_undeclared_resource_never_fits(self):
        store, plugin, sched, _ = _setup(
            nodes=[Node("cpu-only", allocatable={"cpu": "64"})]
        )
        store.create_pod(
            make_pod("gpu-pod", requests={"cpu": "100m", "nvidia.com/gpu": "1"})
        )
        assert sched.run_until_idle() == 0
        assert sched.pending_count() == 1

    def test_zero_request_for_undeclared_resource_still_fits(self):
        """NodeResourcesFit skips zero requests: a 0-gpu request must not
        block binding on a cpu-only node."""
        store, plugin, sched, _ = _setup(
            nodes=[Node("cpu-only", allocatable={"cpu": "64"})]
        )
        store.create_pod(
            make_pod("zero-gpu", requests={"cpu": "100m", "nvidia.com/gpu": "0"})
        )
        assert sched.run_until_idle() == 1

    def test_resource_blind_node_still_binds_anything(self):
        store, plugin, sched, _ = _setup(nodes=[Node("blind")])
        store.create_pod(make_pod("big", requests={"cpu": "10000"}))
        assert sched.run_until_idle() == 1


class TestBurstAdmission:
    def test_21_pods_exactly_20_fit_under_1_cpu(self):
        """throttle_test.go:167-197 — the reserve path must prevent
        double-admission inside a burst."""
        store, plugin, sched, recorder = _setup()
        store.create_throttle(_throttle("t1", cpu="1"))
        plugin.run_pending_once()
        for i in range(21):
            store.create_pod(
                make_pod(f"burst-{i:02d}", labels={"throttle": "t1"}, requests={"cpu": "50m"})
            )
        bound = sched.run_until_idle()
        assert bound == 20
        scheduled = [p for p in store.list_pods() if p.is_scheduled()]
        assert len(scheduled) == 20
        assert sched.pending_count() == 1
        status = store.get_throttle("default", "t1").status
        assert status.used.resource_requests["cpu"] == 1
        assert status.throttled.resource_requests["cpu"] is True
        assert any(e.reason == "FailedScheduling" for e in recorder.events)

    def test_pod_count_threshold_burst(self):
        store, plugin, sched, _ = _setup()
        store.create_throttle(_throttle("t1", pod=3))
        plugin.run_pending_once()
        for i in range(5):
            store.create_pod(make_pod(f"p{i}", labels={"throttle": "t1"}))
        assert sched.run_until_idle() == 3
        assert sched.pending_count() == 2


class TestEventDrivenRequeue:
    def test_threshold_edit_wakes_pending_pod(self):
        """README walkthrough: pod2 stays Pending under the old threshold and
        schedules after the threshold edit (a Throttle MODIFIED hint)."""
        store, plugin, sched, _ = _setup()
        store.create_throttle(_throttle("t1", cpu="200m"))
        store.create_pod(make_pod("pod1", labels={"throttle": "t1"}, requests={"cpu": "200m"}))
        assert sched.run_until_idle() == 1
        store.create_pod(make_pod("pod2", labels={"throttle": "t1"}, requests={"cpu": "300m"}))
        assert sched.run_until_idle() == 0
        assert sched.pending_count() == 1

        thr = store.get_throttle("default", "t1")
        new_spec = replace(thr.spec, threshold=ResourceAmount.of(requests={"cpu": "700m"}))
        store.update_throttle_spec(replace(thr, spec=new_spec))
        assert sched.run_until_idle() == 1
        assert store.get_pod("default", "pod2").is_scheduled()

    def test_pod_delete_frees_capacity_and_requeues(self):
        store, plugin, sched, _ = _setup()
        store.create_throttle(_throttle("t1", pod=1))
        store.create_pod(make_pod("p1", labels={"throttle": "t1"}))
        assert sched.run_until_idle() == 1
        store.create_pod(make_pod("p2", labels={"throttle": "t1"}))
        assert sched.run_until_idle() == 0
        store.delete_pod("default", "p1")
        assert sched.run_until_idle() == 1
        assert store.get_pod("default", "p2").is_scheduled()

    def test_node_poke_requeues_backed_off_pod(self):
        """The WakeupBackoffPod hack (util_pod_test.go:206-225): a Node event
        retries unschedulable pods without any throttle change."""
        store, plugin, sched, _ = _setup(nodes=[Node("n1", max_pods=0)])
        store.create_pod(make_pod("p1"))
        assert sched.run_until_idle() == 0
        sched.nodes[0].max_pods = 10  # capacity appears out-of-band
        assert sched.run_until_idle(settle=False) == 0  # nothing requeued it yet
        sched.poke_nodes()
        assert sched.run_until_idle() == 1


class TestNodeOccupancy:
    def test_delete_frees_node_capacity_under_churn(self):
        """Bind/delete churn beyond max_pods must not exhaust the node: the
        slot is freed on pod deletion (occupancy is event-driven, not a
        high-water mark)."""
        store, plugin, sched, _ = _setup(nodes=[Node("n1", max_pods=2)])
        for i in range(6):
            store.create_pod(make_pod(f"churn-{i}"))
            assert sched.run_until_idle() >= 1, f"churn round {i} failed to bind"
            store.delete_pod("default", f"churn-{i}")
        assert sched._bound_per_node["n1"] == 0

    def test_terminal_phase_frees_slot(self):
        store, plugin, sched, _ = _setup(nodes=[Node("n1", max_pods=1)])
        store.create_pod(make_pod("p1"))
        assert sched.run_until_idle() == 1
        p1 = store.get_pod("default", "p1")
        store.update_pod(replace(p1, status=replace(p1.status, phase="Succeeded")))
        assert sched._bound_per_node["n1"] == 0
        store.create_pod(make_pod("p2"))
        assert sched.run_until_idle() == 1

    def test_preexisting_bound_pods_counted_via_replay(self):
        store = Store()
        store.create_namespace(Namespace("default"))
        store.create_pod(make_pod("existing", node_name="n1"))
        plugin = KubeThrottler(
            decode_plugin_args(
                {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
            ),
            store,
            use_device=False,
        )
        sched = Scheduler(plugin, store, nodes=[Node("n1", max_pods=1)])
        assert sched._bound_per_node["n1"] == 1
        store.create_pod(make_pod("p2"))
        assert sched.run_until_idle() == 0  # node already full

    def test_sync_drain_then_realtime_loop_not_stranded(self):
        """A pod parked during an inf-clock sync drain must stay eligible for
        the real-time loop (backoff anchors to the real clock, not inf)."""
        store, plugin, sched, _ = _setup(nodes=[Node("n1", max_pods=0)])
        store.create_pod(make_pod("p1"))
        assert sched.run_until_idle() == 0
        sched.nodes[0].max_pods = 1
        sched.poke_nodes()
        deadline = time.monotonic() + 10
        key = None
        while key is None and time.monotonic() < deadline:
            key = sched.schedule_one()  # real clock
            if key is None:
                time.sleep(0.01)
        assert key == "default/p1"


class TestConcurrentPatch:
    def test_parallel_patches_both_land(self):
        from kube_throttler_tpu.client import new_fake_clientset

        cs = new_fake_clientset()
        api = cs.schedule_v1alpha1().cluster_throttles()
        from kube_throttler_tpu.api import (
            ClusterThrottle,
            ClusterThrottleSpec,
        )

        api.create(ClusterThrottle(name="ct", spec=ClusterThrottleSpec()))
        errs = []

        def patch_many(field, n):
            try:
                for i in range(n):
                    api.patch(
                        "ct", {"spec": {"threshold": {"resourceRequests": {field: str(i + 1)}}}}
                    )
            except Exception as e:  # pragma: no cover
                errs.append(e)

        t1 = threading.Thread(target=patch_many, args=("cpu", 50))
        t2 = threading.Thread(target=patch_many, args=("memory", 50))
        t1.start(), t2.start(), t1.join(), t2.join()
        assert errs == []
        reqs = api.get("ct").spec.threshold.resource_requests
        # both writers' final values survive — no lost updates
        assert reqs["cpu"] == 50 and reqs["memory"] == 50


class TestBackgroundLoop:
    def test_threaded_scheduler_drains_burst(self):
        store, plugin, sched, _ = _setup()
        store.create_throttle(_throttle("t1", pod=10))
        plugin.start()  # controller worker threads
        sched.start()
        try:
            for i in range(10):
                store.create_pod(make_pod(f"p{i}", labels={"throttle": "t1"}))
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                if sum(p.is_scheduled() for p in store.list_pods()) == 10:
                    break
                time.sleep(0.02)
            assert sum(p.is_scheduled() for p in store.list_pods()) == 10
        finally:
            sched.stop()
            plugin.stop()


class TestRequeueRaces:
    """Regression tests for the three scheduler findings: delete-wake,
    mid-cycle lost wakeup, and non-atomic bind."""

    def test_pod_delete_frees_slot_and_wakes_parked_pods(self):
        # Node(max_pods=1), NO throttles: p1 binds, p2 parks on "0/1 nodes
        # available". Deleting p1 must requeue p2 without any throttle event.
        store, plugin, sched, _ = _setup(nodes=[Node("n1", max_pods=1)])
        store.create_pod(make_pod("p1", requests={"cpu": "1m"}))
        store.create_pod(make_pod("p2", requests={"cpu": "1m"}))
        assert sched.run_until_idle() == 1
        assert len(sched._unschedulable) == 1
        store.delete_pod("default", "p1")
        # the DELETED handler freed the slot and moved p2 back to active
        assert not sched._unschedulable
        assert sched.run_until_idle() == 1
        assert store.get_pod("default", "p2").is_scheduled()

    def test_wake_during_cycle_keeps_pod_active(self):
        # A requeue hint that fires while the pod is popped (pre-park) must
        # not be lost: the pod re-enters _active instead of _unschedulable.
        store, plugin, sched, _ = _setup()
        store.create_throttle(_throttle("t1", cpu="100m"))
        store.create_pod(make_pod("p1", labels={"throttle": "t1"}, requests={"cpu": "500m"}))
        plugin.run_pending_once()

        orig = plugin.pre_filter

        def pre_filter_with_midcycle_event(pod):
            status = orig(pod)
            # a threshold edit lands while this cycle is in flight
            thr = store.get_throttle("default", "t1")
            store.update_throttle_spec(
                replace(thr, spec=replace(thr.spec, threshold=ResourceAmount.of(requests={"cpu": "1"})))
            )
            return status

        plugin.pre_filter = pre_filter_with_midcycle_event
        assert sched.schedule_one(now=float("inf")) is None  # blocked by stale state
        plugin.pre_filter = orig
        # the mid-cycle wake kept p1 in the active queue
        assert not sched._unschedulable and len(sched._active) == 1
        assert sched.run_until_idle() == 1

    def test_bind_preserves_concurrent_pod_patch(self):
        # A label patch landing between the cycle's read and its bind write
        # must survive the bind (bind sets only spec.nodeName).
        store, plugin, sched, _ = _setup()
        store.create_pod(make_pod("p1", requests={"cpu": "1m"}))

        orig = plugin.pre_filter

        def pre_filter_with_patch(pod):
            status = orig(pod)
            store.mutate(
                "Pod", pod.key,
                lambda cur: replace(cur, labels={**cur.labels, "patched": "yes"}),
            )
            return status

        plugin.pre_filter = pre_filter_with_patch
        assert sched.run_until_idle() == 1
        final = store.get_pod("default", "p1")
        assert final.is_scheduled()
        assert final.labels.get("patched") == "yes"
