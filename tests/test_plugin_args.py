"""Plugin-args decoding table tests (reference plugin_args.go:29-60).

The duration grammar mirrors Go ``time.ParseDuration`` exactly: the
reference's args decode through ``fwkruntime.DecodeInto`` → ParseDuration,
which rejects trailing garbage and unit-less numbers — config typos fail
loudly instead of silently truncating.
"""

from datetime import timedelta

import pytest

from kube_throttler_tpu.plugin.args import (
    DEFAULT_RECONCILE_TEMPORARY_THRESHOLD_INTERVAL,
    _parse_go_duration,
    decode_plugin_args,
)


# (input, expected seconds) — the accept table matches Go's ParseDuration
ACCEPT = [
    ("0", 0.0),
    ("+0", 0.0),
    ("-0", 0.0),
    ("15s", 15.0),
    ("500ms", 0.5),
    ("1m30s", 90.0),
    ("1.5h", 5400.0),
    (".5s", 0.5),
    ("2.s", 2.0),
    ("1h2m3s", 3723.0),
    ("100ns", 1e-7),
    ("250us", 0.00025),
    ("250µs", 0.00025),  # U+00B5 micro sign
    ("250μs", 0.00025),  # U+03BC greek mu
    ("-1m", -60.0),
    ("+2s", 2.0),
    ("1m1m", 120.0),  # repeated units are legal in Go
]

REJECT = [
    "",
    "garbage",
    "15sgarbage",  # the VERDICT repro: trailing garbage must fail
    "15",  # unit required (only bare "0" is exempt)
    "s",
    ".s",
    "-",
    "+",
    "1d",  # Go has no day unit
    "1.2.3s",
    "15s ",  # whitespace is not part of the grammar
    " 15s",
    "0x1s",
]


@pytest.mark.parametrize("text,seconds", ACCEPT)
def test_go_duration_accepts(text, seconds):
    assert _parse_go_duration(text) == pytest.approx(
        timedelta(seconds=seconds), abs=timedelta(microseconds=1)
    )


@pytest.mark.parametrize("text", REJECT)
def test_go_duration_rejects(text):
    with pytest.raises(ValueError):
        _parse_go_duration(text)


def test_decode_requires_name_and_target():
    with pytest.raises(ValueError, match="Name"):
        decode_plugin_args({"targetSchedulerName": "sched"})
    with pytest.raises(ValueError, match="TargetSchedulerName"):
        decode_plugin_args({"name": "kt"})


def test_decode_interval_default_and_parse():
    base = {"name": "kt", "targetSchedulerName": "sched"}
    assert (
        decode_plugin_args(base).reconcile_temporary_threshold_interval
        == DEFAULT_RECONCILE_TEMPORARY_THRESHOLD_INTERVAL
    )
    got = decode_plugin_args(
        {**base, "reconcileTemporaryThresholdInterval": "1m30s"}
    )
    assert got.reconcile_temporary_threshold_interval == timedelta(seconds=90)


def test_decode_negative_interval_rejected():
    # the parser is faithful to Go (sign parses), but a negative resync
    # interval would busy-loop the workqueue — decode must refuse it
    base = {"name": "kt", "targetSchedulerName": "sched"}
    with pytest.raises(ValueError, match="negative"):
        decode_plugin_args(
            {**base, "reconcileTemporaryThresholdInterval": "-15s"}
        )


def test_decode_interval_garbage_fails_loudly():
    base = {"name": "kt", "targetSchedulerName": "sched"}
    with pytest.raises(ValueError, match="invalid duration"):
        decode_plugin_args(
            {**base, "reconcileTemporaryThresholdInterval": "15sgarbage"}
        )


def test_decode_threadiness_typo_compat_key():
    # the Go struct tag is the "controllerThrediness" typo — SURVEY §2.3 quirk
    got = decode_plugin_args(
        {"name": "kt", "targetSchedulerName": "s", "controllerThrediness": 3}
    )
    assert got.controller_threadiness == 3


# ------------------------------------------------- serving-knob parse paths
# The gen-4 envguard sweep's regression pins: every env/CLI knob on the
# PR 15-17 serving surface must fail LOUDLY (CLI usage error, ValueError)
# or fall back to its documented default — never configure a dead or
# fail-open gate from a typo.


class TestServingKnobParsing:
    def test_positive_seconds_accepts_and_rejects(self):
        import argparse

        from kube_throttler_tpu.cli import _positive_seconds

        finite = _positive_seconds(allow_inf=False)
        assert finite("30") == 30.0
        assert finite("0.5") == 0.5
        for bad in ("nan", "-1", "0", "inf", "soon"):
            with pytest.raises(argparse.ArgumentTypeError):
                finite(bad)
        lag = _positive_seconds(allow_inf=True)
        assert lag("inf") == float("inf")  # explicit "never refuse"
        for bad in ("nan", "-3", "0"):
            with pytest.raises(argparse.ArgumentTypeError):
                lag(bad)

    @pytest.mark.parametrize(
        "flag,val",
        [
            ("--replica-max-lag", "nan"),
            ("--replica-max-lag", "-2"),
            ("--shard-rpc-deadline", "nan"),
            ("--shard-rpc-deadline", "inf"),
            ("--shard-rpc-deadline", "0"),
        ],
    )
    def test_cli_rejects_degenerate_durations(self, flag, val):
        from kube_throttler_tpu.cli import main

        with pytest.raises(SystemExit) as ei:
            main(["serve", "--name", "kt", "--target-scheduler-name", "s",
                  flag, val])
        assert ei.value.code == 2  # argparse usage error, pre-serve

    def test_replica_gate_rejects_nan_and_nonpositive(self):
        # admit() refuses on `lag > max_lag_s`; NaN makes that comparison
        # always-False — i.e. a stale replica SERVES forever (fail-open)
        from kube_throttler_tpu.engine.replication import ReplicaGate

        for bad in (float("nan"), 0.0, -5.0):
            with pytest.raises(ValueError, match="positive"):
                ReplicaGate(object(), max_lag_s=bad)

    def test_replica_gate_allows_explicit_inf(self):
        from kube_throttler_tpu.engine.replication import ReplicaGate

        gate = ReplicaGate(object(), max_lag_s=float("inf"))
        assert gate.max_lag_s == float("inf")

    def test_verdict_cache_size_malformed_falls_back_plugin(self, monkeypatch):
        from kube_throttler_tpu.api.pod import Namespace
        from kube_throttler_tpu.engine.store import Store
        from kube_throttler_tpu.plugin import KubeThrottler

        monkeypatch.setenv("KT_VERDICT_CACHE_SIZE", "lots")
        store = Store()
        plugin = KubeThrottler(
            decode_plugin_args({"name": "kt", "targetSchedulerName": "s"}),
            store, use_device=True, start_workers=False,
        )
        assert plugin.verdict_cache is not None
        assert plugin.verdict_cache.capacity == 65536  # documented default

    def test_verdict_cache_size_malformed_falls_back_front(self, monkeypatch):
        from kube_throttler_tpu.sharding.front import AdmissionFront

        monkeypatch.setenv("KT_VERDICT_CACHE_SIZE", "64k")
        front = AdmissionFront(1)
        try:
            if front.verdict_cache is not None:  # arena-gated on this host
                assert front.verdict_cache.capacity == 65536
        finally:
            front.stop()

    def test_verdict_cache_env_disable(self, monkeypatch):
        from kube_throttler_tpu.engine.store import Store
        from kube_throttler_tpu.plugin import KubeThrottler

        monkeypatch.setenv("KT_VERDICT_CACHE", "0")
        plugin = KubeThrottler(
            decode_plugin_args({"name": "kt", "targetSchedulerName": "s"}),
            Store(), use_device=True, start_workers=False,
        )
        assert plugin.verdict_cache is None


class TestAuthKeyResolution:
    def test_env_key_stripped_and_encoded(self, monkeypatch):
        from kube_throttler_tpu.sharding.ipc import load_auth_key

        monkeypatch.setenv("KT_SHARD_AUTH_KEY", "  hunter2\n")
        assert load_auth_key() == b"hunter2"

    def test_blank_env_means_unauthenticated(self, monkeypatch):
        from kube_throttler_tpu.sharding.ipc import load_auth_key

        monkeypatch.setenv("KT_SHARD_AUTH_KEY", "   \n")
        assert load_auth_key() is None
        monkeypatch.delenv("KT_SHARD_AUTH_KEY")
        assert load_auth_key() is None

    def test_key_file_wins_over_env(self, monkeypatch, tmp_path):
        from kube_throttler_tpu.sharding.ipc import load_auth_key

        monkeypatch.setenv("KT_SHARD_AUTH_KEY", "env-key")
        p = tmp_path / "key"
        p.write_bytes(b"file-key\n")
        assert load_auth_key(str(p)) == b"file-key"

    def test_empty_key_file_fails_loudly(self, tmp_path):
        # an empty mounted Secret must NOT silently degrade the fleet to
        # unauthenticated frames
        from kube_throttler_tpu.sharding.ipc import load_auth_key

        p = tmp_path / "key"
        p.write_bytes(b"  \n")
        with pytest.raises(ValueError, match="empty"):
            load_auth_key(str(p))
