"""Plugin-args decoding table tests (reference plugin_args.go:29-60).

The duration grammar mirrors Go ``time.ParseDuration`` exactly: the
reference's args decode through ``fwkruntime.DecodeInto`` → ParseDuration,
which rejects trailing garbage and unit-less numbers — config typos fail
loudly instead of silently truncating.
"""

from datetime import timedelta

import pytest

from kube_throttler_tpu.plugin.args import (
    DEFAULT_RECONCILE_TEMPORARY_THRESHOLD_INTERVAL,
    _parse_go_duration,
    decode_plugin_args,
)


# (input, expected seconds) — the accept table matches Go's ParseDuration
ACCEPT = [
    ("0", 0.0),
    ("+0", 0.0),
    ("-0", 0.0),
    ("15s", 15.0),
    ("500ms", 0.5),
    ("1m30s", 90.0),
    ("1.5h", 5400.0),
    (".5s", 0.5),
    ("2.s", 2.0),
    ("1h2m3s", 3723.0),
    ("100ns", 1e-7),
    ("250us", 0.00025),
    ("250µs", 0.00025),  # U+00B5 micro sign
    ("250μs", 0.00025),  # U+03BC greek mu
    ("-1m", -60.0),
    ("+2s", 2.0),
    ("1m1m", 120.0),  # repeated units are legal in Go
]

REJECT = [
    "",
    "garbage",
    "15sgarbage",  # the VERDICT repro: trailing garbage must fail
    "15",  # unit required (only bare "0" is exempt)
    "s",
    ".s",
    "-",
    "+",
    "1d",  # Go has no day unit
    "1.2.3s",
    "15s ",  # whitespace is not part of the grammar
    " 15s",
    "0x1s",
]


@pytest.mark.parametrize("text,seconds", ACCEPT)
def test_go_duration_accepts(text, seconds):
    assert _parse_go_duration(text) == pytest.approx(
        timedelta(seconds=seconds), abs=timedelta(microseconds=1)
    )


@pytest.mark.parametrize("text", REJECT)
def test_go_duration_rejects(text):
    with pytest.raises(ValueError):
        _parse_go_duration(text)


def test_decode_requires_name_and_target():
    with pytest.raises(ValueError, match="Name"):
        decode_plugin_args({"targetSchedulerName": "sched"})
    with pytest.raises(ValueError, match="TargetSchedulerName"):
        decode_plugin_args({"name": "kt"})


def test_decode_interval_default_and_parse():
    base = {"name": "kt", "targetSchedulerName": "sched"}
    assert (
        decode_plugin_args(base).reconcile_temporary_threshold_interval
        == DEFAULT_RECONCILE_TEMPORARY_THRESHOLD_INTERVAL
    )
    got = decode_plugin_args(
        {**base, "reconcileTemporaryThresholdInterval": "1m30s"}
    )
    assert got.reconcile_temporary_threshold_interval == timedelta(seconds=90)


def test_decode_negative_interval_rejected():
    # the parser is faithful to Go (sign parses), but a negative resync
    # interval would busy-loop the workqueue — decode must refuse it
    base = {"name": "kt", "targetSchedulerName": "sched"}
    with pytest.raises(ValueError, match="negative"):
        decode_plugin_args(
            {**base, "reconcileTemporaryThresholdInterval": "-15s"}
        )


def test_decode_interval_garbage_fails_loudly():
    base = {"name": "kt", "targetSchedulerName": "sched"}
    with pytest.raises(ValueError, match="invalid duration"):
        decode_plugin_args(
            {**base, "reconcileTemporaryThresholdInterval": "15sgarbage"}
        )


def test_decode_threadiness_typo_compat_key():
    # the Go struct tag is the "controllerThrediness" typo — SURVEY §2.3 quirk
    got = decode_plugin_args(
        {"name": "kt", "targetSchedulerName": "s", "controllerThrediness": 3}
    )
    assert got.controller_threadiness == 3
