"""Micro-batched ingest equivalence + group-commit semantics (PR 5).

The load-bearing contract: for ANY partition of an event stream into
micro-batches, the batched pipeline is observably identical to
one-at-a-time ingest — same final store dump, same published ``st_*``
device planes, same ``pre_filter`` verdicts. Deterministic cases pin the
coalescing edge shapes (same-pod runs, delete-after-update, mixed kinds);
the hypothesis property test (importorskip, like test_property_oracle.py)
randomizes streams AND partitions. The batched pending-delta application
is additionally pinned bit-for-bit against the REAL
``apply_pod_deltas_batched`` device kernel.
"""

from __future__ import annotations

import queue
from dataclasses import replace

import numpy as np
import pytest

from kube_throttler_tpu.api.pod import Namespace, make_pod
from kube_throttler_tpu.api.serialization import object_to_dict
from kube_throttler_tpu.api.types import (
    LabelSelector,
    ResourceAmount,
    Throttle,
    ThrottleSelector,
    ThrottleSelectorTerm,
    ThrottleSpec,
)
from kube_throttler_tpu.client.watch import Watch
from kube_throttler_tpu.engine import devicestate as ds_mod
from kube_throttler_tpu.engine.ingest import MicroBatchIngest
from kube_throttler_tpu.engine.store import Event, EventType, Store
from kube_throttler_tpu.faults.plan import FaultPlan
from kube_throttler_tpu.plugin import KubeThrottler, decode_plugin_args


def _throttle(i: int, grp: str, pods: int = 3, cpu: str = "1") -> Throttle:
    return Throttle(
        name=f"t{i}",
        namespace="default",
        spec=ThrottleSpec(
            throttler_name="kube-throttler",
            threshold=ResourceAmount.of(pod=pods, requests={"cpu": cpu}),
            selector=ThrottleSelector(
                selector_terms=(
                    ThrottleSelectorTerm(LabelSelector(match_labels={"grp": grp})),
                )
            ),
        ),
    )


def _pod(name: str, grp: str, cpu_m: int, running: bool = True):
    pod = make_pod(name, labels={"grp": grp}, requests={"cpu": f"{cpu_m}m"})
    if running:
        pod = replace(pod, spec=replace(pod.spec, node_name="node-1"))
        pod.status.phase = "Running"
    return pod


def _build(n_throttles: int = 4):
    store = Store()
    plugin = KubeThrottler(
        decode_plugin_args(
            {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
        ),
        store,
        use_device=True,
        start_workers=False,
    )
    store.create_namespace(Namespace("default"))
    for i in range(n_throttles):
        store.create_throttle(_throttle(i, f"g{i % 2}", pods=2 + i, cpu=str(1 + i)))
    return store, plugin


_NONDETERMINISTIC_KEYS = ("uid", "calculatedAt")


def _strip_uid(doc):
    if isinstance(doc, dict):
        return {
            k: _strip_uid(v)
            for k, v in doc.items()
            if k not in _NONDETERMINISTIC_KEYS
        }
    if isinstance(doc, list):
        return [_strip_uid(v) for v in doc]
    return doc


def _dump(store: Store) -> dict:
    # uids are process-global counters — two independently built stacks
    # assign different ones, so they are normalized out of the comparison
    return _strip_uid(
        {
            "Namespace": {n.name: object_to_dict(n) for n in store.list_namespaces()},
            "Throttle": {t.key: object_to_dict(t) for t in store.list_throttles()},
            "Pod": {p.key: object_to_dict(p) for p in store.list_pods()},
        }
    )


def _verdicts(plugin, store) -> dict:
    out = {}
    for pod in sorted(store.list_pods(), key=lambda p: p.key):
        status = plugin.pre_filter(pod)
        out[pod.key] = (status.code.value, tuple(sorted(status.reasons)))
    return out


def _assert_equivalent(seq, bat):
    """seq/bat = (store, plugin): full observable-equivalence oracle."""
    store_a, plugin_a = seq
    store_b, plugin_b = bat
    assert _dump(store_a) == _dump(store_b)
    # published st_* planes (throttled flags per key, both kinds)
    assert (
        plugin_a.device_manager.published_flags()
        == plugin_b.device_manager.published_flags()
    )
    # aggregates observed through a reconcile settle both sides equally
    plugin_a.run_pending_once()
    plugin_b.run_pending_once()
    assert _dump(store_a) == _dump(store_b)
    assert _verdicts(plugin_a, store_a) == _verdicts(plugin_b, store_b)


def _apply_sequential(store, ops):
    for verb, kind, payload in ops:
        res = store.apply_events([(verb, kind, payload)])
        assert len(res) == 1


def _apply_partition(store, ops, sizes):
    i = 0
    for n in sizes:
        if i >= len(ops):
            break
        store.apply_events(ops[i : i + n])
        i += n
    if i < len(ops):
        store.apply_events(ops[i:])


class TestBatchedIngestEquivalence:
    def _ops_basic(self):
        ops = []
        for i in range(8):
            ops.append(("create", "Pod", _pod(f"p{i}", f"g{i % 2}", 100 * (1 + i % 7))))
        # same-pod run: three updates + the telescoping edge
        for cpu in (300, 500, 200):
            ops.append(("update", "Pod", _pod("p0", "g0", cpu)))
        # relabel mid-batch (mask row moves; row_stable must NOT trigger)
        ops.append(("update", "Pod", _pod("p1", "g0", 400)))
        # delete-after-update in one batch
        ops.append(("update", "Pod", _pod("p2", "g0", 700)))
        ops.append(("delete", "Pod", "default/p2"))
        # a pod that matches nothing
        ops.append(("create", "Pod", _pod("px", "nomatch", 100)))
        # pending (not scheduled) pod — not counted, still indexed
        ops.append(("create", "Pod", _pod("py", "g1", 100, running=False)))
        return ops

    @pytest.mark.parametrize("sizes", [(1,), (2, 3), (5,), (64,), (1, 7, 2)])
    def test_partitions_equivalent(self, sizes):
        seq = _build()
        bat = _build()
        ops = self._ops_basic()
        _apply_sequential(seq[0], ops)
        _apply_partition(bat[0], ops, sizes * 20)
        _assert_equivalent(seq, bat)
        seq[1].stop()
        bat[1].stop()

    def test_mixed_kind_batch_preserves_order(self):
        """A batch interleaving pod events with a throttle selector change
        must apply in order: pods before the selector edit match the OLD
        column, pods after match the NEW one."""
        seq = _build()
        bat = _build()
        moved = _throttle(0, "g1", pods=2, cpu="1")  # t0 now selects g1
        ops = [
            ("create", "Pod", _pod("a", "g0", 100)),
            ("update", "Throttle", moved),
            ("create", "Pod", _pod("b", "g0", 100)),
            ("create", "Pod", _pod("c", "g1", 100)),
        ]
        _apply_sequential(seq[0], ops)
        bat[0].apply_events(ops)
        _assert_equivalent(seq, bat)
        seq[1].stop()
        bat[1].stop()

    def test_per_op_failure_never_tears_batch(self):
        store, plugin = _build()
        ops = [
            ("create", "Pod", _pod("ok1", "g0", 100)),
            ("create", "Pod", _pod("ok1", "g0", 100)),  # duplicate → ValueError
            ("delete", "Pod", "default/never-existed"),  # NotFoundError
            ("create", "Pod", _pod("ok2", "g1", 200)),
        ]
        res = store.apply_events(ops)
        assert not isinstance(res[0], Exception)
        assert isinstance(res[1], Exception)
        assert isinstance(res[2], Exception)
        assert not isinstance(res[3], Exception)
        assert {p.name for p in store.list_pods()} == {"ok1", "ok2"}
        plugin.stop()

    def test_event_rv_stamped_and_ordered(self):
        store = Store()
        store.create_namespace(Namespace("default"))
        seen = []
        store.add_event_handler("Pod", lambda e: seen.append(e.rv))
        store.apply_events(
            [("create", "Pod", _pod(f"r{i}", "g0", 100)) for i in range(5)]
        )
        assert all(rv is not None for rv in seen)
        assert seen == sorted(seen)
        assert seen[-1] == store.latest_resource_version


class TestPropertyEquivalence:
    def test_random_streams_random_partitions(self):
        """hypothesis (importorskip, like test_property_oracle.py): random
        event streams × random batch partitions — batched ingest ≡
        one-at-a-time ingest on store dump, st_* planes, and pre_filter
        verdicts."""
        pytest.importorskip("hypothesis")
        from hypothesis import HealthCheck, given, settings, strategies as st

        pod_names = [f"p{i}" for i in range(5)]
        groups = ["g0", "g1", "nomatch"]

        op_st = st.one_of(
            st.tuples(
                st.just("upsert"),
                st.sampled_from(pod_names),
                st.sampled_from(groups),
                st.integers(1, 8),
                st.booleans(),
            ),
            st.tuples(st.just("delete"), st.sampled_from(pod_names)),
        )

        @settings(
            max_examples=10,
            deadline=None,
            suppress_health_check=[HealthCheck.too_slow],
        )
        @given(
            ops_raw=st.lists(op_st, min_size=1, max_size=25),
            sizes=st.lists(st.integers(1, 9), min_size=1, max_size=8),
        )
        def run(ops_raw, sizes):
            ops = []
            for raw in ops_raw:
                if raw[0] == "delete":
                    ops.append(("delete", "Pod", f"default/{raw[1]}"))
                else:
                    _, name, grp, cpu, running = raw
                    ops.append(
                        ("upsert", "Pod", _pod(name, grp, cpu * 100, running=running))
                    )
            seq = _build(n_throttles=3)
            bat = _build(n_throttles=3)
            try:
                # deletes of absent pods fail per-op on both sides alike
                _apply_sequential(seq[0], ops)
                _apply_partition(bat[0], ops, sizes * 5)
                _assert_equivalent(seq, bat)
            finally:
                seq[1].stop()
                bat[1].stop()

        run()


class TestPendingDeltaKernelParity:
    def test_host_route_matches_device_kernel(self):
        """apply_pending_batched's host mirror is bit-identical to the real
        apply_pod_deltas_batched kernel over the same encoded burst."""
        rng = np.random.default_rng(7)
        store, plugin = _build()
        ks = plugin.device_manager.throttle
        # build a synthetic pending burst in the capture format
        pending = []
        for _ in range(17):
            k = int(rng.integers(1, 4))
            cols = rng.choice(ks.tcap - 1, size=k, replace=False).astype(np.int32)
            sign = int(rng.choice([-1, 1]))
            req = rng.integers(0, 10**9, size=ks.R).astype(np.int64)
            present = rng.random(ks.R) > 0.5
            pending.append((cols, sign, req, present))
        # seed both routes from the same aggregate state
        base_cnt = rng.integers(0, 50, size=ks.tcap).astype(np.int64)
        base_req = rng.integers(0, 10**10, size=(ks.tcap, ks.R)).astype(np.int64)
        base_ctb = rng.integers(0, 20, size=(ks.tcap, ks.R)).astype(np.int32)

        def run(device: bool):
            old = ds_mod._AGG_DEVICE_DELTAS
            ds_mod._AGG_DEVICE_DELTAS = device
            try:
                ks.agg_cnt = base_cnt.copy()
                ks.agg_req = base_req.copy()
                ks.agg_contrib = base_ctb.copy()
                ks.apply_pending_batched(list(pending))
                return ks.agg_cnt.copy(), ks.agg_req.copy(), ks.agg_contrib.copy()
            finally:
                ds_mod._AGG_DEVICE_DELTAS = old

        h_cnt, h_req, h_ctb = run(False)
        d_cnt, d_req, d_ctb = run(True)
        np.testing.assert_array_equal(h_cnt, d_cnt)
        np.testing.assert_array_equal(h_req, d_req)
        np.testing.assert_array_equal(h_ctb, d_ctb)
        plugin.stop()


class TestIngestPipeline:
    def test_adaptive_collapses_to_single_when_idle(self):
        store = Store()
        store.create_namespace(Namespace("default"))
        pipe = MicroBatchIngest(store)
        for i in range(3):
            pipe.submit("upsert", "Pod", _pod(f"i{i}", "g0", 100))
            assert pipe.flush(5)
        st = pipe.stats()
        assert st["events_applied"] == 3
        assert st["cur_max"] == 1  # idle between submits → no batch growth
        pipe.stop()

    def test_backlog_grows_batches_and_drains(self):
        store = Store()
        store.create_namespace(Namespace("default"))
        pipe = MicroBatchIngest(store, max_batch=16)
        pipe.submit_many(
            [("upsert", "Pod", _pod(f"b{i}", "g0", 100)) for i in range(200)]
        )
        assert pipe.flush(10)
        st = pipe.stats()
        assert st["events_applied"] == 200
        assert st["max_batch_seen"] > 1
        assert st["batches"] < 200  # amortization actually happened
        assert len(store.list_pods()) == 200
        pipe.stop()

    def test_overflow_drops_oldest_counting_per_event(self):
        store = Store()
        store.create_namespace(Namespace("default"))
        # stall the dispatcher behind a slow handler so the queue fills
        import threading

        gate = threading.Event()
        store.add_event_handler("Pod", lambda e: gate.wait(2))
        pipe = MicroBatchIngest(store, maxsize=8)
        pipe.submit_many(
            [("upsert", "Pod", _pod(f"o{i}", "g0", 100)) for i in range(30)]
        )
        st = pipe.stats()
        assert st["dropped"] >= 30 - 8 - 2  # per-event accounting (±in-flight)
        assert st["overflowed"]
        gate.set()
        pipe.flush(10)
        pipe.stop()

    def test_partial_batch_fault_splits_and_continues(self):
        store = Store()
        store.create_namespace(Namespace("default"))
        plan = FaultPlan(seed=0).rule("ingest.batch.partial", times=1)
        pipe = MicroBatchIngest(store, faults=plan)
        pipe.submit_many(
            [("upsert", "Pod", _pod(f"f{i}", "g0", 100)) for i in range(9)]
        )
        assert pipe.flush(10)
        st = pipe.stats()
        assert st["op_errors"] >= 1  # the poisoned op
        # every op around the poisoned one landed
        assert len(store.list_pods()) + st["op_errors"] == 9
        pipe.stop()


class TestJournalGroupCommit:
    def test_batch_replay_and_position(self, tmp_path):
        from kube_throttler_tpu.engine.journal import attach, hash_prefix

        path = str(tmp_path / "j.journal")
        store = Store()
        journal = attach(store, path)
        store.create_namespace(Namespace("default"))
        store.apply_events(
            [("create", "Pod", _pod(f"j{i}", "g0", 100)) for i in range(6)]
            + [("delete", "Pod", "default/j3")]
        )
        nbytes, sha = journal.position()
        # the running position matches the on-disk content exactly
        h = hash_prefix(path, nbytes)
        assert h is not None and h.hexdigest() == sha
        journal.close()
        replayed = Store()
        attach(replayed, path).close()
        assert _dump(replayed) == _dump(store)

    def test_torn_line_inside_batch_is_interior_corruption(self, tmp_path):
        from kube_throttler_tpu.engine.journal import attach

        path = str(tmp_path / "j.journal")
        store = Store()
        plan = FaultPlan(seed=0).rule(
            "journal.append", mode="torn", schedule=[3]
        )
        journal = attach(store, path, faults=plan)
        store.create_namespace(Namespace("default"))
        store.apply_events(
            [("create", "Pod", _pod(f"t{i}", "g0", 100)) for i in range(5)]
        )
        assert journal.torn_writes == 1
        journal.close()
        replayed = Store()
        j2 = attach(replayed, path)
        # the torn line ate itself AND the next line (concatenated) — every
        # other event replays; corruption is counted, not fatal
        assert j2.replay_skipped >= 1
        names = {p.name for p in replayed.list_pods()}
        assert "t0" in names and "t4" in names
        j2.close()


class TestWatchBatchDelivery:
    def test_batch_events_delivered_in_order(self):
        store = Store()
        store.create_namespace(Namespace("default"))
        w = Watch(store, "Pod")
        store.apply_events(
            [("create", "Pod", _pod(f"w{i}", "g0", 100)) for i in range(5)]
        )
        got = [w.next(timeout=1).obj.name for _ in range(5)]
        assert got == [f"w{i}" for i in range(5)]
        # batch went in as ONE queue item
        assert w.dropped == 0
        w.stop()

    def test_shed_batch_counts_per_event(self):
        store = Store()
        store.create_namespace(Namespace("default"))
        w = Watch(store, "Pod", maxsize=2)
        # two batches of 4: the second shed the first (4 events), etc.
        for b in range(3):
            store.apply_events(
                [("create", "Pod", _pod(f"s{b}-{i}", "g0", 100)) for i in range(4)]
            )
        # queue holds 2 items (batches); 1 batch of 4 events was shed
        assert w.dropped == 4
        assert w.overflowed
        w.stop()

    def test_next_batch_drains(self):
        store = Store()
        store.create_namespace(Namespace("default"))
        w = Watch(store, "Pod")
        store.apply_events(
            [("create", "Pod", _pod(f"n{i}", "g0", 100)) for i in range(3)]
        )
        store.create_pod(_pod("n3", "g0", 100))
        batch = w.next_batch(timeout=1)
        assert [e.obj.name for e in batch] == ["n0", "n1", "n2", "n3"]
        with pytest.raises(queue.Empty):
            w.next(timeout=0.05)
        w.stop()


class TestReflectorBatching:
    def test_remote_session_routes_watch_through_batcher(self):
        """Remote mode with ``ingest_batch="adaptive"``: watch events reach
        the local mirror through the micro-batcher; deletes and relists
        stay coherent (the relist flushes the queue first)."""
        import time as _time

        from kube_throttler_tpu.client.mockserver import MockApiServer
        from kube_throttler_tpu.client.transport import RemoteSession, RestConfig

        server = MockApiServer()
        remote = server.store
        remote.create_namespace(Namespace("default"))
        remote.create_throttle(_throttle(0, "g0"))
        server.start()
        local = Store()
        session = RemoteSession(
            RestConfig(server=server.url), local, qps=None,
            ingest_batch="adaptive",
        )
        try:
            session.start(sync_timeout=30)
            assert session.ingest is not None
            for i in range(20):
                remote.create_pod(_pod(f"r{i}", "g0", 100))
            remote.delete_pod("default", "r3")

            def _wait(pred, timeout=15.0):
                deadline = _time.monotonic() + timeout
                while _time.monotonic() < deadline:
                    if pred():
                        return True
                    _time.sleep(0.05)
                return pred()

            assert _wait(lambda: len(local.list_pods()) == 19)
            assert {p.name for p in local.list_pods()} == {
                f"r{i}" for i in range(20) if i != 3
            }
            assert session.ingest.stats()["events_applied"] >= 20
        finally:
            session.stop()
            server.stop()


class TestIngestFlipPromotion:
    def test_batch_crossing_promotes_to_priority_lane(self):
        """A micro-batch whose deltas flip a throttle's classification must
        land that key in the controller's PRIORITY lane before any
        reconcile runs (one flip detection + one add_all_priority per
        batch)."""
        store, plugin = _build(n_throttles=2)
        # settle initial state so the st_* planes are published
        plugin.run_pending_once()
        wq = plugin.throttle_ctr.workqueue
        # t0: threshold pod=2 over g0 — two running pods cross it
        store.apply_events(
            [
                ("create", "Pod", _pod("f1", "g0", 100)),
                ("create", "Pod", _pod("f2", "g0", 100)),
                ("create", "Pod", _pod("f3", "g0", 100)),
            ]
        )
        with wq._lock:  # noqa: SLF001 — lane introspection
            hi = [item for _, _, item in wq._queue_hi]  # heap of (-prio, seq, item)
        assert "default/t0" in hi
        plugin.run_pending_once()
        thr = store.get_throttle("default", "t0")
        assert thr.status.throttled.resource_counts_pod
        plugin.stop()


class TestCommitterPerKeyFlipOrdering:
    def test_multiple_same_key_flips_in_one_batch(self):
        """One batch submitting flip(v1), refresh(v2), flip(v3) for one key
        must publish newest-wins in order, never demote the key's lane,
        and never PUT an older object after a newer one."""
        from kube_throttler_tpu.client.transport import AsyncStatusCommitter

        puts = []

        class _Writer:
            def _put(self, kind, obj):
                puts.append((kind, obj.name, obj.status.used.resource_counts))

            def refresh_version(self, kind, obj):
                pass

        committer = AsyncStatusCommitter(_Writer(), workers=1)
        thrs = []
        for used in (1, 2, 3):
            t = _throttle(0, "g0")
            t = t.with_status(
                replace(t.status, used=ResourceAmount(resource_counts=used))
            )
            thrs.append(t)
        # one batch: flip, refresh, flip — all same key, workers not started
        committer.update_throttle_statuses_prioritized(
            [thrs[0]], flip_keys={thrs[0].key}
        )
        committer.update_throttle_statuses_prioritized([thrs[1]])  # refresh
        committer.update_throttle_statuses_prioritized(
            [thrs[2]], flip_keys={thrs[2].key}
        )
        i = hash(thrs[0].key) % 1
        assert thrs[0].key in committer._hi_shards[i]  # never demoted
        slot = committer._hi_shards[i][thrs[0].key]
        assert slot[3] is True and slot[1] is thrs[2]  # newest wins, flip kept
        committer.start()
        assert committer.flush(5)
        committer.stop()
        # exactly one PUT: the newest object; no stale write followed it
        assert puts == [("Throttle", "t0", 3)]
