"""Stress tier — the reference's clusterthrottle_stress_test.go:30-88 scale
(50 ClusterThrottles × 10 namespaces × 10 pods, every throttle driven
exactly to its threshold) plus a multi-threaded scheduler soak that the
reference can only run against a kind cluster."""

import random
import threading
from dataclasses import replace
from datetime import datetime, timezone

import pytest

from kube_throttler_tpu.api import (
    ClusterThrottle,
    ClusterThrottleSelector,
    ClusterThrottleSelectorTerm,
    ClusterThrottleSpec,
    LabelSelector,
    Namespace,
    ResourceAmount,
)
from kube_throttler_tpu.api.pod import make_pod
from kube_throttler_tpu.engine.store import Store
from kube_throttler_tpu.plugin import (
    KubeThrottler,
    RecordingEventRecorder,
    decode_plugin_args,
)
from kube_throttler_tpu.utils.clock import FakeClock

NOW = datetime(2024, 1, 15, 12, 0, 0, tzinfo=timezone.utc)


def _cluster_throttle(i: int, n_pods: int) -> ClusterThrottle:
    return ClusterThrottle(
        name=f"clthr-{i}",
        spec=ClusterThrottleSpec(
            throttler_name="kube-throttler",
            threshold=ResourceAmount.of(
                pod=n_pods, requests={"cpu": f"{n_pods * 100}m"}
            ),
            selector=ClusterThrottleSelector(
                selector_terms=(
                    ClusterThrottleSelectorTerm(
                        pod_selector=LabelSelector(match_labels={"clthr": f"c{i}"}),
                    ),
                )
            ),
        ),
    )


class TestClusterThrottleStress:
    @pytest.mark.parametrize("use_device", [True, False], ids=["device", "oracle"])
    def test_50_throttles_10_ns_10_pods_reach_exact_thresholds(self, use_device):
        """Every throttle is filled to exactly its threshold; the next pod on
        each is blocked (clusterthrottle_stress_test.go semantics)."""
        n_throttles, n_ns, pods_per_throttle = 50, 10, 10
        store = Store()
        plugin = KubeThrottler(
            decode_plugin_args(
                {"name": "kube-throttler", "targetSchedulerName": "my-scheduler", "controllerThrediness": 1}
            ),
            store,
            event_recorder=RecordingEventRecorder(),
            use_device=use_device,
        )
        for i in range(n_ns):
            store.create_namespace(Namespace(f"ns-{i}"))
        for i in range(n_throttles):
            store.create_cluster_throttle(_cluster_throttle(i, pods_per_throttle))
        plugin.run_pending_once()

        rng = random.Random(0)
        admitted = 0
        for i in range(n_throttles):
            for j in range(pods_per_throttle):
                pod = make_pod(
                    f"p-{i}-{j}",
                    namespace=f"ns-{rng.randrange(n_ns)}",
                    labels={"clthr": f"c{i}"},
                    requests={"cpu": "100m"},
                )
                store.create_pod(pod)
                status = plugin.pre_filter(pod)
                assert status.is_success(), f"pod {pod.key}: {status.message()}"
                plugin.reserve(pod)
                bound = replace(pod, spec=replace(pod.spec, node_name="n1"))
                store.update_pod(bound)
                admitted += 1
        plugin.run_pending_once()
        assert admitted == n_throttles * pods_per_throttle

        # every throttle sits exactly at its threshold and is throttled
        for i in range(n_throttles):
            thr = store.get_cluster_throttle(f"clthr-{i}")
            assert thr.status.used.resource_counts == pods_per_throttle
            assert thr.status.throttled.resource_counts_pod is True
            assert thr.status.throttled.resource_requests["cpu"] is True
            # one more pod is rejected with the reference reason
            extra = make_pod(
                f"extra-{i}", namespace="ns-0", labels={"clthr": f"c{i}"}, requests={"cpu": "100m"}
            )
            store.create_pod(extra)
            status = plugin.pre_filter(extra)
            assert not status.is_success()
            assert f"clusterthrottle[active]=/clthr-{i}" in status.message()


class TestThreadedSchedulerSoak:
    def test_concurrent_scheduling_respects_thresholds(self):
        """N scheduler threads race PreFilter/Reserve/bind against async
        controller workers; reservation accounting must never over-admit."""
        store = Store()
        plugin = KubeThrottler(
            decode_plugin_args(
                {"name": "kube-throttler", "targetSchedulerName": "my-scheduler", "controllerThrediness": 4, "numKeyMutex": 16}
            ),
            store,
            event_recorder=RecordingEventRecorder(),
            start_workers=True,
        )
        store.create_namespace(Namespace("default"))
        from kube_throttler_tpu.api import Throttle, ThrottleSelector, ThrottleSelectorTerm, ThrottleSpec

        capacity = 20
        store.create_throttle(
            Throttle(
                name="gate",
                spec=ThrottleSpec(
                    throttler_name="kube-throttler",
                    threshold=ResourceAmount.of(requests={"cpu": "1"}),  # 20 x 50m
                    selector=ThrottleSelector(
                        selector_terms=(
                            ThrottleSelectorTerm(LabelSelector(match_labels={"gate": "g"})),
                        )
                    ),
                ),
            )
        )

        admitted = []
        admit_lock = threading.Lock()

        def scheduler_thread(tid):
            for j in range(10):
                pod = make_pod(
                    f"pod-{tid}-{j}", labels={"gate": "g"}, requests={"cpu": "50m"}
                )
                store.create_pod(pod)
                # PreFilter + Reserve must be serialized per scheduling cycle
                # (kube-scheduler schedules one pod at a time); emulate that
                # with a global cycle lock, binds happen async afterwards.
                with admit_lock:
                    status = plugin.pre_filter(pod)
                    if not status.is_success():
                        continue
                    assert plugin.reserve(pod).is_success()
                    admitted.append(pod.key)
                bound = replace(pod, spec=replace(pod.spec, node_name="n1"))
                store.update_pod(bound)

        threads = [threading.Thread(target=scheduler_thread, args=(t,)) for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        import time

        deadline = time.time() + 10
        while time.time() < deadline:
            thr = store.get_throttle("default", "gate")
            if thr.status.used.resource_counts == len(admitted):
                break
            time.sleep(0.05)

        # never over capacity, and the reconcile converged on the admitted set
        assert len(admitted) <= capacity
        assert len(admitted) == capacity, f"expected full utilization, got {len(admitted)}"
        thr = store.get_throttle("default", "gate")
        assert thr.status.used.resource_counts == capacity
        assert thr.status.throttled.resource_requests["cpu"] is True
        plugin.stop()


class TestCrashOnlyRecovery:
    """SURVEY §5: the reference is crash-only — informer caches resync on
    restart and reservations are scheduler-cycle-transient. A fresh plugin
    over the same store must reach identical decisions."""

    def test_restart_rebuilds_state(self):
        store = Store()
        store.create_namespace(Namespace("default"))
        args = decode_plugin_args(
            {"name": "kube-throttler", "targetSchedulerName": "my-scheduler", "controllerThrediness": 1}
        )
        plugin = KubeThrottler(args, store, event_recorder=RecordingEventRecorder())
        from kube_throttler_tpu.api import Throttle, ThrottleSelector, ThrottleSelectorTerm, ThrottleSpec

        store.create_throttle(
            Throttle(
                name="t1",
                spec=ThrottleSpec(
                    throttler_name="kube-throttler",
                    threshold=ResourceAmount.of(requests={"cpu": "200m"}),
                    selector=ThrottleSelector(
                        selector_terms=(
                            ThrottleSelectorTerm(LabelSelector(match_labels={"throttle": "t1"})),
                        )
                    ),
                ),
            )
        )
        plugin.run_pending_once()
        pod = make_pod("p1", labels={"throttle": "t1"}, requests={"cpu": "200m"})
        store.create_pod(pod)
        plugin.run_pending_once()
        plugin.pre_filter(pod)
        plugin.reserve(pod)
        bound = replace(pod, spec=replace(pod.spec, node_name="n1"))
        store.update_pod(bound)
        plugin.run_pending_once()

        # "crash": drop the plugin; build a fresh one over the same store
        # (replay=True event handlers play the informer cache resync role)
        plugin2 = KubeThrottler(args, store, event_recorder=RecordingEventRecorder())
        plugin2.run_pending_once()

        blocked = make_pod("p2", labels={"throttle": "t1"}, requests={"cpu": "100m"})
        store.create_pod(blocked)
        old_status = plugin.pre_filter(blocked)
        new_status = plugin2.pre_filter(blocked)
        assert new_status.code == old_status.code
        assert new_status.reasons == old_status.reasons
        assert "throttle[active]=default/t1" in new_status.message()
        # reservations are cycle-transient: the fresh ledger starts empty
        assert plugin2.throttle_ctr.cache.reserved_pod_keys("default/t1") == set()
