"""Quantity parsing/arithmetic vs k8s resource.Quantity semantics."""

from fractions import Fraction

import pytest

from kube_throttler_tpu.quantity import (
    QuantityParseError,
    SubMilliPrecisionError,
    cmp_quantity,
    format_quantity,
    from_milli,
    parse_quantity,
    to_milli,
)


@pytest.mark.parametrize(
    "s,expected",
    [
        ("0", Fraction(0)),
        ("1", Fraction(1)),
        ("100m", Fraction(1, 10)),
        ("200m", Fraction(1, 5)),
        ("1500m", Fraction(3, 2)),
        ("0.5", Fraction(1, 2)),
        ("1.5", Fraction(3, 2)),
        ("1Ki", Fraction(1024)),
        ("1Mi", Fraction(1024**2)),
        ("1Gi", Fraction(1024**3)),
        ("512Mi", Fraction(512 * 1024**2)),
        ("1.5Gi", Fraction(3 * 1024**3, 2)),
        ("1k", Fraction(1000)),
        ("1M", Fraction(10**6)),
        ("1G", Fraction(10**9)),
        ("1T", Fraction(10**12)),
        ("1P", Fraction(10**15)),
        ("1E", Fraction(10**18)),
        ("1u", Fraction(1, 10**6)),
        ("1n", Fraction(1, 10**9)),
        ("1e3", Fraction(1000)),
        ("1E3", Fraction(1000)),
        ("2e-2", Fraction(1, 50)),
        ("-100m", Fraction(-1, 10)),
        ("+2", Fraction(2)),
        (".5", Fraction(1, 2)),
        ("5.", Fraction(5)),
        (3, Fraction(3)),
        (0.25, Fraction(1, 4)),
    ],
)
def test_parse(s, expected):
    assert parse_quantity(s) == expected


@pytest.mark.parametrize("s", ["", "abc", "1Zi", "1mm", "--1", "1.2.3", "m", "Ki"])
def test_parse_errors(s):
    with pytest.raises(QuantityParseError):
        parse_quantity(s)


def test_cmp():
    assert cmp_quantity(parse_quantity("100m"), parse_quantity("0.1")) == 0
    assert cmp_quantity(parse_quantity("1Gi"), parse_quantity("1G")) == 1
    assert cmp_quantity(parse_quantity("999m"), parse_quantity("1")) == -1


def test_to_milli_exact():
    assert to_milli(parse_quantity("200m")) == 200
    assert to_milli(parse_quantity("1")) == 1000
    assert to_milli(parse_quantity("1Gi")) == 1024**3 * 1000
    assert from_milli(1500) == Fraction(3, 2)


def test_to_milli_submilli_rejected():
    with pytest.raises(SubMilliPrecisionError):
        to_milli(parse_quantity("1u"))
    with pytest.raises(SubMilliPrecisionError):
        to_milli(Fraction(1, 3))


def test_format_roundtrip():
    for s in ["0", "3", "200m", "1500m", "-100m"]:
        assert parse_quantity(format_quantity(parse_quantity(s))) == parse_quantity(s)
