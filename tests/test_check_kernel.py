"""Property tests: the batched XLA check kernel must agree cell-for-cell
with the pure-Python oracle (api.types.check_throttled_for) across presence
and equality-boundary edge cases."""

import random
from datetime import datetime, timezone

import numpy as np
import pytest

from kube_throttler_tpu.api import (
    ClusterThrottle,
    ClusterThrottleSpec,
    IsResourceAmountThrottled,
    ResourceAmount,
    Throttle,
    ThrottleSpec,
)
from kube_throttler_tpu.api.pod import make_pod
from kube_throttler_tpu.api.types import CalculatedThreshold, ThrottleStatus
from kube_throttler_tpu.ops import (
    CHECK_NOT_AFFECTED,
    STATUS_NAMES,
    DimRegistry,
    check_pods,
    check_pods_compact,
    encode_pods,
    encode_throttle_state,
)

NOW = datetime(2024, 1, 15, tzinfo=timezone.utc)
RESOURCES = ["cpu", "memory", "nvidia.com/gpu"]
# values chosen to sit on comparison boundaries (milli-units as strings)
BOUNDARY_VALUES = ["0", "100m", "200m", "300m", "1"]


def _random_amount(rng, allow_nil_counts=True) -> ResourceAmount:
    counts = None
    if not allow_nil_counts or rng.random() < 0.7:
        counts = rng.choice([0, 1, 2, 3, 5])
    requests = None
    if rng.random() < 0.85:
        requests = {}
        for r in RESOURCES:
            if rng.random() < 0.6:
                requests[r] = rng.choice(BOUNDARY_VALUES)
    return ResourceAmount.of(pod=counts, requests=requests)


def _random_flags(rng) -> IsResourceAmountThrottled:
    req = None
    if rng.random() < 0.7:
        req = {r: rng.random() < 0.3 for r in RESOURCES if rng.random() < 0.6}
    return IsResourceAmountThrottled(
        resource_counts_pod=rng.random() < 0.2, resource_requests=req
    )


def _random_status(rng) -> ThrottleStatus:
    calc = CalculatedThreshold()
    if rng.random() < 0.5:
        calc = CalculatedThreshold(threshold=_random_amount(rng), calculated_at=NOW)
    return ThrottleStatus(
        calculated_threshold=calc,
        throttled=_random_flags(rng),
        used=_random_amount(rng),
    )


def _build_objects(rng, n_throttles, n_pods, kind):
    throttles = []
    reserved = []
    for i in range(n_throttles):
        if kind == "throttle":
            throttles.append(
                Throttle(
                    name=f"t{i}",
                    spec=ThrottleSpec(threshold=_random_amount(rng)),
                    status=_random_status(rng),
                )
            )
        else:
            throttles.append(
                ClusterThrottle(
                    name=f"c{i}",
                    spec=ClusterThrottleSpec(threshold=_random_amount(rng)),
                    status=_random_status(rng),
                )
            )
        reserved.append(
            _random_amount(rng) if rng.random() < 0.6 else ResourceAmount()
        )
    pods = []
    for i in range(n_pods):
        reqs = {}
        for r in RESOURCES:
            if rng.random() < 0.6:
                reqs[r] = rng.choice(BOUNDARY_VALUES)
        pods.append(make_pod(f"p{i}", requests=reqs))
    return throttles, reserved, pods


@pytest.mark.parametrize("kind", ["throttle", "clusterthrottle"])
@pytest.mark.parametrize("on_equal", [False, True])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_kernel_matches_oracle(kind, on_equal, seed):
    rng = random.Random(seed)
    throttles, reserved, pods = _build_objects(rng, n_throttles=40, n_pods=30, kind=kind)

    dims = DimRegistry()
    state = encode_throttle_state(throttles, dims, reserved=reserved)
    batch = encode_pods(pods, dims)
    mask = np.asarray(rng.choices([True, False], k=len(pods) * len(throttles))).reshape(
        len(pods), len(throttles)
    )

    step3 = True if kind == "throttle" else on_equal
    got = np.asarray(check_pods(state, batch, mask, on_equal=on_equal, step3_on_equal=step3))

    for i, pod in enumerate(pods):
        for j, thr in enumerate(throttles):
            if not mask[i, j]:
                assert got[i, j] == CHECK_NOT_AFFECTED
                continue
            want = thr.check_throttled_for(pod, reserved[j], on_equal)
            assert STATUS_NAMES[int(got[i, j])] == want, (
                f"seed={seed} kind={kind} on_equal={on_equal} pod={i} thr={j}: "
                f"kernel={STATUS_NAMES[int(got[i, j])]} oracle={want} "
                f"thr={thr} pod_req={pod.spec.containers[0].requests} reserved={reserved[j]}"
            )


def test_compact_counts_match_full():
    rng = random.Random(7)
    throttles, reserved, pods = _build_objects(rng, 25, 20, "throttle")
    dims = DimRegistry()
    state = encode_throttle_state(throttles, dims, reserved=reserved)
    batch = encode_pods(pods, dims)
    mask = np.ones((20, 25), dtype=bool)

    full = np.asarray(check_pods(state, batch, mask))
    counts, schedulable = check_pods_compact(state, batch, mask)
    counts = np.asarray(counts)
    schedulable = np.asarray(schedulable)
    for i in range(20):
        for c in range(4):
            assert counts[i, c] == np.sum(full[i] == c)
        assert schedulable[i] == (np.sum((full[i] > 0)) == 0)


def test_padding_rows_are_not_affected():
    throttles = [Throttle(name="t0", spec=ThrottleSpec(threshold=ResourceAmount.of(pod=1)))]
    pods = [make_pod("p0", requests={"cpu": "1"})]
    dims = DimRegistry()
    state = encode_throttle_state(throttles, dims, capacity=8)
    batch = encode_pods(pods, dims, capacity=4)
    mask = np.ones((4, 8), dtype=bool)
    got = np.asarray(check_pods(state, batch, mask))
    assert got.shape == (4, 8)
    assert (got[1:, :] == CHECK_NOT_AFFECTED).all()
    assert (got[:, 1:] == CHECK_NOT_AFFECTED).all()
    assert got[0, 0] != CHECK_NOT_AFFECTED


def _cols_of_mask(mask: np.ndarray, K: int) -> np.ndarray:
    """[P,T] bool → int32[P,K] matched cols, -1 padded (test-local twin of
    _KindState._cols_from_mask)."""
    P = mask.shape[0]
    out = np.full((P, K), -1, dtype=np.int32)
    for i in range(P):
        cols = np.nonzero(mask[i])[0]
        out[i, : cols.size] = cols
    return out


@pytest.mark.parametrize("kind", ["throttle", "clusterthrottle"])
@pytest.mark.parametrize("on_equal", [False, True])
@pytest.mark.parametrize("seed", [0, 3])
def test_gather_matches_compact(kind, on_equal, seed):
    """check_pods_gather over [P,K] matched cols must equal
    check_pods_compact over the equivalent [P,T] mask — counts AND gate."""
    from kube_throttler_tpu.ops import check_pods_gather

    rng = random.Random(seed)
    throttles, reserved, pods = _build_objects(rng, n_throttles=17, n_pods=23, kind=kind)
    dims = DimRegistry()
    state = encode_throttle_state(throttles, dims, reserved=reserved)
    batch = encode_pods(pods, dims)
    # sparse-ish random mask incl. empty rows and one full row
    mask = np.asarray(
        rng.choices([True, False], weights=[1, 4], k=len(pods) * len(throttles))
    ).reshape(len(pods), len(throttles))
    mask[0, :] = False
    mask[1, :] = True
    cols = _cols_of_mask(mask, K=int(mask.sum(axis=1).max()))

    step3 = True if kind == "throttle" else on_equal
    want_counts, want_ok = check_pods_compact(
        state, batch, mask, on_equal=on_equal, step3_on_equal=step3
    )
    got_counts, got_ok = check_pods_gather(
        state, batch, cols, on_equal=on_equal, step3_on_equal=step3
    )
    np.testing.assert_array_equal(np.asarray(got_counts), np.asarray(want_counts))
    np.testing.assert_array_equal(np.asarray(got_ok), np.asarray(want_ok))


@pytest.mark.parametrize("seed", [0, 7])
def test_gather_blocked_matches_unblocked(seed, monkeypatch):
    """The P-chunked gather decomposition (lax.map blocks, activated when
    P×K_pad×R exceeds KT_GATHER_CHUNK_ELEMS — the r5 full-scale TPU OOM
    fix) must be bit-identical to the single-dispatch path, including when
    P does not divide evenly into blocks."""
    from kube_throttler_tpu.ops import check, check_pods_gather

    rng = random.Random(seed)
    throttles, reserved, pods = _build_objects(rng, n_throttles=9, n_pods=29, kind="throttle")
    dims = DimRegistry()
    state = encode_throttle_state(throttles, dims, reserved=reserved)
    batch = encode_pods(pods, dims)
    mask = np.asarray(
        rng.choices([True, False], weights=[1, 3], k=len(pods) * len(throttles))
    ).reshape(len(pods), len(throttles))
    cols = _cols_of_mask(mask, K=max(1, int(mask.sum(axis=1).max())))

    # un-jitted bodies: the jitted wrappers cache by shape, so the chunk
    # threshold (read at trace time) must be exercised through the raw
    # functions for the monkeypatch to take effect
    want = np.asarray(check._gather_statuses(state, batch, cols, False, True))
    # force ~4-row blocks (29 pods ⇒ a ragged final block exercises padding)
    monkeypatch.setattr(
        check, "_GATHER_CHUNK_ELEMS", 4 * max(cols.shape[1], 128) * batch.req.shape[1]
    )
    got = np.asarray(check._gather_statuses_blocked(state, batch, cols, False, True))
    np.testing.assert_array_equal(got, want)
    # and through the compact reduction (counts + schedulable gate)
    want_c, want_ok = check_pods_gather(state, batch, cols)
    got_c = check.statuses_to_compact(got)
    np.testing.assert_array_equal(np.asarray(got_c[0]), np.asarray(want_c))
    np.testing.assert_array_equal(np.asarray(got_c[1]), np.asarray(want_ok))


def test_gather_ignores_padding_and_invalid_cols():
    """-1 pad slots and cols pointing at invalid (freed) throttle slots must
    contribute nothing."""
    from kube_throttler_tpu.ops import check_pods_gather

    throttles = [Throttle(name="t0", spec=ThrottleSpec(threshold=ResourceAmount.of(pod=1)))]
    pods = [make_pod("p0", requests={"cpu": "1"})]
    dims = DimRegistry()
    state = encode_throttle_state(throttles, dims, capacity=8)
    batch = encode_pods(pods, dims, capacity=4)
    # slot 0 → the real throttle; slot 1 → padding col 5 (invalid); rest -1
    cols = np.full((4, 4), -1, dtype=np.int32)
    cols[0, 0] = 0
    cols[0, 1] = 5
    counts, ok = check_pods_gather(state, batch, cols)
    counts = np.asarray(counts)
    assert counts[0].sum() == 1  # only the valid throttle counted
    assert counts[1:].sum() == 0  # invalid pod rows contribute nothing
    assert not bool(np.asarray(ok)[0]) or counts[0, 0] == 1


def test_native_handle_lifecycle():
    """The C-side classifier handle must be destroyed exactly once: early
    at plane re-registration (capacity growth replaces staging arrays) OR
    at _KindState GC — weakref.finalize guarantees at-most-once, so the
    two paths cannot double-free."""
    import gc

    from kube_throttler_tpu.engine import devicestate as ds
    from kube_throttler_tpu.ops.schema import DimRegistry

    lib = ds._native_cls_lib()
    if lib is None:
        pytest.skip("native lib unavailable (KT_TPU_NO_NATIVE or no toolchain)")
    ks = ds._KindState("throttle", DimRegistry())
    cols = np.array([0, 1], dtype=np.int64)
    pod_req = np.zeros(ks.R, dtype=np.int64)
    pod_present = np.zeros(ks.R, dtype=bool)
    ds._native_classify_cols(lib, ks, cols, pod_req, pod_present, False, True)
    fin = ks._cls_cache[3]
    assert fin.alive
    ks.thr_cnt = ks.thr_cnt.copy()  # a growth-like plane replacement
    ds._native_classify_cols(lib, ks, cols, pod_req, pod_present, False, True)
    assert not fin.alive, "re-registration must retire the old handle"
    fin2 = ks._cls_cache[3]
    assert fin2.alive and fin2 is not fin
    del ks
    gc.collect()
    assert not fin2.alive, "GC must retire the live handle"


def test_host_single_check_matches_device_kernel():
    """check_pod's default HOST numpy classifier (_host_classify_rows) must
    agree cell-for-cell with the device kernel path
    (KT_SINGLE_CHECK_DEVICE=1) on randomized live state — the two are
    line-for-line ports of the same 4-step resolution and this pins them
    together."""
    import random
    from dataclasses import replace

    from kube_throttler_tpu.api import ResourceAmount, Throttle, ThrottleSpec
    from kube_throttler_tpu.api.pod import Namespace, make_pod
    from kube_throttler_tpu.api.types import (
        LabelSelector,
        ThrottleSelector,
        ThrottleSelectorTerm,
    )
    from kube_throttler_tpu.engine.store import Store
    from kube_throttler_tpu.plugin import KubeThrottler, decode_plugin_args

    rng = random.Random(23)
    store = Store()
    store.create_namespace(Namespace("default"))
    plugin = KubeThrottler(
        decode_plugin_args(
            {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
        ),
        store,
        use_device=True,
    )
    for i in range(40):
        store.create_throttle(
            Throttle(
                name=f"t{i}",
                namespace="default",
                spec=ThrottleSpec(
                    throttler_name="kube-throttler",
                    threshold=ResourceAmount.of(
                        pod=rng.choice([None, 1, 2, 5]),
                        requests={
                            "cpu": f"{rng.randrange(1, 9) * 100}m",
                            "memory": f"{rng.randrange(1, 5)}Gi",
                        },
                    ),
                    selector=ThrottleSelector(
                        selector_terms=(
                            ThrottleSelectorTerm(
                                LabelSelector(match_labels={"grp": f"g{i % 5}"})
                            ),
                        )
                    ),
                ),
            )
        )
    for i in range(120):
        p = make_pod(
            f"p{i}",
            namespace="default",
            labels={"grp": f"g{rng.randrange(5)}"},
            requests={
                "cpu": f"{rng.randrange(1, 6) * 100}m",
                "memory": f"{rng.randrange(1, 3)}Gi",
            },
        )
        p = replace(p, spec=replace(p.spec, node_name="n1"))
        p.status.phase = "Running"
        store.create_pod(p)
    plugin.run_pending_once()

    dm = plugin.device_manager
    # the test pins BOTH implementations against each other explicitly by
    # forcing the route per-iteration (the ambient resolution — kernel on
    # cpu, host on accelerators, KT_SINGLE_CHECK_DEVICE override — is not
    # under test here)
    probes = [
        make_pod(
            f"q{i}",
            namespace="default",
            labels={"grp": f"g{i % 5}"},
            requests={"cpu": f"{rng.randrange(1, 9) * 100}m"},
        )
        for i in range(24)
    ]
    for on_equal in (False, True):
        for kind in ("throttle", "clusterthrottle"):
            for p in probes:
                dm._single_check_device = False
                host = dm.check_pod(p, kind, on_equal)
                dm._single_check_device = True
                dev = dm.check_pod(p, kind, on_equal)
                assert host == dev, (kind, on_equal, p.name, host, dev)

    # the host route has two tiers (native C++ ktn_cls_run when the lib
    # loads, numpy _host_classify_rows otherwise); pin them against each
    # other too by forcing the numpy tier via the module-level lib cache
    from kube_throttler_tpu.engine import devicestate as ds

    if ds._native_cls_lib() is not None:
        dm._single_check_device = False
        native_res = [
            dm.check_pod(p, k, oe)
            for oe in (False, True)
            for k in ("throttle", "clusterthrottle")
            for p in probes
        ]
        old = (ds._cls_lib, ds._cls_lib_tried)
        ds._cls_lib, ds._cls_lib_tried = None, True
        try:
            numpy_res = [
                dm.check_pod(p, k, oe)
                for oe in (False, True)
                for k in ("throttle", "clusterthrottle")
                for p in probes
            ]
        finally:
            ds._cls_lib, ds._cls_lib_tried = old
        assert native_res == numpy_res
