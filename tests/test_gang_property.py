"""Hypothesis property: the batched gang-feasibility kernel
(ops/gang_check.py, dispatched through DeviceStateManager.gang_check_groups)
is equivalent to the SEQUENTIAL per-pod oracle (engine/gang.py
sequential_gang_check — admit members one at a time through the reference
4-step check, counting earlier members as reserved) on:

- the all-or-nothing VERDICT, over generated thresholds (counts + cpu,
  including per-accel-class replacements), statuses (used + persisted
  throttled flags), pre-existing per-pod reservations, and group shapes;
- the LEDGER state and the published ``st_*`` planes across a
  reserve → rollback cycle: a rolled-back gang leaves the reservation
  ledger, the device reserved rows, and every per-pod admission verdict
  exactly as they were (the rollback path is bit-invisible).

Guarded by importorskip like tests/test_property_oracle.py.
"""

from __future__ import annotations

import pytest

pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st

from kube_throttler_tpu.api.pod import Namespace, make_pod
from kube_throttler_tpu.api.types import (
    AccelClassThreshold,
    LabelSelector,
    ResourceAmount,
    Throttle,
    ThrottleSelector,
    ThrottleSelectorTerm,
    ThrottleSpec,
    ThrottleStatus,
)
from kube_throttler_tpu.engine.gang import sequential_gang_check
from kube_throttler_tpu.engine.store import Store
from kube_throttler_tpu.plugin import KubeThrottler, decode_plugin_args

GROUPS = ("g0", "g1")
ACCEL_CLASSES = (None, "v5e", "v5p")


@st.composite
def amounts(draw, max_pod=6):
    cnt = draw(st.one_of(st.none(), st.integers(min_value=0, max_value=max_pod)))
    cpu = draw(st.one_of(st.none(), st.integers(min_value=0, max_value=4000)))
    return ResourceAmount.of(
        pod=cnt, requests={"cpu": f"{cpu}m"} if cpu is not None else None
    )


@st.composite
def throttle_specs(draw, idx):
    threshold = draw(amounts())
    used = draw(amounts())
    accel = []
    for cls in ("v5e", "v5p"):
        if draw(st.booleans()):
            accel.append(AccelClassThreshold(cls, draw(amounts())))
    # selector: one group label, or match-all (both groups)
    grp = draw(st.sampled_from(GROUPS + ("*",)))
    labels = {} if grp == "*" else {"grp": grp}
    thr = Throttle(
        name=f"t{idx}",
        spec=ThrottleSpec(
            throttler_name="kube-throttler",
            threshold=threshold,
            accel_class_thresholds=tuple(accel),
            selector=ThrottleSelector(
                selector_terms=(
                    ThrottleSelectorTerm(LabelSelector(match_labels=labels)),
                )
            ),
        ),
        # persisted status: used + flags derived like a reconcile would
        # (flags against the base threshold, onEqual=True — the Throttle
        # kind's write path), calculated_at left None so the spec
        # threshold stays effective
        status=ThrottleStatus(
            used=used, throttled=threshold.is_throttled(used, True)
        ),
    )
    return thr


@st.composite
def scenarios(draw):
    throttles = [draw(throttle_specs(i)) for i in range(draw(st.integers(1, 3)))]
    n_members = draw(st.integers(1, 5))
    accel = draw(st.sampled_from(ACCEL_CLASSES))
    members = []
    for i in range(n_members):
        cpu = draw(st.integers(0, 2000))
        grp = draw(st.sampled_from(GROUPS))
        members.append((f"m{i}", grp, cpu))
    # optional pre-existing per-pod reservation
    filler = (
        (draw(st.sampled_from(GROUPS)), draw(st.integers(0, 1500)))
        if draw(st.booleans())
        else None
    )
    return throttles, members, accel, filler


def _build(throttles, filler):
    store = Store()
    store.create_namespace(Namespace("default"))
    plugin = KubeThrottler(
        decode_plugin_args(
            {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
        ),
        store,
        use_device=True,
    )
    for thr in throttles:
        store.create_throttle(thr)
    if filler is not None:
        grp, cpu = filler
        plugin.reserve(
            make_pod("filler", labels={"grp": grp}, requests={"cpu": f"{cpu}m"})
        )
    return store, plugin


def _reservation_state(plugin, throttles):
    out = {}
    for thr in throttles:
        amt, keys = plugin.throttle_ctr.cache.reserved_resource_amount(thr.key)
        out[thr.key] = (amt, frozenset(keys))
    return out


@given(scenarios())
@settings(max_examples=40, deadline=None)
def test_batched_gang_kernel_equals_sequential_oracle(scenario):
    throttles, member_specs, accel, filler = scenario
    store, plugin = _build(throttles, filler)
    try:
        members = [
            make_pod(
                name,
                labels={"grp": grp},
                requests={"cpu": f"{cpu}m"},
                group="job",
                group_size=len(member_specs),
                accel_class=accel,
            )
            for name, grp, cpu in member_specs
        ]
        dm = plugin.device_manager
        kernel = dm.gang_check_groups([("default/job", members, accel)])
        kernel_ok = kernel["default/job"]["ok"]
        oracle_ok, blocked = sequential_gang_check(
            members,
            (
                ("throttle", plugin.throttle_ctr, False),
                ("clusterthrottle", plugin.cluster_throttle_ctr, False),
            ),
        )
        assert kernel_ok == oracle_ok, (
            f"kernel={kernel_ok} oracle={oracle_ok} blocked={blocked} "
            f"detail={kernel['default/job']['kinds']} accel={accel} "
            f"throttles={[ (t.key, t.spec.threshold, t.status.used) for t in throttles ]} "
            f"members={member_specs}"
        )

        # reserve → rollback leaves ledger, reserved planes, and per-pod
        # verdicts bit-identical (the rollback path is invisible)
        res_before = _reservation_state(plugin, throttles)
        flags_before = dm.published_flags()
        probe = make_pod("probe", labels={"grp": "g0"}, requests={"cpu": "500m"})
        verdict_before = plugin.pre_filter(probe).code
        assert plugin.reserve_gang("default/job", members).is_success()
        plugin.unreserve_gang("default/job")
        assert _reservation_state(plugin, throttles) == res_before
        assert dm.published_flags() == flags_before
        assert plugin.pre_filter(probe).code == verdict_before
        assert plugin.gang.pending_groups() == 0
    finally:
        plugin.stop()
