"""Checkpoint/resume for standalone mode (SURVEY §5): the Store journal
makes the crash-only stance real — the reference's state of record is the
apiserver; standalone's is this durable event log."""

from __future__ import annotations

from dataclasses import replace

from kube_throttler_tpu.api.pod import Namespace, make_pod
from kube_throttler_tpu.api.types import (
    LabelSelector,
    ResourceAmount,
    Throttle,
    ThrottleSelector,
    ThrottleSelectorTerm,
    ThrottleSpec,
)
from kube_throttler_tpu.engine.journal import attach
from kube_throttler_tpu.engine.store import Store
from kube_throttler_tpu.plugin import KubeThrottler, decode_plugin_args


def _throttle(name, labels, **threshold):
    return Throttle(
        name=name,
        spec=ThrottleSpec(
            throttler_name="kube-throttler",
            threshold=ResourceAmount.of(**threshold),
            selector=ThrottleSelector(
                selector_terms=(
                    ThrottleSelectorTerm(LabelSelector(match_labels=labels)),
                )
            ),
        ),
    )


def _bound(pod):
    bound = replace(pod, spec=replace(pod.spec, node_name="node-1"))
    bound.status.phase = "Running"
    return bound


def _populate(store):
    store.create_namespace(Namespace("default"))
    store.create_throttle(_throttle("t1", {"grp": "a"}, pod=10, requests={"cpu": "1"}))
    store.create_pod(_bound(make_pod("p1", labels={"grp": "a"}, requests={"cpu": "300m"})))
    store.create_pod(make_pod("p2", labels={"grp": "a"}, requests={"cpu": "100m"}))
    store.delete_pod("default", "p2")


class TestJournal:
    def test_crash_resume_round_trip(self, tmp_path):
        path = str(tmp_path / "store.journal")
        store = Store()
        journal = attach(store, path)
        _populate(store)
        # a status write (the thing an informer resync could NOT recover in
        # standalone mode) must survive too
        thr = store.get_throttle("default", "t1")
        store.update_throttle_status(
            thr.with_status(replace(thr.status, used=ResourceAmount.of(pod=1)))
        )
        # crash: no close(), fresh process
        recovered = Store()
        attach(recovered, path).close()
        assert {p.key for p in recovered.list_pods()} == {"default/p1"}
        t1 = recovered.get_throttle("default", "t1")
        assert t1.spec.threshold == ResourceAmount.of(pod=10, requests={"cpu": "1"})
        assert t1.status.used.resource_counts == 1
        assert recovered.get_namespace("default") is not None
        journal.close()

    def test_truncated_tail_is_dropped(self, tmp_path):
        path = str(tmp_path / "store.journal")
        store = Store()
        journal = attach(store, path)
        _populate(store)
        journal.close()
        with open(path, "a") as f:
            f.write('{"type": "ADDED", "kind": "Pod", "obj')  # crash mid-write
        recovered = Store()
        attach(recovered, path).close()
        assert {p.key for p in recovered.list_pods()} == {"default/p1"}

    def test_torn_final_line_is_normal_not_corruption(self, tmp_path):
        """A torn FINAL line is the legal crash artifact: truncated
        silently, counted in torn_tails, and the journal health stays OK —
        an operator page for every unclean shutdown would be noise."""
        path = str(tmp_path / "store.journal")
        store = Store()
        journal = attach(store, path)
        _populate(store)
        journal.close()
        with open(path, "a") as f:
            f.write('{"type": "ADDED", "kind": "Pod", "obj')  # crash mid-write
        recovered = Store()
        j = attach(recovered, path)
        assert j.torn_tails == 1
        assert j.replay_skipped == 0
        state, detail = j.health_state()
        assert state == "ok"
        assert detail["tornTails"] == 1
        j.close()

    def test_interior_corruption_counts_and_degrades(self, tmp_path):
        """A bad line WITH good lines after it cannot be a crash tail —
        it is real corruption: skipped, counted, health degraded."""
        path = str(tmp_path / "store.journal")
        store = Store()
        journal = attach(store, path)
        store.create_namespace(Namespace("default"))
        journal.close()
        with open(path, "a") as f:
            f.write("{corrupt interior line!!\n")
        # more valid history lands AFTER the corruption
        store2 = Store()
        j2 = attach(store2, path)
        assert j2.replay_skipped == 1 and j2.torn_tails == 0
        store2.create_throttle(_throttle("t1", {"grp": "a"}, pod=10))
        state, _ = j2.health_state()
        assert state == "degraded"
        j2.close()
        # and the post-corruption throttle still replays on the NEXT restart
        store3 = Store()
        j3 = attach(store3, path)
        assert len(store3.list_throttles()) == 1
        assert j3.replay_skipped == 1  # the interior line, re-counted per replay
        j3.close()

    def test_trailing_run_counts_all_but_final_line_as_corruption(self, tmp_path):
        """Only the LAST line of a trailing corrupt run can be the
        crash-mid-write artifact; bad lines ahead of it had writes land
        after them, so they are genuine corruption: counted (degraded)
        while the final line truncates silently."""
        path = str(tmp_path / "store.journal")
        store = Store()
        journal = attach(store, path)
        _populate(store)
        journal.close()
        with open(path, "a") as f:
            f.write("!!corrupt-but-complete-line\n")
            f.write('{"type": "ADDED", "kind": "Pod", "obj')  # torn final
        recovered = Store()
        j = attach(recovered, path)
        assert j.replay_skipped == 1  # the complete-but-corrupt line
        assert j.torn_tails == 1  # the torn final line
        state, _ = j.health_state()
        assert state == "degraded"
        assert {p.key for p in recovered.list_pods()} == {"default/p1"}
        j.close()

    def test_post_corruption_appends_survive_the_next_restart(self, tmp_path):
        """attach() must truncate the corrupt tail BEFORE appending: events
        written after a corrupt line would otherwise be stranded behind the
        gap and silently lost on every later replay."""
        path = str(tmp_path / "store.journal")
        store = Store()
        journal = attach(store, path)
        store.create_namespace(Namespace("default"))
        journal.close()
        with open(path, "a") as f:
            f.write('{"type": "ADDED", "kind": "Pod", "obj')  # crash mid-write

        # restart 1: recovers, then writes MORE history
        store2 = Store()
        j2 = attach(store2, path)
        store2.create_throttle(_throttle("t1", {"grp": "a"}, pod=10))
        j2.close()

        # restart 2: the post-corruption throttle MUST still be there
        store3 = Store()
        attach(store3, path).close()
        assert len(store3.list_throttles()) == 1
        assert store3.get_namespace("default") is not None

    def test_compaction_preserves_state_and_shrinks_log(self, tmp_path):
        path = str(tmp_path / "store.journal")
        store = Store()
        journal = attach(store, path, compact_after=50)
        store.create_namespace(Namespace("default"))
        store.create_throttle(_throttle("t1", {"grp": "a"}, pod=10))
        pod = _bound(make_pod("p1", labels={"grp": "a"}, requests={"cpu": "100m"}))
        store.create_pod(pod)
        for i in range(200):  # churn well past compact_after
            store.update_pod(
                _bound(
                    make_pod("p1", labels={"grp": "a"}, requests={"cpu": f"{100 + i}m"})
                )
            )
        journal.close()
        n_lines = sum(1 for _ in open(path))
        assert n_lines < 100  # compacted: snapshot + post-compaction tail
        recovered = Store()
        attach(recovered, path).close()
        assert len(recovered.list_pods()) == 1
        assert len(recovered.list_throttles()) == 1

    def test_daemon_resumes_with_live_state(self, tmp_path):
        """Full loop: daemon writes statuses, 'crashes', a new daemon over
        the same journal serves correct admission immediately."""
        path = str(tmp_path / "store.journal")
        store = Store()
        journal = attach(store, path)
        plugin = KubeThrottler(
            decode_plugin_args(
                {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
            ),
            store,
            use_device=True,
        )
        store.create_namespace(Namespace("default"))
        store.create_throttle(
            _throttle("t1", {"grp": "a"}, requests={"cpu": "1"})
        )
        store.create_pod(
            _bound(make_pod("p1", labels={"grp": "a"}, requests={"cpu": "800m"}))
        )
        plugin.run_pending_once()
        assert store.get_throttle("default", "t1").status.used.resource_counts == 1
        plugin.stop()  # crash (journal deliberately not closed)

        store2 = Store()
        attach(store2, path)
        plugin2 = KubeThrottler(
            decode_plugin_args(
                {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
            ),
            store2,
            use_device=True,
        )
        # recovered status is immediately live — no reconcile needed for the
        # status-flag step, exactly like a restart against a real apiserver
        assert store2.get_throttle("default", "t1").status.used.resource_counts == 1
        verdict = plugin2.pre_filter(
            make_pod("p2", labels={"grp": "a"}, requests={"cpu": "300m"})
        )
        assert not verdict.is_success()
        assert "throttle[insufficient]=default/t1" in verdict.reasons
        plugin2.stop()
