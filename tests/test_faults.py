"""Fault-injection subsystem (faults/plan.py) + the hardening it exists to
exercise: FaultPlan determinism, transport/client fault sites, mockserver
server-side fault verbs, bounded Watch overflow, journal interior-corruption
replay, and the device breaker's half-open probe state."""

from __future__ import annotations

import threading
import time
from dataclasses import replace

import pytest

from kube_throttler_tpu.api.pod import Namespace, make_pod
from kube_throttler_tpu.api.serialization import object_to_dict
from kube_throttler_tpu.api.types import (
    LabelSelector,
    ResourceAmount,
    Throttle,
    ThrottleSelector,
    ThrottleSelectorTerm,
    ThrottleSpec,
)
from kube_throttler_tpu.client.mockserver import MockApiServer
from kube_throttler_tpu.client.transport import (
    ApiClient,
    ApiError,
    Backoff,
    GoneError,
    Reflector,
    RemoteStatusWriter,
    RemoteVersions,
    RestConfig,
)
from kube_throttler_tpu.client.watch import Watch
from kube_throttler_tpu.engine.journal import attach
from kube_throttler_tpu.engine.store import ConflictError, Store
from kube_throttler_tpu.faults import FaultInjected, FaultPlan


def _throttle(name, labels, **threshold):
    return Throttle(
        name=name,
        spec=ThrottleSpec(
            throttler_name="kube-throttler",
            threshold=ResourceAmount.of(**threshold),
            selector=ThrottleSelector(
                selector_terms=(
                    ThrottleSelectorTerm(LabelSelector(match_labels=labels)),
                )
            ),
        ),
    )


def _wait(predicate, timeout=10.0, every=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(every)
    return predicate()


class TestFaultPlanDeterminism:
    def _drive(self, seed):
        plan = FaultPlan(seed)
        plan.rule("transport.watch.read", mode="close", probability=0.3)
        plan.rule("journal.append", mode="torn", schedule=[3, 7], times=2)
        plan.rule("mock.*", probability=0.5, times=4)
        for _ in range(40):
            plan.check("transport.watch.read")
        for _ in range(10):
            plan.check("journal.append")
        for _ in range(20):
            plan.check("mock.list")
            plan.check("mock.status.conflict")
        return plan.snapshot()

    def test_same_seed_same_sequence(self):
        assert self._drive(42) == self._drive(42)

    def test_different_seed_different_sequence(self):
        # probabilistic rules must actually depend on the seed
        assert self._drive(1) != self._drive(2)

    def test_reproducible_across_threads(self):
        """Per-site sequences are pure functions of (seed, site, hit):
        concurrent hits on OTHER sites cannot perturb a site's fault
        sequence — the property the chaos soak's reproducibility rests on."""

        def run(with_noise):
            plan = FaultPlan(7)
            plan.rule("site.a", probability=0.4)
            plan.rule("site.noise", probability=0.9)
            noise_stop = threading.Event()

            def noise():
                while not noise_stop.is_set():
                    plan.check("site.noise")

            t = threading.Thread(target=noise)
            if with_noise:
                t.start()
            fired = [bool(plan.check("site.a")) for _ in range(200)]
            if with_noise:
                noise_stop.set()
                t.join()
            return fired

        assert run(False) == run(True)

    def test_schedule_times_after(self):
        plan = FaultPlan(0)
        plan.rule("s", schedule=[2, 4, 6], times=2, after=1)
        # hit 1 skipped (after); schedule counts from hit-after
        fired = [plan.check("s") is not None for _ in range(10)]
        # hits 3 and 5 fire ((hit-after) in {2,4,6}), then times=2 caps it
        assert fired == [False, False, True, False, True, False, False, False, False, False]

    def test_maybe_raise_default_and_custom(self):
        plan = FaultPlan(0)
        plan.rule("a", times=1)
        plan.rule("b", error=lambda: ConnectionResetError("boom"), times=1)
        with pytest.raises(FaultInjected):
            plan.maybe_raise("a")
        plan.maybe_raise("a")  # exhausted: passes through
        with pytest.raises(ConnectionResetError):
            plan.maybe_raise("b")

    def test_reset_replays_identically(self):
        plan = FaultPlan(3)
        plan.rule("s", probability=0.5)
        first = [bool(plan.check("s")) for _ in range(30)]
        witness = plan.snapshot()
        plan.reset()
        assert [bool(plan.check("s")) for _ in range(30)] == first
        assert plan.snapshot() == witness


class TestBackoff:
    def test_exponential_jittered_capped_reset(self):
        import random

        b = Backoff(base=1.0, cap=8.0, rng=random.Random(0))
        delays = [b.next() for _ in range(6)]
        raws = [1.0, 2.0, 4.0, 8.0, 8.0, 8.0]
        for d, raw in zip(delays, raws):
            assert raw / 2 <= d <= raw, (d, raw)
        b.reset()
        assert b.next() <= 1.0  # back to base after a healthy stream

    def test_reflector_resets_backoff_on_event(self):
        server = MockApiServer(bookmark_interval=0.05)
        server.store.create_namespace(Namespace("default"))
        server.start()
        try:
            client = ApiClient(RestConfig(server=server.url))
            local = Store()
            refl = Reflector(client, "Namespace", local, backoff=0.01)
            refl._backoff._attempts = 5  # pretend we were mid-ladder
            refl.consecutive_failures = 5
            refl.start()
            assert refl.wait_for_sync(5)
            server.store.create_namespace(Namespace("fresh"))
            assert _wait(lambda: local.get_namespace("fresh") is not None)
            assert refl._backoff.attempts == 0
            assert refl.consecutive_failures == 0
            assert refl.health_state() == "ok"
        finally:
            refl.stop()
            server.stop()


class TestTransportFaultSites:
    @pytest.fixture()
    def apiserver(self):
        server = MockApiServer(bookmark_interval=0.05)
        server.store.create_namespace(Namespace("default"))
        server.start()
        yield server
        server.stop()

    def test_request_site_raises_connection_reset(self, apiserver):
        plan = FaultPlan(0)
        plan.rule("transport.request", times=1)
        client = ApiClient(RestConfig(server=apiserver.url), faults=plan)
        with pytest.raises(ConnectionResetError):
            client.list("Namespace")
        items, _ = client.list("Namespace")  # exhausted: next call lands
        assert len(items) == 1

    def test_put_conflict_storm_site(self, apiserver):
        apiserver.store.create_throttle(_throttle("t1", {"a": "b"}, pod=5))
        plan = FaultPlan(0)
        plan.rule("transport.put.conflict", times=2)
        client = ApiClient(RestConfig(server=apiserver.url), faults=plan)
        writer = RemoteStatusWriter(client, RemoteVersions())
        thr = apiserver.store.get_throttle("default", "t1")
        for _ in range(2):
            with pytest.raises(ConflictError):
                writer.update_throttle_status(thr)
        writer.update_throttle_status(thr)  # storm over

    def test_watch_read_gone_site_forces_relist(self, apiserver):
        plan = FaultPlan(0)
        plan.rule("transport.watch.read", mode="gone", schedule=[2])
        client = ApiClient(RestConfig(server=apiserver.url), faults=plan)
        from kube_throttler_tpu.metrics import Registry
        from kube_throttler_tpu.client.transport import ReflectorMetrics

        registry = Registry()
        local = Store()
        refl = Reflector(
            client, "Namespace", local, backoff=0.01,
            metrics=ReflectorMetrics(registry),
        )
        refl.start()
        try:
            assert refl.wait_for_sync(5)
            apiserver.store.create_namespace(Namespace("n1"))
            assert _wait(lambda: local.get_namespace("n1") is not None)
            # the injected 410 forced (at least) one gone→relist round trip
            assert _wait(
                lambda: (registry.flush() or True)
                and registry.counter_vec(
                    "kube_throttler_reflector_gone_total", "", ["kind"]
                ).collect().get(("Namespace",), 0) >= 1
            )
            # and the cache is still correct after the relist
            apiserver.store.create_namespace(Namespace("n2"))
            assert _wait(lambda: local.get_namespace("n2") is not None)
        finally:
            refl.stop()

    def test_watch_close_site_reconnects_without_losing_events(self, apiserver):
        plan = FaultPlan(5)
        plan.rule("transport.watch.read", mode="close", probability=0.3)
        client = ApiClient(RestConfig(server=apiserver.url), faults=plan)
        local = Store()
        refl = Reflector(client, "Namespace", local, backoff=0.01)
        refl.start()
        try:
            assert refl.wait_for_sync(5)
            for i in range(30):
                apiserver.store.create_namespace(Namespace(f"ns-{i:02d}"))
            assert _wait(lambda: len(local.list_namespaces()) == 31)
            assert plan.fired("transport.watch.read") > 0, "faults never fired"
        finally:
            refl.stop()


class TestMockserverFaultVerbs:
    def test_list_error_verb(self):
        server = MockApiServer()
        server.store.create_namespace(Namespace("default"))
        plan = FaultPlan(0)
        plan.rule("mock.list", mode="error", times=1)
        server.faults = plan
        server.start()
        try:
            client = ApiClient(RestConfig(server=server.url))
            with pytest.raises(ApiError) as exc:
                client.list("Namespace")
            assert exc.value.status == 500
            items, _ = client.list("Namespace")  # exhausted → serves
            assert len(items) == 1
        finally:
            server.stop()

    def test_list_gone_verb(self):
        server = MockApiServer()
        plan = FaultPlan(0)
        plan.rule("mock.list", mode="gone", times=1)
        server.faults = plan
        server.start()
        try:
            client = ApiClient(RestConfig(server=server.url))
            with pytest.raises(GoneError):
                client.list("Namespace")
        finally:
            server.stop()

    def test_status_conflict_verb(self):
        server = MockApiServer()
        server.store.create_namespace(Namespace("default"))
        server.store.create_throttle(_throttle("t1", {"a": "b"}, pod=5))
        plan = FaultPlan(0)
        plan.rule("mock.status.conflict", times=1)
        server.faults = plan
        server.start()
        try:
            client = ApiClient(RestConfig(server=server.url))
            writer = RemoteStatusWriter(client, RemoteVersions())
            thr = server.store.get_throttle("default", "t1")
            with pytest.raises(ConflictError):
                writer.update_throttle_status(thr)
            writer.update_throttle_status(thr)  # storm over → lands
            assert (
                server.store.get_throttle("default", "t1").status.used
                == thr.status.used
            )
        finally:
            server.stop()

    def test_watch_cut_verb_reflector_recovers(self):
        """The server severs watch streams mid-flight; the reflector must
        re-watch from its resume point and end with a complete cache (no
        lost events across reconnects)."""
        server = MockApiServer(bookmark_interval=0.02)
        server.store.create_namespace(Namespace("default"))
        plan = FaultPlan(9)
        plan.rule("mock.watch.cut", probability=0.3, times=5)
        server.faults = plan
        server.start()
        try:
            client = ApiClient(RestConfig(server=server.url))
            local = Store()
            refl = Reflector(client, "Namespace", local, backoff=0.01)
            refl.start()
            assert refl.wait_for_sync(5)
            for i in range(25):
                server.store.create_namespace(Namespace(f"cut-{i:02d}"))
                time.sleep(0.005)  # let the stream interleave with cuts
            assert _wait(lambda: len(local.list_namespaces()) == 26)
            assert plan.fired("mock.watch.cut") > 0, "cut verb never fired"
        finally:
            refl.stop()
            server.stop()

    def test_watch_gone_verb_forces_relist(self):
        server = MockApiServer(bookmark_interval=0.02)
        plan = FaultPlan(0)
        plan.rule("mock.watch.gone", schedule=[2], times=1)
        server.faults = plan
        server.start()
        try:
            client = ApiClient(RestConfig(server=server.url))
            local = Store()
            refl = Reflector(client, "Namespace", local, backoff=0.01)
            refl.start()
            assert refl.wait_for_sync(5)
            assert _wait(lambda: plan.fired("mock.watch.gone") == 1, timeout=5)
            server.store.create_namespace(Namespace("after-gone"))
            assert _wait(lambda: local.get_namespace("after-gone") is not None)
        finally:
            refl.stop()
            server.stop()


class TestWatchOverflow:
    def test_slow_consumer_does_not_block_dispatch(self):
        """The store's dispatch thread must never block on a full watch
        queue: drop-oldest sheds, counts, and flags the gap."""
        store = Store()
        w = Watch(store, "Namespace", maxsize=4)
        t0 = time.monotonic()
        for i in range(100):  # nobody consuming
            store.create_namespace(Namespace(f"ns-{i:03d}"))
        elapsed = time.monotonic() - t0
        assert elapsed < 2.0, f"dispatch blocked on a slow consumer ({elapsed:.1f}s)"
        assert w.dropped == 96
        assert w.overflowed
        # the consumer sees the NEWEST 4 events (oldest shed)
        kept = [w.next(timeout=1) for _ in range(4)]
        assert [e.obj.name for e in kept] == [f"ns-{i:03d}" for i in range(96, 100)]
        w.stop()

    def test_no_overflow_under_capacity(self):
        store = Store()
        w = Watch(store, "Namespace", maxsize=16)
        for i in range(10):
            store.create_namespace(Namespace(f"n-{i}"))
        assert w.dropped == 0 and not w.overflowed
        assert [e.obj.name for e in (w.next(timeout=1) for _ in range(10))]
        w.stop()

    def test_stop_on_full_queue_still_terminates(self):
        store = Store()
        w = Watch(store, "Namespace", maxsize=2)
        for i in range(5):
            store.create_namespace(Namespace(f"x-{i}"))
        w.stop()  # full queue: stop must shed one event, never block
        drained = []
        with pytest.raises(StopIteration):
            while True:
                drained.append(w.next(timeout=1))
        assert len(drained) <= 2

    def test_block_policy_preserves_every_event(self):
        store = Store()
        w = Watch(store, "Namespace", maxsize=8, overflow="block")
        seen = []
        done = threading.Event()

        def consume():
            for event in w:
                seen.append(event.obj.name)
                if len(seen) == 50:
                    done.set()

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        for i in range(50):
            store.create_namespace(Namespace(f"b-{i:02d}"))
        assert done.wait(5), f"only {len(seen)} events arrived"
        assert seen == [f"b-{i:02d}" for i in range(50)]  # no loss, in order
        assert w.dropped == 0
        w.stop()
        t.join(timeout=2)

    def test_invalid_policy_rejected(self):
        with pytest.raises(ValueError):
            Watch(Store(), "Namespace", overflow="banana")

    def test_stats_and_metrics_exposition(self):
        from kube_throttler_tpu.metrics import Registry, register_watch_metrics

        store = Store()
        w = Watch(store, "Namespace", maxsize=2)
        for i in range(5):
            store.create_namespace(Namespace(f"m-{i}"))
        registry = Registry()
        register_watch_metrics(registry)
        expo = registry.exposition()
        assert "kube_throttler_watch_queue_depth" in expo
        assert "kube_throttler_watch_overflow_total" in expo
        stats = Watch.stats()
        assert stats["dropped_total"] >= 3
        assert stats["depth"] >= 2
        w.stop()


class TestJournalCorruption:
    def _populate(self, store):
        store.create_namespace(Namespace("default"))
        store.create_throttle(_throttle("t1", {"grp": "a"}, pod=10))
        store.create_pod(make_pod("p1", labels={"grp": "a"}))
        store.create_pod(make_pod("p2", labels={"grp": "a"}))

    def test_interior_corruption_skipped_and_counted(self, tmp_path):
        path = str(tmp_path / "j")
        store = Store()
        journal = attach(store, path)
        self._populate(store)
        journal.close()
        # corrupt an INTERIOR line (the throttle), keep everything after
        lines = open(path, "rb").read().splitlines(keepends=True)
        lines[1] = b'{"type": "ADDED", "kind": "Thro\xff GARBAGE\n'
        open(path, "wb").write(b"".join(lines))
        recovered = Store()
        j2 = attach(recovered, path)
        # the pods AFTER the corrupt line survived — replay did not abort
        assert {p.key for p in recovered.list_pods()} == {"default/p1", "default/p2"}
        assert recovered.get_namespace("default") is not None
        assert recovered.list_throttles() == []  # the corrupted event is lost
        assert j2.replay_skipped == 1
        state, detail = j2.health_state()
        assert state == "degraded" and detail["replaySkipped"] == 1
        # the file was NOT truncated at the corruption point
        assert len(open(path, "rb").read().splitlines()) == len(lines)
        j2.close()

    def test_interior_plus_torn_tail(self, tmp_path):
        path = str(tmp_path / "j")
        store = Store()
        journal = attach(store, path)
        self._populate(store)
        journal.close()
        lines = open(path, "rb").read().splitlines(keepends=True)
        lines[2] = b"NOT JSON AT ALL\n"  # interior
        with open(path, "wb") as f:
            f.write(b"".join(lines))
            f.write(b'{"type": "ADDED", "kind": "Pod", "obj')  # torn tail
        recovered = Store()
        j2 = attach(recovered, path)
        assert j2.replay_skipped == 1  # interior skipped
        # tail truncated so post-recovery appends aren't stranded
        recovered.create_namespace(Namespace("late"))
        j2.close()
        third = Store()
        j3 = attach(third, path)
        assert third.get_namespace("late") is not None
        assert j3.replay_skipped == 1  # interior line still there, still skipped
        j3.close()

    def test_torn_write_fault_produces_interior_corruption(self, tmp_path):
        """The journal.append 'torn' fault forges the exact artifact a
        crash mid-write leaves: fragment + next line = one corrupt interior
        line; replay skips it and keeps everything else."""
        path = str(tmp_path / "j")
        plan = FaultPlan(0)
        plan.rule("journal.append", mode="torn", schedule=[3], times=1)
        store = Store()
        journal = attach(store, path, faults=plan)
        self._populate(store)  # 4 events; #3 is torn, #4 merges into it
        store.create_namespace(Namespace("late"))  # a good line AFTER the merge
        assert journal.torn_writes == 1
        journal.close()
        recovered = Store()
        j2 = attach(recovered, path)
        # events 1-2 fine; 3+4 became one corrupt INTERIOR line (both lost);
        # event 5 after the gap survived
        assert recovered.get_namespace("default") is not None
        assert recovered.get_namespace("late") is not None
        assert len(recovered.list_throttles()) == 1
        assert recovered.list_pods() == []
        assert j2.replay_skipped == 1
        j2.close()

    def test_write_error_fault_drops_event(self, tmp_path):
        path = str(tmp_path / "j")
        plan = FaultPlan(0)
        plan.rule("journal.append", mode="error", schedule=[2], times=1)
        store = Store()
        journal = attach(store, path, faults=plan)
        self._populate(store)
        assert journal.write_errors == 1
        journal.close()
        recovered = Store()
        attach(recovered, path).close()
        # event #2 (the throttle) never hit the log
        assert recovered.list_throttles() == []
        assert {p.key for p in recovered.list_pods()} == {"default/p1", "default/p2"}

    def test_fsync_fault_fails_compaction_but_not_dispatch(self, tmp_path):
        path = str(tmp_path / "j")
        plan = FaultPlan(0)
        plan.rule("journal.fsync", times=1)
        store = Store()
        journal = attach(store, path, compact_after=6, faults=plan)
        self._populate(store)  # 4 events
        # two more events cross compact_after → compaction runs, fsync fails
        store.create_pod(make_pod("p3", labels={"grp": "a"}))
        store.create_pod(make_pod("p4", labels={"grp": "a"}))
        assert journal.compact_failures == 1
        # dispatch survived; the uncompacted log is intact and still grows
        store.create_pod(make_pod("p5", labels={"grp": "a"}))
        journal.close()
        recovered = Store()
        attach(recovered, path).close()
        assert {p.name for p in recovered.list_pods()} == {"p1", "p2", "p3", "p4", "p5"}

    def test_compact_heals_torn_log(self, tmp_path):
        path = str(tmp_path / "j")
        plan = FaultPlan(1)
        plan.rule("journal.append", mode="torn", probability=0.3)
        store = Store()
        journal = attach(store, path, faults=plan)
        self._populate(store)
        for i in range(20):
            store.create_pod(make_pod(f"extra-{i:02d}", labels={"grp": "a"}))
        assert journal.torn_writes > 0, "torn faults never fired"
        journal.compact()  # snapshot from the live store: gaps erased
        journal.close()
        recovered = Store()
        j2 = attach(recovered, path)
        assert j2.replay_skipped == 0
        assert {p.name for p in recovered.list_pods()} == {
            p.name for p in store.list_pods()
        }
        assert [object_to_dict(t) for t in recovered.list_throttles()] == [
            object_to_dict(t) for t in store.list_throttles()
        ]
        j2.close()


class TestBreakerHalfOpen:
    def _dm(self):
        from kube_throttler_tpu.engine.devicestate import DeviceStateManager

        store = Store()
        dm = DeviceStateManager(store, "kt", "sched")
        now = [1000.0]
        dm._monotonic = lambda: now[0]
        return dm, now

    def test_closed_open_halfopen_closed_cycle(self):
        dm, now = self._dm()
        assert dm.breaker_state() == "closed"
        calls = []

        def ok():
            calls.append("ok")
            return {"fine": True}

        def boom():
            calls.append("boom")
            raise RuntimeError("tunnel died")

        assert dm.guarded("t", ok) == {"fine": True}
        assert dm.guarded("t", boom) is None  # opens
        assert dm.breaker_state() == "open"
        assert dm.guarded("t", ok) is None  # open: not dispatched
        assert calls == ["ok", "boom"]
        now[0] += dm.device_retry_cooldown + 1
        assert dm.breaker_state() == "half-open"
        assert dm.device_available()
        assert dm.guarded("t", ok) == {"fine": True}  # the probe
        assert dm.breaker_state() == "closed"
        assert calls == ["ok", "boom", "ok"]

    def test_failed_probe_reopens(self):
        dm, now = self._dm()

        def boom():
            raise RuntimeError("still dead")

        dm.guarded("t", boom)
        now[0] += dm.device_retry_cooldown + 1
        assert dm.breaker_state() == "half-open"
        assert dm.guarded("t", boom) is None  # probe fails
        assert dm.breaker_state() == "open"
        assert not dm.device_available()

    def test_single_probe_no_stampede(self):
        """While one thread's probe is in flight, every other guarded call
        must fall back WITHOUT dispatching (exactly one probe per
        half-open window)."""
        dm, now = self._dm()
        dm.guarded("t", lambda: (_ for _ in ()).throw(RuntimeError("die")))
        now[0] += dm.device_retry_cooldown + 1

        probe_entered = threading.Event()
        release_probe = threading.Event()
        dispatches = []

        def slow_probe():
            dispatches.append("probe")
            probe_entered.set()
            release_probe.wait(5)
            return {"ok": True}

        t = threading.Thread(target=lambda: dm.guarded("t", slow_probe))
        t.start()
        assert probe_entered.wait(5)
        # probe in flight: other callers are rejected without dispatch
        for _ in range(5):
            assert dm.guarded("t", lambda: dispatches.append("stampede")) is None
        release_probe.set()
        t.join(timeout=5)
        assert dispatches == ["probe"]
        assert dm.breaker_state() == "closed"

    def test_injected_device_fault_site(self):
        dm, now = self._dm()
        plan = FaultPlan(0)
        plan.rule("device.dispatch", times=1)
        dm.faults = plan
        assert dm.guarded("t", lambda: {"x": 1}) is None  # injected failure
        assert dm.breaker_state() == "open"
        now[0] += dm.device_retry_cooldown + 1
        assert dm.guarded("t", lambda: {"x": 1}) == {"x": 1}  # plan exhausted
        assert dm.breaker_state() == "closed"

    def test_breaker_state_gauge_exported(self):
        from kube_throttler_tpu.metrics import Registry, register_breaker_metrics

        dm, now = self._dm()
        registry = Registry()
        register_breaker_metrics(registry, dm)
        assert "kube_throttler_device_breaker_state 0" in registry.exposition()
        dm.note_device_failure("t", RuntimeError("die"))
        assert "kube_throttler_device_breaker_state 1" in registry.exposition()
        now[0] += dm.device_retry_cooldown + 1
        assert "kube_throttler_device_breaker_state 2" in registry.exposition()


class TestReadyzHealth:
    def test_degraded_stays_200_down_503(self):
        import json as _json
        import urllib.error
        import urllib.request

        from kube_throttler_tpu.plugin import (
            KubeThrottler,
            RecordingEventRecorder,
            decode_plugin_args,
        )
        from kube_throttler_tpu.server import ThrottlerHTTPServer

        store = Store()
        store.create_namespace(Namespace("default"))
        plugin = KubeThrottler(
            decode_plugin_args(
                {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
            ),
            store,
            event_recorder=RecordingEventRecorder(),
        )
        server = ThrottlerHTTPServer(plugin, port=0)
        server.start()
        try:
            url = f"http://127.0.0.1:{server.port}/readyz"

            def readyz():
                with urllib.request.urlopen(url, timeout=5) as resp:
                    return resp.status, _json.load(resp)

            code, body = readyz()
            assert code == 200 and body["ok"] and body["state"] == "ok"
            assert body["components"]["device"]["state"] == "ok"
            assert body["components"]["workqueues"]["state"] == "ok"

            # open the breaker → degraded, still 200 (host oracle serves)
            plugin.device_manager.note_device_failure("t", RuntimeError("die"))
            code, body = readyz()
            assert code == 200 and body["state"] == "degraded"
            assert body["components"]["device"]["breaker"] == "open"
            assert body["device"]["breaker"] == "open"

            # a down component → 503 (probes yank the pod)
            plugin.health.register("reflector.Pod", lambda: ("down", {}))
            with pytest.raises(urllib.error.HTTPError) as exc:
                readyz()
            assert exc.value.code == 503
            assert _json.load(exc.value)["state"] == "down"
        finally:
            server.stop()
            plugin.stop()
