"""Kill-a-shard robustness smoke: SIGKILL a worker process mid-churn.

Drives the REAL multiprocess stack (front + ShardSupervisor + worker
subprocesses over socketpair IPC, tools/harness.py fixtures/oracles):

- while a shard is dark, the front degrades FAIL-SAFE — pods matching
  that shard's keyspace report unschedulable, health reports degraded;
- the supervisor restarts the worker and resyncs its keyspace slice;
- after recovery, verdicts equal a single-process oracle over the same
  final state and every published throttled flag equals the recomputed
  one — no lost flips.

The second test arms the ``shard.worker.kill`` fault site instead of an
external SIGKILL: the worker dies BY THE SEEDED PLAN at its Nth routed
event batch (the registered chaos site), and the same recovery contract
holds.
"""

from __future__ import annotations

import os
import signal
import time

import pytest

import tools.harness as H
from kube_throttler_tpu.api.pod import Namespace, make_pod
from kube_throttler_tpu.engine.store import Store
from kube_throttler_tpu.plugin.framework import StatusCode
from kube_throttler_tpu.sharding.front import AdmissionFront
from kube_throttler_tpu.sharding.supervisor import ShardSupervisor

N_SHARDS = 2
N_GROUPS = 6
N_PODS = 24


def _seed(front):
    front.store.create_namespace(Namespace("default"))
    for i in range(N_GROUPS):
        front.store.create_throttle(H.make_throttle(i))
    for i in range(N_PODS):
        front.store.create_pod(_pod(i, 500))


def _pod(i, cpu_m):
    return make_pod(
        f"p{i}",
        labels={"grp": f"g{i % N_GROUPS}"},
        requests={"cpu": f"{cpu_m}m"},
        node_name="node-1",
        phase="Running",
    )


def _churn(front, rng, n=40):
    for _ in range(n):
        i = rng.randrange(N_PODS)
        front.store.update_pod(_pod(i, rng.randrange(1, 9) * 100))


def _oracle_state(front):
    """Single-process oracle over a copy of the front's final state."""
    store = Store()
    store.create_namespace(Namespace("default"))
    for thr in front.store.list_throttles():
        store.create_throttle(thr)
    for pod in front.store.list_pods():
        store.create_pod(pod)
    plugin = H.build_plugin(store)
    plugin.run_pending_once()
    return store, plugin


def _assert_converged(front):
    """Verdict + flip oracle: front verdicts ≡ single-process verdicts on
    the same state, and every published flag ≡ deterministic recompute."""
    store, oracle = _oracle_state(front)
    for pod in store.list_pods():
        got, want = front.pre_filter(pod), oracle.pre_filter(pod)
        assert got.code == want.code, (pod.key, got.reasons, want.reasons)
        assert H.normalized_reasons(got.reasons) == H.normalized_reasons(
            want.reasons
        ), pod.key
    for thr in front.store.list_throttles():
        want_thr = H.recompute_status(front.store, thr)
        assert thr.status.throttled.resource_counts_pod == (
            want_thr.status.throttled.resource_counts_pod
        ), thr.key
        assert thr.status.throttled.resource_requests.get("cpu") == (
            want_thr.status.throttled.resource_requests.get("cpu")
        ), thr.key
        assert thr.status.used == want_thr.status.used, thr.key


def _settle(front, timeout=60.0):
    assert front.drain(timeout=timeout)
    time.sleep(0.8)  # status pushes flush on their own cadence


def _wait_health(front, state, timeout=90.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got, _ = front._shards_health()
        if got == state:
            return True
        time.sleep(0.1)
    return False


@pytest.fixture
def sharded_stack(tmp_path):
    front = AdmissionFront(N_SHARDS)
    sup = ShardSupervisor(
        front, use_device=False, restart_backoff=0.3,
        env={**os.environ, "KT_SHARD_QUIET": "1", "KT_LOCK_ASSERT": "0"},
    )
    try:
        sup.start(ready_timeout=180.0)
        yield front, sup
    finally:
        sup.stop()
        front.stop()


def test_sigkill_worker_mid_churn_degrades_then_recovers(sharded_stack):
    import random

    front, sup = sharded_stack
    rng = random.Random(7)
    _seed(front)
    _settle(front)
    # pick a victim shard + a pod whose verdict depends on it
    victim = front.owner_of("Throttle", "default/t1")
    probe = make_pod("probe", labels={"grp": "g1"}, requests={"cpu": "100m"})
    assert victim in front._pod_target_shards(probe)
    _churn(front, rng, 30)
    os.kill(sup.shard_proc(victim).pid, signal.SIGKILL)
    _churn(front, rng, 20)  # churn continues against a dark shard
    # degraded window: fail-safe verdicts + degraded health (sampled
    # before the supervisor's restart completes)
    saw_failsafe = False
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        status = front.pre_filter(probe)
        state, _ = front._shards_health()
        if (
            status.code == StatusCode.UNSCHEDULABLE_AND_UNRESOLVABLE
            and any("shard[unavailable]" in r for r in status.reasons)
        ):
            saw_failsafe = True
            assert state in ("degraded", "down")
            break
        if state == "ok" and sup.restart_counts()[victim] > 0:
            break  # restarted before we could sample the window
        time.sleep(0.01)
    assert saw_failsafe or sup.restart_counts()[victim] > 0
    # recovery: restart + resync must bring health back and lose nothing
    assert _wait_health(front, "ok", timeout=120.0)
    assert sup.restart_counts()[victim] >= 1
    _churn(front, rng, 20)  # post-recovery churn lands on the rejoined shard
    _settle(front)
    _assert_converged(front)


@pytest.mark.slow
def test_sharded_bad_day_scenario_gates():
    """The composed bad-day trace through 4 shard workers with a
    kill-a-shard episode (scenarios/sharded.py — the make scenario-test
    rung): pace, recovery, flip-p99, and zero-wrong-verdict gates."""
    from kube_throttler_tpu.scenarios.sharded import run_sharded_bad_day

    report = run_sharded_bad_day(n_shards=4, seed=0)
    assert report["pass"], report["gates"]


def test_fault_site_shard_worker_kill_recovers(tmp_path):
    """The registered ``shard.worker.kill`` site: the worker SIGKILLs
    ITSELF at its 6th routed event batch (seeded FaultPlan, the crash
    harness idiom) — same degrade/restart/resync/no-lost-flips contract."""
    import random

    front = AdmissionFront(N_SHARDS)
    sup = ShardSupervisor(
        front, use_device=False, restart_backoff=0.3,
        worker_args=["--fault-site", "shard.worker.kill:kill:5"],
        env={**os.environ, "KT_SHARD_QUIET": "1", "KT_LOCK_ASSERT": "0"},
    )
    rng = random.Random(11)
    try:
        sup.start(ready_timeout=180.0)
        _seed(front)
        # churn until the plan fires on some worker (hit 6 at one shard)
        deadline = time.monotonic() + 60.0
        while (
            sum(sup.restart_counts().values()) == 0
            and time.monotonic() < deadline
        ):
            _churn(front, rng, 10)
            time.sleep(0.1)
        assert sum(sup.restart_counts().values()) >= 1, "fault site never fired"
        assert _wait_health(front, "ok", timeout=120.0)
        _settle(front)
        _assert_converged(front)
    finally:
        sup.stop()
        front.stop()
