"""Cross-host TCP shard transport: framing faults, reconnect/backoff,
per-op deadlines, epoch fencing over the wire, and transport
equivalence (TCP ≡ socketpair ≡ single-process).

The in-process harness here runs the REAL wire stack — ``serve_tcp``
accept loops against :class:`ShardCore`, dialed by
:class:`TcpShardClient` over loopback — with no subprocesses, so every
failure path (torn frame, partition, refused dial, flapping link,
stale epoch) is deterministic under a seeded :class:`FaultPlan`. The
subprocess fleet is covered by the chaos smoke at the bottom (one
small ``tools/netchaostest.py`` case; the full matrix is
``make net-chaos``).
"""

from __future__ import annotations

import io
import pickle
import socket
import threading
import time

import pytest

import tools.harness as H
from kube_throttler_tpu.api.pod import Namespace, make_pod
from kube_throttler_tpu.engine.store import Store
from kube_throttler_tpu.faults.plan import FaultPlan
from kube_throttler_tpu.sharding.front import AdmissionFront
from kube_throttler_tpu.sharding.ipc import (
    _LEN,
    MAX_FRAME,
    FencedError,
    ShardClient,
    ShardUnavailable,
    TcpShardClient,
    read_frame,
    send_frame,
)
from kube_throttler_tpu.sharding.worker import ShardCore, serve, serve_tcp


def wait_until(pred, timeout=10.0, interval=0.02, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


def start_tcp_worker(core):
    """Listen on an ephemeral loopback port and serve the core — the
    in-process analog of ``kube-throttler-shard --listen``."""
    srv = socket.create_server(("127.0.0.1", 0))
    port = srv.getsockname()[1]
    threading.Thread(
        target=serve_tcp, args=(core, srv),
        name=f"test-shard{core.shard_id}-accept", daemon=True,
    ).start()
    return srv, port


class WorkerRig:
    """One in-process ShardCore behind a real TCP listener, plus a
    client factory that tears everything down in reverse order."""

    def __init__(self, shard_id=0, n_shards=1, prepare_ttl=30.0):
        self.core = ShardCore(shard_id, n_shards, use_device=False,
                              prepare_ttl=prepare_ttl)
        self.srv, self.port = start_tcp_worker(self.core)
        self.clients = []

    def client(self, **kw):
        kw.setdefault("connect_timeout", 2.0)
        c = TcpShardClient(self.core.shard_id, "127.0.0.1", self.port, **kw)
        self.clients.append(c)
        return c

    def close(self):
        for c in self.clients:
            c.close()
        self.srv.close()
        self.core.stop()


@pytest.fixture
def rig():
    r = WorkerRig()
    try:
        yield r
    finally:
        r.close()


# --------------------------------------------------------------------------
# framing-layer fault sites (net.*) — unit level, socketpair
# --------------------------------------------------------------------------


class TestFramingFaults:
    def test_torn_frame_surfaces_as_eof(self):
        """net.send.torn_frame writes only a prefix and raises; the peer
        must see a clean EOF, never a partial frame."""
        a, b = socket.socketpair()
        try:
            plan = FaultPlan(seed=0).rule("net.send.torn_frame", mode="torn",
                                          times=1)
            lock = threading.Lock()
            with pytest.raises(OSError, match="torn frame"):
                send_frame(a, lock, "evt", 1, ["x" * 64], faults=plan)
            assert plan.fired("net.send.torn_frame") == 1
            a.close()  # the sender's lane dies with the torn write
            assert read_frame(b.makefile("rb")) is None
        finally:
            b.close()

    def test_corrupt_payload_surfaces_as_eof(self):
        """A tear can leave the stream mid-frame: the bytes after the
        tear parse as a length + garbage payload. read_frame must treat
        undecodable bytes as EOF (framing lost), not raise."""
        a, b = socket.socketpair()
        try:
            garbage = b"\x00\x01\x02" * 11
            a.sendall(_LEN.pack(len(garbage)) + garbage)
            assert read_frame(b.makefile("rb")) is None
        finally:
            a.close()
            b.close()

    def test_partition_blackholes_the_send(self):
        """net.partition raises without writing a byte — an asymmetric
        blackhole, not a tear."""
        a, b = socket.socketpair()
        try:
            plan = FaultPlan(seed=0).rule("net.partition", mode="error",
                                          times=1)
            with pytest.raises(OSError, match="partition"):
                send_frame(a, threading.Lock(), "req", 1, ("ping", None),
                           faults=plan)
            b.settimeout(0.05)
            with pytest.raises((socket.timeout, TimeoutError)):
                b.recv(1)
        finally:
            a.close()
            b.close()

    def test_recv_stall_delays_delivery(self):
        """net.recv.stall sleeps the reader — the slow-link shape the
        per-op deadlines exist for. The frame still arrives intact."""
        a, b = socket.socketpair()
        try:
            send_frame(a, threading.Lock(), "res", 9, (True, "pong"), epoch=4)
            plan = FaultPlan(seed=0).rule("net.recv.stall", mode="delay",
                                          times=1, delay=0.25)
            t0 = time.monotonic()
            frame = read_frame(b.makefile("rb"), faults=plan)
            assert time.monotonic() - t0 >= 0.25
            assert frame == ("res", 9, (True, "pong"), 4)
        finally:
            a.close()
            b.close()


# --------------------------------------------------------------------------
# TcpShardClient against a live in-process worker
# --------------------------------------------------------------------------


class TestTcpClient:
    def test_rpc_roundtrip_and_push_subscription(self, rig):
        pushes = []
        client = rig.client(on_push=lambda sid, items: pushes.append((sid, items)))
        wait_until(lambda: client.alive, msg="client up")
        assert client.request("ping")["shard"] == 0
        # lane 0's sub frame nominated it as the push stream
        wait_until(lambda: rig.core.push is not None, msg="sub bound")
        rig.core.push([("Throttle", "marker")])
        wait_until(lambda: pushes, msg="push delivered")
        assert pushes[0] == (0, [("Throttle", "marker")])

    def test_per_op_deadline_fires_and_counts(self, rig):
        client = rig.client(deadlines={"ping": 0.2})
        wait_until(lambda: client.alive, msg="client up")

        orig = rig.core._rpc_ping

        def slow(payload):
            time.sleep(0.8)
            return orig(payload)

        rig.core._rpc_ping = slow
        try:
            with pytest.raises(ShardUnavailable, match="within 0.2s"):
                client.request("ping")
            assert client.deadline_exceeded == 1
            # the link itself is fine: the lane survives a deadline miss
            assert client.alive
        finally:
            rig.core._rpc_ping = orig
        assert client.request("stats")["shard"] == 0

    def test_reconnect_after_drop_fires_on_up(self, rig):
        down, up = threading.Event(), threading.Event()
        client = rig.client(pool_size=1, on_down=lambda sid: down.set(),
                            on_up=lambda sid: up.set())
        wait_until(lambda: client.alive, msg="client up")
        plan = FaultPlan(seed=0).rule("net.send.torn_frame", mode="torn",
                                      times=1)
        client.faults = plan
        with pytest.raises(ShardUnavailable):
            client.request("ping")
        assert down.wait(5.0), "on_down never fired"
        assert up.wait(5.0), "on_up (the resync trigger) never fired"
        wait_until(lambda: client.alive, msg="reconnect")
        assert client.reconnects == 1
        assert client.request("ping")["shard"] == 0

    def test_connect_refused_is_retried_through_backoff(self, rig):
        plan = FaultPlan(seed=0).rule("net.connect.refused", mode="error",
                                      times=2)
        client = rig.client(faults=plan)
        wait_until(lambda: client.alive, timeout=15.0,
                   msg="client up after refused dials")
        assert plan.fired("net.connect.refused") == 2
        assert client.reconnects == 0  # first establishment, not a heal

    def test_reconnect_storm_converges(self, rig):
        """Every fresh connection dies at birth (flapping link): the
        jittered backoff must keep dialing through to the heal."""
        up = threading.Event()
        client = rig.client(pool_size=1, on_up=lambda sid: up.set())
        wait_until(lambda: client.alive, msg="client up")
        plan = (
            FaultPlan(seed=1)
            .rule("net.send.torn_frame", mode="torn", times=1)
            .rule("net.reconnect.storm", mode="error", times=2)
        )
        client.faults = plan
        with pytest.raises(ShardUnavailable):
            client.request("ping")
        assert up.wait(15.0), "client never healed through the storm"
        assert plan.fired("net.reconnect.storm") == 2
        assert client.reconnects == 1
        assert client.outage_seconds() > 0.0


# --------------------------------------------------------------------------
# epoch fencing over the wire (the acceptance pin)
# --------------------------------------------------------------------------


class TestWireFencing:
    def test_stale_epoch_request_is_fenced_over_tcp(self, rig):
        """A front whose epoch is behind the worker's max-seen epoch is
        a peer from the past (healed after missing a resync): its RPCs
        must be REFUSED with the wire-level 409, not answered from
        untrusted state."""
        client = rig.client()
        wait_until(lambda: client.alive, msg="client up")
        assert client.request("ping")["shard"] == 0  # epoch 1 accepted
        # another front resynced this worker at a higher epoch while we
        # were partitioned away
        assert rig.core.observe_epoch(5)
        with pytest.raises(FencedError, match="stale epoch 1 < 5"):
            client.request("ping")
        assert rig.core._fenced_counts()["reqs"] == 1
        # the heal path: resync bumps the front's epoch past the fence
        while client.epoch < 5:
            client.bump_epoch()
        assert client.request("ping")["shard"] == 0
        assert client.request("stats")["wire_epoch"] == 5

    def test_stale_evt_batch_is_dropped(self, rig):
        """Stale-epoch event batches (bytes that sat in a kernel buffer
        across a heal) must not touch worker state."""
        client = rig.client()
        wait_until(lambda: client.alive, msg="client up")
        assert rig.core.observe_epoch(3)
        pod = make_pod("stale", labels={"grp": "g0"}, requests={"cpu": "1"})
        client.enqueue_ops([("upsert", "Pod", pod)])
        wait_until(lambda: rig.core._fenced_counts()["events"] >= 1,
                   msg="evt batch fenced")
        assert rig.core.store.list_pods() == []

    def test_stale_push_is_dropped_client_side(self, rig):
        """Pushes stamped with a pre-resync epoch are a healed worker
        replaying its pre-partition view — the front must drop them and
        let the resync re-push carry the truth."""
        pushes = []
        client = rig.client(on_push=lambda sid, items: pushes.append(items))
        wait_until(lambda: client.alive, msg="client up")
        wait_until(lambda: rig.core.push is not None, msg="sub bound")
        client.bump_epoch()  # front is at 2; the worker still pushes at 1
        rig.core.push([("Throttle", "stale-view")])
        wait_until(lambda: client.fenced_pushes >= 1, msg="push fenced")
        assert pushes == []


    def test_stale_sub_cannot_steal_the_push_stream(self, rig):
        """A partitioned-then-healed (not yet resynced) peer's ``sub``
        is a frame from the past: it must be counted fenced AND must not
        rebind the worker's push stream — otherwise every flip would
        stream to a connection the fencing contract says not to trust
        until the next resync."""
        pushes = []
        client = rig.client(on_push=lambda sid, items: pushes.append(items))
        wait_until(lambda: client.alive, msg="client up")
        wait_until(lambda: rig.core.push is not None, msg="sub bound")
        # the fleet moved on while some peer was partitioned away
        assert rig.core.observe_epoch(4)
        while client.epoch < 4:
            client.bump_epoch()
        stale = socket.create_connection(("127.0.0.1", rig.port), timeout=2.0)
        try:
            send_frame(stale, threading.Lock(), "sub", 0, None, epoch=2)
            wait_until(lambda: rig.core._fenced_counts()["reqs"] >= 1,
                       msg="stale sub fenced")
            rig.core.push([("Throttle", "truth")])
            wait_until(lambda: pushes, msg="push still rides the primary")
            assert pushes[0] == [("Throttle", "truth")]
        finally:
            stale.close()


# --------------------------------------------------------------------------
# frame auth — the pickle trust boundary (cross-host mode)
# --------------------------------------------------------------------------


_EVIL_CALLS: list = []


def _evil_sink(marker):
    _EVIL_CALLS.append(marker)


class _EvilPayload:
    """The RCE shape: unpickling this executes attacker-chosen code
    (a module-level callable, so it pickles by reference and fires in
    the reader's process)."""

    def __reduce__(self):
        return (_evil_sink, ("executed",))


class TestFrameAuth:
    KEY = b"test-fleet-psk"

    def test_authenticated_roundtrip(self):
        a, b = socket.socketpair()
        try:
            send_frame(a, threading.Lock(), "res", 7, (True, "pong"),
                       epoch=3, key=self.KEY)
            frame = read_frame(b.makefile("rb"), key=self.KEY)
            assert frame == ("res", 7, (True, "pong"), 3)
        finally:
            a.close()
            b.close()

    def test_unauthenticated_frame_never_reaches_the_deserializer(self):
        """A crafted pickle from a peer WITHOUT the key must die at the
        MAC check — pickle.loads on it would be arbitrary code
        execution in the worker."""
        del _EVIL_CALLS[:]
        payload = pickle.dumps(_EvilPayload(), protocol=5)
        raw = _LEN.pack(len(payload)) + payload
        assert read_frame(io.BytesIO(raw), key=self.KEY) is None
        assert _EVIL_CALLS == []  # the deserializer never ran
        # sanity check on the threat model: the SAME bytes execute on a
        # keyless reader — which is exactly why a non-loopback --listen
        # refuses to start without a key
        read_frame(io.BytesIO(raw))
        assert _EVIL_CALLS == ["executed"]

    def test_wrong_key_is_a_torn_stream(self):
        a, b = socket.socketpair()
        try:
            send_frame(a, threading.Lock(), "req", 1, ("ping", None),
                       key=b"some-other-key")
            assert read_frame(b.makefile("rb"), key=self.KEY) is None
        finally:
            a.close()
            b.close()

    def test_keyed_frame_is_noise_to_a_keyless_reader(self):
        a, b = socket.socketpair()
        try:
            send_frame(a, threading.Lock(), "req", 1, ("ping", None),
                       key=self.KEY)
            assert read_frame(b.makefile("rb")) is None
        finally:
            a.close()
            b.close()

    def test_keyed_fleet_end_to_end(self):
        """A keyed worker serves keyed clients; a keyless client can
        connect but never speak — its frames fail the MAC before the
        deserializer and the lane dies."""
        core = ShardCore(0, 1, use_device=False)
        srv = socket.create_server(("127.0.0.1", 0))
        port = srv.getsockname()[1]
        threading.Thread(
            target=serve_tcp, args=(core, srv),
            kwargs={"auth_key": self.KEY},
            name="test-keyed-accept", daemon=True,
        ).start()
        client = keyless = None
        try:
            pushes = []
            client = TcpShardClient(
                0, "127.0.0.1", port, auth_key=self.KEY,
                connect_timeout=2.0,
                on_push=lambda sid, items: pushes.append(items),
            )
            wait_until(lambda: client.alive, msg="keyed client up")
            assert client.request("ping")["shard"] == 0
            wait_until(lambda: core.push is not None, msg="sub bound")
            core.push([("Throttle", "keyed")])
            wait_until(lambda: pushes, msg="keyed push delivered")
            keyless = TcpShardClient(
                0, "127.0.0.1", port, connect_timeout=2.0,
                default_deadline=0.5,
            )
            with pytest.raises(ShardUnavailable):
                keyless.request("ping")
        finally:
            if client is not None:
                client.close()
            if keyless is not None:
                keyless.close()
            srv.close()
            core.stop()

    def test_worker_refuses_keyless_nonloopback_listen(self, monkeypatch):
        monkeypatch.delenv("KT_SHARD_AUTH_KEY", raising=False)
        from kube_throttler_tpu.sharding import worker as worker_mod

        with pytest.raises(SystemExit):
            worker_mod.main([
                "--shard-id", "0", "--shards", "1",
                "--listen", "0.0.0.0:0", "--no-device",
            ])
        assert worker_mod.listen_requires_auth("0.0.0.0")
        assert worker_mod.listen_requires_auth("10.0.0.7")
        assert not worker_mod.listen_requires_auth("127.0.0.1")
        assert not worker_mod.listen_requires_auth("localhost")
        assert not worker_mod.listen_requires_auth("")


# --------------------------------------------------------------------------
# framing hygiene + sender resilience
# --------------------------------------------------------------------------


class TestFramingHygiene:
    def test_bogus_length_header_is_rejected_before_the_payload_read(self):
        """A misaligned tear (or garbage) parses as a length up to
        4 GiB; read_frame must reject it as a torn stream BEFORE
        buffering toward it — no reader stall, no allocation spike."""
        buf = io.BytesIO(_LEN.pack(MAX_FRAME + 1) + b"x" * 64)
        assert read_frame(buf) is None
        assert buf.tell() == _LEN.size  # not one payload byte was read

    def test_max_frame_boundary_still_decodes(self):
        payload = pickle.dumps(("evt", 0, ["ok"], 1), protocol=5)
        buf = io.BytesIO(_LEN.pack(len(payload)) + payload)
        assert read_frame(buf) == ("evt", 0, ["ok"], 1)

    def test_sender_unexpected_error_degrades_fail_safe(self, rig, monkeypatch):
        """A non-OSError escaping the TCP send path must tear down the
        primary lane (on_down fires, the front degrades fail-safe, heal
        resyncs) and the sender must SURVIVE to drain after the heal —
        never a live-looking handle with events queued behind a dead
        thread."""
        import kube_throttler_tpu.sharding.ipc as ipc_mod

        down, up = threading.Event(), threading.Event()
        client = rig.client(pool_size=1, on_down=lambda sid: down.set(),
                            on_up=lambda sid: up.set())
        wait_until(lambda: client.alive, msg="client up")
        real = ipc_mod.send_frame
        fired = threading.Event()

        def boom(sock, lock, mtype, rid, body, **kw):
            if mtype == "evt" and not fired.is_set():
                fired.set()
                raise ValueError("injected non-OSError sender bug")
            return real(sock, lock, mtype, rid, body, **kw)

        monkeypatch.setattr(ipc_mod, "send_frame", boom)
        pod = make_pod("p0", labels={"grp": "g"}, requests={"cpu": "1"})
        client.enqueue_ops([("upsert", "Pod", pod)])
        assert down.wait(5.0), "sender death never degraded the shard"
        assert up.wait(10.0), "sender death was permanent (no heal)"
        wait_until(lambda: client.alive, msg="reconnect after sender bug")
        assert client.is_dirty()  # the lost batch is a resync's problem
        client.enqueue_ops([("upsert", "Pod", pod)])
        wait_until(lambda: client.events_sent >= 1,
                   msg="sender survived and drains after the heal")


# --------------------------------------------------------------------------
# transport equivalence: TCP ≡ socketpair ≡ single-process
# --------------------------------------------------------------------------


def build_tcp_front(n_shards, rpc_deadlines=None, prepare_ttl=30.0):
    """An AdmissionFront over in-process cores behind REAL TCP
    listeners — the full wire stack, deterministic teardown."""
    front = AdmissionFront(n_shards, rpc_deadlines=rpc_deadlines)
    cores, servers = [], []
    for i in range(n_shards):
        core = ShardCore(i, n_shards, use_device=False,
                         prepare_ttl=prepare_ttl)
        srv, port = start_tcp_worker(core)
        cores.append(core)
        servers.append(srv)
        front.attach_shard(
            i,
            TcpShardClient(i, "127.0.0.1", port,
                           on_push=front.apply_status_push,
                           on_up=front.resync_shard, connect_timeout=2.0),
        )
    wait_until(lambda: all(h.alive for h in front.shards.values()),
               msg="tcp fleet up")
    return front, cores, servers


def teardown_tcp_front(front, cores, servers):
    front.stop()  # closes the TcpShardClient handles
    for srv in servers:
        srv.close()
    for core in cores:
        core.stop()


def build_socketpair_front(n_shards):
    """An AdmissionFront over ShardClient socketpairs served by
    in-process cores — the child-process transport without the child."""
    front = AdmissionFront(n_shards)
    cores = []
    for i in range(n_shards):
        core = ShardCore(i, n_shards, use_device=False)
        cores.append(core)
        a, b = socket.socketpair()
        threading.Thread(target=serve, args=(core, b),
                         name=f"test-shard{i}-serve", daemon=True).start()
        front.attach_shard(
            i, ShardClient(i, a, on_push=front.apply_status_push)
        )
    return front, cores


def settle(front, timeout=60.0):
    assert front.drain(timeout=timeout)
    time.sleep(0.3)  # push loops flush on their own cadence


@pytest.mark.parametrize("seed", [0, 3])
def test_transport_equivalence(seed):
    """Identical populations through (a) single-process oracle, (b) a
    2-shard socketpair fleet, (c) a 2-shard TCP fleet: every pod's
    verdict must agree on code + normalized reasons — the wire must be
    invisible to admission semantics."""
    from test_sharding import apply_population, seeded_population

    ops = seeded_population(seed)
    oracle_store = Store()
    apply_population(oracle_store, ops)
    oracle = H.build_plugin(oracle_store)
    oracle.run_pending_once()
    sp_front, sp_cores = build_socketpair_front(2)
    tcp_front, tcp_cores, tcp_servers = build_tcp_front(2)
    try:
        for front in (sp_front, tcp_front):
            apply_population(front.store, ops)
            settle(front)
        for pod in oracle_store.list_pods():
            want = oracle.pre_filter(pod)
            for label, front in (("socketpair", sp_front), ("tcp", tcp_front)):
                got = front.pre_filter(pod)
                assert got.code == want.code, (label, pod.key, got.reasons)
                assert H.normalized_reasons(got.reasons) == H.normalized_reasons(
                    want.reasons
                ), (label, pod.key)
    finally:
        oracle.stop()
        for core in sp_cores:
            core.stop()
        sp_front.stop()
        teardown_tcp_front(tcp_front, tcp_cores, tcp_servers)


def test_tcp_reservations_match_single_process():
    """Two-phase reserve over real TCP changes downstream verdicts
    exactly like the oracle's local reserve; unreserve restores them."""
    oracle_store = Store()
    tcp_front, tcp_cores, tcp_servers = build_tcp_front(2)
    try:
        for store in (tcp_front.store, oracle_store):
            store.create_namespace(Namespace("default"))
            for i in range(4):
                store.create_throttle(H.make_throttle(i))
        oracle = H.build_plugin(oracle_store)
        oracle.run_pending_once()
        settle(tcp_front)
        held = [
            make_pod(f"r{i}", labels={"grp": f"g{i % 4}"},
                     requests={"cpu": "600m"})
            for i in range(6)
        ]
        for pod in held:
            assert tcp_front.reserve(pod).is_success()
            assert oracle.reserve(pod).is_success()
        probe = make_pod("probe", labels={"grp": "g2"}, requests={"cpu": "600m"})
        got, want = tcp_front.pre_filter(probe), oracle.pre_filter(probe)
        assert got.code == want.code
        assert H.normalized_reasons(got.reasons) == H.normalized_reasons(
            want.reasons
        )
        for pod in held:
            tcp_front.unreserve(pod)
            oracle.unreserve(pod)
        got2, want2 = tcp_front.pre_filter(probe), oracle.pre_filter(probe)
        assert got2.code == want2.code
        oracle.stop()
    finally:
        teardown_tcp_front(tcp_front, tcp_cores, tcp_servers)


# --------------------------------------------------------------------------
# prepare-timeout regression: deadline fires ⇒ abort, never an orphan
# --------------------------------------------------------------------------


def _slow_after(core, op, extra=1.0):
    """Wrap an RPC so it does its real work, then outlives the caller's
    deadline before answering — the 'prepare LANDED, the answer did
    not' shape front.reserve's abort-to-all-targets exists for."""
    orig = getattr(core, f"_rpc_{op}")

    def slow(payload):
        result = orig(payload)
        time.sleep(extra)
        return result

    setattr(core, f"_rpc_{op}", slow)
    return orig


class TestPrepareDeadlineAbort:
    def _population(self, store):
        store.create_namespace(Namespace("default"))
        for i in range(4):
            store.create_throttle(H.make_throttle(i))

    def _assert_no_orphans(self, front):
        def clean():
            for sid in range(front.n_shards):
                stats = front.shards[sid].request("stats")
                if stats["pending_txns"] or stats["reservations"]:
                    return False
                audit = front.shards[sid].request("reshard_audit")
                if audit["orphan_reservations"]:
                    return False
            return True

        wait_until(clean, timeout=10.0,
                   msg="aborted txn fully released on every shard")

    def test_reserve_prepare_timeout_aborts_everywhere(self):
        front, cores, servers = build_tcp_front(
            2, rpc_deadlines={"reserve_prepare": 0.3}
        )
        try:
            self._population(front.store)
            settle(front)
            pod = make_pod("slowpod", labels={"grp": "g1"},
                           requests={"cpu": "100m"})
            origs = [_slow_after(core, "reserve_prepare") for core in cores]
            aborts_before = front.two_phase_aborts
            status = front.reserve(pod)
            assert not status.is_success()
            assert any("within 0.3s" in r for r in status.reasons), status.reasons
            assert front.two_phase_aborts == aborts_before + 1
            assert any(h.deadline_exceeded >= 1 for h in front.shards.values())
            self._assert_no_orphans(front)
            # the fleet is not wedged: a normal reserve goes through
            for core, orig in zip(cores, origs):
                core._rpc_reserve_prepare = orig
            assert front.reserve(pod).is_success()
            front.unreserve(pod)
        finally:
            teardown_tcp_front(front, cores, servers)

    def test_gang_prepare_timeout_aborts_everywhere(self):
        front, cores, servers = build_tcp_front(
            2, rpc_deadlines={"gang_prepare": 0.3}
        )
        try:
            self._population(front.store)
            settle(front)
            members = [
                make_pod(f"gm{i}", labels={"grp": "g2"},
                         requests={"cpu": "100m"}, group="job1", group_size=3)
                for i in range(3)
            ]
            origs = [_slow_after(core, "gang_prepare") for core in cores]
            status = front.reserve_gang("default/job1", members)
            assert not status.is_success()
            self._assert_no_orphans(front)
            wait_until(
                lambda: all(
                    front.shards[sid].request("gang_groups") == []
                    for sid in range(2)
                ),
                msg="gang ledger record released",
            )
            for core, orig in zip(cores, origs):
                core._rpc_gang_prepare = orig
            assert front.reserve_gang("default/job1", members).is_success()
            front.unreserve_gang("default/job1")
        finally:
            teardown_tcp_front(front, cores, servers)


# --------------------------------------------------------------------------
# mid-reshard partition over TCP: abort-back-to-source, then retry lands
# --------------------------------------------------------------------------


def test_reshard_partition_aborts_back_to_source_over_tcp():
    """A destination partitioned mid-handoff must abort the handoff back
    to the source (the PR 13 path, now over real TCP); once the link
    heals the coordinator's retry completes the retarget with
    oracle-equivalent verdicts and zero orphan reservations."""
    from kube_throttler_tpu.sharding.reshard import ReshardCoordinator
    from kube_throttler_tpu.sharding.ring import HashRing

    front, cores, servers = build_tcp_front(2)
    try:
        front.store.create_namespace(Namespace("default"))
        for i in range(8):
            front.store.create_throttle(H.make_throttle(i))
        pods = [
            make_pod(f"p{i}", labels={"grp": f"g{i % 8}"},
                     requests={"cpu": "100m"})
            for i in range(48)
        ]
        for pod in pods:
            front.store.create_pod(pod)
        settle(front)
        for pod in pods[:6]:
            assert front.reserve(pod).is_success()

        # attach the destination shard over TCP, then blackhole its link
        core = ShardCore(2, 3, use_device=False)
        srv, port = start_tcp_worker(core)
        cores.append(core)
        servers.append(srv)
        handle = TcpShardClient(2, "127.0.0.1", port,
                                on_push=front.apply_status_push,
                                on_up=front.resync_shard, connect_timeout=2.0)
        front.attach_shard(2, handle)
        wait_until(lambda: handle.alive, msg="shard 2 up")
        front.resync_shard(2)
        front.n_shards = 3

        plan = FaultPlan(seed=0).rule("net.partition", mode="error", times=6)
        handle.faults = plan
        report = ReshardCoordinator(front).rescale(HashRing(3), deadline_s=60.0)
        assert plan.fired("net.partition") >= 1, "partition never fired"
        assert report["aborts"] >= 1, (
            "partitioned destination never aborted a handoff back to source"
        )
        assert front.drain(60.0)
        time.sleep(0.4)

        # oracle equivalence + clean audits after the heal-and-retry
        oracle_store = Store()
        oracle_store.create_namespace(Namespace("default"))
        for thr in front.store.list_throttles():
            oracle_store.create_throttle(thr)
        for pod in front.store.list_pods():
            oracle_store.create_pod(pod)
        oracle = H.build_plugin(oracle_store)
        oracle.run_pending_once()
        for pod in oracle_store.list_pods():
            got, want = front.pre_filter(pod), oracle.pre_filter(pod)
            assert got.code == want.code, (pod.key, got.reasons, want.reasons)
        oracle.stop()
        for sid in range(3):
            audit = front.shards[sid].request("reshard_audit")
            assert not audit["orphan_reservations"], (sid, audit)
            assert not audit["pending_handoffs"], (sid, audit)
    finally:
        teardown_tcp_front(front, cores, servers)


# --------------------------------------------------------------------------
# subprocess fleet smoke (the full matrix is `make net-chaos`)
# --------------------------------------------------------------------------


def test_net_chaos_smoke_torn_frame():
    """One small netchaostest case through a LIVE 2-worker TCP fleet
    (real processes, real loopback sockets): a torn frame mid-churn,
    then the full recovery contract — no supervisor restart, zero wrong
    verdicts, zero lost flips, zero orphan reservations."""
    from tools.netchaostest import run_case

    result = run_case("net.send.torn_frame", "torn", seed=0,
                      rule_kwargs={"times": 2}, n_pods=48, rounds=3)
    assert result["ok"]
    assert result["fired"] >= 1
    assert result["reconnects"] >= 1
