"""Crash-consistent snapshot/recovery subsystem (engine/snapshot.py,
engine/recovery.py) + the SIGKILL crash-point harness (tools/crashtest.py).

Fast tier covers the snapshot file format (atomicity, checksum fallback,
pruning), the three recovery modes (tail / genesis / snapshot-only), the
divergence reconcile, graceful-shutdown /readyz draining, and ONE seeded
subprocess crash cycle. The full ≥6-site × 3-seed SIGKILL matrix runs
behind ``-m slow`` (also: ``make crash-test``).
"""

from __future__ import annotations

import importlib.util
import json
import os
from dataclasses import replace
from datetime import datetime, timedelta, timezone
from pathlib import Path

import pytest

from kube_throttler_tpu.api.pod import Namespace, make_pod
from kube_throttler_tpu.api.types import (
    LabelSelector,
    ResourceAmount,
    Throttle,
    ThrottleSelector,
    ThrottleSelectorTerm,
    ThrottleSpec,
)
from kube_throttler_tpu.engine.journal import attach
from kube_throttler_tpu.engine.recovery import RecoveryManager
from kube_throttler_tpu.engine.reservations import ReservedResourceAmounts
from kube_throttler_tpu.engine.snapshot import (
    SnapshotError,
    SnapshotManager,
    find_snapshots,
    load_snapshot,
)
from kube_throttler_tpu.engine.store import Store
from kube_throttler_tpu.plugin import KubeThrottler, decode_plugin_args
from kube_throttler_tpu.utils.clock import FakeClock

ROOT = Path(__file__).resolve().parents[1]

_spec = importlib.util.spec_from_file_location(
    "crashtest", ROOT / "tools" / "crashtest.py"
)
crashtest = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(crashtest)


def _throttle(name, labels, **threshold):
    return Throttle(
        name=name,
        spec=ThrottleSpec(
            throttler_name="kube-throttler",
            threshold=ResourceAmount.of(**threshold),
            selector=ThrottleSelector(
                selector_terms=(
                    ThrottleSelectorTerm(LabelSelector(match_labels=labels)),
                )
            ),
        ),
    )


def _bound(pod):
    bound = replace(pod, spec=replace(pod.spec, node_name="node-1"))
    bound.status.phase = "Running"
    return bound


def _populate(store, n_pods=3):
    store.create_namespace(Namespace("default"))
    store.create_throttle(_throttle("t1", {"grp": "a"}, pod=10, requests={"cpu": "2"}))
    for i in range(n_pods):
        store.create_pod(
            _bound(make_pod(f"p{i}", labels={"grp": "a"}, requests={"cpu": "300m"}))
        )


def _dump(store):
    return crashtest._dump_store(store)


class TestSnapshotFile:
    def test_write_load_roundtrip_and_payload_shape(self, tmp_path):
        store = Store()
        journal = attach(store, str(tmp_path / "store.journal"))
        _populate(store)
        cache = ReservedResourceAmounts(4)
        cache.add_pod("default/t1", make_pod("r1", labels={"grp": "a"}), ttl=60.0)
        mgr = SnapshotManager(
            str(tmp_path), store, reservations={"throttle": cache}
        )
        mgr.journal = journal
        path = mgr.write(reason="test")
        assert path is not None and os.path.exists(path)
        payload = load_snapshot(path)
        assert payload["seq"] == 1 and payload["reason"] == "test"
        assert payload["rv"] == store.latest_resource_version
        kinds = [d["kind"] for d in payload["objects"]]
        # namespaces first (replay creation-order dependency); pods live in
        # the v2 columnar block, not the objects list
        assert kinds[0] == "Namespace" and kinds.count("Pod") == 0
        assert len(payload["podColumns"]["name"]) == 3
        # every pod of one test shape interns to ONE request/label shape
        assert len(payload["podColumns"]["requestShapes"]) == 1
        assert len(payload["podColumns"]["labelShapes"]) == 1
        res = payload["reservations"]["throttle"]["default/t1"]["default/r1"]
        assert 0 < res["ttlRemainingSeconds"] <= 60.0
        off, sha = payload["journal"]["offset"], payload["journal"]["sha256"]
        assert off == os.path.getsize(tmp_path / "store.journal") and len(sha) == 64
        journal.close()

    def test_corrupt_snapshot_detected_and_pruning_keeps_newest(self, tmp_path):
        store = Store()
        _populate(store)
        mgr = SnapshotManager(str(tmp_path), store, keep=2)
        paths = [mgr.write() for _ in range(4)]
        kept = find_snapshots(str(tmp_path))
        assert [seq for seq, _ in kept] == [4, 3]  # newest two survive pruning
        # flip one payload byte: the checksum gate must refuse the file
        with open(paths[-1], "r+b") as f:
            f.seek(os.path.getsize(paths[-1]) - 10)
            f.write(b"X")
        with pytest.raises(SnapshotError):
            load_snapshot(paths[-1])

    def test_mid_write_tmp_never_visible_as_snapshot(self, tmp_path):
        # a torn tmp file (crash mid-write) must neither list nor load
        store = Store()
        mgr = SnapshotManager(str(tmp_path), store)
        (tmp_path / "garbage.tmp").write_bytes(b'{"format": "kube-thr')
        assert find_snapshots(str(tmp_path)) == []
        mgr.write()
        assert len(find_snapshots(str(tmp_path))) == 1


class TestRecoveryModes:
    def _churn(self, store, start, n):
        for i in range(start, start + n):
            store.create_pod(
                _bound(
                    make_pod(f"c{i}", labels={"grp": "a"}, requests={"cpu": "100m"})
                )
            )

    def test_tail_replay_equals_genesis_replay(self, tmp_path):
        data = tmp_path / "data"
        data.mkdir()
        store = Store()
        journal = attach(store, str(data / "store.journal"))
        _populate(store)
        mgr = SnapshotManager(str(data), store)
        mgr.journal = journal
        mgr.write()
        self._churn(store, 100, 5)  # tail the snapshot does not carry
        journal.close()

        recovered = Store()
        rec = RecoveryManager(str(data))
        rec.recover_store(recovered).close()
        assert rec.report.journal_mode == "tail"
        assert rec.report.journal_lines_replayed == 5
        assert rec.report.snapshot_objects > 0

        pure = Store()
        attach(pure, str(data / "store.journal")).close()
        assert _dump(recovered) == _dump(pure)

    def test_compaction_after_snapshot_forces_genesis(self, tmp_path):
        data = tmp_path / "data"
        data.mkdir()
        store = Store()
        journal = attach(store, str(data / "store.journal"), compact_after=10_000)
        _populate(store)
        # create+delete BEFORE the snapshot: compaction drops the pair, so
        # the rewritten journal's prefix can no longer hash-match the
        # snapshot's recorded anchor (a pure-ADDED history would compact to
        # a byte-identical prefix and tail mode would stay legitimate)
        store.create_pod(make_pod("ephemeral", labels={"grp": "a"}))
        store.delete_pod("default", "ephemeral")
        mgr = SnapshotManager(str(data), store)
        mgr.journal = journal
        mgr.write()
        self._churn(store, 100, 3)
        journal.compact()  # rewrites the file: the snapshot's anchor is stale
        journal.close()

        recovered = Store()
        rec = RecoveryManager(str(data))
        rec.recover_store(recovered).close()
        assert rec.report.journal_mode == "genesis"
        pure = Store()
        attach(pure, str(data / "store.journal")).close()
        assert _dump(recovered) == _dump(pure)

    def test_snapshot_only_mode_rebuilds_a_complete_journal(self, tmp_path):
        data = tmp_path / "data"
        data.mkdir()
        store = Store()
        journal = attach(store, str(data / "store.journal"))
        _populate(store)
        mgr = SnapshotManager(str(data), store)
        mgr.journal = journal
        mgr.write()
        journal.close()
        os.unlink(data / "store.journal")  # journal lost; snapshot survives

        recovered = Store()
        rec = RecoveryManager(str(data))
        rec.recover_store(recovered).close()
        assert rec.report.journal_mode == "snapshot-only"
        assert len(recovered.list_pods()) == 3

        # invariant: after recovery the journal ALONE reproduces the store
        # (recover_store compacts the fresh log), so a second crash before
        # the next snapshot loses nothing
        pure = Store()
        attach(pure, str(data / "store.journal")).close()
        assert _dump(recovered) == _dump(pure)

    def test_newest_corrupt_snapshot_falls_back_to_older(self, tmp_path):
        data = tmp_path / "data"
        data.mkdir()
        store = Store()
        journal = attach(store, str(data / "store.journal"))
        _populate(store)
        mgr = SnapshotManager(str(data), store, keep=3)
        mgr.journal = journal
        mgr.write()
        self._churn(store, 100, 2)
        newest = mgr.write()
        journal.close()
        with open(newest, "r+b") as f:  # bit rot on the newest snapshot
            f.seek(os.path.getsize(newest) - 5)
            f.write(b"?")

        recovered = Store()
        rec = RecoveryManager(str(data))
        rec.recover_store(recovered).close()
        assert rec.report.snapshots_rejected == 1
        assert rec.report.snapshot_seq == 1  # the older, valid one
        state, detail = rec.health_state()
        assert state == "degraded" and detail["snapshotsRejected"] == 1
        pure = Store()
        attach(pure, str(data / "store.journal")).close()
        assert _dump(recovered) == _dump(pure)

    def test_reservation_restore_via_recovery_rebases_ttls(self, tmp_path):
        data = tmp_path / "data"
        data.mkdir()
        t0 = datetime(2026, 8, 4, tzinfo=timezone.utc)
        clock = FakeClock(t0)
        store = Store()
        journal = attach(store, str(data / "store.journal"))
        _populate(store)
        cache = ReservedResourceAmounts(4, clock=clock)
        cache.add_pod("default/t1", make_pod("keep", labels={"grp": "a"}), ttl=100.0)
        cache.add_pod("default/t1", make_pod("die", labels={"grp": "a"}), ttl=10.0)
        cache.add_pod("default/t1", make_pod("eternal", labels={"grp": "a"}))
        mgr = SnapshotManager(
            str(data), store, reservations={"throttle": cache}, clock=clock
        )
        mgr.journal = journal
        mgr.write()
        journal.close()

        # the process is dead for 50s: "die" (ttl 10s) must NOT resurrect
        restore_clock = FakeClock(t0 + timedelta(seconds=50))
        recovered = Store()
        rec = RecoveryManager(str(data), clock=restore_clock)
        rec.recover_store(recovered).close()
        fresh = ReservedResourceAmounts(4, clock=restore_clock)
        rec.restore_reservations({"throttle": fresh})
        keys = fresh.reserved_pod_keys("default/t1")
        assert keys == {"default/keep", "default/eternal"}
        assert rec.report.reservations_restored == 2
        assert rec.report.reservations_expired_dropped == 1
        # the survivor's budget was rebased, not re-anchored: ~90s remain
        restore_clock.advance(timedelta(seconds=95))
        assert fresh.reserved_pod_keys("default/t1") == {"default/eternal"}


class TestReconcile:
    def _plugin(self, store):
        return KubeThrottler(
            decode_plugin_args(
                {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
            ),
            store,
            use_device=True,
            start_workers=False,
        )

    def test_clean_recovery_reconciles_with_zero_divergence(self, tmp_path):
        data = tmp_path / "data"
        data.mkdir()
        store = Store()
        journal = attach(store, str(data / "store.journal"))
        _populate(store)
        plugin = self._plugin(store)
        plugin.run_pending_once()  # real statuses through the real reconcile
        SnapshotManager(
            str(data), store, device_manager=plugin.device_manager
        ).write()
        plugin.stop()
        journal.close()

        recovered = Store()
        rec = RecoveryManager(str(data))
        rec.recover_store(recovered).close()
        plugin2 = self._plugin(recovered)
        try:
            assert rec.reconcile(plugin2.informers, plugin2.device_manager) == 0
            assert rec.report.divergences == 0
        finally:
            plugin2.stop()

    def test_forced_plane_divergence_is_counted_and_repaired(self, tmp_path):
        import numpy as np

        store = Store()
        _populate(store)
        plugin = self._plugin(store)
        try:
            plugin.run_pending_once()
            dm = plugin.device_manager
            ks = dm.throttle
            col = ks.index.throttle_col("default/t1")
            # sabotage the published plane behind the store's back — the
            # exact artifact a buggy restore would leave
            ks.st_cnt_throttled[col] = not ks.st_cnt_throttled[col]
            rec = RecoveryManager(str("unused-dir"))
            enqueued = []
            n = rec.reconcile(
                plugin.informers,
                dm,
                enqueue={"throttle": enqueued.append, "clusterthrottle": lambda k: None},
            )
            assert n == 1
            assert enqueued == ["default/t1"]
            assert rec.report.repaired_keys == ["throttle/default/t1"]
            state, detail = rec.health_state()
            assert state == "degraded" and detail["reconcileDivergences"] == 1
        finally:
            plugin.stop()


class TestGracefulShutdown:
    def test_mark_draining_flips_readyz_to_503(self):
        import urllib.error
        import urllib.request

        from kube_throttler_tpu.server import ThrottlerHTTPServer

        store = Store()
        _populate(store)
        plugin = self._plugin(store)
        server = ThrottlerHTTPServer(plugin, port=0)
        server.start()
        try:
            url = f"http://127.0.0.1:{server.port}/readyz"
            with urllib.request.urlopen(url, timeout=5) as resp:
                assert resp.status == 200
            server.mark_draining()
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(url, timeout=5)
            assert err.value.code == 503
            body = json.loads(err.value.read())
            assert body["state"] == "down"
            assert body["components"]["shutdown"]["state"] == "down"
            # liveness must stay green: killing the process mid-drain would
            # defeat the final snapshot + journal fsync
            with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/healthz", timeout=5
            ) as resp:
                assert resp.status == 200
        finally:
            server.stop()
            plugin.stop()

    _plugin = TestReconcile._plugin

    def test_readyz_carries_recovery_and_snapshot_components(self, tmp_path):
        import urllib.request

        from kube_throttler_tpu.server import ThrottlerHTTPServer

        data = tmp_path / "data"
        data.mkdir()
        seed = Store()
        journal = attach(seed, str(data / "store.journal"))
        _populate(seed)
        SnapshotManager(str(data), seed).write()
        journal.close()

        store = Store()
        rec = RecoveryManager(str(data))
        journal2 = rec.recover_store(store)
        plugin = self._plugin(store)
        snapshotter = SnapshotManager(str(data), store)
        snapshotter.bind_journal(journal2, every_lines=1000)
        plugin.health.register("recovery", rec.health_state)
        plugin.health.register("snapshot", snapshotter.health_state)
        plugin.health.register("journal", journal2.health_state)
        server = ThrottlerHTTPServer(plugin, port=0)
        server.start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/readyz", timeout=5
            ) as resp:
                body = json.loads(resp.read())
            recovery = body["components"]["recovery"]
            assert recovery["state"] == "ok"
            assert recovery["journalLinesReplayed"] == rec.report.journal_lines_replayed
            assert recovery["snapshotAgeSeconds"] is not None
            assert "reconcileDivergences" in recovery
            assert body["components"]["snapshot"]["state"] == "ok"
            assert body["components"]["journal"]["tornTails"] == 0
        finally:
            server.stop()
            plugin.stop()


class TestCrashHarness:
    def test_seeded_sigkill_smoke(self, tmp_path):
        """Tier-1 smoke: one SIGKILL crash point, full invariant oracle
        (replay + admission + plane + reservation equivalence)."""
        report = crashtest.run_crash_cycle(
            "crash.snapshot.post_rename", 0, str(tmp_path), events=80
        )
        assert report["killed"] is True
        assert report["mode"] in ("tail", "genesis", "snapshot-only")

    def test_gang_partial_reserve_smoke(self, tmp_path):
        """Tier-1 smoke for the gang crash site: SIGKILL mid-group-reserve
        recovers to fully-reserved or fully-rolled-back — run_crash_cycle's
        oracle 5 asserts no partial group and no orphan member
        reservations."""
        report = crashtest.run_crash_cycle(
            "crash.gang.partial_reserve", 0, str(tmp_path), events=120
        )
        assert report["killed"] is True
        assert report["mode"] in ("tail", "genesis", "snapshot-only")

    @pytest.mark.slow
    @pytest.mark.parametrize("site", crashtest.CRASH_SITES)
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_sigkill_matrix(self, site, seed, tmp_path):
        """The acceptance matrix: every registered crash.* site × 3 seeds
        recovers with zero invariant-oracle divergence."""
        crashtest.run_crash_cycle(site, seed, str(tmp_path))


class TestSnapshotTailProperty:
    """Property: snapshot-then-replay-tail state equals pure
    replay-from-genesis for arbitrary event sequences."""

    def test_property_snapshot_tail_equals_genesis(self, tmp_path_factory):
        pytest.importorskip("hypothesis")
        from hypothesis import given, settings, strategies as st

        @settings(max_examples=20, deadline=None)
        @given(
            ops=st.lists(
                st.tuples(st.integers(0, 3), st.integers(0, 5)),
                min_size=1,
                max_size=40,
            ),
            cut=st.integers(0, 39),
        )
        def prop(ops, cut):
            data = tmp_path_factory.mktemp("prop")
            store = Store()
            journal = attach(store, str(data / "store.journal"))
            store.create_namespace(Namespace("default"))
            store.create_throttle(
                _throttle("t1", {"grp": "a"}, pod=3, requests={"cpu": "1"})
            )
            mgr = SnapshotManager(str(data), store)
            mgr.journal = journal
            for i, (op, x) in enumerate(ops):
                if i == min(cut, len(ops) - 1):
                    mgr.write()
                name = f"p{x}"
                if op == 0:
                    try:
                        store.create_pod(
                            _bound(
                                make_pod(
                                    name,
                                    labels={"grp": "a"},
                                    requests={"cpu": f"{100 + x}m"},
                                )
                            )
                        )
                    except ValueError:
                        pass
                elif op == 1:
                    try:
                        store.delete_pod("default", name)
                    except KeyError:
                        pass
                elif op == 2:
                    thr = store.get_throttle("default", "t1")
                    store.update_throttle_status(
                        thr.with_status(
                            replace(
                                thr.status, used=ResourceAmount.of(pod=x)
                            )
                        )
                    )
                else:
                    thr = store.get_throttle("default", "t1")
                    store.update_throttle_spec(
                        replace(
                            thr,
                            spec=replace(
                                thr.spec,
                                threshold=ResourceAmount.of(pod=1 + x),
                            ),
                        )
                    )
            journal.close()

            recovered = Store()
            rec = RecoveryManager(str(data))
            rec.recover_store(recovered).close()
            pure = Store()
            attach(pure, str(data / "store.journal")).close()
            assert _dump(recovered) == _dump(pure)

        prop()
