"""End-to-end integration: the reference README walkthrough + burst and
cluster-throttle scenarios, driven through the full stack (store → watch →
controllers → plugin) with a simulated scheduler loop.

Mirrors the reference's integration tier (test/integration/throttle_test.go,
clusterthrottle_test.go) without its kind-cluster dependency: the in-memory
store plays the apiserver, and reconciles run deterministically via
run_pending_once().
"""

from dataclasses import replace
from datetime import datetime, timedelta, timezone

import pytest

from kube_throttler_tpu.api import (
    ClusterThrottle,
    ClusterThrottleSelector,
    ClusterThrottleSelectorTerm,
    ClusterThrottleSpec,
    LabelSelector,
    Namespace,
    ResourceAmount,
    TemporaryThresholdOverride,
    Throttle,
    ThrottleSelector,
    ThrottleSelectorTerm,
    ThrottleSpec,
)
from kube_throttler_tpu.api.pod import Pod, make_pod
from kube_throttler_tpu.engine.store import Store
from kube_throttler_tpu.plugin import (
    KubeThrottler,
    RecordingEventRecorder,
    StatusCode,
    decode_plugin_args,
)
from kube_throttler_tpu.utils.clock import FakeClock

NOW = datetime(2024, 1, 15, 12, 0, 0, tzinfo=timezone.utc)


def rfc(dt):
    return dt.strftime("%Y-%m-%dT%H:%M:%SZ")


class Harness:
    """store + plugin + deterministic scheduler simulator."""

    def __init__(self, use_device=True):
        self.store = Store()
        self.clock = FakeClock(NOW)
        self.recorder = RecordingEventRecorder()
        self.store.create_namespace(Namespace("default"))
        args = decode_plugin_args(
            {
                "name": "kube-throttler",
                "targetSchedulerName": "my-scheduler",
                "controllerThrediness": 1,
            }
        )
        self.plugin = KubeThrottler(
            args,
            self.store,
            clock=self.clock,
            event_recorder=self.recorder,
            use_device=use_device,
        )

    def settle(self, rounds: int = 5):
        for _ in range(rounds):
            if self.plugin.run_pending_once() == 0:
                break

    def schedule_attempt(self, pod: Pod) -> str:
        """One scheduling cycle: PreFilter → Reserve → bind (set nodeName,
        phase Running). Returns the final pre-filter status/reason summary."""
        status = self.plugin.pre_filter(pod)
        if not status.is_success():
            return status.message()
        assert self.plugin.reserve(pod).is_success()
        bound = replace(
            pod,
            spec=replace(pod.spec, node_name="node-1"),
        )
        bound.status.phase = "Running"
        self.store.update_pod(bound)
        self.settle()
        return "scheduled"

    def create_and_schedule(self, pod: Pod) -> str:
        self.store.create_pod(pod)
        self.settle()
        return self.schedule_attempt(pod)


@pytest.fixture(params=[True, False], ids=["device", "oracle"])
def harness(request):
    return Harness(use_device=request.param)


def t1_throttle(threshold_cpu="200m", pod_count=5):
    return Throttle(
        name="t1",
        namespace="default",
        spec=ThrottleSpec(
            throttler_name="kube-throttler",
            threshold=ResourceAmount.of(
                pod=pod_count, requests={"cpu": threshold_cpu, "memory": "1Gi"}
            ),
            selector=ThrottleSelector(
                selector_terms=(
                    ThrottleSelectorTerm(LabelSelector(match_labels={"throttle": "t1"})),
                )
            ),
        ),
    )


def labeled_pod(name, requests):
    return make_pod(name, labels={"throttle": "t1"}, requests=requests)


class TestReadmeWalkthrough:
    """README.md:202-375 decision sequence."""

    def test_full_sequence(self, harness):
        h = harness
        h.store.create_throttle(t1_throttle())
        h.settle()

        # pod1 (cpu 200m) schedules on the empty throttle
        assert h.create_and_schedule(labeled_pod("pod1", {"cpu": "200m"})) == "scheduled"

        # reconcile marked cpu throttled (used 200m >= threshold 200m)
        thr = h.store.get_throttle("default", "t1")
        assert thr.status.used.resource_counts == 1
        assert thr.status.throttled.resource_requests["cpu"] is True
        assert thr.status.throttled.resource_requests["memory"] is False

        # pod2 (cpu 300m) exceeds the 200m threshold outright
        msg = h.create_and_schedule(labeled_pod("pod2", {"cpu": "300m"}))
        assert "throttle[pod-requests-exceeds-threshold]=default/t1" in msg
        events = h.recorder.events_for("default/pod2")
        assert any(e.reason == "ResourceRequestsExceedsThrottleThreshold" for e in events)

        # pod1m (memory only) sails through — cpu throttle doesn't block it
        assert h.create_and_schedule(labeled_pod("pod1m", {"memory": "512Mi"})) == "scheduled"

        # threshold edit to cpu=700m opens the throttle; pod2 now schedules
        thr = h.store.get_throttle("default", "t1")
        new_spec = replace(
            thr.spec,
            threshold=ResourceAmount.of(pod=5, requests={"cpu": "700m", "memory": "1Gi"}),
        )
        h.store.update_throttle(replace(thr, spec=new_spec))
        h.settle()
        assert h.schedule_attempt(h.store.get_pod("default", "pod2")) == "scheduled"

        # used is now cpu=500m; pod3 (300m) → insufficient (500+300 > 700)
        msg = h.create_and_schedule(labeled_pod("pod3", {"cpu": "300m"}))
        assert "throttle[insufficient]=default/t1" in msg

    def test_pod_count_throttle(self, harness):
        h = harness
        thr = Throttle(
            name="t1",
            spec=ThrottleSpec(
                throttler_name="kube-throttler",
                threshold=ResourceAmount.of(pod=2),
                selector=ThrottleSelector(
                    selector_terms=(
                        ThrottleSelectorTerm(LabelSelector(match_labels={"throttle": "t1"})),
                    )
                ),
            ),
        )
        h.store.create_throttle(thr)
        h.settle()
        assert h.create_and_schedule(labeled_pod("p1", {})) == "scheduled"
        assert h.create_and_schedule(labeled_pod("p2", {})) == "scheduled"
        msg = h.create_and_schedule(labeled_pod("p3", {}))
        assert "throttle[active]=default/t1" in msg

    def test_burst_exactly_20_of_21_fit(self, harness):
        """throttle_test.go:167-197 — reservation double-count prevention:
        21 pods × 50m vs cpu=1; exactly 20 admit BEFORE any reconcile."""
        h = harness
        thr = Throttle(
            name="burst",
            spec=ThrottleSpec(
                throttler_name="kube-throttler",
                threshold=ResourceAmount.of(requests={"cpu": "1"}),
                selector=ThrottleSelector(
                    selector_terms=(
                        ThrottleSelectorTerm(LabelSelector(match_labels={"throttle": "t1"})),
                    )
                ),
            ),
        )
        h.store.create_throttle(thr)
        h.settle()
        admitted = 0
        for i in range(21):
            pod = labeled_pod(f"b{i}", {"cpu": "50m"})
            h.store.create_pod(pod)
            status = h.plugin.pre_filter(pod)
            if status.is_success():
                assert h.plugin.reserve(pod).is_success()
                admitted += 1
            # deliberately NO settle: reservations alone must prevent
            # double-admission within the scheduling cycle window
        assert admitted == 20

    def test_clusterthrottle_burst_exactly_20_of_21_fit(self, harness):
        """clusterthrottle_test.go mirror of the burst: the CLUSTER kind's
        separately-implemented reserve path must prevent double-admission
        inside the scheduling-cycle window just like the namespaced one."""
        h = harness
        ct = ClusterThrottle(
            name="cburst",
            spec=ClusterThrottleSpec(
                throttler_name="kube-throttler",
                threshold=ResourceAmount.of(requests={"cpu": "1"}),
                selector=ClusterThrottleSelector(
                    selector_terms=(
                        ClusterThrottleSelectorTerm(
                            pod_selector=LabelSelector(match_labels={"throttle": "t1"})
                        ),
                    )
                ),
            ),
        )
        h.store.create_cluster_throttle(ct)
        h.settle()
        admitted = 0
        for i in range(21):
            pod = labeled_pod(f"cb{i}", {"cpu": "50m"})
            h.store.create_pod(pod)
            status = h.plugin.pre_filter(pod)
            if status.is_success():
                assert h.plugin.reserve(pod).is_success()
                admitted += 1
            # deliberately NO settle (see the namespaced variant)
        assert admitted == 20

    def test_unreserve_on_bind_failure(self, harness):
        h = harness
        thr = Throttle(
            name="t1",
            spec=ThrottleSpec(
                throttler_name="kube-throttler",
                threshold=ResourceAmount.of(requests={"cpu": "100m"}),
                selector=ThrottleSelector(
                    selector_terms=(
                        ThrottleSelectorTerm(LabelSelector(match_labels={"throttle": "t1"})),
                    )
                ),
            ),
        )
        h.store.create_throttle(thr)
        h.settle()
        pod = labeled_pod("p1", {"cpu": "100m"})
        h.store.create_pod(pod)
        assert h.plugin.pre_filter(pod).is_success()
        h.plugin.reserve(pod)
        # second pod is blocked by the reservation
        pod2 = labeled_pod("p2", {"cpu": "100m"})
        h.store.create_pod(pod2)
        assert not h.plugin.pre_filter(pod2).is_success()
        # bind fails → Unreserve rolls back → pod2 passes again
        h.plugin.unreserve(pod)
        assert h.plugin.pre_filter(pod2).is_success()


class TestTemporaryOverrides:
    def test_override_lifecycle_with_wakeup(self, harness):
        h = harness
        thr = Throttle(
            name="t1",
            spec=ThrottleSpec(
                throttler_name="kube-throttler",
                threshold=ResourceAmount.of(requests={"cpu": "100m"}),
                selector=ThrottleSelector(
                    selector_terms=(
                        ThrottleSelectorTerm(LabelSelector(match_labels={"throttle": "t1"})),
                    )
                ),
                temporary_threshold_overrides=(
                    TemporaryThresholdOverride(
                        begin=rfc(NOW - timedelta(hours=1)),
                        end=rfc(NOW + timedelta(hours=1)),
                        threshold=ResourceAmount.of(requests={"cpu": "1"}),
                    ),
                ),
            ),
        )
        h.store.create_throttle(thr)
        h.settle()
        got = h.store.get_throttle("default", "t1")
        assert got.status.calculated_threshold.threshold == ResourceAmount.of(
            requests={"cpu": "1"}
        )
        # while the override is active a 500m pod fits
        assert h.create_and_schedule(labeled_pod("p1", {"cpu": "500m"})) == "scheduled"

        # advance past the override end; the enqueue_after wakeup fires
        h.clock.advance(timedelta(hours=1, seconds=1))
        import time

        deadline = time.time() + 2
        while time.time() < deadline:
            if h.plugin.run_pending_once() > 0:
                break
            time.sleep(0.01)
        h.settle()
        got = h.store.get_throttle("default", "t1")
        # threshold reverts to spec (100m) and used 500m ≥ 100m → throttled
        assert got.status.calculated_threshold.threshold == ResourceAmount.of(
            requests={"cpu": "100m"}
        )
        assert got.status.throttled.resource_requests["cpu"] is True


class TestClusterThrottle:
    def test_namespace_scoped_matching(self, harness):
        h = harness
        h.store.create_namespace(Namespace("team-a", labels={"team": "a"}))
        h.store.create_namespace(Namespace("team-b", labels={"team": "b"}))
        clthr = ClusterThrottle(
            name="ct1",
            spec=ClusterThrottleSpec(
                throttler_name="kube-throttler",
                threshold=ResourceAmount.of(pod=1),
                selector=ClusterThrottleSelector(
                    selector_terms=(
                        ClusterThrottleSelectorTerm(
                            pod_selector=LabelSelector(match_labels={"throttle": "t1"}),
                            namespace_selector=LabelSelector(match_labels={"team": "a"}),
                        ),
                    )
                ),
            ),
        )
        h.store.create_cluster_throttle(clthr)
        h.settle()

        pod_a = make_pod("p1", namespace="team-a", labels={"throttle": "t1"})
        assert h.create_and_schedule(pod_a) == "scheduled"

        # second pod in the matched namespace is blocked (pod-count 1 reached)
        pod_a2 = make_pod("p2", namespace="team-a", labels={"throttle": "t1"})
        msg = h.create_and_schedule(pod_a2)
        assert "clusterthrottle[active]=/ct1" in msg

        # same labels in an unmatched namespace sail through
        pod_b = make_pod("p3", namespace="team-b", labels={"throttle": "t1"})
        assert h.create_and_schedule(pod_b) == "scheduled"

    def test_missing_namespace_is_error(self, harness):
        h = harness
        pod = make_pod("p1", namespace="ghost", labels={})
        h.store._create("Pod", pod)  # bypass: create pod without namespace object
        status = h.plugin.pre_filter(pod)
        assert status.code == StatusCode.ERROR


class TestLabelMove:
    def test_reservation_moves_on_label_change(self, harness):
        h = harness

        def throttle_for(label):
            return Throttle(
                name=f"t-{label}",
                spec=ThrottleSpec(
                    throttler_name="kube-throttler",
                    threshold=ResourceAmount.of(requests={"cpu": "100m"}),
                    selector=ThrottleSelector(
                        selector_terms=(
                            ThrottleSelectorTerm(LabelSelector(match_labels={"throttle": label})),
                        )
                    ),
                ),
            )

        h.store.create_throttle(throttle_for("a"))
        h.store.create_throttle(throttle_for("b"))
        h.settle()
        pod = make_pod("p1", labels={"throttle": "a"}, requests={"cpu": "100m"})
        h.store.create_pod(pod)
        h.plugin.reserve(pod)
        assert h.plugin.throttle_ctr.cache.reserved_pod_keys("default/t-a") == {"default/p1"}

        # bind the pod WITHOUT settling — the reservation is still held, and
        # only scheduled pods pass shouldCountIn in the update handler
        # (throttle_controller.go:453: pending-pod label changes are ignored)
        bound = make_pod(
            "p1", labels={"throttle": "a"}, requests={"cpu": "100m"}, node_name="node-1"
        )
        h.store.update_pod(bound)
        assert h.plugin.throttle_ctr.cache.reserved_pod_keys("default/t-a") == {"default/p1"}

        # label flips a→b on the bound pod while still reserved
        moved = make_pod(
            "p1", labels={"throttle": "b"}, requests={"cpu": "100m"}, node_name="node-1"
        )
        h.store.update_pod(moved)
        assert h.plugin.throttle_ctr.cache.reserved_pod_keys("default/t-a") == set()
        assert h.plugin.throttle_ctr.cache.reserved_pod_keys("default/t-b") == {"default/p1"}
