"""State-engine components: store/watch, workqueue, reservations, index."""

import random
import threading
from datetime import datetime, timedelta, timezone

import numpy as np
import pytest

from kube_throttler_tpu.api import (
    ClusterThrottle,
    ClusterThrottleSelector,
    ClusterThrottleSelectorTerm,
    ClusterThrottleSpec,
    LabelSelector,
    Namespace,
    ResourceAmount,
    Throttle,
    ThrottleSelector,
    ThrottleSelectorTerm,
    ThrottleSpec,
)
from kube_throttler_tpu.api.pod import make_pod
from kube_throttler_tpu.api.types import LabelSelectorRequirement
from kube_throttler_tpu.engine import RateLimitingQueue, ReservedResourceAmounts, Store
from kube_throttler_tpu.engine.index import SelectorIndex
from kube_throttler_tpu.engine.store import ConflictError, Event, EventType
from kube_throttler_tpu.utils.clock import FakeClock


class TestStore:
    def test_watch_events_and_replay(self):
        store = Store()
        events = []
        pod = make_pod("p1")
        store.create_pod(pod)
        store.add_event_handler("Pod", events.append)  # replay existing
        store.update_pod(make_pod("p1", labels={"a": "b"}))
        store.delete_pod("default", "p1")
        assert [e.type for e in events] == [
            EventType.ADDED,
            EventType.MODIFIED,
            EventType.DELETED,
        ]
        assert events[1].old_obj.labels == {}

    def test_status_update_optimistic_concurrency(self):
        store = Store()
        thr = Throttle(name="t1", spec=ThrottleSpec(threshold=ResourceAmount.of(pod=1)))
        store.create_throttle(thr)
        rv = store.resource_version("Throttle", "default/t1")
        from kube_throttler_tpu.api.types import ThrottleStatus

        updated = thr.with_status(ThrottleStatus(used=ResourceAmount.of(pod=1)))
        store.update_throttle_status(updated, expected_version=rv)
        with pytest.raises(ConflictError):
            store.update_throttle_status(updated, expected_version=rv)
        # spec is preserved on status write
        assert store.get_throttle("default", "t1").spec.threshold.resource_counts == 1


class TestWorkqueue:
    def test_dedup_while_queued(self):
        q = RateLimitingQueue("test")
        q.add("a")
        q.add("a")
        q.add("b")
        assert len(q) == 2

    def test_requeue_if_added_while_processing(self):
        q = RateLimitingQueue("test")
        q.add("a")
        item = q.get()
        q.add("a")  # while processing → dirty, not queued
        assert len(q) == 0
        q.done(item)
        assert len(q) == 1

    def test_add_after_with_fake_clock(self):
        clock = FakeClock(datetime(2024, 1, 1, tzinfo=timezone.utc))
        q = RateLimitingQueue("test", clock=clock)
        q.add_after("x", timedelta(seconds=60))
        import time

        time.sleep(0.02)
        assert len(q) == 0
        clock.advance(timedelta(seconds=61))
        deadline = time.time() + 2
        while len(q) == 0 and time.time() < deadline:
            time.sleep(0.005)
        assert len(q) == 1

    def test_rate_limited_backoff_and_forget(self):
        q = RateLimitingQueue("test")
        q.add_rate_limited("k")  # 5ms
        import time

        time.sleep(0.1)
        assert len(q) == 1
        assert q.num_requeues("k") == 1
        q.forget("k")
        assert q.num_requeues("k") == 0


class TestReservations:
    def test_idempotent_add_remove(self):
        cache = ReservedResourceAmounts(8)
        pod = make_pod("p1", requests={"cpu": "100m"})
        assert cache.add_pod("default/t1", pod)
        assert not cache.add_pod("default/t1", pod)  # overwrite, not new
        amt, keys = cache.reserved_resource_amount("default/t1")
        assert amt.resource_counts == 1 and keys == {"default/p1"}
        assert cache.remove_pod("default/t1", pod)
        assert not cache.remove_pod("default/t1", pod)
        amt, keys = cache.reserved_resource_amount("default/t1")
        assert amt == ResourceAmount() and keys == set()

    def test_move_assignment(self):
        cache = ReservedResourceAmounts(8)
        pod = make_pod("p1", requests={"cpu": "100m"})
        cache.add_pod("default/t1", pod)
        cache.move_throttle_assignment(pod, ["default/t1"], ["default/t2"])
        assert cache.reserved_pod_keys("default/t1") == set()
        assert cache.reserved_pod_keys("default/t2") == {"default/p1"}

    def test_concurrent_stress(self):
        # reserved_resource_amounts_test.go:33-108, scaled to Python threads
        cache = ReservedResourceAmounts(16)
        pods = [make_pod(f"p{i}", requests={"cpu": "1"}) for i in range(50)]
        keys = [f"default/t{i}" for i in range(8)]
        errors = []

        def worker(seed):
            rng = random.Random(seed)
            try:
                for _ in range(200):
                    key = rng.choice(keys)
                    pod = rng.choice(pods)
                    op = rng.random()
                    if op < 0.45:
                        cache.add_pod(key, pod)
                    elif op < 0.9:
                        cache.remove_pod(key, pod)
                    else:
                        cache.reserved_resource_amount(key)
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        # ledger remains consistent: every remaining entry sums correctly
        for key in keys:
            amt, pod_keys = cache.reserved_resource_amount(key)
            assert (amt.resource_counts or 0) == len(pod_keys)


def _random_label(rng):
    return {f"k{rng.randrange(3)}": f"v{rng.randrange(3)}"}


class TestSelectorIndex:
    def _oracle_mask(self, index, pods, throttles, namespaces):
        out = {}
        for pk, pod in pods.items():
            for tk, thr in throttles.items():
                if isinstance(thr, Throttle):
                    want = thr.namespace == pod.namespace and thr.spec.selector.matches_to_pod(pod)
                else:
                    ns = namespaces.get(pod.namespace)
                    want = ns is not None and thr.spec.selector.matches_to_pod(pod, ns)
                out[(pk, tk)] = want
        return out

    @pytest.mark.parametrize("kind", ["throttle", "clusterthrottle"])
    def test_random_churn_matches_oracle(self, kind):
        rng = random.Random(42)
        index = SelectorIndex(kind, pod_capacity=4, throttle_capacity=2)  # force growth
        pods, throttles, namespaces = {}, {}, {}

        for name in ("ns1", "ns2", "ns3"):
            ns = Namespace(name, labels=_random_label(rng))
            namespaces[name] = ns
            index.upsert_namespace(ns)

        def rand_throttle(i):
            n_terms = rng.randrange(0, 3)
            if kind == "throttle":
                terms = tuple(
                    ThrottleSelectorTerm(LabelSelector(match_labels=_random_label(rng)))
                    for _ in range(n_terms)
                )
                # occasionally a matchExpressions (general-tier) term
                if rng.random() < 0.3:
                    terms += (
                        ThrottleSelectorTerm(
                            LabelSelector(
                                match_expressions=(
                                    LabelSelectorRequirement(f"k{rng.randrange(3)}", "Exists"),
                                )
                            )
                        ),
                    )
                return Throttle(
                    name=f"t{i}",
                    namespace=rng.choice(["ns1", "ns2", "ns3"]),
                    spec=ThrottleSpec(selector=ThrottleSelector(selector_terms=terms)),
                )
            terms = tuple(
                ClusterThrottleSelectorTerm(
                    pod_selector=LabelSelector(match_labels=_random_label(rng)),
                    namespace_selector=LabelSelector(match_labels=_random_label(rng))
                    if rng.random() < 0.7
                    else LabelSelector(),
                )
                for _ in range(n_terms)
            )
            return ClusterThrottle(
                name=f"c{i}", spec=ClusterThrottleSpec(selector=ClusterThrottleSelector(selector_terms=terms))
            )

        for step in range(300):
            op = rng.random()
            if op < 0.35:
                pod = make_pod(
                    f"p{rng.randrange(20)}",
                    namespace=rng.choice(["ns1", "ns2", "ns3"]),
                    labels=_random_label(rng) if rng.random() < 0.8 else {},
                )
                pods[pod.key] = pod
                index.upsert_pod(pod)
            elif op < 0.5 and pods:
                key = rng.choice(list(pods))
                del pods[key]
                index.remove_pod(key)
            elif op < 0.8:
                thr = rand_throttle(rng.randrange(6))
                throttles[thr.key] = thr
                index.upsert_throttle(thr)
            elif op < 0.9 and throttles:
                key = rng.choice(list(throttles))
                del throttles[key]
                index.remove_throttle(key)
            else:
                name = rng.choice(["ns1", "ns2", "ns3"])
                ns = Namespace(name, labels=_random_label(rng))
                namespaces[name] = ns
                index.upsert_namespace(ns)
                # ns label change can flip throttle matches for its pods
                # (handled inside upsert_namespace)

        oracle = self._oracle_mask(index, pods, throttles, namespaces)
        for (pk, tk), want in oracle.items():
            row = index.pod_row(pk)
            col = index.throttle_col(tk)
            got = bool(index.mask[row, col])
            assert got == want, f"({pk},{tk}): index={got} oracle={want}"
        # affected queries agree with the mask
        for pk in pods:
            got = set(index.affected_throttle_keys(pk))
            want_keys = {tk for tk in throttles if oracle[(pk, tk)]}
            assert got == want_keys

    @pytest.mark.parametrize("kind", ["throttle", "clusterthrottle"])
    def test_probe_cache_tracks_mutations(self, kind):
        """match_row_cached_locked must never serve a stale compiled-column
        evaluation: interleave probe queries (repeating (ns,labels) keys,
        so hits DO occur) with throttle/namespace churn and diff every
        result against the uncached evaluation."""
        rng = random.Random(7)
        index = SelectorIndex(kind, throttle_capacity=2)
        for name in ("ns1", "ns2"):
            index.upsert_namespace(Namespace(name, labels=_random_label(rng)))

        labels_pool = [_random_label(rng) for _ in range(5)]

        def probe():
            pod = make_pod(
                f"probe{rng.randrange(3)}",
                namespace=rng.choice(["ns1", "ns2"]),
                labels=rng.choice(labels_pool),
            )
            with index._lock:
                got = index.match_row_cached_locked(pod).copy()
                want = index._match_row_arbitrary_locked(pod)
            np.testing.assert_array_equal(got, want)

        mk_throttle = (
            (lambda i: Throttle(
                name=f"t{i}", namespace="ns1",
                spec=ThrottleSpec(selector=ThrottleSelector(selector_terms=(
                    ThrottleSelectorTerm(LabelSelector(match_labels=_random_label(rng))),
                ))),
            ))
            if kind == "throttle"
            else (lambda i: ClusterThrottle(
                name=f"c{i}",
                spec=ClusterThrottleSpec(selector=ClusterThrottleSelector(selector_terms=(
                    ClusterThrottleSelectorTerm(
                        pod_selector=LabelSelector(match_labels=_random_label(rng))
                    ),
                ))),
            ))
        )
        live = {}
        for step in range(200):
            op = rng.random()
            if op < 0.5:
                probe()
            elif op < 0.8:
                thr = mk_throttle(rng.randrange(4))
                live[thr.key] = thr
                index.upsert_throttle(thr)
                probe()
            elif op < 0.9 and live:
                index.remove_throttle(live.popitem()[0])
                probe()
            else:
                index.upsert_namespace(
                    Namespace(rng.choice(["ns1", "ns2"]), labels=_random_label(rng))
                )
                probe()
        assert index._probe_cache, "cache should have entries"


class TestDeviceMirrorRegressions:
    """Round-1 review findings on the device mirror."""

    def _manager(self):
        from kube_throttler_tpu.engine.devicestate import DeviceStateManager

        store = Store()
        store.create_namespace(Namespace("default"))
        mgr = DeviceStateManager(store, "kube-throttler", "my-scheduler")
        return store, mgr

    def _throttle(self, name, namespace="default", label="x", throttler="kube-throttler"):
        return Throttle(
            name=name,
            namespace=namespace,
            spec=ThrottleSpec(
                throttler_name=throttler,
                threshold=ResourceAmount.of(requests={"cpu": "100m"}),
                selector=ThrottleSelector(
                    selector_terms=(
                        ThrottleSelectorTerm(LabelSelector(match_labels={"throttle": label})),
                    )
                ),
            ),
        )

    def test_unknown_pod_fallback_respects_throttle_namespace(self):
        store, mgr = self._manager()
        store.create_namespace(Namespace("other"))
        store.create_throttle(self._throttle("t1", namespace="other"))
        # pod NOT in the store → fallback mask path
        pod = make_pod("ghost", namespace="default", labels={"throttle": "x"}, requests={"cpu": "1"})
        assert mgr.check_pod(pod, "throttle") == {}

    def test_throttler_name_change_removes_device_row(self):
        from dataclasses import replace

        store, mgr = self._manager()
        thr = self._throttle("t2")
        store.create_throttle(thr)
        pod = make_pod("p", labels={"throttle": "x"}, requests={"cpu": "1"})
        store.create_pod(pod)
        assert "default/t2" in mgr.check_pod(pod, "throttle")
        # rename the throttler → this throttler no longer governs t2
        store.update_throttle(replace(thr, spec=replace(thr.spec, throttler_name="someone-else")))
        assert mgr.check_pod(pod, "throttle") == {}

    def test_indexed_and_dense_check_branches_agree(self):
        """check_pod's indexed hot path vs its dense fallback over the SAME
        state — forced by tuning indexed_check_max (review finding: the dense
        branch was unreachable at the default 1024 threshold)."""
        store, mgr = self._manager()
        # several throttles at different saturation levels, all matching
        for i, cpu in enumerate(["50m", "100m", "1", "10"]):
            store.create_throttle(
                Throttle(
                    name=f"t{i}",
                    spec=ThrottleSpec(
                        throttler_name="kube-throttler",
                        threshold=ResourceAmount.of(pod=2 if i % 2 else None, requests={"cpu": cpu}),
                        selector=ThrottleSelector(
                            selector_terms=(
                                ThrottleSelectorTerm(LabelSelector(match_labels={"throttle": "x"})),
                            )
                        ),
                    ),
                )
            )
        pod = make_pod("p", labels={"throttle": "x"}, requests={"cpu": "200m"})
        store.create_pod(pod)
        for on_equal in (False, True):
            mgr.indexed_check_max = 1024
            hot = mgr.check_pod(pod, "throttle", on_equal=on_equal)
            mgr.indexed_check_max = 0  # force the dense branch
            dense = mgr.check_pod(pod, "throttle", on_equal=on_equal)
            assert hot == dense and len(hot) == 4

    def test_incremental_device_sync_matches_full_upload(self):
        """device_pods' row-scatter path (single-pod events) must produce
        the same check_batch results as a freshly-built manager that
        full-uploads, across interleaved pod churn, label moves, deletes,
        and a throttle edit (which still forces a full mask rebuild)."""
        import random
        from dataclasses import replace as dc_replace

        import numpy as np

        rng = random.Random(5)
        store, mgr = self._manager()
        store.create_throttle(self._throttle("t1", label="x"))
        store.create_throttle(self._throttle("t2", label="y"))

        live = {}
        for step in range(40):
            op = rng.random()
            if op < 0.5 or not live:
                name = f"p{step}"
                pod = make_pod(
                    name,
                    labels={"throttle": rng.choice("xy")},
                    requests={"cpu": f"{rng.randint(1, 4)}00m"},
                    node_name="n1" if rng.random() < 0.5 else "",
                )
                live[name] = pod
                try:
                    store.create_pod(pod)
                except ValueError:
                    store.update_pod(pod)
            elif op < 0.7:
                name = rng.choice(list(live))
                moved = dc_replace(live[name], labels={"throttle": rng.choice("xy")})
                live[name] = moved
                store.update_pod(moved)
            elif op < 0.85:
                name = rng.choice(list(live))
                del live[name]
                store.delete_pod("default", name)
            else:  # throttle edit → full mask invalidation interleaved
                thr = store.get_throttle("default", "t1")
                store.update_throttle(
                    dc_replace(
                        thr,
                        spec=dc_replace(
                            thr.spec,
                            threshold=ResourceAmount.of(requests={"cpu": f"{rng.randint(1, 9)}00m"}),
                        ),
                    )
                )

            counts_inc, sched_inc, rows_inc = mgr.check_batch("throttle")
            # fresh manager rebuilds everything from the same store state;
            # unsubscribe it afterwards or stale managers pile up handlers
            from kube_throttler_tpu.engine.devicestate import DeviceStateManager

            fresh = DeviceStateManager(store, "kube-throttler", "my-scheduler")
            counts_full, sched_full, rows_full = fresh.check_batch("throttle")
            for kind_name, handler in (
                ("Namespace", fresh._on_namespace),
                ("Pod", fresh._on_pod),
                ("Throttle", fresh._on_throttle),
                ("ClusterThrottle", fresh._on_cluster_throttle),
            ):
                store.remove_event_handler(kind_name, handler)
            for key, row in rows_inc.items():
                frow = rows_full[key]
                np.testing.assert_array_equal(
                    np.asarray(counts_inc)[row], np.asarray(counts_full)[frow], err_msg=f"{step}:{key}"
                )
                assert bool(np.asarray(sched_inc)[row]) == bool(np.asarray(sched_full)[frow])

    def test_missing_namespace_never_matches_clusterthrottle(self):
        from kube_throttler_tpu.engine.devicestate import DeviceStateManager

        store = Store()  # note: no namespace objects at all
        mgr = DeviceStateManager(store, "kube-throttler", "my-scheduler")
        clthr = ClusterThrottle(
            name="c1",
            spec=ClusterThrottleSpec(
                throttler_name="kube-throttler",
                threshold=ResourceAmount.of(requests={"cpu": "100m"}),
                selector=ClusterThrottleSelector(
                    selector_terms=(
                        ClusterThrottleSelectorTerm(
                            pod_selector=LabelSelector(match_labels={"throttle": "x"})
                        ),
                    )
                ),
            ),
        )
        store.create_cluster_throttle(clthr)
        pod = make_pod("p", namespace="ghost", labels={"throttle": "x"}, requests={"cpu": "1"})
        store.create_pod(pod)
        assert mgr.check_pod(pod, "clusterthrottle") == {}
        # once the namespace exists, the match appears
        store.create_namespace(Namespace("ghost"))
        assert "/c1" in mgr.check_pod(pod, "clusterthrottle")


def test_recording_event_recorder_aggregates_and_caps():
    from kube_throttler_tpu.plugin.framework import RecordingEventRecorder

    r = RecordingEventRecorder(max_events=3)
    for _ in range(100):
        r.eventf("ns/p", "Warning", "FailedScheduling", "Scheduling", "same msg")
    assert len(r.events) == 1
    assert r.counts[r.events[0]] == 100
    for i in range(5):
        r.eventf("ns/p", "Warning", "FailedScheduling", "Scheduling", f"msg-{i}")
    assert len(r.events) == 3  # capped, oldest evicted
    assert len(r.counts) == 3


def test_sparse_cols_k_growth_through_dirty_row_path():
    """A pod relabel that multiplies its match count must escalate the
    sparse [P,K] cols ladder (K rung growth) through the dirty-row update,
    and the batch verdict must keep matching a fresh manager's."""
    from dataclasses import replace as dc_replace

    import numpy as np

    from kube_throttler_tpu.api.pod import Namespace, make_pod
    from kube_throttler_tpu.api.types import (
        LabelSelector,
        ResourceAmount,
        Throttle,
        ThrottleSelector,
        ThrottleSelectorTerm,
        ThrottleSpec,
    )
    from kube_throttler_tpu.engine.devicestate import DeviceStateManager
    from kube_throttler_tpu.engine.store import Store

    def throttle(name, labels):
        return Throttle(
            name=name,
            spec=ThrottleSpec(
                throttler_name="kube-throttler",
                threshold=ResourceAmount.of(requests={"cpu": "100m"}),
                selector=ThrottleSelector(
                    selector_terms=(
                        ThrottleSelectorTerm(LabelSelector(match_labels=labels)),
                    )
                ),
            ),
        )

    store = Store()
    store.create_namespace(Namespace("default"))
    mgr = DeviceStateManager(store, "kube-throttler", "my-scheduler")
    # 100 fillers (unique labels, match nothing) push tcap high enough that
    # the sparse path engages (at tiny tcap the dense fallback is correct)
    for i in range(100):
        store.create_throttle(throttle(f"t-fill{i}", {"fill": f"f{i}"}))
    for i in range(8):
        store.create_throttle(throttle(f"t-group{i}", {"grp": "a"}))
    store.create_throttle(throttle("t-solo", {"solo": "y"}))

    pod = make_pod("p0", labels={"solo": "y"}, requests={"cpu": "200m"},
                   node_name="n1")
    store.create_pod(pod)
    counts, _, rows = mgr.check_batch("throttle")
    assert int(np.asarray(counts)[rows["default/p0"]].sum()) == 1
    ks = mgr.throttle
    assert ks._cols_host is not None  # sparse path active
    k_before = ks._cols_K

    # relabel: now ALSO matches the 8 group throttles — nnz 9 > the K rung
    store.update_pod(
        dc_replace(pod, labels={"solo": "y", "grp": "a"})
    )
    counts, _, rows = mgr.check_batch("throttle")
    assert int(np.asarray(counts)[rows["default/p0"]].sum()) == 9
    assert ks._cols_host is not None and ks._cols_K > k_before  # rung grew

    fresh = DeviceStateManager(store, "kube-throttler", "my-scheduler")
    fcounts, _, frows = fresh.check_batch("throttle")
    np.testing.assert_array_equal(
        np.asarray(counts)[rows["default/p0"]],
        np.asarray(fcounts)[frows["default/p0"]],
    )
    for kind_name, handler in (
        ("Namespace", fresh._on_namespace),
        ("Pod", fresh._on_pod),
        ("Throttle", fresh._on_throttle),
        ("ClusterThrottle", fresh._on_cluster_throttle),
    ):
        store.remove_event_handler(kind_name, handler)


def test_store_batched_status_write_mixed_results():
    """One lock-hold batch write: successes update + dispatch MODIFIED with
    old_obj; a missing key reports NotFoundError in-place without failing
    the rest."""
    from kube_throttler_tpu.api.pod import Namespace
    from kube_throttler_tpu.api.types import (
        ResourceAmount,
        Throttle,
        ThrottleSpec,
        ThrottleStatus,
    )
    from kube_throttler_tpu.engine.store import NotFoundError, Store

    store = Store()
    store.create_namespace(Namespace("default"))
    for name in ("a", "b"):
        store.create_throttle(
            Throttle(
                name=name,
                spec=ThrottleSpec(
                    throttler_name="kt", threshold=ResourceAmount.of(pod=3)
                ),
            )
        )
    events = []
    store.add_event_handler("Throttle", lambda e: events.append(e), replay=False)

    def with_used(name, pods):
        thr = store.get_throttle("default", name) if name != "ghost" else Throttle(
            name="ghost",
            spec=ThrottleSpec(throttler_name="kt", threshold=ResourceAmount.of(pod=3)),
        )
        return thr.with_status(
            ThrottleStatus(
                calculated_threshold=thr.status.calculated_threshold,
                throttled=thr.status.throttled,
                used=ResourceAmount.of(pod=pods),
            )
        )

    out = store.update_throttle_statuses(
        [with_used("a", 1), with_used("ghost", 9), with_used("b", 2)]
    )
    assert isinstance(out["default/ghost"], NotFoundError)
    assert out["default/a"].status.used.resource_counts == 1
    assert out["default/b"].status.used.resource_counts == 2
    mods = [e for e in events if e.type.name == "MODIFIED"]
    assert len(mods) == 2
    assert all(e.old_obj is not None for e in mods)
    # rv strictly increases across the batch
    assert store.resource_version("Throttle", "default/a") < store.resource_version(
        "Throttle", "default/b"
    )


def test_drain_requeues_only_failed_status_writes():
    """A per-key write failure inside the batched drain lands in the error
    map (→ rate-limited requeue) while the rest of the drain completes."""
    from kube_throttler_tpu.api.pod import Namespace, make_pod
    from kube_throttler_tpu.api.types import (
        LabelSelector,
        ResourceAmount,
        Throttle,
        ThrottleSelector,
        ThrottleSelectorTerm,
        ThrottleSpec,
    )
    from kube_throttler_tpu.engine.store import Store
    from kube_throttler_tpu.plugin import KubeThrottler, decode_plugin_args

    store = Store()
    store.create_namespace(Namespace("default"))
    plugin = KubeThrottler(
        decode_plugin_args({"name": "kt", "targetSchedulerName": "my-scheduler"}),
        store,
        use_device=False,
        start_workers=False,
    )
    for i in range(4):
        store.create_throttle(
            Throttle(
                name=f"t{i}",
                spec=ThrottleSpec(
                    throttler_name="kt",
                    threshold=ResourceAmount.of(pod=5),
                    selector=ThrottleSelector(
                        selector_terms=(
                            ThrottleSelectorTerm(
                                LabelSelector(match_labels={"g": f"g{i}"})
                            ),
                        )
                    ),
                ),
            )
        )
    for i in range(4):
        pod = make_pod(f"p{i}", labels={"g": f"g{i}"}, node_name="n1")
        pod.status.phase = "Running"
        store.create_pod(pod)

    ctr = plugin.throttle_ctr
    orig = store.update_throttle_statuses

    def poisoned(thrs):
        out = orig([t for t in thrs if t.name != "t2"])
        for t in thrs:
            if t.name == "t2":
                out["default/t2"] = RuntimeError("boom")
        return out

    store.update_throttle_statuses = poisoned
    errors = ctr.reconcile_batch([f"default/t{i}" for i in range(4)])
    assert set(errors) == {"default/t2"}
    assert isinstance(errors["default/t2"], RuntimeError)
    # the others' statuses landed
    for i in (0, 1, 3):
        assert (
            store.get_throttle("default", f"t{i}").status.used.resource_counts == 1
        )
    assert store.get_throttle("default", "t2").status.used.resource_counts is None


def test_self_echo_suppression_is_thread_scoped():
    """The self-echo signature is (writer thread, key, status identity):
    the SAME event object must suppress on the writing thread and must NOT
    suppress from any other thread — a concurrent spec-update write
    re-attaches the stored status object (with_status), and ITS echo,
    dispatched on the other writer's thread, has to enqueue or a threshold
    edit would sit until resync (review finding, r5)."""
    import threading

    from kube_throttler_tpu.api import ResourceAmount, Throttle, ThrottleSpec
    from kube_throttler_tpu.controllers import ThrottleController
    from kube_throttler_tpu.engine.store import Event, EventType, Store

    store = Store()
    ctr = ThrottleController(
        throttler_name="kube-throttler",
        target_scheduler_name="my-scheduler",
        store=store,
    )
    thr = Throttle(
        name="t1", namespace="default",
        spec=ThrottleSpec(throttler_name="kube-throttler",
                          threshold=ResourceAmount.of(pod=1)),
    )
    ctr._inflight_status_echoes[thr.key] = (
        threading.get_ident(), id(thr.status),
    )
    event = Event(EventType.MODIFIED, "Throttle", thr, old_obj=thr)
    assert ctr._is_self_status_echo(event) is True

    seen = {}
    t = threading.Thread(
        target=lambda: seen.__setitem__("other", ctr._is_self_status_echo(event))
    )
    t.start(); t.join()
    assert seen["other"] is False  # other thread: never suppressed

    ctr._inflight_status_echoes.clear()
    assert ctr._is_self_status_echo(event) is False  # marker gone


class TestReservationTTL:
    """TTL'd reservations under a frozen clock: expiry, snapshot's
    remaining-budget serialization, and restore's
    charge-elapsed-then-rebase rule (never resurrect expired entries)."""

    T0 = datetime(2026, 8, 4, tzinfo=timezone.utc)

    def _cache(self, clock):
        return ReservedResourceAmounts(4, clock=clock)

    def test_ttl_expiry_is_clock_driven(self):
        clock = FakeClock(self.T0)
        cache = self._cache(clock)
        cache.add_pod("ns/t1", make_pod("p1"), ttl=30.0)
        cache.add_pod("ns/t1", make_pod("p2"))  # no TTL: reference lifetime
        amount, keys = cache.reserved_resource_amount("ns/t1")
        assert keys == {"default/p1", "default/p2"}
        assert amount.resource_counts == 2
        clock.advance(timedelta(seconds=29))
        assert cache.reserved_pod_keys("ns/t1") == {"default/p1", "default/p2"}
        clock.advance(timedelta(seconds=2))  # past p1's deadline
        amount, keys = cache.reserved_resource_amount("ns/t1")
        assert keys == {"default/p2"}
        assert amount.resource_counts == 1
        assert cache.expired_total == 1

    def test_re_add_refreshes_and_clears_deadlines(self):
        clock = FakeClock(self.T0)
        cache = self._cache(clock)
        cache.add_pod("ns/t1", make_pod("p1"), ttl=10.0)
        clock.advance(timedelta(seconds=8))
        cache.add_pod("ns/t1", make_pod("p1"))  # re-reserve WITHOUT a TTL
        clock.advance(timedelta(seconds=1000))
        assert cache.reserved_pod_keys("ns/t1") == {"default/p1"}

    def test_snapshot_serializes_remaining_budget_and_omits_expired(self):
        clock = FakeClock(self.T0)
        cache = self._cache(clock)
        cache.add_pod("ns/t1", make_pod("p1"), ttl=100.0)
        cache.add_pod("ns/t1", make_pod("p2"), ttl=10.0)
        clock.advance(timedelta(seconds=40))  # p2 already expired
        state = cache.snapshot_state()
        entries = state["ns/t1"]
        assert set(entries) == {"default/p1"}
        assert entries["default/p1"]["ttlRemainingSeconds"] == pytest.approx(60.0)

    def test_restore_charges_dead_time_then_rebases_on_restored_clock(self):
        clock = FakeClock(self.T0)
        cache = self._cache(clock)
        cache.add_pod("ns/t1", make_pod("keep"), ttl=100.0)
        cache.add_pod("ns/t1", make_pod("die"), ttl=10.0)
        state = cache.snapshot_state()

        # restart on a clock 50s later (the process was dead that long):
        # "die" (10s budget) must NOT resurrect; "keep" has 50s left
        restore_clock = FakeClock(self.T0 + timedelta(seconds=50))
        fresh = self._cache(restore_clock)
        restored, dropped, touched = fresh.restore_state(
            state, elapsed_s=50.0
        )
        assert (restored, dropped, touched) == (1, 1, ["ns/t1"])
        assert fresh.reserved_pod_keys("ns/t1") == {"default/keep"}
        restore_clock.advance(timedelta(seconds=49))
        assert fresh.reserved_pod_keys("ns/t1") == {"default/keep"}
        restore_clock.advance(timedelta(seconds=2))
        assert fresh.reserved_pod_keys("ns/t1") == set()

    def test_restore_is_skew_proof_frozen_clock(self):
        """Even a restored clock BEHIND the snapshot clock cannot extend a
        deadline: budgets are relative, never absolute timestamps."""
        clock = FakeClock(self.T0)
        cache = self._cache(clock)
        cache.add_pod("ns/t1", make_pod("p1"), ttl=20.0)
        state = cache.snapshot_state()
        skewed = FakeClock(self.T0 - timedelta(hours=3))  # clock went backwards
        fresh = self._cache(skewed)
        fresh.restore_state(state, elapsed_s=0.0)
        skewed.advance(timedelta(seconds=19))
        assert fresh.reserved_pod_keys("ns/t1") == {"default/p1"}
        skewed.advance(timedelta(seconds=2))
        assert fresh.reserved_pod_keys("ns/t1") == set()
