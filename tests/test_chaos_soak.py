"""Seeded chaos runs over the integration stack: the invariants that matter
under churn and partial failure (ISSUE 1 / SURVEY §5 — admission control is
only trustworthy under failure):

- **no lost/duplicated watch events after reconnect** — the local reflector
  cache converges to exact equality with the remote store despite stream
  cuts, 410 storms, and connection resets;
- **status converges after conflict storms** — injected 409s on the status
  subresource delay but never lose publications;
- **admission never over-admits while degraded** — device-dispatch faults
  flip the breaker through open/half-open mid-burst and the host oracle
  keeps the reservation arithmetic exact;
- **journal replay recovers to the pre-crash store** — torn/dropped writes
  and a failed compaction fsync, then a crash, still replay to the live
  store's exact contents.

The fast smoke variants run one seeded deterministic pass each (tier-1);
the randomized multi-seed soak is behind ``-m slow``.
"""

from __future__ import annotations

import random
import time
from dataclasses import replace

import pytest

from kube_throttler_tpu.api.pod import Namespace, make_pod
from kube_throttler_tpu.api.serialization import object_to_dict
from kube_throttler_tpu.api.types import (
    LabelSelector,
    ResourceAmount,
    Throttle,
    ThrottleSelector,
    ThrottleSelectorTerm,
    ThrottleSpec,
)
from kube_throttler_tpu.client.mockserver import MockApiServer
from kube_throttler_tpu.client.transport import RemoteSession, RestConfig
from kube_throttler_tpu.engine.journal import attach
from kube_throttler_tpu.engine.store import Store
from kube_throttler_tpu.faults import FaultPlan
from kube_throttler_tpu.plugin import KubeThrottler, decode_plugin_args

SMOKE_SEED = 1337


def _throttle(name, labels, **threshold):
    return Throttle(
        name=name,
        spec=ThrottleSpec(
            throttler_name="kube-throttler",
            threshold=ResourceAmount.of(**threshold),
            selector=ThrottleSelector(
                selector_terms=(
                    ThrottleSelectorTerm(LabelSelector(match_labels=labels)),
                )
            ),
        ),
    )


def _bound(pod):
    bound = replace(pod, spec=replace(pod.spec, node_name="node-1"))
    bound.status.phase = "Running"
    return bound


def _wait(predicate, timeout=20.0, every=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(every)
    return predicate()


def _dump(store: Store) -> dict:
    """Canonical content snapshot of every kind (no resourceVersions — the
    two stores version independently — and no uids: make_pod's uid counter
    is process-global, so independent same-seed runs differ only there)."""

    def strip(obj) -> dict:
        doc = object_to_dict(obj)
        (doc.get("metadata") or {}).pop("uid", None)
        return doc

    return {
        "Namespace": {n.name: strip(n) for n in store.list_namespaces()},
        "Pod": {p.key: strip(p) for p in store.list_pods()},
        "Throttle": {t.key: strip(t) for t in store.list_throttles()},
        "ClusterThrottle": {
            t.name: strip(t) for t in store.list_cluster_throttles()
        },
    }


# --------------------------------------------------------------------------
# remote-mode convergence: watch cuts + 410s + resets + conflict storms


def _remote_chaos_round(seed: int, pods: int = 24, settle_timeout: float = 30.0):
    """One seeded chaos pass over the remote-mode stack. Returns the plans
    (for firing assertions) after asserting the convergence invariants."""
    server = MockApiServer(bookmark_interval=0.02, log_size=512)
    remote = server.store
    remote.create_namespace(Namespace("default"))
    remote.create_throttle(_throttle("t1", {"grp": "a"}, pod=1000, requests={"cpu": "100"}))

    server_plan = FaultPlan(seed)
    # sever live watch streams; storm the status subresource with 409s
    server_plan.rule("mock.watch.cut", probability=0.10, times=6)
    server_plan.rule("mock.status.conflict", probability=0.25, times=8)
    server.faults = server_plan

    client_plan = FaultPlan(seed + 1)
    # client-side: torn streams, a 410 mid-read, resets on the REST path
    # (after= lets the initial 4-kind sync land before the storm starts)
    client_plan.rule("transport.watch.read", mode="close", probability=0.02, times=6)
    client_plan.rule("transport.watch.read", mode="gone", schedule=[25], times=1)
    client_plan.rule("transport.request", probability=0.05, times=5, after=12)

    server.start()
    local = Store()
    session = RemoteSession(
        RestConfig(server=server.url), local, faults=client_plan
    )
    session.start(sync_timeout=20)
    plugin = KubeThrottler(
        decode_plugin_args(
            {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
        ),
        local,
        use_device=True,
        start_workers=True,
        status_writer=session.status_committer,
    )
    try:
        rng = random.Random(seed)
        # churn: bound pods appear/mutate/disappear on the REMOTE cluster
        # while reconciles publish status back through the conflict storm
        alive = []
        for i in range(pods):
            name = f"chaos-{i:03d}"
            remote.create_pod(
                _bound(
                    make_pod(
                        name,
                        labels={"grp": "a"},
                        requests={"cpu": f"{rng.choice([50, 100, 150])}m"},
                    )
                )
            )
            alive.append(name)
            if rng.random() < 0.3 and len(alive) > 2:
                victim = alive.pop(rng.randrange(len(alive)))
                remote.delete_pod("default", victim)
            time.sleep(0.005)

        # settle: remote and local must converge to IDENTICAL content —
        # every delete/add survived the stream cuts and relists (no lost,
        # no resurrected/duplicated objects)
        assert _wait(
            lambda: {p.key for p in local.list_pods()}
            == {p.key for p in remote.list_pods()},
            timeout=settle_timeout,
        ), "local pod set never converged to remote"

        # ... and the throttle status converged THROUGH the conflict storm:
        # used counts exactly the bound matching pods (status publications
        # were delayed by 409s, never lost)
        expected = len(alive)
        assert _wait(
            lambda: remote.get_throttle("default", "t1").status.used.resource_counts
            == expected,
            timeout=settle_timeout,
        ), (
            f"remote status.used={remote.get_throttle('default', 't1').status.used.resource_counts} "
            f"never converged to {expected}"
        )
        # the echo closes the loop: local mirrors the remote status
        assert _wait(
            lambda: local.get_throttle("default", "t1") is not None
            and local.get_throttle("default", "t1").status.used.resource_counts
            == expected,
            timeout=settle_timeout,
        )
        # full-content equality across every kind
        assert _wait(lambda: _dump(local) == _dump(remote), timeout=settle_timeout)
        return server_plan, client_plan
    finally:
        plugin.stop()
        session.stop()
        server.stop()


def test_chaos_smoke_remote_convergence():
    """Tier-1 smoke: one seeded deterministic chaos pass; the plans must
    actually fire (a chaos test whose faults never trigger is a no-op)."""
    server_plan, client_plan = _remote_chaos_round(SMOKE_SEED)
    assert server_plan.fired() > 0, "server-side faults never fired"
    assert client_plan.fired() > 0, "client-side faults never fired"


# --------------------------------------------------------------------------
# admission: never over-admit while the device layer is degraded


def _admission_chaos_round(seed: int):
    store = Store()
    store.create_namespace(Namespace("default"))
    plugin = KubeThrottler(
        decode_plugin_args(
            {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
        ),
        store,
        use_device=True,
        start_workers=False,
    )
    dm = plugin.device_manager
    now = [5000.0]
    dm._monotonic = lambda: now[0]
    plan = FaultPlan(seed)
    plan.rule("device.dispatch", probability=0.4)
    dm.faults = plan
    store.create_throttle(_throttle("burst", {"grp": "a"}, requests={"cpu": "1"}))
    plugin.run_pending_once()

    admitted = 0
    states = set()
    for i in range(21):
        pod = make_pod(f"b{i:02d}", labels={"grp": "a"}, requests={"cpu": "50m"})
        store.create_pod(pod)
        if plugin.pre_filter(pod).is_success():
            assert plugin.reserve(pod).is_success()
            admitted += 1
        states.add(dm.breaker_state())
        if i % 4 == 3:
            # roll the cooldown forward so the breaker cycles through
            # half-open probes mid-burst (probe outcome is fault-driven)
            now[0] += dm.device_retry_cooldown + 1
            states.add(dm.breaker_state())
    plugin.stop()
    return admitted, states, plan


def test_chaos_smoke_admission_never_over_admits():
    """21 × 50m against cpu=1 admits EXACTLY 20 — the host oracle keeps
    reservation arithmetic exact while injected dispatch faults flip the
    breaker through open and half-open mid-burst."""
    admitted, states, plan = _admission_chaos_round(SMOKE_SEED)
    assert admitted == 20, f"over/under-admission under device chaos: {admitted}"
    assert plan.fired("device.dispatch") > 0, "device faults never fired"
    assert "open" in states, "the breaker never opened — chaos was a no-op"


# --------------------------------------------------------------------------
# journal: replay converges to the pre-crash store


def _journal_chaos_round(seed: int, tmp_path, ops: int = 150):
    """Deterministic single-threaded journal chaos: torn writes, dropped
    writes, one failed compaction fsync — then heal (compact), crash, and
    replay. Returns (plan history, live dump, replayed dump)."""
    path = str(tmp_path / f"chaos-{seed}.journal")
    plan = FaultPlan(seed)
    plan.rule("journal.append", mode="torn", probability=0.06)
    plan.rule("journal.append", mode="error", probability=0.04)
    plan.rule("journal.fsync", times=1)
    store = Store()
    journal = attach(store, path, compact_after=60, faults=plan)
    store.create_namespace(Namespace("default"))
    store.create_throttle(_throttle("t1", {"grp": "a"}, pod=100))
    rng = random.Random(seed)
    alive = []
    for i in range(ops):
        roll = rng.random()
        if roll < 0.5 or not alive:
            name = f"p-{i:03d}"
            store.create_pod(
                _bound(make_pod(name, labels={"grp": "a"},
                                requests={"cpu": f"{rng.choice([50, 100])}m"}))
            )
            alive.append(name)
        elif roll < 0.8:
            name = rng.choice(alive)
            store.update_pod(
                _bound(make_pod(name, labels={"grp": "a"},
                                requests={"cpu": f"{rng.choice([60, 120])}m"}))
            )
        else:
            store.delete_pod("default", alive.pop(rng.randrange(len(alive))))
    assert journal.torn_writes > 0, "torn faults never fired"
    assert journal.write_errors > 0, "write-error faults never fired"
    assert journal.compact_failures >= 1, "the fsync fault never hit a compaction"

    # heal the log (operational compact), then CRASH (no close())
    journal.compact()
    live = _dump(store)

    recovered = Store()
    j2 = attach(recovered, path)
    replayed = _dump(recovered)
    j2.close()
    assert j2.replay_skipped == 0, "post-compact replay must be clean"
    return plan.snapshot(), live, replayed


def test_chaos_smoke_journal_replay_converges(tmp_path):
    history, live, replayed = _journal_chaos_round(SMOKE_SEED, tmp_path)
    assert replayed == live, "journal replay diverged from the pre-crash store"


def test_chaos_journal_run_is_bit_for_bit_reproducible(tmp_path):
    """Acceptance: same seed → same injected fault sequence AND same final
    state, across two fully independent runs."""
    for sub in ("a", "b", "c"):
        (tmp_path / sub).mkdir()
    h1, live1, rep1 = _journal_chaos_round(SMOKE_SEED, tmp_path / "a")
    h2, live2, rep2 = _journal_chaos_round(SMOKE_SEED, tmp_path / "b")
    assert h1 == h2, "fault sequences diverged for the same seed"
    assert live1 == live2 and rep1 == rep2
    # and a different seed produces a different fault sequence
    h3, _, _ = _journal_chaos_round(SMOKE_SEED + 1, tmp_path / "c")
    assert h3 != h1


# --------------------------------------------------------------------------
# the long randomized soak (behind -m slow)


@pytest.mark.slow
@pytest.mark.parametrize("seed", [3, 7, 11, 19, 31])
def test_chaos_soak_randomized(seed, tmp_path):
    """Multi-seed soak of all three chaos surfaces (tier-2; tier-1 runs the
    single-seed smoke variants above)."""
    server_plan, client_plan = _remote_chaos_round(seed, pods=60, settle_timeout=60)
    assert server_plan.fired() + client_plan.fired() > 0
    admitted, _, _ = _admission_chaos_round(seed)
    assert admitted == 20
    _, live, replayed = _journal_chaos_round(seed, tmp_path, ops=400)
    assert replayed == live
