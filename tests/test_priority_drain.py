"""Two-lane status pipeline: priority-drained flip publication.

Covers the stack bottom-up:

- workqueue priority lane (promote/move/requeue semantics, enqueue
  timestamps);
- AsyncStatusCommitter lanes: flips overtake the refresh backlog, per-key
  ordering holds ACROSS lanes, promote-never-demote, refresh conflict
  storms never starve flips (the PR-1 fault-injection plan drives the
  409s/watch cuts in the end-to-end case);
- devicestate classification-delta flip detection (drained vs promote);
- controller commit ordering (a flipping key's status write dispatches
  before the refresh keys drained in the same batch, regardless of
  enqueue order);
- the two ADVICE r5 regressions: mid-batch R growth in check_pods_multi,
  and the KT_GATHER_CHUNK_ELEMS import-time env parse.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time

from kube_throttler_tpu.api.pod import Namespace, make_pod
from kube_throttler_tpu.api.types import (
    LabelSelector,
    ResourceAmount,
    Throttle,
    ThrottleSelector,
    ThrottleSelectorTerm,
    ThrottleSpec,
    ThrottleStatus,
)
from kube_throttler_tpu.client.mockserver import MockApiServer
from kube_throttler_tpu.client.transport import AsyncStatusCommitter, RemoteSession, RestConfig
from kube_throttler_tpu.engine.store import ConflictError, EventType, Store
from kube_throttler_tpu.engine.workqueue import RateLimitingQueue
from kube_throttler_tpu.faults import FaultPlan
from kube_throttler_tpu.metrics import Registry
from kube_throttler_tpu.plugin import KubeThrottler, decode_plugin_args


def _wait(predicate, timeout=10.0, every=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(every)
    return predicate()


def _throttle(name, labels, **threshold):
    return Throttle(
        name=name,
        spec=ThrottleSpec(
            throttler_name="kube-throttler",
            threshold=ResourceAmount.of(**threshold),
            selector=ThrottleSelector(
                selector_terms=(
                    ThrottleSelectorTerm(LabelSelector(match_labels=labels)),
                )
            ),
        ),
    )


def _bound(name, labels, cpu="100m", **kw):
    return make_pod(
        name, labels=labels, requests={"cpu": cpu},
        node_name="node-1", phase="Running", **kw,
    )


def _stack():
    store = Store()
    plugin = KubeThrottler(
        decode_plugin_args(
            {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
        ),
        store,
        use_device=True,
    )
    store.create_namespace(Namespace("default"))
    return store, plugin


# ---------------------------------------------------------------------------
# workqueue priority lane
# ---------------------------------------------------------------------------


class TestWorkqueuePriorityLane:
    def test_priority_lane_drains_first(self):
        q = RateLimitingQueue("t")
        q.add("a")
        q.add("b")
        q.add_priority("hot")
        assert [q.get(0.1), q.get(0.1), q.get(0.1)] == ["hot", "a", "b"]

    def test_promote_moves_item_out_of_normal_lane(self):
        q = RateLimitingQueue("t")
        for k in ("a", "b", "c"):
            q.add(k)
        q.add_priority("b")
        got = [q.get(0.1), q.get(0.1), q.get(0.1)]
        assert got == ["b", "a", "c"]
        # moved, not duplicated
        assert q.try_get() is None

    def test_promote_while_processing_requeues_into_hi(self):
        q = RateLimitingQueue("t")
        q.add("a")
        assert q.get(0.1) == "a"  # processing
        q.add("b")
        q.add_priority("a")  # dirty-while-processing, flagged hi
        q.done("a")
        assert q.get(0.1) == "a"  # re-queued ahead of b
        assert q.get(0.1) == "b"

    def test_promote_unknown_item_enqueues_hi(self):
        q = RateLimitingQueue("t")
        q.add("a")
        q.add_all_priority(["x", "y"])
        assert [q.get(0.1), q.get(0.1), q.get(0.1)] == ["x", "y", "a"]

    def test_len_counts_both_lanes(self):
        q = RateLimitingQueue("t")
        q.add("a")
        q.add_priority("b")
        assert len(q) == 2

    def test_claim_ts_pops_first_event_time(self):
        q = RateLimitingQueue("t")
        before = time.monotonic()
        q.add("a")
        q.add("a")  # dedup: must not advance the first-event time
        assert q.get(0.1) == "a"
        ts = q.claim_ts("a")
        assert ts is not None and before <= ts <= time.monotonic()
        assert q.claim_ts("a") is None  # one sample per hand-out


# ---------------------------------------------------------------------------
# two-lane committer
# ---------------------------------------------------------------------------


class _FakeWriter:
    """RemoteStatusWriter stand-in recording _put calls; can be armed to
    raise per-key and to gate (block) the first call."""

    def __init__(self, gate=None):
        self.calls = []  # (kind, key, obj)
        self.fail_plan = {}  # key -> list of exceptions to raise first
        self.lock = threading.Lock()
        self.gate = gate  # threading.Event: first _put blocks on it
        self.entered = threading.Event()

    def _put(self, kind, obj):
        from kube_throttler_tpu.engine.store import key_of

        key = key_of(kind, obj)
        gate = None
        with self.lock:
            plan = self.fail_plan.get(key)
            if plan:
                raise plan.pop(0)
            if self.gate is not None:
                gate, self.gate = self.gate, None
        if gate is not None:
            self.entered.set()
            gate.wait(10)
        with self.lock:
            self.calls.append((kind, key, obj))

    def refresh_version(self, kind, obj):
        pass


def _thr_status(name, pods, throttled=False):
    from kube_throttler_tpu.api.types import IsResourceAmountThrottled

    return Throttle(
        name=name,
        namespace="default",
        spec=ThrottleSpec(throttler_name="kt"),
        status=ThrottleStatus(
            used=ResourceAmount.of(pod=pods),
            throttled=IsResourceAmountThrottled(resource_counts_pod=throttled),
        ),
    )


class TestCommitterTwoLane:
    def test_flip_overtakes_refresh_backlog(self):
        gate = threading.Event()
        w = _FakeWriter(gate=gate)
        c = AsyncStatusCommitter(w, workers=1)
        c.start()
        try:
            c.update_throttle_status(_thr_status("hold", 1))
            assert w.entered.wait(5)  # worker is parked inside the PUT
            for i in range(50):
                c.update_throttle_status(_thr_status(f"ref{i:02d}", i))
            c.update_throttle_statuses_prioritized(
                [_thr_status("flip", 9, throttled=True)],
                flip_keys={"default/flip"},
            )
            gate.set()
            assert c.flush(10.0)
        finally:
            c.stop()
        keys = [k for (_, k, _) in w.calls]
        # the flip is the very next PUT after the parked one, ahead of all
        # 50 queued refreshes
        assert keys[0] == "default/hold"
        assert keys[1] == "default/flip"

    def test_per_key_ordering_across_lanes(self):
        w = _FakeWriter()
        c = AsyncStatusCommitter(w, workers=4)
        c.start()
        try:
            for i in range(30):
                # alternate lanes for the same two keys
                if i % 2:
                    c.update_throttle_statuses_prioritized(
                        [_thr_status("x", i), _thr_status("y", i)],
                        flip_keys={"default/x", "default/y"},
                    )
                else:
                    c.update_throttle_status(_thr_status("x", i))
                    c.update_throttle_status(_thr_status("y", i))
            assert c.flush(10.0)
        finally:
            c.stop()
        for key in ("default/x", "default/y"):
            seq = [o.status.used.resource_counts for (_, k, o) in w.calls if k == key]
            assert seq == sorted(seq), seq  # never out of submission order
            assert seq[-1] == 29  # newest landed last

    def test_refresh_never_demotes_pending_flip(self):
        w = _FakeWriter()
        c = AsyncStatusCommitter(w, workers=1)
        # no start: inspect lane assignment directly
        c.update_throttle_statuses_prioritized(
            [_thr_status("a", 1, throttled=True)], flip_keys={"default/a"}
        )
        c.update_throttle_status(_thr_status("a", 2))  # value-only follow-up
        (hi,) = [s for s in c._hi_shards if s]
        assert list(hi) == ["default/a"]
        assert sum(len(s) for s in c._lo_shards) == 0
        # the single PUT carries the NEWEST object (which includes the flip)
        c.start()
        assert c.flush(5.0)
        c.stop()
        assert len(w.calls) == 1
        assert w.calls[0][2].status.used.resource_counts == 2

    def test_refresh_conflict_storm_does_not_starve_flip(self):
        w = _FakeWriter()
        # a refresh key stuck in a 409 storm must hand the shard to the
        # flip between attempts (re-stage), not retry-sleep through it
        w.fail_plan["default/stuck"] = [ConflictError("rv")] * 3
        c = AsyncStatusCommitter(w, workers=1)
        c.start()
        try:
            c.update_throttle_status(_thr_status("stuck", 1))
            time.sleep(0.02)  # let the worker enter the retry loop
            c.update_throttle_statuses_prioritized(
                [_thr_status("flip", 5, throttled=True)],
                flip_keys={"default/flip"},
            )
            assert c.flush(10.0)
        finally:
            c.stop()
        keys = [k for (_, k, _) in w.calls]
        assert "default/flip" in keys and "default/stuck" in keys
        assert keys.index("default/flip") < keys.index("default/stuck")

    def test_lag_histograms_observed_per_lane(self):
        reg = Registry()
        w = _FakeWriter()
        c = AsyncStatusCommitter(w, workers=1, metrics_registry=reg)
        c.start()
        try:
            now = time.monotonic()
            c.update_throttle_statuses_prioritized(
                [_thr_status("f", 1, throttled=True), _thr_status("r", 2)],
                flip_keys={"default/f"},
                event_ts={"default/f": now, "default/r": now},
            )
            assert c.flush(5.0)
        finally:
            c.stop()
        total = reg.histogram_vec(
            "kube_throttler_status_lag_seconds", "", ["kind", "path"]
        ).snapshot({"kind": "Throttle", "path": "remote"})
        flip = reg.histogram_vec(
            "kube_throttler_status_flip_lag_seconds", "", ["kind", "path"]
        ).snapshot({"kind": "Throttle", "path": "remote"})
        assert total is not None and total[1] == 2
        assert flip is not None and flip[1] == 1


# ---------------------------------------------------------------------------
# devicestate classification-delta flip detection
# ---------------------------------------------------------------------------


class TestFlipDetection:
    def test_drained_flip_detected_and_cleared_by_publication(self):
        store, plugin = _stack()
        store.create_throttle(_throttle("t1", {"grp": "a"}, pod=1))
        store.create_pod(_bound("p0", {"grp": "a"}))
        plugin.run_pending_once()  # publish used=1, throttled (1 >= 1)
        dm = plugin.device_manager
        # second pod: used 2 — no flag change (still throttled); then
        # delete both: used 0 — flips OFF
        store.create_pod(_bound("p1", {"grp": "a"}))
        flips: dict = {}
        dm.aggregate_used_for("throttle", ["default/t1"], flips_out=flips)
        assert "default/t1" not in flips["drained"]  # 2 ≥ 1 == 1 ≥ 1: no flip
        plugin.run_pending_once()
        store.delete_pod("default", "p0")
        store.delete_pod("default", "p1")
        flips = {}
        dm.aggregate_used_for("throttle", ["default/t1"], flips_out=flips)
        assert "default/t1" in flips["drained"]

    def test_unrelated_drain_promotes_flipping_key(self):
        store, plugin = _stack()
        store.create_throttle(_throttle("t1", {"grp": "a"}, pod=1))
        store.create_throttle(_throttle("t2", {"grp": "b"}, pod=100))
        plugin.run_pending_once()
        dm = plugin.device_manager
        store.create_pod(_bound("p0", {"grp": "a"}))  # flips t1, not drained
        flips: dict = {}
        dm.aggregate_used_for("throttle", ["default/t2"], flips_out=flips)
        assert flips["drained"] == set()
        assert "default/t1" in flips["promote"]

    def test_published_state_yields_no_candidates(self):
        store, plugin = _stack()
        store.create_throttle(_throttle("t1", {"grp": "a"}, pod=1))
        store.create_pod(_bound("p0", {"grp": "a"}))
        plugin.run_pending_once()  # status + its echo land in the st planes
        dm = plugin.device_manager
        flips: dict = {}
        dm.aggregate_used_for("throttle", ["default/t1"], flips_out=flips)
        assert flips["drained"] == set() and flips["promote"] == set()


# ---------------------------------------------------------------------------
# controller commit ordering (local batched path)
# ---------------------------------------------------------------------------


class TestControllerFlipFirstCommit:
    def test_flip_key_commits_before_refresh_keys(self):
        store, plugin = _stack()
        # tflip: pod-count threshold 2 over grp a (flips when p2 arrives);
        # trefresh_*: huge thresholds over grp b (value-only refreshes)
        store.create_throttle(_throttle("tflip", {"grp": "a"}, pod=2))
        for i in range(8):
            store.create_throttle(_throttle(f"tref{i}", {"grp": "b"}, pod=10**6))
        store.create_pod(_bound("pa", {"grp": "a"}))
        store.create_pod(_bound("pb", {"grp": "b"}))
        plugin.run_pending_once()

        order = []

        def record(event):
            if event.type == EventType.MODIFIED:
                order.append(event.obj.key)

        store.add_event_handler("Throttle", record, replay=False)
        # enqueue the REFRESH keys first (cpu-value change in grp b), the
        # flip trigger last — FIFO alone would commit the refreshes first
        store.update_pod(_bound("pb", {"grp": "b"}, cpu="200m"))
        store.create_pod(_bound("pa2", {"grp": "a"}))  # used 2 ≥ 2: flip
        plugin.run_pending_once()
        store.remove_event_handler("Throttle", record)

        assert "default/tflip" in order
        flip_at = order.index("default/tflip")
        ref_ats = [order.index(k) for k in order if k.startswith("default/tref")]
        assert ref_ats, "refresh writes missing"
        assert flip_at < min(ref_ats), order
        flipped = store.get_throttle("default", "tflip")
        assert flipped.status.throttled.resource_counts_pod is True


# ---------------------------------------------------------------------------
# end-to-end remote loop under the PR-1 fault plan (409 storm + watch cuts)
# ---------------------------------------------------------------------------


class TestRemoteFlipUnderFaults:
    def test_flip_publishes_through_conflict_storm_and_watch_cuts(self):
        server = MockApiServer(bookmark_interval=0.05)
        remote = server.store
        remote.create_namespace(Namespace("default"))
        remote.create_throttle(_throttle("tflip", {"grp": "a"}, pod=2))
        remote.create_throttle(_throttle("tref", {"grp": "a"}, pod=10**6))
        remote.create_pod(_bound("p0", {"grp": "a"}))
        plan = FaultPlan(3)
        plan.rule("mock.status.conflict", probability=0.5, times=20)
        plan.rule("mock.watch.cut", probability=0.2, times=3)
        server.faults = plan
        server.start()

        # per-key PUT arrival order at the apiserver: used counts for one
        # key must never regress (flip and refresh never race out of order)
        seq: dict = {}

        def record(event):
            if event.type == EventType.MODIFIED:
                counts = event.obj.status.used.resource_counts
                seq.setdefault(event.obj.key, []).append(counts)

        remote.add_event_handler("Throttle", record, replay=False)
        local = Store()
        session = RemoteSession(RestConfig(server=server.url), local, qps=None)
        plugin = None
        try:
            session.start(sync_timeout=15)
            plugin = KubeThrottler(
                decode_plugin_args(
                    {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
                ),
                local,
                use_device=True,
                start_workers=True,
                status_writer=session.status_committer,
            )
            assert _wait(
                lambda: (
                    remote.get_throttle("default", "tflip").status.used.resource_counts
                    == 1
                ),
                timeout=15,
            )
            remote.create_pod(_bound("p1", {"grp": "a"}))  # used 2 ≥ 2: flip
            assert _wait(
                lambda: remote.get_throttle(
                    "default", "tflip"
                ).status.throttled.resource_counts_pod,
                timeout=15,
            ), "flip never published through the fault storm"
        finally:
            if plugin is not None:
                plugin.stop()
            session.stop()
            server.stop()
            remote.remove_event_handler("Throttle", record)
        assert plan.fired("mock.status.conflict") > 0, "conflict verb never fired"
        for key, counts in seq.items():
            present = [c for c in counts if c is not None]
            assert present == sorted(present), (key, counts)


# ---------------------------------------------------------------------------
# ADVICE r5 regressions
# ---------------------------------------------------------------------------


class TestCheckPodsMultiRGrowth:
    def _grown_batch(self):
        store, plugin = _stack()
        store.create_throttle(_throttle("t1", {"grp": "a"}, requests={"cpu": "1"}))
        store.create_pod(_bound("p0", {"grp": "a"}, cpu="900m"))
        plugin.run_pending_once()
        # probe pods NOT in the store; the second introduces a never-seen
        # resource name mid-batch, growing ks.R after p-first was encoded
        first = make_pod("probe-a", labels={"grp": "a"}, requests={"cpu": "200m"})
        grower = make_pod(
            "probe-b",
            labels={"grp": "a"},
            requests={"cpu": "200m", "vendor.example/widget": "3"},
        )
        third = make_pod("probe-c", labels={"grp": "a"}, requests={"cpu": "200m"})
        return plugin, [first, grower, third]

    def test_host_route_matches_single_pod_checks(self):
        plugin, pods = self._grown_batch()
        dm = plugin.device_manager
        multi = dm.check_pods_multi(pods, "throttle")
        # fresh equivalent objects so the per-pod path re-encodes at the
        # grown R rather than hitting the batch's memo entries
        import copy

        singles = [dm.check_pod(copy.deepcopy(p), "throttle") for p in pods]
        assert multi == singles
        # every verdict present: 0.9 + 0.2 ≥ 1 cpu ⇒ insufficient for all
        for res in multi:
            assert res == {"default/t1": "insufficient"}

    def test_device_route_survives_mid_batch_growth(self, monkeypatch):
        # the fused-kernel route previously crashed on the row-width
        # mismatch (req[i] = rq[0] broadcast error); with the re-encode it
        # must return the same verdicts as the host route
        plugin, pods = self._grown_batch()
        dm = plugin.device_manager
        monkeypatch.setattr(dm, "_single_check_device", True)
        multi = dm.check_pods_multi(pods, "throttle")
        for res in multi:
            assert res == {"default/t1": "insufficient"}


class TestGcHygiene:
    def test_disabled_via_env(self, monkeypatch):
        from kube_throttler_tpu.utils import gchygiene

        monkeypatch.setenv("KT_GC_FREEZE", "0")
        assert not gchygiene.enabled()
        assert gchygiene.freeze_startup_heap() == -1

    def test_freeze_and_backstop_thread(self, monkeypatch):
        import gc

        from kube_throttler_tpu.utils.gchygiene import (
            GcHygieneThread,
            freeze_startup_heap,
        )

        # floor 1: any heap qualifies, so the freeze branch is exercised
        # deterministically regardless of the test process's heap size
        monkeypatch.setenv("KT_GC_FREEZE_MIN_OBJECTS", "1")
        thresholds = gc.get_threshold()
        try:
            frozen = freeze_startup_heap()
            assert frozen > 0
            assert gc.get_threshold()[2] == 1_000_000  # gen2 deferred
            t = GcHygieneThread(interval_s=0.05)
            t.start()
            assert _wait(lambda: t.ticks >= 1, timeout=5)
            t.stop()
            assert t.last_pause_s is not None and t.last_pause_s >= 0
        finally:
            # don't leak the posture into the rest of the test process
            gc.set_threshold(*thresholds)
            gc.unfreeze()

    def test_small_heap_skips_freeze(self, monkeypatch):
        # the columnar-arena retune: below the tracked-object floor the
        # posture is a no-op — default generational GC stays in charge
        import gc

        from kube_throttler_tpu.utils.gchygiene import freeze_startup_heap

        monkeypatch.setenv("KT_GC_FREEZE_MIN_OBJECTS", str(1 << 40))
        thresholds = gc.get_threshold()
        frozen_before = gc.get_freeze_count()
        assert freeze_startup_heap() == 0
        assert gc.get_threshold() == thresholds  # gen2 NOT deferred
        assert gc.get_freeze_count() == frozen_before

    def test_malformed_floor_env_falls_back(self, monkeypatch):
        from kube_throttler_tpu.utils.gchygiene import freeze_min_objects

        monkeypatch.setenv("KT_GC_FREEZE_MIN_OBJECTS", "half-a-million")
        assert freeze_min_objects() == 200_000


class TestGatherChunkEnvGuard:
    def test_malformed_env_falls_back_to_default(self):
        code = (
            "import kube_throttler_tpu.ops.check as m\n"
            "print(m._GATHER_CHUNK_ELEMS)\n"
        )
        env = dict(os.environ)
        env["KT_GATHER_CHUNK_ELEMS"] = "sixty-four-million"
        env["JAX_PLATFORMS"] = "cpu"
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, timeout=120, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        assert r.returncode == 0, r.stderr.decode()[-2000:]
        assert r.stdout.decode().strip() == str(64 * 1024 * 1024)
