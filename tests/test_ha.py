"""Active/standby HA subsystem (engine/replication.py + tools/hatest.py):
fenced leadership epochs, journal-tail streaming to warm standbys, and the
kill-the-leader chaos matrix.

Fast tier covers: FencingEpoch persistence + staleness, EPOCH journal
control lines (append/replay/compaction), stale-epoch gates (journal,
snapshot, mockserver status + lease writes, transport FencedError, the
async committer's demotion), the mock.lease fault verbs, the
HttpLeaseElector's monotonic-clock staleness (NTP-step regressions),
FileLeaseElector fd hygiene, in-process leader→standby streaming
convergence (incl. restart resync and divergence detection), the
plugin-less standby HTTP server, promotion flip re-publication, and ONE
seeded kill-the-leader subprocess cycle. The full ha.* site × seed matrix
runs behind ``-m slow`` (also: ``make ha-test``).
"""

from __future__ import annotations

import importlib.util
import json
import os
import threading
import time
from datetime import datetime, timedelta, timezone
from pathlib import Path

import pytest

from kube_throttler_tpu.api.pod import Namespace, make_pod
from kube_throttler_tpu.engine.journal import attach
from kube_throttler_tpu.engine.recovery import RecoveryManager
from kube_throttler_tpu.engine.replication import (
    FencingEpoch,
    HaCoordinator,
    ReplicationDiverged,
    ReplicationServer,
    ReplicationSource,
    StandbyReplicator,
)
from kube_throttler_tpu.engine.snapshot import SnapshotManager, load_snapshot
from kube_throttler_tpu.engine.store import Store
from kube_throttler_tpu.faults.plan import FaultPlan
from kube_throttler_tpu.utils.clock import FakeClock

ROOT = Path(__file__).parent.parent

_spec = importlib.util.spec_from_file_location(
    "hatest", ROOT / "tools" / "hatest.py"
)
hatest = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(hatest)
# the workload/oracle helpers moved to the shared tools/harness.py (PR 8);
# keep the historical local names the fixtures below use
from types import SimpleNamespace  # noqa: E402

crashtest = SimpleNamespace(
    _throttle=hatest.harness.make_throttle,
    _recompute_status=hatest.harness.recompute_status,
    _dump_store=hatest.harness.dump_store,
    _verdicts=hatest.harness.verdicts,
    _build_plugin=hatest.harness.build_plugin,
)


def _wait(predicate, timeout=5.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


# --------------------------------------------------------------------------
# FencingEpoch
# --------------------------------------------------------------------------


class TestFencingEpoch:
    def test_bump_persists_across_restarts(self, tmp_path):
        e = FencingEpoch(str(tmp_path))
        assert e.current() == 0 and not e.is_stale()
        assert e.bump() == 1
        assert e.bump() == 2
        # a new process over the same data dir resumes past the old term
        e2 = FencingEpoch(str(tmp_path))
        assert e2.current() == 2
        assert e2.bump() == 3

    def test_observe_higher_epoch_fences(self, tmp_path):
        e = FencingEpoch(str(tmp_path))
        e.bump()  # we lead term 1
        e.observe(1)  # our own term echoing back: no-op
        assert not e.is_stale()
        e.observe(3)  # someone took over twice: we are deposed
        assert e.is_stale() and e.current() == 3
        # bump clears staleness (a NEW term we own)
        assert e.bump() == 4 and not e.is_stale()

    def test_memory_only_epoch(self):
        e = FencingEpoch()
        assert e.bump() == 1  # no data dir: no persistence, no crash

    def test_standby_never_claimed_does_not_self_fence(self):
        """A streaming standby legitimately observes every new leader term
        (a leader restart bumps N→N+1 mid-stream). Only a process that
        CLAIMED a term via bump() is deposed by a higher observation —
        a self-fenced standby would reject every replicated event into
        its own journal and silently lose them at the next promotion."""
        e = FencingEpoch()
        e.observe(1)
        e.observe(2)  # leader restarted: new term — normal standby diet
        assert e.current() == 2 and not e.is_stale()
        # once it claims (promotion), a higher term DOES depose it
        assert e.bump() == 3
        e.observe(4)
        assert e.is_stale()


# --------------------------------------------------------------------------
# journal EPOCH lines + fencing gate
# --------------------------------------------------------------------------


class TestJournalEpoch:
    def _journal(self, tmp_path, **kw):
        store = Store()
        journal = attach(store, str(tmp_path / "j.journal"), **kw)
        return store, journal

    def test_epoch_line_roundtrip(self, tmp_path):
        store, journal = self._journal(tmp_path)
        journal.set_epoch(7)
        store.create_namespace(Namespace("default"))
        journal.close()
        store2 = Store()
        j2 = attach(store2, str(tmp_path / "j.journal"))
        assert j2.last_epoch == 7
        assert store2.get_namespace("default") is not None
        j2.close()

    def test_set_epoch_is_monotonic(self, tmp_path):
        _, journal = self._journal(tmp_path)
        journal.set_epoch(5)
        journal.set_epoch(3)  # stale term: ignored
        journal.set_epoch(5)  # duplicate: ignored
        assert journal.last_epoch == 5
        journal.close()
        # exactly ONE epoch line hit the file
        lines = (tmp_path / "j.journal").read_bytes().splitlines()
        assert sum(1 for ln in lines if b'"EPOCH"' in ln) == 1

    def test_compaction_preserves_epoch(self, tmp_path):
        store, journal = self._journal(tmp_path)
        journal.set_epoch(4)
        store.create_namespace(Namespace("default"))
        store.create_pod(make_pod("p1"))
        journal.compact()
        journal.close()
        store2 = Store()
        j2 = attach(store2, str(tmp_path / "j.journal"))
        assert j2.last_epoch == 4, "compaction dropped the fencing term"
        assert len(store2.list_pods()) == 1
        j2.close()

    def test_stale_epoch_append_rejected(self, tmp_path):
        store, journal = self._journal(tmp_path)
        epoch = FencingEpoch()
        epoch.bump()
        journal.fencing = epoch
        store.create_namespace(Namespace("default"))
        pos_before = journal.position()
        epoch.observe(2)  # deposed
        assert epoch.is_stale()
        store.create_pod(make_pod("zombie"))  # store mutates...
        assert journal.stale_epoch_rejected == 1  # ...but the log refuses
        assert journal.position() == pos_before
        state, detail = journal.health_state()
        assert state == "down" and detail["staleEpochRejected"] == 1
        journal.close()

    def test_stale_epoch_batch_rejected(self, tmp_path):
        store, journal = self._journal(tmp_path)
        epoch = FencingEpoch()
        epoch.bump()
        journal.fencing = epoch
        store.create_namespace(Namespace("default"))
        epoch.fence("test")
        store.apply_events(
            [("upsert", "Pod", make_pod(f"z{i}")) for i in range(3)]
        )
        assert journal.stale_epoch_rejected == 3
        journal.close()


# --------------------------------------------------------------------------
# snapshot epoch + fencing gate
# --------------------------------------------------------------------------


class TestSnapshotEpoch:
    def test_epoch_in_header_and_payload(self, tmp_path):
        store = Store()
        journal = attach(store, str(tmp_path / "store.journal"))
        epoch = FencingEpoch(str(tmp_path))
        epoch.bump()
        epoch.bump()
        snap = SnapshotManager(str(tmp_path), store)
        snap.fencing = epoch
        snap.bind_journal(journal, every_lines=0)
        store.create_namespace(Namespace("default"))
        path = snap.write(reason="test")
        payload = load_snapshot(path)
        assert payload["epoch"] == 2
        header = json.loads(open(path, "rb").readline())
        assert header["epoch"] == 2
        journal.close()

    def test_stale_epoch_snapshot_refused(self, tmp_path):
        store = Store()
        epoch = FencingEpoch()
        epoch.bump()
        snap = SnapshotManager(str(tmp_path), store)
        snap.fencing = epoch
        epoch.fence("test")
        assert snap.write(reason="zombie") is None
        assert snap.stale_epoch_rejected == 1
        assert snap.snapshot_failures == 0  # a refusal is not an I/O failure
        state, _ = snap.health_state()
        assert state == "down"

    def test_recovery_surfaces_max_epoch(self, tmp_path):
        store = Store()
        journal = attach(store, str(tmp_path / "store.journal"))
        epoch = FencingEpoch(str(tmp_path))
        epoch.bump()
        snap = SnapshotManager(str(tmp_path), store)
        snap.fencing = epoch
        snap.bind_journal(journal, every_lines=0)
        store.create_namespace(Namespace("default"))
        snap.write(reason="test")
        journal.set_epoch(5)  # journal outran the snapshot's term
        journal.close()
        store2 = Store()
        rec = RecoveryManager(str(tmp_path))
        j2 = rec.recover_store(store2)
        assert rec.report.epoch == 5
        j2.close()


# --------------------------------------------------------------------------
# mockserver fencing + lease fault verbs
# --------------------------------------------------------------------------


@pytest.fixture()
def apiserver():
    from kube_throttler_tpu.client.mockserver import MockApiServer

    server = MockApiServer()
    server.store.create_namespace(Namespace("default"))
    server.start()
    yield server
    server.stop()


class TestMockserverFencing:
    def _client(self, apiserver, epoch=None):
        from kube_throttler_tpu.client.transport import ApiClient, RestConfig

        return ApiClient(
            RestConfig(server=apiserver.url),
            qps=None,
            epoch_provider=(lambda: epoch) if epoch is not None else None,
        )

    def _status_put(self, apiserver, client, thr):
        from kube_throttler_tpu.api.serialization import object_to_dict

        key = f"{thr.namespace}/{thr.name}"
        rv = apiserver.store.resource_version("Throttle", key)
        body = object_to_dict(thr)
        body.setdefault("metadata", {})["resourceVersion"] = str(rv)
        return client.put(
            f"/apis/schedule.k8s.everpeace.github.com/v1alpha1/"
            f"namespaces/{thr.namespace}/throttles/{thr.name}/status",
            body,
        )

    def test_stale_status_write_rejected_and_state_untouched(self, apiserver):
        from kube_throttler_tpu.api.serialization import object_to_dict
        from kube_throttler_tpu.client.transport import FencedError

        thr = crashtest._throttle(0)
        apiserver.store.create_throttle(thr)
        live = apiserver.store.get_throttle("default", thr.name)
        self._status_put(
            apiserver, self._client(apiserver, epoch=2),
            crashtest._recompute_status(apiserver.store, live),
        )
        assert apiserver.fencing_epoch == 2
        before = object_to_dict(apiserver.store.get_throttle("default", thr.name))
        with pytest.raises(FencedError):
            self._status_put(
                apiserver, self._client(apiserver, epoch=1),
                crashtest._recompute_status(apiserver.store, live),
            )
        assert apiserver.stale_rejections() == 1
        assert (
            object_to_dict(apiserver.store.get_throttle("default", thr.name))
            == before
        )

    def test_equal_and_higher_epochs_accepted(self, apiserver):
        thr = crashtest._throttle(1)
        apiserver.store.create_throttle(thr)
        live = apiserver.store.get_throttle("default", thr.name)
        for epoch in (3, 3, 4):
            live = apiserver.store.get_throttle("default", thr.name)
            self._status_put(
                apiserver, self._client(apiserver, epoch=epoch),
                crashtest._recompute_status(apiserver.store, live),
            )
        assert apiserver.fencing_epoch == 4
        assert apiserver.stale_rejections() == 0

    def test_no_header_passes(self, apiserver):
        thr = crashtest._throttle(2)
        apiserver.store.create_throttle(thr)
        live = apiserver.store.get_throttle("default", thr.name)
        # raise the gate, then write without any epoch header: unaffected
        self._status_put(
            apiserver, self._client(apiserver, epoch=5),
            crashtest._recompute_status(apiserver.store, live),
        )
        live = apiserver.store.get_throttle("default", thr.name)
        self._status_put(
            apiserver, self._client(apiserver),
            crashtest._recompute_status(apiserver.store, live),
        )
        assert apiserver.stale_rejections() == 0

    def test_stale_lease_write_rejected(self, apiserver):
        from kube_throttler_tpu.client.transport import FencedError

        doc = {"metadata": {"name": "kt"}, "spec": {"holderIdentity": "a"}}
        self._client(apiserver, epoch=2).post(
            "/apis/coordination.k8s.io/v1/namespaces/kube-system/leases", doc
        )
        with pytest.raises(FencedError):
            self._client(apiserver, epoch=1).put(
                "/apis/coordination.k8s.io/v1/namespaces/kube-system/leases/kt",
                doc,
            )
        assert apiserver.stale_rejections() == 1


class TestMockLeaseFaults:
    def _elector(self, apiserver, identity, **kw):
        from kube_throttler_tpu.client.transport import ApiClient, RestConfig
        from kube_throttler_tpu.utils.leaderelect import HttpLeaseElector

        kw.setdefault("lease_duration", 1.5)
        kw.setdefault("renew_period", 0.1)
        kw.setdefault("retry_period", 0.05)
        return HttpLeaseElector(
            ApiClient(RestConfig(server=apiserver.url)),
            name="kt", identity=identity, **kw,
        )

    def test_lease_error_verb_blocks_acquisition(self, apiserver):
        apiserver.faults = FaultPlan(seed=0).rule(
            "mock.lease", mode="error", times=2
        )
        a = self._elector(apiserver, "a")
        assert not a.try_acquire()  # 500 on the GET: not leader, no crash
        assert not a.try_acquire()  # 500 on the create path too
        assert a.try_acquire()  # plan exhausted: wins normally
        a.release()
        assert apiserver.faults.fired("mock.lease") == 2

    def test_lease_conflict_verb_survived_by_renewer(self, apiserver):
        a = self._elector(apiserver, "a")
        assert a.acquire()
        apiserver.faults = FaultPlan(seed=0).rule(
            "mock.lease", mode="conflict", times=1
        )
        # one injected 409 on a renew: the renewer re-reads and re-renews
        # (its own identity still holds) instead of demoting
        assert _wait(lambda: apiserver.faults.fired("mock.lease") >= 1, 3.0)
        time.sleep(0.3)
        assert a.is_leader
        a.release()


# --------------------------------------------------------------------------
# HttpLeaseElector monotonic staleness (NTP-step regressions)
# --------------------------------------------------------------------------


class TestHttpElectorMonotonicClock:
    def _elector(self, apiserver, clock, **kw):
        from kube_throttler_tpu.client.transport import ApiClient, RestConfig
        from kube_throttler_tpu.utils.leaderelect import HttpLeaseElector

        kw.setdefault("lease_duration", 2.0)
        kw.setdefault("renew_period", 0.1)
        kw.setdefault("retry_period", 0.05)
        return HttpLeaseElector(
            ApiClient(RestConfig(server=apiserver.url)),
            name="kt", identity="standby", clock=clock, **kw,
        )

    def _plant_lease(self, apiserver, renew_time: str):
        from kube_throttler_tpu.client.transport import ApiClient, RestConfig

        ApiClient(RestConfig(server=apiserver.url)).post(
            "/apis/coordination.k8s.io/v1/namespaces/kube-system/leases",
            {
                "metadata": {"name": "kt"},
                "spec": {
                    "holderIdentity": "other",
                    "leaseDurationSeconds": 2,
                    "renewTime": renew_time,
                },
            },
        )

    def test_ancient_renew_time_does_not_cause_instant_takeover(self, apiserver):
        """The holder's renewTime is hours in the past by OUR wall clock
        (their clock may simply be skewed). Takeover must wait a full
        lease_duration of LOCAL monotonic observation, not trust the
        wall-clock delta."""
        self._plant_lease(apiserver, "1999-01-01T00:00:00Z")
        clock = FakeClock(datetime.now(timezone.utc))
        b = self._elector(apiserver, clock)
        assert not b.try_acquire()  # first sight: window starts NOW
        clock.advance_monotonic(1.0)
        assert not b.try_acquire()  # window not yet over
        clock.advance_monotonic(1.5)
        assert b.try_acquire()  # unchanged for > duration: holder is dead
        b.release()

    def test_wall_clock_jump_does_not_expire_lease(self, apiserver):
        """An NTP step (wall jumps forward by hours, monotonic untouched)
        must not fabricate staleness — the old datetime-delta math took
        over here."""
        self._plant_lease(apiserver, datetime.now(timezone.utc).isoformat())
        clock = FakeClock(datetime.now(timezone.utc))
        b = self._elector(apiserver, clock)
        assert not b.try_acquire()
        clock.set(datetime.now(timezone.utc) + timedelta(hours=6))  # NTP step
        assert not b.try_acquire(), "wall-clock jump caused premature takeover"
        clock.advance_monotonic(2.5)  # real elapsed time without renewal
        assert b.try_acquire()
        b.release()

    def test_renewal_change_restarts_window(self, apiserver):
        self._plant_lease(apiserver, "2000-01-01T00:00:00Z")
        clock = FakeClock(datetime.now(timezone.utc))
        b = self._elector(apiserver, clock)
        assert not b.try_acquire()
        clock.advance_monotonic(1.5)
        # the holder renews (any CHANGE to the heartbeat string)
        self._heartbeat(apiserver)
        assert not b.try_acquire()  # window restarted at the new pair
        clock.advance_monotonic(1.5)
        assert not b.try_acquire()  # only 1.5s since the change
        clock.advance_monotonic(1.0)
        assert b.try_acquire()
        b.release()

    def _heartbeat(self, apiserver):
        with apiserver._lock:
            doc, rv = apiserver._leases[("kube-system", "kt")]
            doc = dict(doc)
            doc["spec"] = {**doc["spec"], "renewTime": "2000-01-01T00:00:01Z"}
            apiserver._lease_rv += 1
            apiserver._leases[("kube-system", "kt")] = (doc, apiserver._lease_rv)

    def test_renew_deadline_on_monotonic_clock(self, apiserver):
        """A leader that cannot reach the apiserver demotes only when the
        MONOTONIC renew deadline passes — a frozen monotonic clock means
        no demotion regardless of real time, and advancing it past the
        deadline demotes promptly."""
        from kube_throttler_tpu.client.transport import ApiClient, RestConfig

        clock = FakeClock(datetime.now(timezone.utc))
        lost = threading.Event()
        a = self._elector(apiserver, clock, renew_period=0.05, retry_period=0.02)
        a.on_lost = lost.set
        assert a.acquire()
        # sever connectivity: renews fail from here on
        a.client = ApiClient(RestConfig(server="http://127.0.0.1:1"), timeout=0.1)
        time.sleep(0.5)  # many real seconds of failed renews...
        assert not lost.is_set() and a.is_leader  # ...frozen monotonic: no demote
        clock.advance_monotonic(a.renew_deadline + 1.0)
        assert lost.wait(3.0)
        assert not a.is_leader
        a.release()


# --------------------------------------------------------------------------
# FileLeaseElector fd hygiene
# --------------------------------------------------------------------------


class TestFileElectorFdHygiene:
    def test_double_release_is_idempotent(self, tmp_path):
        from kube_throttler_tpu.utils.leaderelect import FileLeaseElector

        a = FileLeaseElector(str(tmp_path / "l.lock"))
        assert a.try_acquire()
        a.release()
        a.release()  # second release: no-op, no EBADF double-close
        assert not a.is_leader
        # the lease is actually free again
        b = FileLeaseElector(str(tmp_path / "l.lock"))
        assert b.try_acquire()
        b.release()

    def test_release_without_acquire(self, tmp_path):
        from kube_throttler_tpu.utils.leaderelect import FileLeaseElector

        FileLeaseElector(str(tmp_path / "l.lock")).release()  # no-op

    def test_exception_during_flock_closes_fd(self, tmp_path, monkeypatch):
        """A non-OSError escaping between open and flock must not leak the
        descriptor (a leaked fd holds the flock for the process lifetime,
        wedging every later acquire on this host)."""
        import fcntl as _fcntl

        from kube_throttler_tpu.utils.leaderelect import FileLeaseElector

        def count_fds():
            return len(os.listdir("/proc/self/fd"))

        class Boom(BaseException):  # the KeyboardInterrupt class itself
            pass  # aborts the pytest session, so stand in for it

        def boom(*a, **k):
            raise Boom

        real_flock = _fcntl.flock  # capture BEFORE the patch mutates the module
        a = FileLeaseElector(str(tmp_path / "l.lock"))
        before = count_fds()
        monkeypatch.setattr(
            "kube_throttler_tpu.utils.leaderelect.fcntl.flock", boom
        )
        with pytest.raises(Boom):
            a.try_acquire()
        monkeypatch.setattr(
            "kube_throttler_tpu.utils.leaderelect.fcntl.flock", real_flock
        )
        assert count_fds() == before, "fd leaked on acquire exception"
        assert not a.is_leader
        assert a.try_acquire()  # the path is not wedged
        a.release()


# --------------------------------------------------------------------------
# in-process replication: leader → standby streaming
# --------------------------------------------------------------------------


class _Pair:
    """Leader (store+journal+snapshot+source+HTTP) and standby
    (store+journal+replicator) over two tmp dirs."""

    def __init__(self, tmp_path, snapshot_first=True):
        self.leader_dir = str(tmp_path / "A")
        self.standby_dir = str(tmp_path / "B")
        os.makedirs(self.leader_dir)
        os.makedirs(self.standby_dir)
        self.ls = Store()
        lrec = RecoveryManager(self.leader_dir)
        self.lj = lrec.recover_store(self.ls)
        self.lepoch = FencingEpoch(self.leader_dir)
        self.lj.fencing = self.lepoch
        self.snap = SnapshotManager(self.leader_dir, self.ls)
        self.snap.fencing = self.lepoch
        self.snap.bind_journal(self.lj, every_lines=0)
        self.ha = HaCoordinator(
            self.lepoch, role="leader", journal=self.lj, snapshotter=self.snap
        )
        self.ha.become_leader()
        self.ls.create_namespace(Namespace("default"))
        if snapshot_first:
            self.snap.write(reason="bootstrap")
        self.source = ReplicationSource(self.leader_dir, self.lj, self.lepoch)
        self.server = ReplicationServer(self.source)
        self.server.start()
        self.url = f"http://127.0.0.1:{self.server.port}"
        self.ss = Store()
        srec = RecoveryManager(self.standby_dir)
        self.sj = srec.recover_store(self.ss)
        self.sepoch = FencingEpoch(self.standby_dir)
        self.sj.fencing = self.sepoch
        self.rep = StandbyReplicator(
            self.ss, self.sj, self.url, epoch=self.sepoch, poll_interval=0.02
        )

    def converge(self, timeout=5.0):
        def caught_up():
            try:
                self.rep.poll_once()
            except OSError:
                return False
            return self.rep.consumed_offset() >= self.lj.position()[0]

        assert _wait(caught_up, timeout), "standby never caught up"

    def close(self):
        self.rep.stop()
        self.server.stop()
        self.sj.close()
        self.lj.close()


class TestReplicationStreaming:
    def test_bootstrap_and_tail_convergence(self, tmp_path):
        pair = _Pair(tmp_path)
        try:
            for i in range(8):
                pair.ls.create_pod(make_pod(f"p{i}", labels={"grp": "g0"}))
            assert pair.rep.bootstrap(5.0)
            # snapshot bootstrap: some objects arrived without streaming
            assert pair.rep.bootstrapped
            for i in range(8, 20):
                pair.ls.create_pod(make_pod(f"p{i}", labels={"grp": "g0"}))
            pair.ls.delete_pod("default", "p3")
            thr = crashtest._throttle(0)
            pair.ls.create_throttle(thr)
            live = pair.ls.get_throttle("default", thr.name)
            pair.ls.update_throttle_status(
                crashtest._recompute_status(pair.ls, live)
            )
            pair.converge()
            assert crashtest._dump_store(pair.ss) == crashtest._dump_store(pair.ls)
            assert pair.sepoch.current() == pair.lepoch.current()
            # the standby's own journal reproduces its store from genesis
            pure = Store()
            pj = attach(pure, os.path.join(pair.standby_dir, "store.journal"))
            assert crashtest._dump_store(pure) == crashtest._dump_store(pair.ss)
            assert pj.last_epoch == pair.lepoch.current()
            pj.close()
        finally:
            pair.close()

    def test_no_snapshot_streams_from_genesis(self, tmp_path):
        pair = _Pair(tmp_path, snapshot_first=False)
        try:
            pair.ls.create_pod(make_pod("p0"))
            assert pair.rep.bootstrap(5.0)
            pair.converge()
            assert {p.key for p in pair.ss.list_pods()} == {"default/p0"}
        finally:
            pair.close()

    def test_restart_resync_drops_stale_extras(self, tmp_path):
        pair = _Pair(tmp_path)
        try:
            pair.ls.create_pod(make_pod("keep"))
            pair.ls.create_pod(make_pod("doomed"))
            assert pair.rep.bootstrap(5.0)
            pair.converge()
            # standby goes down; the leader deletes + creates while it's out
            pair.rep.stop()
            pair.ls.delete_pod("default", "doomed")
            pair.ls.create_pod(make_pod("newborn"))
            pair.snap.write(reason="turnover")
            # a NEW replicator over the same (recovered) standby state
            rep2 = StandbyReplicator(
                pair.ss, pair.sj, pair.url, epoch=pair.sepoch, poll_interval=0.02
            )
            assert rep2.bootstrap(5.0)
            keys = {p.key for p in pair.ss.list_pods()}
            assert keys == {"default/keep", "default/newborn"}, (
                "restart resync must drop objects the leader deleted"
            )
        finally:
            pair.close()

    def test_compaction_under_stream_detected_as_divergence(self, tmp_path):
        pair = _Pair(tmp_path)
        try:
            for i in range(5):
                pair.ls.create_pod(make_pod(f"p{i}"))
            assert pair.rep.bootstrap(5.0)
            pair.converge()
            # deletes make the compacted log DIFFER from the append log
            # (a pure-ADDED history compacts to byte-identical content)
            pair.ls.delete_pod("default", "p1")
            pair.ls.delete_pod("default", "p3")
            pair.converge()
            pair.lj.compact()  # rewrites the journal under the stream
            with pytest.raises((ReplicationDiverged, OSError)):
                for _ in range(3):
                    pair.rep.poll_once()
            assert pair.rep.diverged
            state, detail = pair.rep.health_state()
            assert state == "down"
        finally:
            pair.close()

    def test_leader_term_bump_does_not_fence_streaming_standby(self, tmp_path):
        """A restarting leader bumps its term while the standby streams at
        the old one. The standby must track the higher epoch WITHOUT
        fencing itself: its journal keeps accepting the re-journaled
        replicated events, so nothing is lost at a later promotion."""
        pair = _Pair(tmp_path)
        try:
            pair.ls.create_pod(make_pod("p0"))
            assert pair.rep.bootstrap(5.0)
            pair.converge()
            pair.ha.become_leader()  # leader restart: term 1 → 2
            pair.ls.create_pod(make_pod("p1"))
            pair.converge()
            assert pair.sepoch.current() == pair.lepoch.current() == 2
            assert not pair.sepoch.is_stale(), (
                "standby fenced itself on a normal leader term bump"
            )
            assert pair.sj.stale_epoch_rejected == 0
            assert {p.key for p in pair.ss.list_pods()} == {
                "default/p0", "default/p1",
            }
            # the replicated events actually landed in the standby's OWN
            # journal (a fenced journal drops them while the store mutates)
            pure = Store()
            pj = attach(pure, os.path.join(pair.standby_dir, "store.journal"))
            assert {p.key for p in pure.list_pods()} == {
                "default/p0", "default/p1",
            }
            pj.close()
        finally:
            pair.close()

    def test_compaction_rebootstraps_running_standby(self, tmp_path):
        """A leader compaction rewrites the journal under the stream; the
        BACKGROUND replicator must re-bootstrap from the freshly cut
        post-compaction snapshot and converge again — not freeze at its
        last verified offset until someone restarts the process."""
        pair = _Pair(tmp_path)
        try:
            for i in range(5):
                pair.ls.create_pod(make_pod(f"p{i}"))
            assert pair.rep.bootstrap(5.0)
            pair.rep.start()

            def caught_up():
                return pair.rep.consumed_offset() >= pair.lj.position()[0]

            assert _wait(caught_up, 5.0)
            # deletes make the compacted log differ from the append log
            pair.ls.delete_pod("default", "p1")
            pair.ls.delete_pod("default", "p3")
            assert _wait(caught_up, 5.0)
            pair.lj.compact()  # rewrite + fresh post-compaction snapshot
            pair.ls.create_pod(make_pod("post-compact"))

            def converged_again():
                return (
                    pair.rep.rebootstraps >= 1
                    and not pair.rep.diverged
                    and {p.key for p in pair.ss.list_pods()}
                    == {p.key for p in pair.ls.list_pods()}
                )

            assert _wait(converged_again, 10.0), (
                "standby never re-bootstrapped after leader compaction"
            )
            state, detail = pair.rep.health_state()
            assert state == "ok" and detail["rebootstraps"] >= 1
        finally:
            pair.close()

    def test_torn_chunk_read_surfaces_as_oserror(self):
        """A leader dying mid-send leaves a short body under a declared
        Content-Length; http.client raises IncompleteRead — an
        HTTPException, NOT an OSError — from read(). The replicator must
        normalize it so every retry path (bootstrap, _run, catch_up)
        treats it like any other transport failure instead of the
        replicator thread dying silently."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        class Torn(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_GET(self):
                self.send_response(200)
                self.send_header("Content-Length", "1000")
                self.end_headers()
                self.wfile.write(b'{"half": true}')  # then the socket closes

        httpd = ThreadingHTTPServer(("127.0.0.1", 0), Torn)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            rep = StandbyReplicator(
                Store(), None,
                f"http://127.0.0.1:{httpd.server_address[1]}",
                request_timeout=2.0,
            )
            with pytest.raises(OSError):
                rep.poll_once()
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_bootstrap_retries_non_200_and_reports_false(self):
        """A transient 500 on the snapshot fetch must not raise out of
        bootstrap (the daemon's clean 'standby bootstrap failed' path
        only handles the False return); it retries until the deadline."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        class Err(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_GET(self):
                body = b'{"message": "boom"}'
                self.send_response(500)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        httpd = ThreadingHTTPServer(("127.0.0.1", 0), Err)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            rep = StandbyReplicator(
                Store(), None,
                f"http://127.0.0.1:{httpd.server_address[1]}",
            )
            assert rep.bootstrap(deadline_s=0.5) is False
            assert not rep.bootstrapped
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_promotion_bumps_epoch_and_stamps_journal(self, tmp_path):
        pair = _Pair(tmp_path)
        try:
            pair.ls.create_pod(make_pod("p0"))
            assert pair.rep.bootstrap(5.0)
            pair.converge()
            coord = HaCoordinator(
                pair.sepoch, role="standby", replicator=pair.rep, journal=pair.sj
            )
            new_epoch = coord.promote()
            assert new_epoch == pair.lepoch.current() + 1
            assert coord.role == "leader"
            assert pair.sj.last_epoch == new_epoch
            assert coord.failover_duration_s is not None
            # the deposed leader learns the new term and fences
            pair.lepoch.observe(new_epoch)
            assert pair.lepoch.is_stale()
            pair.ls.create_pod(make_pod("zombie"))
            assert pair.lj.stale_epoch_rejected == 1
            assert pair.snap.write(reason="zombie") is None
        finally:
            pair.close()


# --------------------------------------------------------------------------
# standby HTTP server + promotion reconcile + metrics
# --------------------------------------------------------------------------


class TestStandbyServer:
    def test_standby_surface_then_promotion_flip(self, tmp_path):
        import urllib.error
        import urllib.request

        from kube_throttler_tpu.server import ThrottlerHTTPServer

        store = Store()
        rec = RecoveryManager(str(tmp_path))
        journal = rec.recover_store(store)
        epoch = FencingEpoch(str(tmp_path))
        ha = HaCoordinator(
            epoch, role="standby", journal=journal,
            replicator=StandbyReplicator(store, journal, "http://127.0.0.1:1"),
        )
        ha.source = ReplicationSource(str(tmp_path), journal, epoch)
        srv = ThrottlerHTTPServer(None, port=0, ha=ha)
        srv.start()
        url = f"http://127.0.0.1:{srv.port}"
        try:
            assert urllib.request.urlopen(f"{url}/healthz").status == 200
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(f"{url}/readyz")
            body = json.loads(e.value.read())
            assert e.value.code == 503 and body["state"] == "standby"
            assert body["components"]["ha"]["role"] == "standby"
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(f"{url}/v1/throttles")
            assert e.value.code == 503
            st = json.loads(
                urllib.request.urlopen(f"{url}/v1/replication/status").read()
            )
            assert st["journalOffset"] == journal.position()[0]

            # promotion: build the real plugin with a STALE status (the
            # flip the dead leader never committed) and let the two-lane
            # pipeline re-publish it
            store.create_namespace(Namespace("default"))
            thr = crashtest._throttle(0)  # pod threshold 3
            store.create_throttle(thr)
            for i in range(4):  # over threshold: truth is THROTTLED
                store.create_pod(
                    make_pod(
                        f"p{i}", labels={"grp": "g0"},
                        requests={"cpu": "100m"}, node_name="node-1",
                        phase="Running",
                    )
                )
            from kube_throttler_tpu.plugin import (
                KubeThrottler,
                decode_plugin_args,
            )

            plugin = KubeThrottler(
                decode_plugin_args(
                    {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
                ),
                store,
                use_device=True,
                start_workers=True,
            )
            try:
                ha.promote()
                n = ha.promote_reconcile(plugin)
                assert n >= 1
                srv.set_plugin(plugin)

                def flipped():
                    t = store.get_throttle("default", thr.name)
                    return t.status.throttled.resource_counts_pod

                assert _wait(flipped, 10.0), (
                    "promotion reconcile never re-published the flip"
                )
                ready = json.loads(urllib.request.urlopen(f"{url}/readyz").read())
                assert ready["role"] == "leader" and ready["epoch"] == 1
                listing = json.loads(
                    urllib.request.urlopen(f"{url}/v1/throttles").read()
                )
                assert len(listing) == 1
            finally:
                plugin.stop()
        finally:
            srv.stop()
            journal.close()

    def test_standby_metrics_scrapeable_before_promotion(self, tmp_path):
        """/metrics must answer on a plugin-less standby — replication lag
        is exactly the family that only matters pre-promotion."""
        import urllib.request

        from kube_throttler_tpu.metrics import Registry, register_ha_metrics
        from kube_throttler_tpu.server import ThrottlerHTTPServer

        store = Store()
        rec = RecoveryManager(str(tmp_path))
        journal = rec.recover_store(store)
        epoch = FencingEpoch(str(tmp_path))
        rep = StandbyReplicator(store, journal, "http://127.0.0.1:1")
        ha = HaCoordinator(epoch, role="standby", replicator=rep, journal=journal)
        registry = Registry()
        register_ha_metrics(registry, ha)
        srv = ThrottlerHTTPServer(None, port=0, ha=ha, metrics_registry=registry)
        srv.start()
        try:
            text = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics"
            ).read().decode()
            assert "kube_throttler_replication_lag_bytes" in text
            assert "kube_throttler_leader_state 0" in text
        finally:
            srv.stop()
            journal.close()

    def test_ha_metrics_families(self, tmp_path):
        from kube_throttler_tpu.metrics import Registry, register_ha_metrics

        store = Store()
        rec = RecoveryManager(str(tmp_path))
        journal = rec.recover_store(store)
        epoch = FencingEpoch(str(tmp_path))
        rep = StandbyReplicator(store, journal, "http://127.0.0.1:1")
        ha = HaCoordinator(epoch, role="standby", replicator=rep, journal=journal)
        registry = Registry()
        register_ha_metrics(registry, ha)
        text = registry.exposition()
        assert "kube_throttler_leader_state 0" in text
        assert "kube_throttler_failover_duration_seconds -1" in text
        assert "kube_throttler_replication_lag_bytes" in text
        assert "kube_throttler_stale_epoch_rejections_total 0" in text
        ha.promote()
        text = registry.exposition()
        assert "kube_throttler_leader_state 1" in text
        journal.close()


# --------------------------------------------------------------------------
# the chaos harness: one smoke cycle in tier-1, the matrix behind -m slow
# --------------------------------------------------------------------------


class TestKillTheLeaderSmoke:
    def test_one_failover_cycle(self, tmp_path):
        report = hatest.run_ha_cycle(
            "ha.status.commit", seed=0, workdir=str(tmp_path), events=90
        )
        assert report["killed"]
        assert report["epoch"] >= 2
        assert report["window_s"] <= hatest.DEFAULT_WINDOW_S

    def test_splitbrain_fencing(self):
        report = hatest.run_splitbrain(seed=0)
        assert report["stale_rejected"] >= 2
        assert report["fencing_epoch"] == 2


@pytest.mark.slow
class TestCliHaPair:
    def test_two_daemons_replicate_and_fail_over(self, tmp_path):
        """The README quickstart, end to end: a leader daemon with
        ``--ha-role leader`` and a standby with ``--ha-role standby
        --replicate-from`` over a shared flock lease. An object created on
        the leader is visible on the standby after a SIGKILL failover,
        /readyz flips standby→leader with a bumped epoch."""
        import json as _json
        import re
        import subprocess
        import sys as _sys
        import urllib.error
        import urllib.request

        from tests.conftest import ProcReader

        lock = str(tmp_path / "lease.lock")

        def launch(role, datadir, port, extra):
            os.makedirs(datadir, exist_ok=True)
            env = dict(os.environ)
            env["PYTHONPATH"] = str(ROOT) + os.pathsep + env.get("PYTHONPATH", "")
            env["JAX_PLATFORMS"] = "cpu"
            return subprocess.Popen(
                [
                    _sys.executable, "-m", "kube_throttler_tpu.cli", "serve",
                    "--name", "kt", "--target-scheduler-name", "my-scheduler",
                    "--no-device", "--data-dir", datadir, "--port", str(port),
                    "--lock-file", lock, "--ha-role", role,
                ] + extra,
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, cwd=str(tmp_path),
            )

        a = b = None
        try:
            a = launch("leader", str(tmp_path / "A"), 0, [])
            ra = ProcReader(a)
            lines = ra.wait_for(r"serving on")
            port_a = int(
                re.search(r"serving on [\d.]+:(\d+)", "".join(lines)).group(1)
            )
            body = _json.dumps(
                {
                    "kind": "Throttle",
                    "metadata": {"name": "t1", "namespace": "default"},
                    "spec": {
                        "throttlerName": "kt",
                        "threshold": {"resourceCounts": {"pod": 2}},
                        "selector": {
                            "selectorTerms": [
                                {"podSelector": {"matchLabels": {"g": "x"}}}
                            ]
                        },
                    },
                }
            ).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port_a}/v1/objects",
                data=body, headers={"Content-Type": "application/json"},
            )
            urllib.request.urlopen(req).read()

            b = launch(
                "standby", str(tmp_path / "B"), 0,
                ["--replicate-from", f"http://127.0.0.1:{port_a}"],
            )
            rb = ProcReader(b)
            lines = rb.wait_for(r"standing by")
            port_b = int(
                re.search(
                    r"standby on [\d.]+:(\d+)", "".join(rb.seen)
                ).group(1)
            )
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(f"http://127.0.0.1:{port_b}/readyz")
            assert e.value.code == 503
            assert _json.loads(e.value.read())["state"] == "standby"

            a.kill()
            a.wait(timeout=10)
            rb.wait_for(r"serving on", timeout_s=60)
            ready = _json.loads(
                urllib.request.urlopen(f"http://127.0.0.1:{port_b}/readyz").read()
            )
            assert ready["role"] == "leader" and ready["epoch"] >= 2
            thr = _json.loads(
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port_b}/v1/throttles"
                ).read()
            )
            assert [t["metadata"]["name"] for t in thr] == ["t1"]
        finally:
            for p in (a, b):
                if p is not None:
                    p.kill()
                    p.wait(timeout=10)


@pytest.mark.slow
class TestKillTheLeaderMatrix:
    @pytest.mark.parametrize("site", hatest.HA_SITES)
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_site_seed(self, site, seed, tmp_path):
        report = hatest.run_ha_cycle(site, seed, str(tmp_path))
        assert report["window_s"] <= hatest.DEFAULT_WINDOW_S

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_splitbrain(self, seed):
        hatest.run_splitbrain(seed)
