"""Residual-form fast check must be bit-identical to the direct kernel."""

import random

import numpy as np
import pytest

from kube_throttler_tpu.ops import DimRegistry, check_pods, encode_pods, encode_throttle_state
from kube_throttler_tpu.ops.fastcheck import (
    fast_check_pods,
    fast_check_pods_compact,
    precompute_check_state,
)
from kube_throttler_tpu.ops.check import check_pods_compact

from tests.test_check_kernel import _build_objects


@pytest.mark.parametrize("kind", ["throttle", "clusterthrottle"])
@pytest.mark.parametrize("on_equal", [False, True])
@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_fast_matches_direct(kind, on_equal, seed):
    rng = random.Random(seed)
    throttles, reserved, pods = _build_objects(rng, n_throttles=40, n_pods=30, kind=kind)
    dims = DimRegistry()
    state = encode_throttle_state(throttles, dims, reserved=reserved)
    batch = encode_pods(pods, dims)
    mask = np.asarray(rng.choices([True, False], k=len(pods) * len(throttles))).reshape(
        len(pods), len(throttles)
    )
    step3 = True if kind == "throttle" else on_equal

    direct = np.asarray(check_pods(state, batch, mask, on_equal=on_equal, step3_on_equal=step3))
    pre = precompute_check_state(state)
    fast = np.asarray(fast_check_pods(pre, batch, mask, on_equal=on_equal, step3_on_equal=step3))
    np.testing.assert_array_equal(fast, direct)

    dc, ds = check_pods_compact(state, batch, mask, on_equal=on_equal, step3_on_equal=step3)
    fc, fs = fast_check_pods_compact(pre, batch, mask, on_equal=on_equal, step3_on_equal=step3)
    np.testing.assert_array_equal(np.asarray(fc), np.asarray(dc))
    np.testing.assert_array_equal(np.asarray(fs), np.asarray(ds))
