"""Hot-path concurrency: check_pod readers must not serialize on the
device-state lock (VERDICT r2 item 5 — the reference keeps PreFilter
concurrent via RWMutex + hashed keymutexes,
reserved_resource_amounts.go:154-170; here the lock covers only the
host-side snapshot grab and the kernel runs on immutable device handles).

Correctness under churn: concurrent checkers race a writer that keeps
mutating pods/throttles; every verdict must be internally valid and the
final quiesced state must match the host oracle.
"""

from __future__ import annotations

import threading
import time
from dataclasses import replace

from kube_throttler_tpu.api.pod import Namespace, make_pod
from kube_throttler_tpu.api.types import (
    LabelSelector,
    ResourceAmount,
    Throttle,
    ThrottleSelector,
    ThrottleSelectorTerm,
    ThrottleSpec,
)
from kube_throttler_tpu.engine.store import Store
from kube_throttler_tpu.ops.check import STATUS_NAMES
from kube_throttler_tpu.plugin import KubeThrottler, decode_plugin_args


def _throttle(name, labels, **threshold):
    return Throttle(
        name=name,
        spec=ThrottleSpec(
            throttler_name="kube-throttler",
            threshold=ResourceAmount.of(**threshold),
            selector=ThrottleSelector(
                selector_terms=(
                    ThrottleSelectorTerm(LabelSelector(match_labels=labels)),
                )
            ),
        ),
    )


def _bound(pod):
    bound = replace(pod, spec=replace(pod.spec, node_name="node-1"))
    bound.status.phase = "Running"
    return bound


def _stack():
    store = Store()
    plugin = KubeThrottler(
        decode_plugin_args(
            {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
        ),
        store,
        use_device=True,
    )
    store.create_namespace(Namespace("default"))
    return store, plugin


def _assert_device_matches_oracle(store, plugin, probe):
    """Quiescence check shared by the race tests: the device path's blocked
    verdicts for ``probe`` must equal the pure oracle's over the live
    throttle set."""
    device = plugin.device_manager.check_pod(probe, "throttle", False)
    ctr = plugin.throttle_ctr
    oracle = {}
    for thr in store.list_throttles():
        if not thr.spec.selector.matches_to_pod(probe):
            continue
        reserved, _ = ctr.cache.reserved_resource_amount(thr.key)
        status = thr.check_throttled_for(probe, reserved, False)
        if status != "not-throttled":
            oracle[thr.key] = status
    device_blocked = {k: v for k, v in device.items() if v != "not-throttled"}
    assert device_blocked == oracle


class TestConcurrentCheck:
    def test_readers_race_writer_without_torn_state(self):
        store, plugin = _stack()
        dm = plugin.device_manager
        for i in range(16):
            store.create_throttle(
                _throttle(f"t{i}", {"grp": f"g{i % 4}"}, pod=3, requests={"cpu": "1"})
            )
        for i in range(32):
            store.create_pod(
                _bound(
                    make_pod(f"p{i}", labels={"grp": f"g{i % 4}"}, requests={"cpu": "100m"})
                )
            )
        plugin.run_pending_once()

        stop = threading.Event()
        errors: list = []
        checks = [0]
        valid_names = set(STATUS_NAMES.values())

        def reader(tid: int) -> None:
            probe = make_pod(f"probe{tid}", labels={"grp": f"g{tid % 4}"}, requests={"cpu": "200m"})
            n = 0
            while not stop.is_set():
                try:
                    result = dm.check_pod(probe, "throttle", False)
                    assert all(v in valid_names for v in result.values()), result
                    # the probe matches exactly the 4 throttles of its group
                    assert all(k.startswith("default/t") for k in result), result
                    n += 1
                except Exception as e:  # noqa: BLE001 — collected for the assert
                    errors.append(e)
                    return
            checks[0] += n

        def writer() -> None:
            i = 0
            while not stop.is_set():
                i += 1
                pod = _bound(
                    make_pod(
                        f"p{i % 32}",
                        labels={"grp": f"g{i % 4}"},
                        requests={"cpu": f"{100 + (i % 5) * 50}m"},
                    )
                )
                try:
                    store.update_pod(pod)
                    if i % 7 == 0:
                        store.update_throttle(
                            _throttle(
                                f"t{i % 16}",
                                {"grp": f"g{i % 4}"},
                                pod=3 + i % 3,
                                requests={"cpu": "1"},
                            )
                        )
                except Exception as e:  # noqa: BLE001
                    errors.append(e)
                    return

        threads = [threading.Thread(target=reader, args=(t,)) for t in range(4)]
        wt = threading.Thread(target=writer)
        for t in threads:
            t.start()
        wt.start()
        time.sleep(2.0)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        wt.join(timeout=10)
        assert not errors, errors[:3]
        assert checks[0] > 0

        # quiesce and diff the device path against the host oracle
        plugin.run_pending_once()
        probe = make_pod("probe-final", labels={"grp": "g1"}, requests={"cpu": "200m"})
        _assert_device_matches_oracle(store, plugin, probe)

    def test_readers_race_capacity_growth(self):
        """Readers race a writer that CREATES throttles continuously, so
        the tcap ladder grows and the staging planes REALLOCATE mid-
        flight. This specifically exercises the native classifier's plane
        re-registration (devicestate._native_classify_cols identity check
        swaps the C-side handle under the main lock) against concurrent
        check_pod callers — a stale handle would read freed memory, a
        missed re-registration would classify against dead arrays.
        Correctness is pinned by the oracle diff at quiescence."""
        store, plugin = _stack()
        dm = plugin.device_manager
        for i in range(4):
            store.create_throttle(
                _throttle(f"t{i}", {"grp": f"g{i % 4}"}, pod=3, requests={"cpu": "1"})
            )
        for i in range(16):
            store.create_pod(
                _bound(
                    make_pod(f"p{i}", labels={"grp": f"g{i % 4}"}, requests={"cpu": "100m"})
                )
            )
        plugin.run_pending_once()

        stop = threading.Event()
        errors: list = []
        valid_names = set(STATUS_NAMES.values())
        checks = [0]

        def reader(tid: int) -> None:
            probe = make_pod(
                f"probe{tid}", labels={"grp": f"g{tid % 4}"}, requests={"cpu": "200m"}
            )
            n = 0
            while not stop.is_set():
                try:
                    result = dm.check_pod(probe, "throttle", False)
                    assert all(v in valid_names for v in result.values()), result
                    n += 1
                except Exception as e:  # noqa: BLE001 — collected for the assert
                    errors.append(e)
                    return
            checks[0] += n

        created = [4]

        def grower() -> None:
            i = 4
            while not stop.is_set():
                try:
                    store.create_throttle(
                        _throttle(
                            f"t{i}", {"grp": f"g{i % 4}"}, pod=2 + i % 4,
                            requests={"cpu": f"{1 + i % 3}"},
                        )
                    )
                    plugin.run_pending_once()
                except Exception as e:  # noqa: BLE001
                    errors.append(e)
                    return
                i += 1
                created[0] = i

        tcap0 = dm.throttle.tcap
        threads = [threading.Thread(target=reader, args=(t,)) for t in range(3)]
        gt = threading.Thread(target=grower)
        for t in threads:
            t.start()
        gt.start()
        # run until the tcap ladder actually CROSSED a rung (the event
        # under test — staging reallocation + native plane re-registration)
        # rather than a wall-clock guess; generous deadline for loaded CI
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if dm.throttle.tcap > tcap0 and created[0] > tcap0:
                break
            time.sleep(0.05)
        time.sleep(0.5)  # let readers race the post-growth state a little
        stop.set()
        for t in threads:
            t.join(timeout=10)
        gt.join(timeout=10)
        assert not gt.is_alive(), "grower thread hung"
        assert not any(t.is_alive() for t in threads), "reader thread hung"
        assert not errors, errors[:3]
        assert checks[0] > 0
        assert dm.throttle.tcap > tcap0, (
            f"ladder never grew ({created[0]} creates, tcap {tcap0})"
        )

        plugin.run_pending_once()
        probe = make_pod("probe-final", labels={"grp": "g1"}, requests={"cpu": "200m"})
        _assert_device_matches_oracle(store, plugin, probe)

    def test_check_batch_all_single_snapshot(self):
        """check_batch_all returns both kinds against one lock hold; the
        row maps must cover the same pod set for both kinds."""
        store, plugin = _stack()
        store.create_throttle(_throttle("t1", {"grp": "a"}, pod=10))
        for i in range(8):
            store.create_pod(
                _bound(make_pod(f"p{i}", labels={"grp": "a"}, requests={"cpu": "10m"}))
            )
        plugin.run_pending_once()
        out = plugin.device_manager.check_batch_all(False)
        assert set(out) == {"throttle", "clusterthrottle"}
        t_rows = out["throttle"][2]
        ct_rows = out["clusterthrottle"][2]
        assert set(t_rows) == set(ct_rows) == {f"default/p{i}" for i in range(8)}

    def test_reader_throughput_survives_reconcile_churn(self):
        """check_pod readers must not collapse while a writer continuously
        drives the reconcile data plane (pod deltas + aggregate
        flush/gather). The lock now covers only host-side snapshot grabs —
        kernel dispatch, the batch gather, and device reads run outside it —
        so reader throughput under churn stays a healthy fraction of idle
        throughput instead of queuing behind every reconcile transfer.
        (True thread-scaling is measured on the TPU bench, where device
        kernels dominate; under the CPU test backend the GIL bounds
        everything Python-side, so the bar here is no-collapse, not
        speedup.)"""
        store, plugin = _stack()
        dm = plugin.device_manager
        for i in range(64):
            store.create_throttle(
                _throttle(f"t{i}", {"grp": f"g{i % 8}"}, pod=100, requests={"cpu": "100"})
            )
        for i in range(128):
            store.create_pod(
                _bound(make_pod(f"p{i}", labels={"grp": f"g{i % 8}"}, requests={"cpu": "10m"}))
            )
        plugin.run_pending_once()
        probe = make_pod("probe", labels={"grp": "g0"}, requests={"cpu": "10m"})
        dm.check_pod(probe, "throttle", False)  # warm compile caches
        keys = [f"default/t{i}" for i in range(64)]
        dm.aggregate_used_for("throttle", keys)  # warm the aggregate path

        def measure_reader(duration: float, churn: bool) -> float:
            stop = threading.Event()
            count = [0]

            def reader() -> None:
                p = make_pod("probe-r", labels={"grp": "g0"}, requests={"cpu": "10m"})
                while not stop.is_set():
                    dm.check_pod(p, "throttle", False)
                    count[0] += 1

            def writer() -> None:
                # paced at the BASELINE cfg5 shape: ~1k pod events/sec with
                # periodic batch aggregates, not an unthrottled hot loop (a
                # writer burning a full core is GIL contention, not lock
                # contention — the CPU test backend can't separate those)
                i = 0
                while not stop.is_set():
                    i += 1
                    store.update_pod(
                        _bound(
                            make_pod(
                                f"p{i % 128}",
                                labels={"grp": f"g{i % 8}"},
                                requests={"cpu": f"{10 + i % 7}m"},
                            )
                        )
                    )
                    if i % 16 == 0:
                        dm.aggregate_used_for("throttle", keys)
                    time.sleep(0.001)

            rt = threading.Thread(target=reader)
            wt = threading.Thread(target=writer) if churn else None
            rt.start()
            if wt:
                wt.start()
            time.sleep(duration)
            stop.set()
            rt.join(timeout=10)
            if wt:
                wt.join(timeout=10)
            return count[0] / duration

        idle = measure_reader(1.0, churn=False)
        under_churn = measure_reader(1.5, churn=True)
        # measured ~0.45x idle on this backend (the paced writer's Python
        # work takes its GIL share); full serialization behind the ~14ms
        # aggregate flushes — the regression this guards — sits under 0.1x.
        # The generous bar keeps the test deterministic under suite load.
        assert under_churn > idle * 0.2, (idle, under_churn)


class TestPreFilterCoalescer:
    """The micro-batching front-end must be semantically invisible:
    identical Status (code + reason tuple) to the direct pre_filter for
    every pod, under real concurrency (plugin/coalesce.py)."""

    def _stack(self, n_thr=24, n_pods=60, groups=6):
        import random

        from kube_throttler_tpu.api.pod import Namespace, make_pod
        from kube_throttler_tpu.api.types import (
            LabelSelector,
            ResourceAmount,
            Throttle,
            ThrottleSelector,
            ThrottleSelectorTerm,
            ThrottleSpec,
        )
        from kube_throttler_tpu.engine.store import Store
        from kube_throttler_tpu.plugin import KubeThrottler, decode_plugin_args

        rng = random.Random(11)
        store = Store()
        store.create_namespace(Namespace("default"))
        plugin = KubeThrottler(
            decode_plugin_args(
                {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
            ),
            store,
            use_device=True,
        )
        for i in range(n_thr):
            store.create_throttle(
                Throttle(
                    name=f"t{i}",
                    namespace="default",
                    spec=ThrottleSpec(
                        throttler_name="kube-throttler",
                        threshold=ResourceAmount.of(
                            pod=rng.choice([None, 1, 3]),
                            requests={"cpu": f"{rng.randrange(1, 9) * 100}m"},
                        ),
                        selector=ThrottleSelector(
                            selector_terms=(
                                ThrottleSelectorTerm(
                                    LabelSelector(
                                        match_labels={"grp": f"g{i % groups}"}
                                    )
                                ),
                            )
                        ),
                    ),
                )
            )
        from dataclasses import replace

        for i in range(n_pods):
            p = make_pod(
                f"p{i}",
                namespace="default",
                labels={"grp": f"g{rng.randrange(groups)}"},
                requests={"cpu": f"{rng.randrange(1, 6) * 100}m"},
            )
            p = replace(p, spec=replace(p.spec, node_name="n1"))
            p.status.phase = "Running"
            store.create_pod(p)
        plugin.run_pending_once()
        return store, plugin, rng

    def _probes(self, rng, n, groups=6):
        from kube_throttler_tpu.api.pod import make_pod

        return [
            make_pod(
                f"probe{i}",
                namespace="default",
                labels={"grp": f"g{i % groups}"},
                requests={"cpu": f"{rng.randrange(1, 9) * 100}m"},
            )
            for i in range(n)
        ]

    def test_check_pods_multi_matches_check_pod(self):
        """Both routes of the multi check pinned against check_pod: the
        HOST route (native B sub-µs passes — the default) and the fused
        DEVICE dispatch (forced; the remote-accelerator A/B side)."""
        import os

        from kube_throttler_tpu.engine import devicestate as ds

        _, plugin, rng = self._stack()
        dm = plugin.device_manager
        probes = self._probes(rng, 13)
        # the False leg is the NATIVE host route only when the lib loaded;
        # a silent load failure would run the device path twice and the
        # native multi decode would lose coverage — so demand the lib
        # unless the numpy tier was explicitly requested
        native_available = ds._native_cls_lib() is not None
        assert native_available or os.environ.get("KT_TPU_NO_NATIVE") == "1", (
            "native lib failed to load — the host-route leg would not "
            "exercise the native multi path (run with a C++ toolchain)"
        )
        legs = ([False] if native_available else []) + [True]
        for forced_device in legs:
            dm._single_check_device = forced_device
            for kind in ("throttle", "clusterthrottle"):
                multi = dm.check_pods_multi(probes, kind)
                for pod, got in zip(probes, multi):
                    assert got == dm.check_pod(pod, kind), (
                        forced_device, kind, pod.name,
                    )
        # and the numpy host tier (no native lib) through the same surface
        old = (ds._cls_lib, ds._cls_lib_tried)
        ds._cls_lib, ds._cls_lib_tried = None, True
        try:
            dm._single_check_device = False
            for kind in ("throttle", "clusterthrottle"):
                multi = dm.check_pods_multi(probes, kind)
                for pod, got in zip(probes, multi):
                    assert got == dm.check_pod(pod, kind), ("numpy", kind, pod.name)
        finally:
            ds._cls_lib, ds._cls_lib_tried = old

    def test_coalesced_matches_direct_concurrent(self):
        import threading

        _, plugin, rng = self._stack()
        co = plugin.coalescer(window_s=2e-3)
        probes = self._probes(rng, 32)
        want = {p.name: plugin.pre_filter(p) for p in probes}

        got = {}
        lock = threading.Lock()
        barrier = threading.Barrier(8)

        def worker(idx):
            barrier.wait()
            for p in probes[idx::8]:
                s = co.pre_filter(p)
                with lock:
                    got[p.name] = s

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(20)
        assert len(got) == len(probes)
        for name, status in want.items():
            assert got[name].code == status.code, name
            assert got[name].reasons == status.reasons, name

    def test_coalesced_single_caller(self):
        _, plugin, rng = self._stack()
        co = plugin.coalescer()
        for p in self._probes(rng, 6):
            direct = plugin.pre_filter(p)
            coal = co.pre_filter(p)
            assert coal.code == direct.code and coal.reasons == direct.reasons
