"""Leader-election lease: exclusion, handoff, crash release — flock backend
(single host) and the coordination.k8s.io Lease backend over the apiserver
(multi-host, client-go leaderelection semantics).

The reference gets leader election from the embedded kube-scheduler's
``leaderElection`` config (a Lease on the apiserver); the standalone
analogs live in utils/leaderelect.py."""

import os
import subprocess
import sys
import threading
import time

import pytest

from kube_throttler_tpu.utils.leaderelect import (
    FileLeaseElector,
    HttpLeaseElector,
    default_lease_path,
)


def test_exclusion_and_handoff(tmp_path):
    lock = str(tmp_path / "lease.lock")
    a = FileLeaseElector(lock, retry_period=0.05)
    b = FileLeaseElector(lock, retry_period=0.05)

    assert a.try_acquire() and a.is_leader
    assert not b.try_acquire() and not b.is_leader

    # b blocks until a releases
    acquired = threading.Event()
    t = threading.Thread(target=lambda: (b.acquire(), acquired.set()), daemon=True)
    t.start()
    time.sleep(0.15)
    assert not acquired.is_set()
    a.release()
    assert acquired.wait(2.0) and b.is_leader
    b.release()


def test_acquire_interruptible(tmp_path):
    lock = str(tmp_path / "lease.lock")
    holder = FileLeaseElector(lock)
    assert holder.try_acquire()
    stop = threading.Event()
    standby = FileLeaseElector(lock, retry_period=0.05)
    result = {}
    t = threading.Thread(target=lambda: result.setdefault("r", standby.acquire(stop)), daemon=True)
    t.start()
    stop.set()
    t.join(2.0)
    assert result["r"] is False and not standby.is_leader
    holder.release()


def test_crashed_leader_frees_lease(tmp_path):
    """flock is released by the OS when the holder dies — the standby takes
    over without manual cleanup (crash-only stance)."""
    lock = str(tmp_path / "lease.lock")
    holder = subprocess.Popen(
        [
            sys.executable,
            "-c",
            "import fcntl, os, sys, time\n"
            f"fd = os.open({lock!r}, os.O_CREAT | os.O_RDWR)\n"
            "fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)\n"
            "print('locked', flush=True)\n"
            "time.sleep(30)\n",
        ],
        stdout=subprocess.PIPE,
    )
    assert holder.stdout.readline().strip() == b"locked"
    standby = FileLeaseElector(lock, retry_period=0.05)
    assert not standby.try_acquire()
    holder.kill()
    holder.wait()
    deadline = time.time() + 5
    while time.time() < deadline and not standby.try_acquire():
        time.sleep(0.05)
    assert standby.is_leader
    standby.release()


def test_default_lease_path_is_private(tmp_path, monkeypatch):
    """No world-writable /tmp: the default lease lives in a 0700 per-user
    runtime dir (ADVICE r2 item 1), and the open refuses symlinks."""
    monkeypatch.setenv("XDG_RUNTIME_DIR", str(tmp_path))
    path = default_lease_path("kt")
    assert path.startswith(str(tmp_path))
    d = os.path.dirname(path)
    assert (os.stat(d).st_mode & 0o777) == 0o700

    # symlink squatting is refused (O_NOFOLLOW)
    target = tmp_path / "evil-target"
    target.write_text("")
    os.symlink(target, path)
    with pytest.raises(RuntimeError):
        FileLeaseElector(path).try_acquire()


class TestHttpLeaseElector:
    @pytest.fixture()
    def apiserver(self):
        from kube_throttler_tpu.client.mockserver import MockApiServer

        server = MockApiServer()
        server.start()
        yield server
        server.stop()

    def _elector(self, apiserver, identity, **kw):
        from kube_throttler_tpu.client.transport import ApiClient, RestConfig

        # generous margins: the renewer must never miss a whole
        # lease_duration under CI load, or tests flake
        kw.setdefault("lease_duration", 1.5)
        kw.setdefault("renew_period", 0.2)
        kw.setdefault("retry_period", 0.05)
        return HttpLeaseElector(
            ApiClient(RestConfig(server=apiserver.url)),
            name="kt",
            identity=identity,
            **kw,
        )

    def test_exclusion_and_clean_handoff(self, apiserver):
        a = self._elector(apiserver, "replica-a")
        b = self._elector(apiserver, "replica-b")
        assert a.try_acquire() and a.is_leader
        assert not b.try_acquire() and not b.is_leader

        acquired = threading.Event()
        t = threading.Thread(
            target=lambda: (b.acquire(), acquired.set()), daemon=True
        )
        t.start()
        time.sleep(0.15)
        assert not acquired.is_set()
        a.release()  # clean handoff: holder zeroed, standby takes over fast
        assert acquired.wait(5.0) and b.is_leader
        b.release()

    def test_failover_on_expired_lease(self, apiserver):
        """A crashed leader (renewer stopped, no release) is taken over once
        renewTime goes stale — two 'daemons', shared control plane, no
        shared filesystem: the multi-host scenario."""
        a = self._elector(apiserver, "replica-a")
        assert a.acquire()
        a._stop.set()  # simulate crash: stop renewing WITHOUT releasing
        a._renewer.join(timeout=2)

        b = self._elector(apiserver, "replica-b")
        assert not b.try_acquire()  # lease still fresh
        deadline = time.time() + 5
        while time.time() < deadline and not b.try_acquire():
            time.sleep(0.05)
        assert b.is_leader
        b.release()

    def test_renew_failure_demotes_before_standby_takeover(self, apiserver):
        """A leader that cannot reach the apiserver must demote (on_lost)
        within renew_deadline — strictly BEFORE a standby's lease_duration
        takeover clock expires, so two replicas never both lead."""
        lost = threading.Event()
        a = self._elector(apiserver, "replica-a")
        a.on_lost = lost.set
        assert a.acquire()
        # sever connectivity: point the client at a dead port
        from kube_throttler_tpu.client.transport import ApiClient, RestConfig

        a.client = ApiClient(RestConfig(server="http://127.0.0.1:1"), timeout=0.2)
        assert lost.wait(5.0)
        assert not a.is_leader
        # the standby takes over after lease_duration
        b = self._elector(apiserver, "replica-b")
        deadline = time.time() + 5
        while time.time() < deadline and not b.try_acquire():
            time.sleep(0.05)
        assert b.is_leader
        b.release()

    def test_renewal_keeps_standby_out(self, apiserver):
        a = self._elector(apiserver, "replica-a")
        assert a.acquire()
        b = self._elector(apiserver, "replica-b")
        # well past lease_duration: the renewer must have kept it fresh
        time.sleep(2.0)
        assert not b.try_acquire()
        assert a.is_leader
        a.release()


class TestTwoDaemonFailover:
    def test_two_daemons_fail_over_through_the_shared_apiserver(self, tmp_path):
        """The VERDICT r2 task-8 done-bar, end to end: two REAL daemon
        processes (separate workdirs, no shared filesystem state) compete
        for the Lease on a shared apiserver; the standby only starts
        serving after the leader dies."""
        import re
        import subprocess
        import sys as _sys

        from kube_throttler_tpu.client.mockserver import MockApiServer
        from kube_throttler_tpu.api.pod import Namespace

        apiserver = MockApiServer()
        apiserver.store.create_namespace(Namespace("default"))
        apiserver.start()
        kubeconfig = tmp_path / "kubeconfig"
        kubeconfig.write_text(
            f"clusters:\n- name: m\n  cluster: {{server: \"{apiserver.url}\"}}\n"
            "contexts:\n- name: m\n  context: {cluster: m}\ncurrent-context: m\n"
        )

        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

        def launch(workdir):
            workdir.mkdir()
            env = dict(os.environ)
            env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
            return subprocess.Popen(
                [
                    _sys.executable, "-m", "kube_throttler_tpu.cli", "serve",
                    "--name", "kube-throttler",
                    "--target-scheduler-name", "my-scheduler",
                    "--kubeconfig", str(kubeconfig), "--leader-elect",
                    "--port", "0", "--no-device",
                ],
                cwd=workdir,  # separate workdirs: nothing shared but the apiserver
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )

        from tests.conftest import ProcReader

        a = b = None
        try:
            a = launch(tmp_path / "daemon-a")
            ra = ProcReader(a)
            ra.wait_for(r"serving on")  # A acquired and serves

            b = launch(tmp_path / "daemon-b")
            rb = ProcReader(b)
            rb.wait_for(r"waiting")
            # mutual exclusion: B must NOT start serving while A holds the
            # lease (keep draining — a vacuous check on already-seen lines
            # would pass even if both replicas acquired)
            rb.assert_absent(r"serving on", during_s=3.0)

            a.kill()  # crash, no release — failover must come from expiry
            a.wait(timeout=10)
            # default leaseDuration is 15s; B takes over after expiry
            rb.wait_for(r"serving on", timeout_s=60)
        finally:
            for p in (a, b):
                if p is not None:
                    p.kill()
                    p.wait(timeout=10)
            apiserver.stop()


def test_cli_wires_leader_election(tmp_path, monkeypatch):
    """`serve --leader-elect` blocks behind a held lease and starts once it
    frees (driven via SIGINT→stop to keep the test fast)."""
    lock = str(tmp_path / "cli.lock")
    holder = FileLeaseElector(lock)
    assert holder.try_acquire()

    from kube_throttler_tpu import cli

    rc = {}

    def run():
        rc["v"] = cli.main(
            [
                "serve",
                "--name", "kt", "--target-scheduler-name", "s",
                "--leader-elect", "--lock-file", lock,
                "--no-device", "--nodes", "0", "--port", "0",
            ]
        )

    # signal.signal only works on the main thread — stub it and capture the
    # stop event the CLI creates
    events = []
    real_event = threading.Event

    class CapturingEvent(real_event):
        def __init__(self):
            super().__init__()
            events.append(self)

    monkeypatch.setattr(cli.signal, "signal", lambda *a, **k: None)
    monkeypatch.setattr(cli.threading, "Event", CapturingEvent)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    time.sleep(0.3)
    assert t.is_alive() and rc == {}  # standing by behind the held lease
    # the monkeypatch covers the global threading module, so other
    # components' Events are captured too — fire them all ("SIGINT")
    for ev in list(events):
        ev.set()
    t.join(5.0)
    assert rc["v"] == 0  # clean exit without ever serving
    holder.release()
