"""Interned-verdict cache (engine/verdictcache.py) + the epoch planes that
invalidate it.

The load-bearing suite is the epoch-bump COMPLETENESS sweep: a seeded
mutation mix (status flips, threshold edits, override windows, policy
swaps, reservations, gang reserve/rollback, preemption, namespace churn)
where after EVERY mutation the cached ``pre_filter`` must agree with the
uncached recompute on the same state — any mutation site missing its
epoch bump serves a stale verdict here and fails the pin. The front-tier
mirror (sharding/front.py) gets the same treatment over the scatter
path, including the reshard/resync/attach global bumps.
"""

from __future__ import annotations

import random

import pytest

from conftest import normalize_reasons as norm
from kube_throttler_tpu.api.pod import Namespace, make_pod
from kube_throttler_tpu.api.types import (
    ClusterThrottle,
    ClusterThrottleSelector,
    ClusterThrottleSelectorTerm,
    ClusterThrottleSpec,
    LabelSelector,
    ResourceAmount,
    TemporaryThresholdOverride,
    Throttle,
    ThrottleSelector,
    ThrottleSelectorTerm,
    ThrottleSpec,
)
from kube_throttler_tpu.engine.store import Store
from kube_throttler_tpu.engine.verdictcache import VerdictCache
from kube_throttler_tpu.plugin import KubeThrottler, decode_plugin_args
from kube_throttler_tpu.plugin.framework import Status, StatusCode


def _throttle(name="t1", cpu="200m", grp="a", overrides=()):
    return Throttle(
        name=name,
        spec=ThrottleSpec(
            throttler_name="kube-throttler",
            threshold=ResourceAmount.of(requests={"cpu": cpu}),
            temporary_threshold_overrides=tuple(overrides),
            selector=ThrottleSelector(
                selector_terms=(
                    ThrottleSelectorTerm(
                        pod_selector=LabelSelector(match_labels={"grp": grp})
                    ),
                )
            ),
        ),
    )


def _cluster_throttle(name="ct1", cpu="500m", grp="a"):
    return ClusterThrottle(
        name=name,
        spec=ClusterThrottleSpec(
            throttler_name="kube-throttler",
            threshold=ResourceAmount.of(requests={"cpu": cpu}),
            selector=ClusterThrottleSelector(
                selector_terms=(
                    ClusterThrottleSelectorTerm(
                        LabelSelector(match_labels={"grp": grp}),
                        LabelSelector(),
                    ),
                )
            ),
        ),
    )


def _plugin(store, policies=None):
    config = {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
    if policies is not None:
        config["policies"] = policies
    return KubeThrottler(
        decode_plugin_args(config), store, use_device=True, start_workers=False
    )


# --------------------------------------------------------------------------
# cache data structure
# --------------------------------------------------------------------------


class TestVerdictCacheUnit:
    def test_hit_requires_epoch_match(self):
        c = VerdictCache(capacity=8)
        st = Status(StatusCode.SUCCESS)
        c.put(("k",), 3, st)
        assert c.get(("k",), 3) is st
        # a bumped epoch sum invalidates without any explicit eviction
        assert c.get(("k",), 4) is None
        hits, misses, *_ = c.stats()
        assert hits == 1 and misses >= 1

    def test_unknown_key_misses(self):
        c = VerdictCache(capacity=8)
        assert c.get(("nope",), 0) is None
        assert c.stats()[1] == 1

    def test_put_replaces_stale_entry(self):
        c = VerdictCache(capacity=8)
        old, new = Status(StatusCode.SUCCESS), Status(StatusCode.ERROR, ("x",))
        c.put(("k",), 1, old)
        c.put(("k",), 2, new)
        assert c.get(("k",), 2) is new
        assert c.get(("k",), 1) is None

    def test_rotation_bounds_size(self):
        c = VerdictCache(capacity=16)  # segments of 8
        st = Status(StatusCode.SUCCESS)
        for i in range(200):
            c.put((f"k{i}",), 0, st)
        assert len(c) <= 16

    def test_rotation_promotes_hot_entries(self):
        c = VerdictCache(capacity=8)  # segments of 4
        hot = Status(StatusCode.SUCCESS)
        c.put(("hot",), 0, hot)
        for i in range(4):  # rotate: "hot" falls into the old segment
            c.put((f"cold{i}",), 0, hot)
        assert c.get(("hot",), 0) is hot  # old-segment hit promotes
        for i in range(4, 8):  # rotate again: promoted entry survives
            c.put((f"cold{i}",), 0, hot)
        assert c.get(("hot",), 0) is hot

    def test_invalidate_all(self):
        c = VerdictCache(capacity=8)
        c.put(("k",), 0, Status(StatusCode.SUCCESS))
        c.invalidate_all()
        assert len(c) == 0
        assert c.get(("k",), 0) is None
        assert c.stats()[3] == 1  # invalidations


# --------------------------------------------------------------------------
# plugin hot path
# --------------------------------------------------------------------------


class TestPluginCacheHotPath:
    def _stack(self):
        store = Store()
        plugin = _plugin(store)
        store.create_namespace(Namespace("default"))
        store.create_throttle(_throttle())
        plugin.run_pending_once()
        return store, plugin

    def test_repeat_verdict_is_a_cache_hit(self):
        _, plugin = self._stack()
        assert plugin.verdict_cache is not None
        pod = make_pod("p", labels={"grp": "a"}, requests={"cpu": "100m"})
        first = plugin.pre_filter(pod)
        hits0 = plugin.verdict_cache.stats()[0]
        second = plugin.pre_filter(pod)
        assert second is first  # the interned Status object itself
        assert plugin.verdict_cache.stats()[0] == hits0 + 1

    def test_same_shape_different_pod_shares_entry(self):
        _, plugin = self._stack()
        a = make_pod("a", labels={"grp": "a"}, requests={"cpu": "100m"})
        b = make_pod("b", labels={"grp": "a"}, requests={"cpu": "100m"})
        sa = plugin.pre_filter(a)
        hits0 = plugin.verdict_cache.stats()[0]
        sb = plugin.pre_filter(b)
        assert sb is sa
        assert plugin.verdict_cache.stats()[0] == hits0 + 1

    def test_unknown_namespace_is_uncacheable(self):
        _, plugin = self._stack()
        ghost = make_pod("g", namespace="ghost", requests={"cpu": "1m"})
        st1 = plugin.pre_filter(ghost)
        st2 = plugin.pre_filter(ghost)
        assert st1.code == StatusCode.ERROR and st2.code == StatusCode.ERROR
        # never entered the cache: the (shape, empty-cols) key would
        # collide with known-namespace pods that cached SUCCESS
        assert plugin.verdict_cache.stats()[0] == 0

    def test_exceeds_verdict_not_cached_and_reemits_event(self):
        from kube_throttler_tpu.plugin import RecordingEventRecorder

        store = Store()
        plugin = KubeThrottler(
            decode_plugin_args(
                {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
            ),
            store,
            event_recorder=RecordingEventRecorder(),
            use_device=True,
            start_workers=False,
        )
        store.create_namespace(Namespace("default"))
        store.create_throttle(_throttle())
        plugin.run_pending_once()
        whale = make_pod("whale", labels={"grp": "a"}, requests={"cpu": "900m"})
        for _ in range(2):
            st = plugin.pre_filter(whale)
            assert any("exceeds-threshold" in r for r in st.reasons)
        # one Warning per CALL (the recorder aggregates identical events
        # into a count) — a cache hit would have swallowed the second
        assert sum(plugin.event_recorder.counts.values()) == 2

    def test_selector_edit_invalidates_memoized_cols(self):
        store, plugin = self._stack()
        pod = make_pod("p", labels={"grp": "b"}, requests={"cpu": "900m"})
        assert plugin.pre_filter(pod).code is StatusCode.SUCCESS  # matches nothing
        # the selector edit makes t1 match grp=b: the memoized cols for
        # the SAME pod object are now stale (index generation bumped)
        cur = store.get_throttle("default", "t1")
        store.update_throttle(
            Throttle(name="t1", spec=_throttle(grp="b").spec, status=cur.status)
        )
        plugin.run_pending_once()
        st = plugin.pre_filter(pod)
        assert st.code is not StatusCode.SUCCESS, st.reasons
        assert norm(st.reasons) == norm(plugin._pre_filter_uncached(pod).reasons)

    def test_policy_swap_drops_cache(self):
        _, plugin = self._stack()
        pod = make_pod("p", labels={"grp": "a"}, requests={"cpu": "100m"})
        plugin.pre_filter(pod)
        assert len(plugin.verdict_cache) > 0
        plugin.set_policy_specs(
            [{"name": "swap", "preemptionEnabled": True, "minPriorityGap": 1}]
        )
        assert len(plugin.verdict_cache) == 0


# --------------------------------------------------------------------------
# intra-batch dedupe
# --------------------------------------------------------------------------


class TestBatchDedupe:
    def test_batch_agrees_with_uncached_on_degenerate_mix(self):
        def build():
            store = Store()
            plugin = _plugin(store)
            store.create_namespace(Namespace("default"))
            store.create_throttle(_throttle(cpu="450m"))
            store.create_throttle(_throttle("t2", cpu="300m", grp="b"))
            store.create_cluster_throttle(_cluster_throttle())
            for i in range(40):  # one degenerate group: identical shape
                store.create_pod(
                    make_pod(
                        f"same{i}", labels={"grp": "a"},
                        requests={"cpu": "10m"}, node_name="n1", phase="Running",
                    )
                )
            for i in range(6):  # distinct shapes and groups
                store.create_pod(
                    make_pod(
                        f"diff{i}", labels={"grp": "ab"[i % 2]},
                        requests={"cpu": f"{(i + 1) * 50}m"},
                    )
                )
            store.create_pod(make_pod("lost", namespace="ghost"))
            plugin.run_pending_once()
            return plugin

        cached, uncached = build(), build()
        uncached.verdict_cache = None
        out_c = cached.pre_filter_batch()
        out_u = uncached.pre_filter_batch()
        assert out_c == out_u
        # the dedupe actually engaged: 46 known-ns pods collapsed into a
        # handful of (shape, cols) groups, one evaluation each
        hits1, misses1, *_ = cached.verdict_cache.stats()
        assert misses1 <= 15, f"dedupe evaluated {misses1} groups for 46 pods"
        # a second batch over unchanged state serves the groups from cache
        assert cached.pre_filter_batch() == out_u
        hits2, misses2, *_ = cached.verdict_cache.stats()
        assert hits2 > hits1, "warm batch never hit the cache"
        assert misses2 == misses1

    def test_batch_dedupe_declines_on_diverse_population(self):
        store = Store()
        plugin = _plugin(store)
        store.create_namespace(Namespace("default"))
        store.create_throttle(_throttle(cpu="10000m"))
        for i in range(300):  # every pod a distinct shape → groups*2 > pods
            store.create_pod(
                make_pod(f"p{i}", labels={"grp": "a"}, requests={"cpu": f"{i + 1}m"})
            )
        plugin.run_pending_once()
        out = plugin.pre_filter_batch()
        assert len(out["schedulable"]) == 300
        ref = plugin.verdict_cache
        plugin.verdict_cache = None
        assert plugin.pre_filter_batch() == out
        plugin.verdict_cache = ref


# --------------------------------------------------------------------------
# epoch-bump completeness: mutation sweep, cache ≡ recompute
# --------------------------------------------------------------------------


class TestEpochBumpCompleteness:
    @pytest.mark.parametrize("seed", [7, 19, 31])
    def test_mutation_sweep_cache_equals_recompute(self, seed):
        """Every mutation class that can change a verdict, in a seeded
        mix; after each one the cached path must agree with the uncached
        recompute for a probe population spanning matched/unmatched
        shapes. A missing epoch bump anywhere = a stale verdict here."""
        rng = random.Random(seed)
        store = Store()
        plugin = _plugin(
            store,
            policies=[{"name": "p0", "preemptionEnabled": True, "minPriorityGap": 1}],
        )
        assert plugin.verdict_cache is not None
        store.create_namespace(Namespace("default"))

        probes = [
            make_pod(
                f"probe{i}",
                labels={"grp": "ab"[i % 2]},
                requests={"cpu": f"{(i % 5 + 1)}00m"},
            )
            for i in range(6)
        ]
        reserved: list = []
        gangs: list = []

        def check():
            plugin.run_pending_once()  # status flips land (epoch-covered)
            for pod in probes:
                fresh = plugin._pre_filter_uncached(pod)
                for _ in range(2):  # miss-then-hit: both must match fresh
                    got = plugin.pre_filter(pod)
                    assert got.code == fresh.code, (pod.key, got.reasons, fresh.reasons)
                    assert norm(got.reasons) == norm(fresh.reasons), pod.key

        def op_throttle_edit():
            name = f"t{rng.randint(0, 3)}"
            thr = _throttle(name, cpu=f"{rng.randint(1, 6)}00m", grp=rng.choice("ab"))
            try:
                store.create_throttle(thr)
            except ValueError:
                cur = store.get_throttle("default", name)
                store.update_throttle(
                    Throttle(name=name, spec=thr.spec, status=cur.status)
                )

        def op_clusterthrottle_edit():
            name = f"ct{rng.randint(0, 1)}"
            ct = _cluster_throttle(
                name, cpu=f"{rng.randint(2, 8)}00m", grp=rng.choice("ab")
            )
            try:
                store.create_cluster_throttle(ct)
            except ValueError:
                cur = store.get_cluster_throttle(name)
                store.update_cluster_throttle(
                    ClusterThrottle(name=name, spec=ct.spec, status=cur.status)
                )

        def op_override_window():
            # an override that is active NOW halves (or floods) the
            # threshold; it reaches verdicts via the status write the
            # next reconcile stamps — which must bump the epoch
            cpu = rng.choice(["50m", "900m"])
            thr = _throttle(
                "t0",
                cpu="300m",
                grp="a",
                overrides=(
                    TemporaryThresholdOverride(
                        begin="2000-01-01T00:00:00Z",
                        end="2100-01-01T00:00:00Z",
                        threshold=ResourceAmount.of(requests={"cpu": cpu}),
                    ),
                ),
            )
            try:
                store.create_throttle(thr)
            except ValueError:
                cur = store.get_throttle("default", "t0")
                store.update_throttle(
                    Throttle(name="t0", spec=thr.spec, status=cur.status)
                )

        def op_pod_churn():
            if rng.random() < 0.6 or not store.list_pods("default"):
                store.create_pod(
                    make_pod(
                        f"w{rng.randrange(10**6)}",
                        labels={"grp": rng.choice("ab")},
                        requests={"cpu": f"{rng.randint(1, 4)}00m"},
                        node_name="n1",
                        phase="Running",
                    )
                )
            else:
                doomed = rng.choice(store.list_pods("default"))
                store.delete_pod("default", doomed.name)

        def op_reserve():
            pod = make_pod(
                f"r{rng.randrange(10**6)}",
                labels={"grp": rng.choice("ab")},
                requests={"cpu": f"{rng.randint(1, 3)}00m"},
            )
            if plugin.reserve(pod).is_success():
                reserved.append(pod)

        def op_unreserve():
            if reserved:
                plugin.unreserve(reserved.pop(rng.randrange(len(reserved))))

        def op_gang_reserve():
            gid = f"default/g{rng.randrange(10**6)}"
            members = [
                make_pod(
                    f"gm{rng.randrange(10**6)}",
                    labels={"grp": rng.choice("ab")},
                    requests={"cpu": "50m"},
                )
                for _ in range(2)
            ]
            if plugin.reserve_gang(gid, members).is_success():
                gangs.append(gid)

        def op_gang_rollback():
            if gangs:
                plugin.unreserve_gang(gangs.pop(rng.randrange(len(gangs))))

        def op_policy_swap():
            plugin.set_policy_specs(
                [
                    {
                        "name": f"p{rng.randrange(10**6)}",
                        "preemptionEnabled": bool(rng.getrandbits(1)),
                        "minPriorityGap": rng.randint(1, 3),
                    }
                ]
            )

        def op_preempt_cycle():
            members = [
                make_pod(
                    f"hi{rng.randrange(10**6)}",
                    labels={"grp": "a"},
                    requests={"cpu": "100m"},
                    priority=5,
                )
            ]
            # commit or infeasible-rollback — either way any evictions
            # land as pod deletes whose epoch bumps the probes see
            plugin.maybe_preempt_gang(f"default/pg{rng.randrange(10**6)}", members)

        def op_namespace_churn():
            if store.get_namespace("burst") is None:
                store.create_namespace(Namespace("burst"))
            else:
                store.delete_namespace("burst")

        ops = [
            op_throttle_edit,
            op_clusterthrottle_edit,
            op_override_window,
            op_pod_churn,
            op_pod_churn,
            op_reserve,
            op_unreserve,
            op_gang_reserve,
            op_gang_rollback,
            op_policy_swap,
            op_preempt_cycle,
            op_namespace_churn,
        ]
        for _ in range(40):
            rng.choice(ops)()
            check()


# --------------------------------------------------------------------------
# front-tier cache (scatter path)
# --------------------------------------------------------------------------


class TestFrontCache:
    def _build(self, n_shards=3):
        from kube_throttler_tpu.sharding.front import AdmissionFront
        from kube_throttler_tpu.sharding.ipc import LocalShard
        from kube_throttler_tpu.sharding.worker import ShardCore

        front = AdmissionFront(n_shards)
        cores = [ShardCore(i, n_shards, use_device=False) for i in range(n_shards)]
        for i, core in enumerate(cores):
            front.attach_shard(
                i, LocalShard(i, core, on_push=front.apply_status_push)
            )
        front.store.create_namespace(Namespace("default"))
        return front, cores

    @staticmethod
    def _teardown(front, cores):
        for core in cores:
            core.stop()
        front.stop()

    @staticmethod
    def _settle(front):
        assert front.drain(timeout=30.0)
        import time

        time.sleep(0.3)  # shard push loops flush on their own cadence

    @staticmethod
    def _fresh(front, pod):
        cache, front.verdict_cache = front.verdict_cache, None
        try:
            return front.pre_filter(pod)
        finally:
            front.verdict_cache = cache

    def test_scatter_cache_equals_recompute_under_churn(self):
        rng = random.Random(13)
        front, cores = self._build()
        try:
            assert front.verdict_cache is not None
            probes = [
                make_pod(
                    f"probe{i}",
                    labels={"grp": "ab"[i % 2]},
                    requests={"cpu": f"{(i % 4 + 1)}00m"},
                )
                for i in range(4)
            ]

            def check():
                self._settle(front)
                for pod in probes:
                    fresh = self._fresh(front, pod)
                    for _ in range(2):
                        got = front.pre_filter(pod)
                        assert got.code == fresh.code, (pod.key, got.reasons)
                        assert norm(got.reasons) == norm(fresh.reasons), pod.key

            for step in range(12):
                r = rng.random()
                if r < 0.4:
                    name = f"t{rng.randint(0, 4)}"
                    thr = _throttle(
                        name, cpu=f"{rng.randint(1, 5)}00m", grp=rng.choice("ab")
                    )
                    try:
                        front.store.create_throttle(thr)
                    except ValueError:
                        cur = front.store.get_throttle("default", name)
                        front.store.update_throttle(
                            Throttle(name=name, spec=thr.spec, status=cur.status)
                        )
                elif r < 0.7:
                    front.store.create_pod(
                        make_pod(
                            f"w{step}",
                            labels={"grp": rng.choice("ab")},
                            requests={"cpu": f"{rng.randint(1, 3)}00m"},
                            node_name="n1",
                            phase="Running",
                        )
                    )
                else:
                    front.reserve(
                        make_pod(
                            f"r{step}",
                            labels={"grp": rng.choice("ab")},
                            requests={"cpu": "100m"},
                        )
                    )
                check()
            # the cache is actually in play on this path
            assert front.verdict_cache.stats()[0] > 0
        finally:
            self._teardown(front, cores)

    def test_status_push_bumps_front_epoch(self):
        """A shard's status push (flip) must invalidate without any spec
        route: cache a verdict, flip the throttle via shard-side state,
        and pin that the pushed status re-derives the verdict."""
        front, cores = self._build(n_shards=1)
        try:
            front.store.create_throttle(_throttle(cpu="200m"))
            self._settle(front)
            pod = make_pod("p", labels={"grp": "a"}, requests={"cpu": "100m"})
            assert front.pre_filter(pod).code is StatusCode.SUCCESS
            # saturate the throttle: the shard reconciles, pushes the
            # flipped status back, and the Router's echo path must bump
            front.store.create_pod(
                make_pod(
                    "hog", labels={"grp": "a"}, requests={"cpu": "200m"},
                    node_name="n1", phase="Running",
                )
            )
            self._settle(front)
            st = front.pre_filter(pod)
            fresh = self._fresh(front, pod)
            assert st.code == fresh.code and st.code is not StatusCode.SUCCESS
        finally:
            self._teardown(front, cores)

    def test_structural_ops_bump_global_epoch(self):
        """Reshard cutover, finish/cancel, resync, and attach all change
        what a cached verdict means without touching per-key epochs —
        each must move the global counter (= fingerprint sum)."""
        from kube_throttler_tpu.sharding.ring import HashRing, RangeMove

        front, cores = self._build(n_shards=2)
        try:
            front.store.create_throttle(_throttle())
            self._settle(front)
            pod = make_pod("p", labels={"grp": "a"}, requests={"cpu": "100m"})

            def esum():
                fp = front._verdict_fingerprint(pod)
                assert fp is not None
                return fp[1]

            e0 = esum()
            move = RangeMove(index=0, lo=0, hi=1, src=0, dst=1)
            front.cutover_range(move)
            e1 = esum()
            assert e1 > e0
            front.finish_reshard(HashRing(2), 2)
            e2 = esum()
            assert e2 > e1
            front.cancel_reshard()
            e3 = esum()
            assert e3 > e2
            front.resync_shard(0)
            e4 = esum()
            assert e4 > e3
            front.attach_shard(0, front.shards[0])
            assert esum() > e4
        finally:
            self._teardown(front, cores)
