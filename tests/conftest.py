"""Test bootstrap: force an 8-device virtual CPU mesh before JAX imports.

Multi-chip TPU hardware is not available in CI; sharding tests run against
XLA's host-platform device partitioning instead (same SPMD partitioner the
TPU path uses).
"""

import os

# force, not setdefault: the ambient environment may point JAX_PLATFORMS at
# real TPU hardware, and unit tests must be deterministic CPU runs
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
