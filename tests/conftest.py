"""Test bootstrap: force an 8-device virtual CPU mesh before JAX imports.

Multi-chip TPU hardware is not available in CI; sharding tests run against
XLA's host-platform device partitioning instead (same SPMD partitioner the
TPU path uses).
"""

import os

# force, not setdefault: the ambient environment points JAX_PLATFORMS at real
# TPU hardware AND preloads jax via sitecustomize, so the env var alone is
# too late — jax.config must be updated before the first backend init
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import re

import jax

jax.config.update("jax_platforms", "cpu")
_want = int(
    re.search(
        r"xla_force_host_platform_device_count=(\d+)", os.environ["XLA_FLAGS"]
    ).group(1)
)
assert jax.local_device_count() == _want, (
    f"expected {_want} virtual CPU devices, got {jax.devices()}; either a "
    "backend was initialized before conftest could force the CPU platform, "
    "or the ambient XLA_FLAGS device count disagrees (tests need 8)"
)
assert jax.local_device_count() == 8, (
    f"tests assume an 8-device mesh; ambient XLA_FLAGS pinned "
    f"{jax.local_device_count()} — unset xla_force_host_platform_device_count"
)
