"""Test bootstrap: force an 8-device virtual CPU mesh before JAX imports.

Multi-chip TPU hardware is not available in CI; sharding tests run against
XLA's host-platform device partitioning instead (same SPMD partitioner the
TPU path uses).
"""

import os

# Runtime lock-order assassin (utils/lockorder.py): on for the whole
# suite so the chaos/soak tiers double as a race detector. Must be set
# before any kube_throttler_tpu import — module- and class-level locks
# are created at import time. Opt out per-run with KT_LOCK_ASSERT=0.
os.environ.setdefault("KT_LOCK_ASSERT", "1")

# Eraser-style lockset race detector (utils/racedetect.py): also armed
# suite-wide — every GUARDED_BY attribute access refines a per-(object,
# attribute) candidate lockset, and pytest_sessionfinish below fails
# the run on any unwaived report. Same import-time constraint as the
# assassin (guard_attrs installs the tracking descriptors at class
# decoration). Opt out per-run with KT_RACE_DETECT=0.
os.environ.setdefault("KT_RACE_DETECT", "1")

# Verdict-coherence assassin (utils/epochassert.py): sampled VerdictCache
# hits are shadow-recomputed through the uncached oracle route; a
# divergence at an unchanged epoch sum proves a mutation skipped its
# epoch bump and raises StaleVerdict at first observation. Same
# import-time constraint (plugin caches the flag at construction).
# Opt out per-run with KT_EPOCH_ASSERT=0.
os.environ.setdefault("KT_EPOCH_ASSERT", "1")

# force, not setdefault: the ambient environment points JAX_PLATFORMS at real
# TPU hardware AND preloads jax via sitecustomize, so the env var alone is
# too late — jax.config must be updated before the first backend init
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import re

import jax

jax.config.update("jax_platforms", "cpu")
_want = int(
    re.search(
        r"xla_force_host_platform_device_count=(\d+)", os.environ["XLA_FLAGS"]
    ).group(1)
)
assert jax.local_device_count() == _want, (
    f"expected {_want} virtual CPU devices, got {jax.devices()}; either a "
    "backend was initialized before conftest could force the CPU platform, "
    "or the ambient XLA_FLAGS device count disagrees (tests need 8)"
)
assert jax.local_device_count() == 8, (
    f"tests assume an 8-device mesh; ambient XLA_FLAGS pinned "
    f"{jax.local_device_count()} — unset xla_force_host_platform_device_count"
)


def pytest_sessionfinish(session, exitstatus):
    """Race-detector gate: any unwaived lockset report fails the run —
    the dynamic twin of the analyzer's exit-1-on-new-finding contract.
    Planted-race fixtures isolate themselves via racedetect.capture(),
    so anything left here came from real code under real tests."""
    from kube_throttler_tpu.utils import racedetect

    if not racedetect.enabled():
        return
    reps = racedetect.reports()
    if reps:
        print(
            "\n=== racedetect: unwaived lockset race(s) — fix, or waive in "
            "kube_throttler_tpu/analysis/race_allow.txt with a justification ==="
        )
        for r in reps:
            print(r.render())
        session.exitstatus = 1


class ProcReader:
    """Deadline-safe stdout scraping for daemon subprocesses: readline()
    has no timeout, so a drain thread feeds a queue and callers poll with
    deadlines. ONE reader per process — competing drain threads steal each
    other's lines."""

    def __init__(self, proc):
        import queue
        import threading

        self.proc = proc
        self.lines: "queue.Queue[str]" = queue.Queue()
        self.seen: list = []
        threading.Thread(target=self._drain, daemon=True).start()

    def _drain(self) -> None:
        for line in self.proc.stdout:
            self.lines.put(line)

    def wait_for(self, pattern: str, timeout_s: float = 60.0) -> list:
        """Block until a line matches ``pattern`` (regex); returns all lines
        seen so far. Raises AssertionError (with the transcript) at the
        deadline."""
        import queue
        import re
        import time

        deadline = time.time() + timeout_s
        while time.time() < deadline:
            try:
                line = self.lines.get(timeout=0.5)
            except queue.Empty:
                continue
            self.seen.append(line)
            if re.search(pattern, line):
                return list(self.seen)
        raise AssertionError(f"pattern {pattern!r} not seen in {self.seen}")

    def assert_absent(self, pattern: str, during_s: float) -> None:
        """Drain for ``during_s`` asserting no line matches ``pattern``."""
        import queue
        import re
        import time

        deadline = time.time() + during_s
        while time.time() < deadline:
            try:
                line = self.lines.get(timeout=0.2)
            except queue.Empty:
                continue
            self.seen.append(line)
            assert not re.search(pattern, line), (pattern, self.seen)


def normalize_reasons(reasons):
    """Order-insensitive form of PreFilter reason strings: throttle-name
    order within one reason is not part of the contract (set iteration
    differs between the device decode and the host walk). Shared by the
    differential and flaky-device soaks."""
    out = []
    for r in reasons:
        head, _, names = r.partition("=")
        out.append(f"{head}={','.join(sorted(names.split(',')))}")
    return sorted(out)
