"""Sharded full-update step vs the single-device program on the 8-device
virtual CPU mesh (same SPMD partitioner as TPU)."""

import random
from datetime import datetime, timedelta, timezone

import jax
import numpy as np
import pytest

from kube_throttler_tpu.api import ResourceAmount, TemporaryThresholdOverride
from kube_throttler_tpu.api.types import ThrottleSpecBase
from kube_throttler_tpu.ops.overrides import encode_override_schedule
from kube_throttler_tpu.ops.schema import DimRegistry, PodBatch
from kube_throttler_tpu.parallel import (
    full_update_step,
    make_mesh,
    sharded_apply_deltas,
    sharded_full_update,
)

NOW = datetime(2024, 1, 15, tzinfo=timezone.utc)


def rfc(dt):
    return dt.strftime("%Y-%m-%dT%H:%M:%SZ")


def _build_inputs(rng, P_, T_, R_used=3):
    specs = []
    for i in range(T_):
        overrides = ()
        if rng.random() < 0.5:
            overrides = (
                TemporaryThresholdOverride(
                    begin=rfc(NOW - timedelta(hours=1)),
                    end=rfc(NOW + timedelta(hours=1)),
                    threshold=ResourceAmount.of(requests={"cpu": f"{rng.randrange(1,9)}00m"}),
                ),
            )
        specs.append(
            ThrottleSpecBase(
                threshold=ResourceAmount.of(
                    pod=rng.randrange(1, 5), requests={"cpu": "500m", "memory": "1Gi"}
                ),
                temporary_threshold_overrides=overrides,
            )
        )
    dims = DimRegistry()
    sched = encode_override_schedule(specs, dims, throttle_capacity=T_)

    pod_req = np.zeros((P_, dims.capacity), dtype=np.int64)
    pod_present = np.zeros((P_, dims.capacity), dtype=bool)
    for i in range(P_):
        for r in range(R_used):
            if rng.random() < 0.7:
                pod_req[i, r] = rng.randrange(0, 5) * 100
                pod_present[i, r] = True
    pods = PodBatch(
        valid=np.ones(P_, dtype=bool), req=pod_req, req_present=pod_present
    )
    mask = np.asarray(rng.choices([True, False], k=P_ * T_)).reshape(P_, T_)
    counted = np.asarray(rng.choices([True, False], k=P_))
    res_cnt = np.zeros(T_, dtype=np.int64)
    res_cnt_p = np.zeros(T_, dtype=bool)
    res_req = np.zeros((T_, dims.capacity), dtype=np.int64)
    res_req_p = np.zeros((T_, dims.capacity), dtype=bool)
    for t in range(T_):
        if rng.random() < 0.4:
            res_cnt[t] = rng.randrange(0, 3)
            res_cnt_p[t] = True
            res_req[t, 0] = rng.randrange(0, 3) * 100
            res_req_p[t, 0] = True
    thr_valid = np.ones(T_, dtype=bool)
    now_ns = np.int64(int(NOW.timestamp()) * 10**9)
    return sched, pods, mask, counted, res_cnt, res_cnt_p, res_req, res_req_p, thr_valid, now_ns


def test_sharded_matches_single_device():
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    rng = random.Random(0)
    # P=32 pods over dp=4, T=16 throttles over tp=2
    inputs = _build_inputs(rng, 32, 16)

    single = full_update_step(*inputs)
    mesh = make_mesh(8, shape=(4, 2))
    stepped = sharded_full_update(mesh)(*inputs)

    for got, want in zip(stepped, single):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_mesh_factorization():
    mesh = make_mesh(8)
    assert mesh.devices.size == 8
    assert mesh.axis_names == ("pods", "throttles")


@pytest.mark.parametrize("shape", [(8, 1), (2, 4), (1, 8)])
def test_all_mesh_shapes(shape):
    rng = random.Random(1)
    inputs = _build_inputs(rng, 16, 8)
    single = full_update_step(*inputs)
    mesh = make_mesh(8, shape=shape)
    stepped = sharded_full_update(mesh)(*inputs)
    for got, want in zip(stepped, single):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.parametrize("shape", [(1, 8), (4, 2), (8, 1)])
def test_sharded_deltas_match_single_device(shape):
    """cfg5's streaming scatter-add over a throttle-sharded mesh must be
    bit-identical to the single-device batched apply: every global id
    lands in exactly one tile, out-of-tile slots drop, int64 scatter-adds
    commute."""
    from kube_throttler_tpu.ops.aggregate import apply_pod_deltas_batched

    rng = np.random.default_rng(3)
    T, R, N, K = 16, 4, 24, 3
    used_cnt = rng.integers(0, 50, T).astype(np.int64)
    used_req = rng.integers(0, 64, (T, R)).astype(np.int64) * 1000
    contrib = rng.integers(0, 10, (T, R)).astype(np.int32)
    # ids include out-of-range padding (T) that must drop on every shard
    ids = rng.integers(0, T + 1, (N, K)).astype(np.int32)
    sign = rng.choice(np.array([-1, 0, 1], dtype=np.int64), (N, K))
    pod_req = rng.integers(0, 900, (N, R)).astype(np.int64)
    pod_present = rng.random((N, R)) < 0.7

    want = apply_pod_deltas_batched(
        used_cnt, used_req, contrib, ids, sign, pod_req, pod_present
    )
    mesh = make_mesh(8, shape=shape)
    got = sharded_apply_deltas(mesh)(
        used_cnt, used_req, contrib, ids, sign, pod_req, pod_present
    )
    for g, w in zip(got, want):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
