"""Stateless read-replica admission tier (PERFORMANCE.md "Verdict cache &
read replicas"): a replica bootstraps from the owner's snapshot, streams
its journal tail, serves ``/v1/prefilter*`` from its mirrored planes +
verdict cache, FORWARDS every write surface to the owner, and refuses
reads with 503 once replication lag exceeds the staleness bound.

Covers: the ReplicaGate lag/admit/health contract, replica HTTP serving
(verdicts agree with the owner's), forward-on-write (reserve + object
writes land on the owner, responses relayed with the forwarded-by
marker), /readyz role reporting, and the staleness refusal path.
"""

from __future__ import annotations

import json
import os
import time
import urllib.error
import urllib.request
from types import SimpleNamespace

import pytest

from kube_throttler_tpu.api.pod import Namespace
from kube_throttler_tpu.engine.recovery import RecoveryManager
from kube_throttler_tpu.engine.replication import (
    FencingEpoch,
    HaCoordinator,
    ReplicaGate,
    ReplicationServer,
    ReplicationSource,
    StandbyReplicator,
)
from kube_throttler_tpu.engine.snapshot import SnapshotManager
from kube_throttler_tpu.engine.store import Store
from kube_throttler_tpu.plugin import KubeThrottler, decode_plugin_args
from kube_throttler_tpu.server import ThrottlerHTTPServer


def _wait(predicate, timeout=10.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


def _req(port, method, path, body=None):
    url = f"http://127.0.0.1:{port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            payload = resp.read().decode()
            headers = dict(resp.headers)
            status = resp.status
    except urllib.error.HTTPError as e:
        payload = e.read().decode()
        headers = dict(e.headers)
        status = e.code
    try:
        return status, json.loads(payload), headers
    except json.JSONDecodeError:
        return status, payload, headers


# --------------------------------------------------------------------------
# gate contract
# --------------------------------------------------------------------------


class TestReplicaGate:
    def _gate(self, max_lag_s=5.0, **rep_attrs):
        rep = SimpleNamespace(
            diverged=False, bootstrapped=True, last_contact_monotonic=100.0
        )
        for k, v in rep_attrs.items():
            setattr(rep, k, v)
        gate = ReplicaGate(rep, max_lag_s=max_lag_s)
        return gate, rep

    def test_fresh_replica_admits(self):
        gate, _ = self._gate()
        gate._monotonic = lambda: 102.0  # lag 2s < 5s
        assert gate.current_lag() == pytest.approx(2.0)
        assert gate.admit()
        assert gate.served_total == 1 and gate.refused_total == 0
        state, detail = gate.health_state()
        assert state == "ok"

    def test_stale_replica_refuses_and_counts(self):
        gate, _ = self._gate()
        gate._monotonic = lambda: 110.0  # lag 10s > 5s
        assert not gate.admit()
        assert gate.refused_total == 1 and gate.lag_events_total == 1
        state, detail = gate.health_state()
        assert state == "down"
        assert "staleness" in detail.get("error", "")

    def test_unbootstrapped_and_diverged_are_infinitely_stale(self):
        gate, rep = self._gate(bootstrapped=False)
        assert gate.current_lag() == float("inf")
        rep.bootstrapped = True
        rep.diverged = True
        assert gate.current_lag() == float("inf")
        rep.diverged = False
        rep.last_contact_monotonic = None
        assert gate.current_lag() == float("inf")

    def test_clock_never_goes_negative(self):
        gate, _ = self._gate()
        gate._monotonic = lambda: 99.0  # contact "in the future"
        assert gate.current_lag() == 0.0


# --------------------------------------------------------------------------
# replica rig: owner (admission + replication) + replica (serving tier)
# --------------------------------------------------------------------------


class _Rig:
    def __init__(self, tmp_path, max_lag_s=5.0):
        self.owner_dir = str(tmp_path / "owner")
        self.replica_dir = str(tmp_path / "replica")
        os.makedirs(self.owner_dir)
        os.makedirs(self.replica_dir)
        args = decode_plugin_args(
            {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
        )
        # owner: store + journal + snapshot + admission HTTP + replication
        self.ls = Store()
        lrec = RecoveryManager(self.owner_dir)
        self.lj = lrec.recover_store(self.ls)
        self.lepoch = FencingEpoch(self.owner_dir)
        self.lj.fencing = self.lepoch
        self.snap = SnapshotManager(self.owner_dir, self.ls)
        self.snap.fencing = self.lepoch
        self.snap.bind_journal(self.lj, every_lines=0)
        self.ha = HaCoordinator(
            self.lepoch, role="leader", journal=self.lj, snapshotter=self.snap
        )
        self.ha.become_leader()
        self.ls.create_namespace(Namespace("default"))
        self.snap.write(reason="bootstrap")
        self.owner_plugin = KubeThrottler(args, self.ls, use_device=True)
        self.owner_http = ThrottlerHTTPServer(self.owner_plugin, port=0)
        self.owner_http.start()
        self.source = ReplicationSource(self.owner_dir, self.lj, self.lepoch)
        self.repl_server = ReplicationServer(self.source)
        self.repl_server.start()
        # replica: bootstrap + stream, then the gated serving tier
        self.rs = Store()
        rrec = RecoveryManager(self.replica_dir)
        self.rj = rrec.recover_store(self.rs)
        self.repoch = FencingEpoch(self.replica_dir)
        self.rj.fencing = self.repoch
        self.rep = StandbyReplicator(
            self.rs,
            self.rj,
            f"http://127.0.0.1:{self.repl_server.port}",
            epoch=self.repoch,
            poll_interval=0.02,
        )
        assert self.rep.bootstrap(10.0)
        self.rep.start()
        self.replica_plugin = KubeThrottler(args, self.rs, use_device=True)
        self.gate = ReplicaGate(self.rep, max_lag_s=max_lag_s)
        self.replica_http = ThrottlerHTTPServer(
            self.replica_plugin,
            port=0,
            replica_gate=self.gate,
            owner_url=f"http://127.0.0.1:{self.owner_http.port}",
        )
        self.replica_http.start()

    def close(self):
        self.replica_http.stop()
        self.rep.stop()
        self.owner_http.stop()
        self.repl_server.stop()
        self.replica_plugin.stop()
        self.owner_plugin.stop()
        self.rj.close()
        self.lj.close()


THROTTLE_MANIFEST = {
    "kind": "Throttle",
    "metadata": {"name": "t1", "namespace": "default"},
    "spec": {
        "throttlerName": "kube-throttler",
        "threshold": {"resourceRequests": {"cpu": "200m"}},
        "selector": {
            "selectorTerms": [{"podSelector": {"matchLabels": {"grp": "a"}}}]
        },
    },
}


def _pod_manifest(name, cpu="100m", labels=None):
    return {
        "kind": "Pod",
        "metadata": {
            "name": name,
            "namespace": "default",
            "labels": {"grp": "a"} if labels is None else labels,
        },
        "spec": {
            "schedulerName": "my-scheduler",
            "containers": [
                {"name": "c", "resources": {"requests": {"cpu": cpu}}}
            ],
        },
    }


class TestReplicaServing:
    def test_replica_serves_reads_and_forwards_writes(self, tmp_path):
        rig = _Rig(tmp_path)
        try:
            owner, replica = rig.owner_http.port, rig.replica_http.port
            # /readyz reports the role
            code, ready, _ = _req(replica, "GET", "/readyz")
            assert ready.get("role") == "replica"

            # seed the OWNER; the stream mirrors it to the replica
            code, _, _ = _req(owner, "POST", "/v1/objects", THROTTLE_MANIFEST)
            assert code == 200
            code, _, _ = _req(owner, "POST", "/v1/objects", _pod_manifest("p1"))
            assert code == 200
            assert _wait(
                lambda: any(t.name == "t1" for t in rig.rs.list_throttles())
                and any(p.name == "p1" for p in rig.rs.list_pods("default"))
            ), "replica never mirrored the owner's objects"

            # the replica answers prefilter LOCALLY, agreeing with owner
            def verdicts_agree():
                _, ov, _ = _req(owner, "POST", "/v1/prefilter", {"podKey": "default/p1"})
                _, rv, _ = _req(replica, "POST", "/v1/prefilter", {"podKey": "default/p1"})
                return ov["code"] == rv["code"]

            assert _wait(verdicts_agree), "replica verdict diverged from owner"
            assert rig.gate.served_total > 0

            # a second identical probe is a verdict-cache hit replica-side
            hits0 = rig.replica_plugin.verdict_cache.stats()[0]
            _req(replica, "POST", "/v1/prefilter", {"podKey": "default/p1"})
            assert rig.replica_plugin.verdict_cache.stats()[0] > hits0

            # WRITES forward to the owner: reserve through the replica
            code, _, headers = _req(
                replica, "POST", "/v1/reserve", {"podKey": "default/p1"}
            )
            assert code == 200
            assert headers.get("X-KT-Forwarded-By") == "replica"
            assert _wait(
                lambda: "default/p1"
                in rig.owner_plugin.throttle_ctr.cache.reserved_pod_keys(
                    "default/t1"
                )
            ), "forwarded reserve never landed on the owner"

            # object writes forward too, then stream back to the replica
            code, _, headers = _req(
                replica, "POST", "/v1/objects", _pod_manifest("p2", cpu="50m")
            )
            assert code == 200
            assert headers.get("X-KT-Forwarded-By") == "replica"
            owner_pods = lambda: {p.name for p in rig.ls.list_pods("default")}  # noqa: E731
            assert "p2" in owner_pods()
            assert _wait(
                lambda: any(p.name == "p2" for p in rig.rs.list_pods("default"))
            ), "forwarded object write never streamed back"

            # DELETE forwards as well
            code, _, headers = _req(
                replica, "DELETE", "/v1/objects/pods/default/p2"
            )
            assert code == 200
            assert headers.get("X-KT-Forwarded-By") == "replica"
            assert _wait(lambda: "p2" not in owner_pods())
        finally:
            rig.close()

    def test_stale_replica_refuses_reads_with_503(self, tmp_path):
        rig = _Rig(tmp_path)
        try:
            replica = rig.replica_http.port
            _req(rig.owner_http.port, "POST", "/v1/objects", THROTTLE_MANIFEST)
            _req(rig.owner_http.port, "POST", "/v1/objects", _pod_manifest("p1"))
            assert _wait(
                lambda: any(p.name == "p1" for p in rig.rs.list_pods("default"))
            )
            code, _, _ = _req(replica, "POST", "/v1/prefilter", {"podKey": "default/p1"})
            assert code == 200
            # freeze the gate's clock far past the staleness bound: reads
            # refuse, health flips, but writes still forward
            rig.gate._monotonic = (
                lambda: rig.rep.last_contact_monotonic + rig.gate.max_lag_s + 60.0
            )
            code, body, _ = _req(
                replica, "POST", "/v1/prefilter", {"podKey": "default/p1"}
            )
            assert code == 503
            assert "stale" in body["error"]
            assert body["maxLagSeconds"] == rig.gate.max_lag_s
            code, body, _ = _req(replica, "POST", "/v1/prefilter-batch", {})
            assert code == 503
            assert rig.gate.refused_total >= 2
            code, _, headers = _req(
                replica, "POST", "/v1/reserve", {"podKey": "default/p1"}
            )
            assert code == 200  # forwarded writes are never staleness-gated
            assert headers.get("X-KT-Forwarded-By") == "replica"
        finally:
            rig.close()

    def test_dead_owner_makes_forwards_502(self, tmp_path):
        rig = _Rig(tmp_path)
        try:
            replica = rig.replica_http.port
            rig.owner_http.stop()
            code, body, _ = _req(
                replica, "POST", "/v1/reserve", {"podKey": "default/nope"}
            )
            assert code == 502
            assert "owner unreachable" in body["error"]
        finally:
            rig.close()

    def test_replica_metrics_families_export(self, tmp_path):
        from kube_throttler_tpu.metrics import (
            register_replica_metrics,
            register_verdict_cache_metrics,
        )

        rig = _Rig(tmp_path)
        try:
            registry = rig.replica_plugin.metrics_registry
            register_replica_metrics(registry, rig.gate)
            register_verdict_cache_metrics(
                registry, rig.replica_plugin.verdict_cache
            )
            _req(rig.owner_http.port, "POST", "/v1/objects", THROTTLE_MANIFEST)
            _req(rig.owner_http.port, "POST", "/v1/objects", _pod_manifest("p1"))
            assert _wait(
                lambda: any(p.name == "p1" for p in rig.rs.list_pods("default"))
            )
            _req(rig.replica_http.port, "POST", "/v1/prefilter", {"podKey": "default/p1"})
            text = registry.exposition()
            assert 'kube_throttler_replica_verdicts_total{outcome="served"}' in text
            assert "kube_throttler_replica_lag_events_total" in text
            assert "kube_throttler_replica_lag_seconds" in text
            assert "kube_throttler_verdict_cache_hits_total" in text
            assert "kube_throttler_verdict_cache_entries" in text
        finally:
            rig.close()
