"""Static-analyzer test tier: each checker must fire on a seeded fixture
violation (with the right file:line) and stay silent on the real repo.

Fixtures are tiny source trees written to tmp_path and analyzed through
the same ``load_package``/``run_checks`` pipeline the CLI uses, so the
tests exercise path scoping and baseline handling too — not just the AST
visitors. The final tier-1 gate shells out to ``python -m
kube_throttler_tpu.analysis`` exactly the way ``make lint`` does.
"""

from __future__ import annotations

import os
import subprocess
import sys
import textwrap

import pytest

from kube_throttler_tpu.analysis import run_checks, run_repo
from kube_throttler_tpu.analysis.__main__ import main as analysis_main
from kube_throttler_tpu.analysis.core import (
    apply_baseline,
    load_baseline,
    load_package,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def write_tree(root, files):
    for rel, src in files.items():
        path = root / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src))
    return str(root)


def findings_for(root, checks, allowlist_path=None):
    return run_checks(load_package(str(root)), checks, allowlist_path=allowlist_path)


# ------------------------------------------------------------------ guarded


class TestGuardedBy:
    def test_unguarded_write_fires_with_line(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "mod.py": '''\
                import threading


                class Box:
                    GUARDED_BY = {"_items": "self._lock"}

                    def __init__(self):
                        self._lock = threading.Lock()
                        self._items = []

                    def ok(self):
                        with self._lock:
                            self._items.append(1)

                    def bad(self):
                        self._items.append(2)
                '''
            },
        )
        found = findings_for(root, ("guarded",))
        assert len(found) == 1
        f = found[0]
        assert f.checker == "guarded"
        assert f.relpath == "mod.py"
        assert f.line == 16  # the self._items read in bad()
        assert "_items" in f.message and "Box.bad" in f.message

    def test_inline_annotation_and_locked_suffix(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "mod.py": '''\
                import threading


                class Box:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._n = 0  #: guarded-by: self._lock

                    def _bump_locked(self):
                        self._n += 1  # caller-holds-lock contract: no finding

                    def bad(self):
                        return self._n
                '''
            },
        )
        found = findings_for(root, ("guarded",))
        assert [f.line for f in found] == [13]
        assert "Box.bad" in found[0].message

    def test_condition_alias_satisfies_lock_guard(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "mod.py": '''\
                import threading


                class Q:
                    GUARDED_BY = {"_q": "self._lock"}

                    def __init__(self):
                        self._lock = threading.Lock()
                        self._cv = threading.Condition(self._lock)
                        self._q = []

                    def put(self, x):
                        with self._cv:  # holding the condition IS holding the lock
                            self._q.append(x)
                '''
            },
        )
        assert findings_for(root, ("guarded",)) == []


# ---------------------------------------------------------------- lockorder


_CYCLE_SRC = {
    "mod.py": '''\
    import threading


    class AB:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def ab(self):
            with self._a:
                with self._b:
                    pass

        def ba(self):
            with self._b:
                with self._a:
                    pass
    '''
}


class TestLockOrder:
    def test_cycle_fires(self, tmp_path):
        found = findings_for(write_tree(tmp_path, _CYCLE_SRC), ("lockorder",))
        cycles = [f for f in found if "cycle" in f.message]
        assert len(cycles) == 1
        assert "mod.AB._a" in cycles[0].message and "mod.AB._b" in cycles[0].message
        assert cycles[0].relpath == "mod.py"

    def test_allowlist_silences_vetted_edge(self, tmp_path):
        root = write_tree(tmp_path, _CYCLE_SRC)
        allow = tmp_path / "allow.txt"
        # removing either direction breaks the 2-cycle
        allow.write_text("mod.AB._b -> mod.AB._a  # vetted: ba() only runs in tests\n")
        found = findings_for(root, ("lockorder",), allowlist_path=str(allow))
        assert [f for f in found if "cycle" in f.message] == []

    def test_nonreentrant_self_reacquire_fires(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "mod.py": '''\
                import threading


                class S:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def outer(self):
                        with self._lock:
                            self.inner()

                    def inner(self):
                        with self._lock:
                            pass
                '''
            },
        )
        found = findings_for(root, ("lockorder",))
        assert any("re-acquired while held" in f.message for f in found)

    def test_rlock_self_nesting_is_fine(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "mod.py": '''\
                import threading


                class R:
                    def __init__(self):
                        self._lock = threading.RLock()

                    def outer(self):
                        with self._lock:
                            self.inner()

                    def inner(self):
                        with self._lock:
                            pass
                '''
            },
        )
        assert findings_for(root, ("lockorder",)) == []


# ------------------------------------------------------------------- purity


class TestPurity:
    def test_host_call_in_jitted_fn(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "ops/k.py": '''\
                import time

                import jax


                @jax.jit
                def tick(x):
                    t = time.monotonic()
                    return x + t
                ''',
            },
        )
        found = findings_for(root, ("purity",))
        assert len(found) == 1
        assert found[0].line == 8
        assert "time.monotonic()" in found[0].message

    def test_host_call_reachable_through_helper(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "ops/k.py": '''\
                import random

                import jax


                def helper(x):
                    return x * random.random()


                @jax.jit
                def entry(x):
                    return helper(x)
                ''',
            },
        )
        found = findings_for(root, ("purity",))
        assert len(found) == 1
        assert "random.random()" in found[0].message
        assert found[0].line == 7

    def test_branch_on_traced_param(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "ops/k.py": '''\
                import jax


                @jax.jit
                def f(x, n):
                    if n > 3:
                        return x
                    return -x
                ''',
            },
        )
        found = findings_for(root, ("purity",))
        assert len(found) == 1
        assert found[0].line == 6
        assert "Python if on traced parameter(s) n" in found[0].message

    def test_static_argnames_and_structure_checks_exempt(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "ops/k.py": '''\
                from functools import partial

                import jax


                @partial(jax.jit, static_argnames=("n",))
                def f(x, n, y=None):
                    if n > 3:            # static arg: fine
                        return x
                    if y is None:        # structure check: fine
                        return x
                    if x.shape[0] > 2:   # trace-time shape: fine
                        return x
                    return -x
                ''',
            },
        )
        assert findings_for(root, ("purity",)) == []

    def test_shard_map_body_checked(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "parallel/s.py": '''\
                import threading

                from somewhere import shard_map


                def build(mesh):
                    def _body(a):
                        threading.Lock()
                        return a

                    return shard_map(_body, mesh=mesh, in_specs=(), out_specs=())
                ''',
            },
        )
        found = findings_for(root, ("purity",))
        assert len(found) == 1
        assert "threading.Lock()" in found[0].message


# ----------------------------------------------------------------- registry


_REGISTRY_BASE = {
    "faults/plan.py": '''\
    KNOWN_SITES = frozenset({"transport.request", "journal.append"})
    ''',
    "metrics.py": '''\
    METRIC_NAMES = frozenset({"kube_throttler_good_total"})
    ''',
}


class TestRegistry:
    def test_unregistered_fault_site(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                **_REGISTRY_BASE,
                "mod.py": '''\
                def f(self):
                    self.faults.check("transport.request")
                    self.faults.check("transport.typo")
                ''',
            },
        )
        found = findings_for(root, ("registry",))
        assert len(found) == 1
        assert found[0].line == 3
        assert "transport.typo" in found[0].message

    def test_faultrule_pattern_must_match_some_site(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                **_REGISTRY_BASE,
                "mod.py": '''\
                from faults import FaultRule

                ok = FaultRule(site="transport.*")
                bad = FaultRule(site="watch.*")
                ''',
            },
        )
        found = findings_for(root, ("registry",))
        assert len(found) == 1
        assert found[0].line == 4
        assert "watch.*" in found[0].message

    def test_undeclared_metric_name(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                **_REGISTRY_BASE,
                "mod.py": '''\
                def setup(registry):
                    registry.gauge_vec("kube_throttler_good_total", "h", ["a"])
                    registry.counter_vec("kube_throttler_drifted_total", "h", ["a"])
                ''',
            },
        )
        found = findings_for(root, ("registry",))
        assert len(found) == 1
        assert found[0].line == 3
        assert "kube_throttler_drifted_total" in found[0].message

    def test_missing_registry_declarations_flagged(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "faults/plan.py": "SITES = None\n",
                "metrics.py": "x = 1\n",
            },
        )
        messages = "\n".join(f.message for f in findings_for(root, ("registry",)))
        assert "KNOWN_SITES" in messages
        assert "METRIC_NAMES" in messages


# ------------------------------------------------------- baseline mechanics


class TestBaseline:
    def test_waived_findings_do_not_fail(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "mod.py": '''\
                import threading


                class Box:
                    GUARDED_BY = {"_items": "self._lock"}

                    def __init__(self):
                        self._lock = threading.Lock()
                        self._items = []

                    def bad(self):
                        return self._items
                '''
            },
        )
        found = findings_for(root, ("guarded",))
        assert len(found) == 1
        baseline_path = tmp_path / "baseline.txt"
        baseline_path.write_text(f"{found[0].key()}  # vetted lock-free read\n")
        baseline = load_baseline(str(baseline_path))
        new, waived, stale = apply_baseline(found, baseline)
        assert new == [] and len(waived) == 1 and stale == []

    def test_stale_waivers_reported(self, tmp_path):
        baseline = {"guarded|gone.py|read of '_x' outside its lock in G.f": "old"}
        new, waived, stale = apply_baseline([], baseline)
        assert new == [] and waived == [] and len(stale) == 1

    def test_key_is_line_stable(self, tmp_path):
        """Shifting a violation by a line must not change its baseline key."""

        body = textwrap.dedent(
            '''\
            import threading


            class Box:
                GUARDED_BY = {"_items": "self._lock"}

                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []

                def bad(self):
                    return self._items
            '''
        )

        def tree(prefix_lines):
            return {"mod.py": "# pad\n" * prefix_lines + body}

        a = findings_for(write_tree(tmp_path / "a", tree(0)), ("guarded",))
        b = findings_for(write_tree(tmp_path / "b", tree(3)), ("guarded",))
        assert a[0].line != b[0].line
        assert a[0].key() == b[0].key()


# ----------------------------------------------------------- CLI / repo gate


class TestCli:
    def test_cli_nonzero_on_seeded_violation(self, tmp_path):
        root = write_tree(tmp_path, _CYCLE_SRC)
        empty_baseline = tmp_path / "baseline.txt"
        empty_baseline.write_text("")
        rc = analysis_main(
            ["--root", root, "--baseline", str(empty_baseline), "-q"]
        )
        assert rc == 1

    def test_cli_zero_on_clean_tree(self, tmp_path):
        root = write_tree(tmp_path, {"mod.py": "x = 1\n"})
        rc = analysis_main(["--root", root, "--no-baseline", "-q"])
        assert rc == 0

    def test_repo_is_clean_inprocess(self):
        """The real package must analyze clean against the checked-in
        baseline, and every baseline waiver must still be live."""
        new, waived, stale = run_repo()
        assert new == [], "\n".join(f.render() for f in new)
        assert stale == [], f"stale baseline waivers: {stale}"

    def test_repo_gate_subprocess(self):
        """Tier-1 regression gate: exactly what `make lint` runs."""
        proc = subprocess.run(
            [sys.executable, "-m", "kube_throttler_tpu.analysis"],
            cwd=REPO_ROOT,
            capture_output=True,
            text=True,
            timeout=300,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
